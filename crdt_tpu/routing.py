"""Keyspace routing for the federated serving tier.

One `ServeTier` proved 10k sessions on a single replica (SERVE_r01);
the federation layer scales *out* by giving each of N tiers ownership
of a contiguous share of the slot space. This module is the pure-host
half of that design — no sockets, no device work, no metrics — so the
router can be unit-tested exhaustively and shared verbatim between
servers, the proxy fallback, clients and the bench harness:

- `RoutingTable`: an epoch-versioned, immutable partition map. The
  keyspace `[0, n_slots)` is covered by disjoint contiguous ranges,
  each owned by one tier address. Ranges are contiguous **by
  construction** so a migrating range is exactly what
  `DenseCrdt.pack_since(ranges=...)` streams (docs/ANTIENTROPY.md) —
  consistent hashing here places *owner tokens* on the slot ring and
  assigns arcs, rather than hashing each key independently, which
  would shred locality and make range migration impossible.
- `PartitionRouter`: the per-tier view — "which table do I believe,
  and is this op mine?". `check()` is the single admission gate the
  serve loop consults before a keyspace op may enqueue; the crdtlint
  `router-epoch-bypass` rule holds serve-loop code to that shape.

Epoch discipline: tables are totally ordered by `epoch`; a split
produces `epoch + 1`. Routers adopt a table only if it is newer
(`install`), so gossiped tables may arrive in any order. Clients send
the epoch they routed with on every keyspace op; a stale epoch is
refused with `moved` even when the slot still lands on the same owner
— the refusal is what forces the client to refetch the table *before*
its next write can race a migrating range (docs/FEDERATION.md).
"""

from __future__ import annotations

from bisect import bisect_right
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["RoutingTable", "PartitionRouter", "PROXY"]

# FNV-1a 64-bit, hand-rolled: token placement must be stable across
# processes and Python versions (builtin hash() is salted per process),
# and the router must not grow a hashlib dependency for 8 tokens.
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def _fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


# Sentinel returned by `PartitionRouter.check` when the op belongs to
# another tier but the session never negotiated the `federation` cap:
# the server must answer by proxying to the owner, not by sending a
# `moved` reply the client cannot parse.
PROXY = "proxy"


class RoutingTable:
    """Immutable epoch-versioned map from slot ranges to owner
    addresses.

    ``ranges`` is a tuple of ``(lo, hi, owner)`` half-open intervals,
    sorted by ``lo``, disjoint, and covering ``[0, n_slots)`` exactly —
    validated at construction so a malformed gossiped table fails
    loudly at install time rather than misrouting writes later.
    """

    __slots__ = ("n_slots", "epoch", "ranges", "_los")

    def __init__(self, n_slots: int, epoch: int,
                 ranges: Sequence[Tuple[int, int, str]]):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1; got {n_slots}")
        if epoch < 0:
            raise ValueError(f"epoch must be >= 0; got {epoch}")
        rs = tuple((int(lo), int(hi), str(owner))
                   for lo, hi, owner in ranges)
        if not rs:
            raise ValueError("routing table needs at least one range")
        cursor = 0
        for lo, hi, owner in rs:
            if lo != cursor or hi <= lo:
                raise ValueError(
                    f"ranges must be sorted, disjoint and cover "
                    f"[0, {n_slots}); got gap/overlap at [{lo}, {hi})")
            if not owner:
                raise ValueError(f"empty owner for range [{lo}, {hi})")
            cursor = hi
        if cursor != n_slots:
            raise ValueError(
                f"ranges cover [0, {cursor}) but n_slots={n_slots}")
        self.n_slots = int(n_slots)
        self.epoch = int(epoch)
        self.ranges = rs
        self._los = [lo for lo, _, _ in rs]

    # --- construction ---

    @classmethod
    def build(cls, n_slots: int, owners: Sequence[str],
              vnodes: int = 8) -> "RoutingTable":
        """Consistent-hash placement: each owner contributes ``vnodes``
        tokens at FNV-1a positions on the slot ring; each arc between
        consecutive tokens is owned by the arc-opening token's owner.
        Adding an owner moves only the arcs its new tokens bisect —
        the classic consistent-hashing stability property, with arcs
        that stay contiguous so they remain streamable ranges."""
        names = list(dict.fromkeys(str(o) for o in owners))
        if not names:
            raise ValueError("need at least one owner")
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1; got {vnodes}")
        tokens: Dict[int, str] = {}
        for name in names:
            for i in range(vnodes):
                pos = _fnv1a64(f"{name}#{i}".encode()) % n_slots
                # Token collisions resolve to the lexicographically
                # smaller owner: deterministic, order-independent.
                prev = tokens.get(pos)
                if prev is None or name < prev:
                    tokens[pos] = name
        pts = sorted(tokens)
        ranges: List[Tuple[int, int, str]] = []
        first_owner = tokens[pts[0]]
        if pts[0] != 0:
            # The wrap arc [last_token, n_slots) + [0, first_token)
            # belongs to the last token's owner; it lands as two
            # contiguous ranges.
            ranges.append((0, pts[0], tokens[pts[-1]]))
        for i, lo in enumerate(pts):
            hi = pts[i + 1] if i + 1 < len(pts) else n_slots
            if hi > lo:
                ranges.append((lo, hi, tokens[lo]))
        merged = cls._merge_adjacent(ranges)
        table = cls(n_slots, 0, merged)
        missing = set(names) - set(table.owners())
        if missing:
            # Tiny rings can starve an owner of arcs; fall back to the
            # even split so every started tier owns something.
            return cls.even(n_slots, names)
        return table

    @classmethod
    def even(cls, n_slots: int, owners: Sequence[str]) -> "RoutingTable":
        """Equal contiguous shares in owner order — the predictable
        layout the bench uses so "partition 0 runs hot" is a statement
        about a known range."""
        names = list(dict.fromkeys(str(o) for o in owners))
        if not names:
            raise ValueError("need at least one owner")
        n = len(names)
        if n > n_slots:
            raise ValueError(
                f"{n} owners cannot split {n_slots} slots")
        ranges = []
        for i, name in enumerate(names):
            lo = n_slots * i // n
            hi = n_slots * (i + 1) // n
            ranges.append((lo, hi, name))
        return cls(n_slots, 0, ranges)

    @staticmethod
    def _merge_adjacent(
            ranges: Iterable[Tuple[int, int, str]]
    ) -> List[Tuple[int, int, str]]:
        out: List[Tuple[int, int, str]] = []
        for lo, hi, owner in ranges:
            if out and out[-1][2] == owner and out[-1][1] == lo:
                out[-1] = (out[-1][0], hi, owner)
            else:
                out.append((lo, hi, owner))
        return out

    # --- queries ---

    def owner_of(self, slot: int) -> str:
        """Owner address for one slot (O(log ranges))."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(
                f"slot {slot} outside keyspace [0, {self.n_slots})")
        return self.ranges[bisect_right(self._los, slot) - 1][2]

    def owned_mask(self, slots, addr: str):
        """Vectorized ownership test for a batch of slots (the binary
        op lane's admission path): one ``searchsorted`` over the range
        starts against a precomputed per-range owner mask, O(k log r)
        for a k-op batch instead of k Python-level ``owner_of`` calls.
        Callers bound the slots to ``[0, n_slots)`` first (the serve
        tier's per-op slot guard runs before routing)."""
        import numpy as np
        slots = np.asarray(slots)
        idx = np.searchsorted(np.asarray(self._los, np.int64),
                              slots.astype(np.int64, copy=False),
                              side="right") - 1
        owned = np.fromiter((o == addr for _, _, o in self.ranges),
                            bool, count=len(self.ranges))
        return owned[idx]

    def owners(self) -> Tuple[str, ...]:
        """Distinct owners in first-range order."""
        return tuple(dict.fromkeys(o for _, _, o in self.ranges))

    def ranges_of(self, owner: str) -> Tuple[Tuple[int, int], ...]:
        """The (lo, hi) ranges one owner holds — the exact argument
        shape `pack_since(ranges=...)` takes when migrating them."""
        return tuple((lo, hi) for lo, hi, o in self.ranges
                     if o == owner)

    def slots_of(self, owner: str) -> int:
        return sum(hi - lo for lo, hi in self.ranges_of(owner))

    # --- evolution ---

    def split(self, lo: int, at: int, new_owner: str) -> "RoutingTable":
        """New table (epoch + 1) with ``[at, hi)`` of the range that
        starts at ``lo`` reassigned to ``new_owner`` — the routing flip
        at the end of a live migration. The old owner keeps
        ``[lo, at)``."""
        for rlo, rhi, owner in self.ranges:
            if rlo == lo:
                if not lo < at < rhi:
                    raise ValueError(
                        f"split point {at} outside ({lo}, {rhi})")
                out = []
                for r in self.ranges:
                    if r[0] == lo:
                        out.append((lo, at, owner))
                        out.append((at, rhi, str(new_owner)))
                    else:
                        out.append(r)
                return RoutingTable(self.n_slots, self.epoch + 1, out)
        raise ValueError(f"no range starts at slot {lo}")

    def reassign(self, old_owner: str,
                 new_owner: str) -> "RoutingTable":
        """New table (epoch + 1) with every range of ``old_owner``
        handed to ``new_owner`` — the routing flip at the end of a
        failover. Unlike `split` the range geometry is unchanged:
        replica promotion moves *ownership of an arc*, never its
        boundaries, so in-flight `pack_since(ranges=...)` bookkeeping
        keyed on (lo, hi) stays valid across the flip."""
        old, new = str(old_owner), str(new_owner)
        if old not in self.owners():
            raise ValueError(f"{old!r} owns no ranges at epoch "
                             f"{self.epoch}")
        out = [(lo, hi, new if o == old else o)
               for lo, hi, o in self.ranges]
        return RoutingTable(self.n_slots, self.epoch + 1,
                            self._merge_adjacent(out))

    def merge(self, retiring: str, into: str) -> "RoutingTable":
        """New table (epoch + 1) with every range of ``retiring``
        handed to ``into`` — the routing flip at the end of a live
        merge (`FederatedTier.merge_cold`). Unlike `reassign`, the
        recipient must ALREADY be an owner: a merge shrinks the fleet
        by one partition, it never introduces an address, so a typo'd
        recipient fails here instead of minting a ghost owner the
        fleet would route writes to. Adjacent ranges coalesce, so a
        donor arc bordered by the recipient's own arc disappears from
        the range list entirely."""
        old, new = str(retiring), str(into)
        if old == new:
            raise ValueError(f"cannot merge {old!r} into itself")
        owners = self.owners()
        if old not in owners:
            raise ValueError(f"{old!r} owns no ranges at epoch "
                             f"{self.epoch}")
        if new not in owners:
            raise ValueError(
                f"merge recipient {new!r} owns no ranges at epoch "
                f"{self.epoch}; a merge hands arcs to an EXISTING "
                f"owner (use reassign for promotion flips)")
        out = [(lo, hi, new if o == old else o)
               for lo, hi, o in self.ranges]
        return RoutingTable(self.n_slots, self.epoch + 1,
                            self._merge_adjacent(out))

    @staticmethod
    def newest(a: Optional["RoutingTable"],
               b: Optional["RoutingTable"]) -> Optional["RoutingTable"]:
        """Join for gossiped tables: the higher epoch wins; ties keep
        ``a`` (epochs only ever move through `split`, so equal epochs
        are equal tables)."""
        if a is None:
            return b
        if b is None or b.epoch <= a.epoch:
            return a
        return b

    # --- wire form (rides hello/metrics JSON surfaces) ---

    def to_json(self) -> dict:
        return {"n_slots": self.n_slots, "epoch": self.epoch,
                "ranges": [[lo, hi, owner]
                           for lo, hi, owner in self.ranges]}

    @classmethod
    def from_json(cls, obj: dict) -> "RoutingTable":
        return cls(int(obj["n_slots"]), int(obj["epoch"]),
                   [(int(lo), int(hi), str(owner))
                    for lo, hi, owner in obj["ranges"]])

    def __eq__(self, other) -> bool:
        return (isinstance(other, RoutingTable)
                and self.n_slots == other.n_slots
                and self.epoch == other.epoch
                and self.ranges == other.ranges)

    def __hash__(self):
        return hash((self.n_slots, self.epoch, self.ranges))

    def __repr__(self) -> str:
        return (f"RoutingTable(n_slots={self.n_slots}, "
                f"epoch={self.epoch}, ranges={len(self.ranges)}, "
                f"owners={len(self.owners())})")


class PartitionRouter:
    """One tier's routing view: the newest table it has adopted plus
    its own address, answering "may this op enqueue here?".

    Single-writer by design: `bind`/`install` run on the tier's serve
    loop (or before it starts), and `check` runs on the same loop —
    no lock needed, matching the serve loop's threading model.
    """

    __slots__ = ("addr", "table")

    def __init__(self, addr: Optional[str] = None,
                 table: Optional[RoutingTable] = None):
        self.addr = addr
        self.table = table

    @property
    def epoch(self) -> Optional[int]:
        return None if self.table is None else self.table.epoch

    def bind(self, addr: str, table: Optional[RoutingTable] = None
             ) -> None:
        """Fix this router's own address (host:port, known only once
        the listening socket reports its port) and optionally seed the
        table in the same step."""
        self.addr = str(addr)
        if table is not None:
            self.install(table)

    def install(self, table: RoutingTable) -> bool:
        """Adopt ``table`` iff it is newer than the current one (so
        out-of-order gossip cannot roll the epoch back). Returns True
        when the table changed."""
        newest = RoutingTable.newest(self.table, table)
        if newest is self.table:
            return False
        self.table = newest
        return True

    def owns(self, slot: int) -> bool:
        return (self.table is not None and self.addr is not None
                and self.table.owner_of(slot) == self.addr)

    def check(self, slot: int, client_epoch: Optional[int],
              fed_ok: bool):
        """The admission gate for one keyspace op.

        Returns ``None`` when the op may enqueue locally, the `PROXY`
        sentinel when the server must forward it for a pre-federation
        session, or a ready-to-send ``moved`` reply dict. A stale
        ``client_epoch`` is refused even for slots this tier owns —
        see the module docstring for why.
        """
        table = self.table
        if table is None or self.addr is None:
            return None          # unbound: single-tier mode, no gate
        owner = table.owner_of(slot)
        stale = (client_epoch is not None
                 and int(client_epoch) != table.epoch)
        if owner == self.addr and not stale:
            return None
        if not fed_ok and owner != self.addr:
            return PROXY
        return {"ok": False, "code": "moved", "owner": owner,
                "epoch": table.epoch,
                "error": (f"slot {slot} owned by {owner} at routing "
                          f"epoch {table.epoch}")}

    def check_batch(self, slots, client_epoch: Optional[int],
                    fed_ok: bool):
        """Vectorized admission for one binary op batch: ``None`` when
        EVERY op may enqueue locally (the hot all-owned path costs one
        searchsorted), else a bool admit-mask — the serve loop settles
        each refused op individually through `check`, so the
        moved/stale-epoch/proxy taxonomy stays in one place. A stale
        ``client_epoch`` refuses the whole batch (one epoch stamps the
        frame), same as the per-op rule."""
        table = self.table
        if table is None or self.addr is None:
            return None          # unbound: single-tier mode, no gate
        if client_epoch is not None \
                and int(client_epoch) != table.epoch:
            import numpy as np
            return np.zeros(len(slots), bool)
        mask = table.owned_mask(slots, self.addr)
        if mask.all():
            return None
        return mask

"""Serving-tier suite (docs/SERVING.md): session multiplexing onto
the combiner tick, admission watermark, cold-lane bounds, and wire
compatibility with every client generation — negotiated
`PeerConnection` sessions (packed + merkle) and pre-hello legacy
peers — in both directions."""

import json
import socket
import time

import numpy as np
import pytest

from crdt_tpu import (DenseCrdt, FederatedTier, PeerConnection,
                      ServeTier, SyncTransportError, default_registry,
                      fetch_metrics, sync_merkle_over_conn,
                      sync_over_tcp, sync_packed_over_conn)
from crdt_tpu.net import (BINOP_DELETE, BINOP_GET, BINOP_PUT,
                          BINOP_ST_MOVED, BINOP_ST_OK,
                          BINOP_ST_OK_NULL, BINOP_ST_REJECTED,
                          FrameCodec, binop_round,
                          encode_binop_request, recv_frame,
                          send_bytes_frame, send_frame)
from crdt_tpu.testing import FaultProxy, ScriptedSchedule

pytestmark = pytest.mark.serve


def _connect(tier):
    sock = socket.create_connection((tier.host, tier.port),
                                    timeout=10.0)
    sock.settimeout(10.0)
    return sock


def _req(sock, obj, codec=None):
    send_frame(sock, obj, None, codec)
    return recv_frame(sock, deadline=time.monotonic() + 10.0,
                      codec=codec)


def _binop_session(host, port, extra_caps=()):
    """Negotiated binary-lane session: hello offering binop (plus any
    extra caps), post-hello tagged framing with no compression."""
    sock = socket.create_connection((host, port), timeout=10.0)
    sock.settimeout(10.0)
    reply = _req(sock, {"op": "hello", "proto": 1,
                        "caps": ["binop", *extra_caps]})
    assert reply["ok"] and "binop" in reply["caps"]
    return sock, FrameCodec(compress=False)


# --- serve-only ops: put / get / delete over the framed wire ---

def test_put_get_delete_roundtrip():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        with _connect(tier) as sock:
            assert _req(sock, {"op": "put", "slot": 3,
                               "value": 42}) == {"ok": True}
            # read-your-writes: the ack resolved AFTER the commit, so
            # the overlay/store answers immediately.
            assert _req(sock, {"op": "get", "slot": 3}) \
                == {"ok": True, "value": 42}
            assert _req(sock, {"op": "delete", "slot": 3}) \
                == {"ok": True}
            assert _req(sock, {"op": "get", "slot": 3})["value"] is None
            send_frame(sock, {"op": "bye"})
    # tier stopped -> ingest window closed; direct reads are safe.
    assert crdt.get(3) is None


def test_malformed_write_rejected_session_survives():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            for bad in ({"op": "put", "slot": 999, "value": 1},
                        {"op": "put", "slot": -1, "value": 1},
                        {"op": "put", "slot": 1, "value": "x"},
                        {"op": "get", "slot": "nope"}):
                reply = _req(sock, bad)
                assert reply["ok"] is False
                assert reply["code"] == "write_rejected"
            # ...and the session is still alive afterwards.
            assert _req(sock, {"op": "put", "slot": 5,
                               "value": 7}) == {"ok": True}
            send_frame(sock, {"op": "bye"})
    assert crdt.get(5) == 7


def test_out_of_range_and_bool_writes_rejected_flusher_survives():
    """An int outside int64 passes `isinstance(value, int)` but would
    blow up the flush tick's np.int64 conversion — it must be rejected
    per-write at the session, and the flusher must survive even if
    something slips through (a dead flusher hangs EVERY later ack).
    JSON true/false are ints to isinstance and must be rejected too."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            for bad in ({"op": "put", "slot": 1, "value": 2 ** 63},
                        {"op": "put", "slot": 1, "value": -(2 ** 63) - 1},
                        {"op": "put", "slot": 1, "value": 2 ** 200},
                        {"op": "put", "slot": True, "value": 1},
                        {"op": "put", "slot": 1, "value": False},
                        {"op": "delete", "slot": False},
                        {"op": "get", "slot": True}):
                reply = _req(sock, bad)
                assert reply["ok"] is False
                assert reply["code"] == "write_rejected"
            # int64 boundaries themselves are legal...
            assert _req(sock, {"op": "put", "slot": 2,
                               "value": 2 ** 63 - 1}) == {"ok": True}
            # ...and the flusher is still ticking afterwards.
            assert _req(sock, {"op": "put", "slot": 5,
                               "value": 7}) == {"ok": True}
            assert _req(sock, {"op": "get", "slot": 5}) \
                == {"ok": True, "value": 7}
            send_frame(sock, {"op": "bye"})
    assert crdt.get(5) == 7


def test_malformed_digest_more_replies_merkle_rejected():
    """A 'more' entry that is not a [level, idx] pair must get the
    merkle_rejected reply (like SyncServer), not an unhandled
    TypeError that kills the session without a reply."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        for more in ([5], ["xy"], [[0]], [[0, [0], 9]]):
            with _connect(tier) as sock:
                reply = _req(sock, {"op": "digest", "level": 0,
                                    "idx": [0], "more": more})
                assert reply["code"] == "merkle_rejected"


def test_idle_timeout_is_clean_close_not_a_drop():
    """Routine idle expiry must not inflate dropped_sessions — the
    bench's zero-dropped acceptance criterion reads that counter."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt, idle_timeout=0.2) as tier:
        with _connect(tier) as sock:
            assert _req(sock, {"op": "put", "slot": 1,
                               "value": 1}) == {"ok": True}
            # park past idle_timeout: the server closes cleanly (EOF)
            assert recv_frame(sock,
                              deadline=time.monotonic() + 10.0) is None
        assert tier.idle_closed_sessions == 1
        assert tier.dropped_sessions == 0


def test_unknown_op_hangs_up():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            reply = _req(sock, {"op": "frobnicate"})
            assert reply["code"] == "unknown_op"
            assert recv_frame(sock,
                              deadline=time.monotonic() + 10.0) is None


# --- the tentpole property: N writers, ONE combiner tick ---

def test_many_sessions_share_one_combiner_tick():
    crdt = DenseCrdt("a", n_slots=256)
    flushes = default_registry().counter(
        "crdt_tpu_ingest_flush_total",
        "write-combiner flushes by trigger")
    before = flushes.value(trigger="tick", node="a")
    with ServeTier(crdt, flush_interval=0.05) as tier:
        socks = [_connect(tier) for _ in range(8)]
        try:
            # All eight sessions write BEFORE any reads its ack: the
            # writes land in the same queue window and commit as one
            # put_batch + one combiner flush.
            for i, s in enumerate(socks):
                send_frame(s, {"op": "put", "slot": i, "value": i * 10})
            for s in socks:
                assert recv_frame(
                    s, deadline=time.monotonic() + 10.0) == {"ok": True}
            ticks = flushes.value(trigger="tick", node="a") - before
            # 8 writers, at most 2 ticks (2 only if a tick boundary
            # happened to split the sends) — never one flush per write.
            assert 1 <= ticks <= 2
        finally:
            for s in socks:
                s.close()
    for i in range(8):
        assert crdt.get(i) == i * 10
    assert tier.dropped_sessions == 0


# --- admission watermark ---

def test_admission_watermark_sheds_with_busy():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt, max_sessions=2) as tier:
        c1 = PeerConnection(tier.host, tier.port, timeout=5.0)
        c2 = PeerConnection(tier.host, tier.port, timeout=5.0)
        c3 = PeerConnection(tier.host, tier.port, timeout=5.0)
        try:
            c1.ensure()
            c2.ensure()
            with pytest.raises(SyncTransportError, match="busy"):
                c3.ensure()
            # Retryable refusal, NOT the legacy-downgrade signal.
            assert c3.legacy is False
            assert tier.shed_count >= 1
            shed = default_registry().counter(
                "crdt_tpu_serve_shed_total",
                "requests shed for backpressure (admission watermark "
                "or cold-join lane bound)")
            assert shed.value(lane="admission", node="a") >= 1
            # Freeing a slot readmits the shed client (bye is
            # processed asynchronously server-side, so poll).
            c1.close()
            for _ in range(500):
                try:
                    c3.ensure()
                    break
                except SyncTransportError:
                    time.sleep(0.01)
            else:
                raise AssertionError("slot never freed after close")
            assert "packed" in c3.caps
        finally:
            for c in (c1, c2, c3):
                c.close()


def test_hello_negotiates_full_caps():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            conn.ensure()
            assert {"zlib", "packed", "semantics",
                    "merkle", "trace"} <= conn.caps
            assert conn.codec is not None


# --- cold-join slow lane ---

def test_cold_lane_bound_sheds_digest_with_busy():
    crdt = DenseCrdt("a", n_slots=64)
    crdt.put_batch([1], [1])
    joiner = DenseCrdt("b", n_slots=64)
    with ServeTier(crdt, cold_lane_depth=0) as tier:
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            with pytest.raises(SyncTransportError, match="busy"):
                sync_merkle_over_conn(joiner, conn)
        assert tier.shed_count >= 1
        shed = default_registry().counter(
            "crdt_tpu_serve_shed_total",
            "requests shed for backpressure (admission watermark "
            "or cold-join lane bound)")
        assert shed.value(lane="cold", node="a") >= 1


def test_merkle_cold_join_through_tier():
    crdt = DenseCrdt("a", n_slots=64)
    slots = list(range(0, 64, 7))
    crdt.put_batch(slots, [s * 3 + 1 for s in slots])
    joiner = DenseCrdt("b", n_slots=64)
    with ServeTier(crdt) as tier:
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            stats = {}
            sync_merkle_over_conn(joiner, conn, _stats=stats)
            assert stats["rounds"] >= 1
    for s in slots:
        assert joiner.get(s) == s * 3 + 1


# --- wire compat: negotiated packed sessions, both directions ---

def test_packed_round_through_tier_converges_both_ways():
    served = DenseCrdt("a", n_slots=64)
    client = DenseCrdt("b", n_slots=64)
    served.put_batch([1, 2], [10, 20])
    client.put_batch([5], [50])
    with ServeTier(served) as tier:
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            mark = sync_packed_over_conn(client, conn, since=None)
            assert client.get(1) == 10 and client.get(2) == 20
            for _ in range(6):
                with tier.lock:
                    before = (str(served.canonical_time),
                              str(client.canonical_time))
                mark = sync_packed_over_conn(client, conn, since=mark)
                with tier.lock:
                    after = (str(served.canonical_time),
                             str(client.canonical_time))
                if after == before:
                    break
            else:
                raise AssertionError(
                    "clocks never settled through the tier")
    assert served.get(5) == 50
    assert client.get(5) == 50
    assert served.get(1) == 10 and served.get(2) == 20


def test_writes_landed_mid_session_reach_packed_pulls():
    served = DenseCrdt("a", n_slots=64)
    client = DenseCrdt("b", n_slots=64)
    with ServeTier(served) as tier:
        # A serve-session write...
        with _connect(tier) as wsock:
            assert _req(wsock, {"op": "put", "slot": 9,
                                "value": 99}) == {"ok": True}
            send_frame(wsock, {"op": "bye"})
        # ...is visible to a packed replication pull on the same tier
        # (the pack path drains the combiner as its barrier).
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            sync_packed_over_conn(client, conn, since=None)
    assert client.get(9) == 99


# --- wire compat: pre-hello legacy JSON peers ---

def test_legacy_pre_hello_json_round():
    served = DenseCrdt("a", n_slots=64)
    legacy = DenseCrdt("b", n_slots=64)
    served.put_batch([2], [22])
    legacy.put_batch([4], [44])
    with ServeTier(served) as tier:
        # sync_over_tcp never sends hello: byte-identical legacy wire.
        sync_over_tcp(legacy, tier.host, tier.port)
        assert legacy.get(2) == 22
        with tier.lock:
            assert served.get(4) == 44
    assert served.get(4) == 44


# --- observability surface ---

def test_metrics_op_reports_serve_instruments():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            assert _req(sock, {"op": "put", "slot": 1,
                               "value": 2}) == {"ok": True}
            send_frame(sock, {"op": "bye"})
        snap = fetch_metrics(tier.host, tier.port)
    assert "crdt_tpu_serve_sessions" in snap["gauges"]
    assert "crdt_tpu_serve_ops_total" in snap["counters"]
    assert "crdt_tpu_serve_ack_seconds" in snap["histograms"]
    assert "crdt_tpu_serve_flush_seconds" in snap["histograms"]


# --- ack attribution (PR 11): queue_wait / stamp / scatter / ack_write ---

def test_ack_phase_attribution_sums_to_ack():
    """Every acked write decomposes into queue_wait + stamp + scatter
    + ack_write; the phase-histogram sums must reconstruct the ack
    histogram's sum (per-write observation, shared tick legs)."""
    crdt = DenseCrdt("phase-a", n_slots=64)
    node = str(crdt.node_id)
    reg = default_registry()
    ack = reg.histogram("crdt_tpu_serve_ack_seconds")
    phase = reg.histogram("crdt_tpu_serve_ack_phase_seconds")

    def _sum(h, **labels):
        return sum(s["sum"] for s in h.samples()
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    ack0 = _sum(ack, node=node)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        with _connect(tier) as sock:
            for i in range(20):
                assert _req(sock, {"op": "put", "slot": i,
                                   "value": i})["ok"] is True
            send_frame(sock, {"op": "bye"})
    ack_sum = _sum(ack, node=node) - ack0
    phases = {p: _sum(phase, node=node, phase=p)
              for p in ("queue_wait", "stamp", "scatter", "ack_write")}
    counts = {p: sum(s["count"] for s in phase.samples()
                     if s["labels"] == {"node": node, "phase": p})
              for p in ("queue_wait", "stamp", "scatter", "ack_write")}
    # one observation per phase per acked write
    assert counts["queue_wait"] == 20
    assert counts == {p: 20 for p in counts}
    assert phases["stamp"] > 0 and phases["scatter"] > 0
    total = sum(phases.values())
    assert total == pytest.approx(ack_sum, rel=0.10), \
        (phases, ack_sum)


def test_rejected_tick_observes_ack_but_not_phases():
    """A failed tick still acks (with the rejection) but attributes
    nothing — phase sums must only ever cover committed writes."""
    crdt = DenseCrdt("phase-r", n_slots=64)
    node = str(crdt.node_id)
    reg = default_registry()
    phase = reg.histogram("crdt_tpu_serve_ack_phase_seconds")

    def _count(**labels):
        return sum(s["count"] for s in phase.samples()
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    before = _count(node=node)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        # an in-range slot whose value overflows int64 passes the
        # session-side guard shape but np.fromiter(int64) rejects the
        # WHOLE tick
        import crdt_tpu.serve as serve_mod
        orig = serve_mod._value_ok
        serve_mod._value_ok = lambda v: True
        try:
            with _connect(tier) as sock:
                reply = _req(sock, {"op": "put", "slot": 1,
                                    "value": 1 << 80})
                assert reply["ok"] is False
                assert reply["code"] == "write_rejected"
                send_frame(sock, {"op": "bye"})
        finally:
            serve_mod._value_ok = orig
    assert _count(node=node) == before


# --- binary client op lane (docs/WIRE.md) ---

def test_binop_batched_roundtrip_reads_own_frame():
    """One frame of puts + a delete + gets; one reply frame; gets
    observe writes from the SAME batch (read-your-writes extends into
    the frame — gets run after the batch commits)."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        sock, codec = _binop_session(tier.host, tier.port)
        with sock:
            ops = [BINOP_PUT, BINOP_PUT, BINOP_PUT, BINOP_DELETE,
                   BINOP_GET, BINOP_GET]
            slots = [3, 4, 5, 4, 3, 4]
            vals = [30, 40, 50, 0, 0, 0]
            status, values, details = binop_round(
                sock, ops, slots, vals,
                deadline=time.monotonic() + 10.0, codec=codec)
            assert list(status) == [BINOP_ST_OK] * 4 \
                + [BINOP_ST_OK, BINOP_ST_OK_NULL]
            assert values is not None and int(values[4]) == 30
            assert details == []
            send_frame(sock, {"op": "bye"}, None, codec)
    assert crdt.get(3) == 30
    assert crdt.get(4) is None
    assert crdt.get(5) == 50


def test_binop_per_op_error_isolation():
    """A bad slot inside a well-formed frame fails ITS status byte
    with an indexed detail; its batchmates commit and the session
    stays open for the next frame."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        sock, codec = _binop_session(tier.host, tier.port)
        with sock:
            status, values, details = binop_round(
                sock, [BINOP_PUT, BINOP_PUT, BINOP_PUT],
                [1, 9999, 2], [10, 1, 20],
                deadline=time.monotonic() + 10.0, codec=codec)
            assert list(status) == [BINOP_ST_OK, BINOP_ST_REJECTED,
                                    BINOP_ST_OK]
            assert details == [{"i": 1, "code": "write_rejected",
                                "error": "bad slot"}]
            # ...and the next frame on the same session still works.
            status, _, details = binop_round(
                sock, [BINOP_GET], [1], [0],
                deadline=time.monotonic() + 10.0, codec=codec)
            assert list(status) == [BINOP_ST_OK]
            send_frame(sock, {"op": "bye"}, None, codec)
    assert crdt.get(1) == 10
    assert crdt.get(2) == 20


def test_binop_malformed_frame_is_protocol_violation():
    """A structurally bad binop frame (truncated rows) hangs the
    session up — protocol violation, not a per-op error — and the
    tier survives it."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        sock, codec = _binop_session(tier.host, tier.port)
        with sock:
            pieces = encode_binop_request([BINOP_PUT, BINOP_PUT],
                                          [1, 2], [10, 20])
            body = b"".join(bytes(p) for p in pieces)[:-5]
            send_bytes_frame(sock, [body], None, codec)
            assert recv_frame(sock, deadline=time.monotonic() + 10.0,
                              codec=codec) is None
        # the tier is still serving
        with _connect(tier) as sock2:
            assert _req(sock2, {"op": "put", "slot": 7,
                                "value": 70}) == {"ok": True}
            send_frame(sock2, {"op": "bye"})
    assert crdt.get(7) == 70


def test_binop_frame_without_negotiation_hangs_up():
    """A session that never agreed `binop` sending a 0xB1 frame is a
    protocol violation (the server parses it as JSON and fails) —
    byte-compat: pre-binop behavior is fully governed by hello."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            send_bytes_frame(sock, encode_binop_request(
                [BINOP_PUT], [1], [10]))
            assert recv_frame(
                sock, deadline=time.monotonic() + 10.0) is None
        with _connect(tier) as sock2:
            assert _req(sock2, {"op": "put", "slot": 1,
                                "value": 11}) == {"ok": True}
            send_frame(sock2, {"op": "bye"})
    assert crdt.get(1) == 11


def test_binop_wire_compat_new_client_pre_binop_server():
    """A new client offering `binop` against a pre-binop server: the
    cap is simply not agreed and the session speaks today's JSON
    dialect byte-identically."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        # Simulate the pre-binop server generation: same caps surface
        # minus the new lane.
        orig = ServeTier._caps
        ServeTier._caps = lambda self: orig(self) - {"binop"}
        try:
            with _connect(tier) as sock:
                reply = _req(sock, {"op": "hello", "proto": 1,
                                    "caps": ["binop", "packed"]})
                assert reply["ok"] is True
                assert "binop" not in reply["caps"]
                assert "packed" in reply["caps"]
                codec = FrameCodec(compress=False)
                assert _req(sock, {"op": "put", "slot": 2,
                                   "value": 22},
                            codec) == {"ok": True}
                assert _req(sock, {"op": "get", "slot": 2}, codec) \
                    == {"ok": True, "value": 22}
                send_frame(sock, {"op": "bye"}, None, codec)
        finally:
            ServeTier._caps = orig
    assert crdt.get(2) == 22


def test_binop_moved_and_stale_epoch_redirects():
    """Foreign slots in a binop frame answer MOVED (detail carries the
    owner + epoch), local ops in the same frame commit; a stale frame
    epoch refuses the whole batch with MOVED, same taxonomy as the
    JSON lane."""
    with FederatedTier(256, partitions=2,
                       flush_interval=0.002) as fed:
        tier = fed.tiers[0]
        own = next(s for s in range(256)
                   if fed.table.owner_of(s) == tier.router.addr)
        foreign = next(s for s in range(256)
                       if fed.table.owner_of(s) != tier.router.addr)
        sock, codec = _binop_session(tier.host, tier.port,
                                     extra_caps=["federation"])
        with sock:
            status, _, details = binop_round(
                sock, [BINOP_PUT, BINOP_PUT], [own, foreign],
                [5, 6], epoch=fed.table.epoch,
                deadline=time.monotonic() + 10.0, codec=codec)
            assert status[0] == BINOP_ST_OK
            assert status[1] == BINOP_ST_MOVED
            moved = [d for d in details if d.get("i") == 1]
            assert moved and moved[0]["code"] == "moved"
            assert moved[0]["owner"] != tier.router.addr
            # stale epoch: the WHOLE frame is refused
            status, _, details = binop_round(
                sock, [BINOP_PUT], [own], [7],
                epoch=fed.table.epoch + 1,
                deadline=time.monotonic() + 10.0, codec=codec)
            assert status[0] == BINOP_ST_MOVED
            send_frame(sock, {"op": "bye"}, None, codec)
        with tier.lock:
            assert tier.crdt.get(own) == 5


# --- fault injection on the client wire ---

def test_fault_mid_hello_truncate_tier_survives():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        sched = ScriptedSchedule([{"kind": "truncate", "after": 6}])
        with FaultProxy(tier.host, tier.port,
                        schedule=sched) as proxy:
            sock = socket.create_connection((proxy.host, proxy.port),
                                            timeout=10.0)
            sock.settimeout(10.0)
            with sock:
                send_frame(sock, {"op": "hello", "proto": 1,
                                  "caps": ["binop"]})
                assert recv_frame(
                    sock, deadline=time.monotonic() + 10.0) is None
            assert proxy.counters.get("truncate", 0) == 1
        # the tier took a half-hello and kept serving
        with _connect(tier) as sock2:
            assert _req(sock2, {"op": "put", "slot": 3,
                                "value": 33}) == {"ok": True}
            send_frame(sock2, {"op": "bye"})
    assert crdt.get(3) == 33


def test_fault_mid_batch_truncate_tier_survives():
    """The cut lands INSIDE a binop batch frame (after a clean hello):
    the client sees a dead socket, the tier sees a partial frame and
    drops the session — and keeps serving everyone else."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        hello = {"op": "hello", "proto": 1, "caps": ["binop"]}
        hello_bytes = 4 + len(json.dumps(hello).encode())
        sched = ScriptedSchedule(
            [{"kind": "truncate", "after": hello_bytes + 9}])
        with FaultProxy(tier.host, tier.port,
                        schedule=sched) as proxy:
            sock = socket.create_connection((proxy.host, proxy.port),
                                            timeout=10.0)
            sock.settimeout(10.0)
            with sock:
                reply = _req(sock, hello)
                assert reply["ok"] and "binop" in reply["caps"]
                codec = FrameCodec(compress=False)
                with pytest.raises(SyncTransportError):
                    binop_round(sock,
                                [BINOP_PUT] * 4, [1, 2, 3, 4],
                                [10, 20, 30, 40],
                                deadline=time.monotonic() + 10.0,
                                codec=codec)
            assert proxy.counters.get("truncate", 0) == 1
        with _connect(tier) as sock2:
            assert _req(sock2, {"op": "put", "slot": 9,
                                "value": 90}) == {"ok": True}
            send_frame(sock2, {"op": "bye"})
    assert crdt.get(9) == 90
    assert crdt.get(1) is None   # the truncated batch never landed


# --- per-lane observability ---

def test_binop_lane_counters_and_sketches():
    crdt = DenseCrdt("lane-a", n_slots=64)
    node = str(crdt.node_id)
    reg = default_registry()
    ops = reg.counter("crdt_tpu_serve_ops_total")
    lane_sk = reg.sketch("crdt_tpu_serve_ack_lane_seconds_sketch")
    with ServeTier(crdt, flush_interval=0.002) as tier:
        with _connect(tier) as jsock:
            assert _req(jsock, {"op": "put", "slot": 1,
                                "value": 1}) == {"ok": True}
            send_frame(jsock, {"op": "bye"})
        bsock, codec = _binop_session(tier.host, tier.port)
        with bsock:
            status, _, _ = binop_round(
                bsock, [BINOP_PUT, BINOP_DELETE, BINOP_GET],
                [2, 3, 2], [20, 0, 0],
                deadline=time.monotonic() + 10.0, codec=codec)
            assert list(status)[:2] == [BINOP_ST_OK, BINOP_ST_OK]
            send_frame(bsock, {"op": "bye"}, None, codec)
    assert ops.value(op="put", lane="json", node=node) == 1
    assert ops.value(op="put", lane="bin", node=node) == 1
    assert ops.value(op="delete", lane="bin", node=node) == 1
    assert ops.value(op="get", lane="bin", node=node) == 1
    assert lane_sk.quantile(0.99, lane="json", node=node) is not None
    assert lane_sk.quantile(0.99, lane="bin", node=node) is not None


def test_binop_ack_phases_include_decode_and_reconstruct():
    """The binary lane adds a `decode` phase (frame decode +
    admission) and the phase sums still reconstruct the ack sum
    within 10% — the PR 11 property, extended."""
    crdt = DenseCrdt("binphase-a", n_slots=64)
    node = str(crdt.node_id)
    reg = default_registry()
    ack = reg.histogram("crdt_tpu_serve_ack_seconds")
    phase = reg.histogram("crdt_tpu_serve_ack_phase_seconds")

    def _sum(h, **labels):
        return sum(s["sum"] for s in h.samples()
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    names = ("decode", "queue_wait", "stamp", "scatter", "ack_write")
    ack0 = _sum(ack, node=node)
    frames = 10
    with ServeTier(crdt, flush_interval=0.002) as tier:
        sock, codec = _binop_session(tier.host, tier.port)
        with sock:
            for i in range(frames):
                status, _, _ = binop_round(
                    sock, [BINOP_PUT] * 4,
                    [4 * i % 64, (4 * i + 1) % 64,
                     (4 * i + 2) % 64, (4 * i + 3) % 64],
                    [i, i, i, i],
                    deadline=time.monotonic() + 10.0, codec=codec)
                assert list(status) == [BINOP_ST_OK] * 4
            send_frame(sock, {"op": "bye"}, None, codec)
    ack_sum = _sum(ack, node=node) - ack0
    counts = {p: sum(s["count"] for s in phase.samples()
                     if s["labels"] == {"node": node, "phase": p})
              for p in names}
    # one observation per phase per acked FRAME (the batch is the
    # client-visible ack unit)
    assert counts == {p: frames for p in names}
    total = sum(_sum(phase, node=node, phase=p) for p in names)
    assert total == pytest.approx(ack_sum, rel=0.10), \
        (counts, total, ack_sum)


# --- SO_REUSEPORT multi-loop serving ---

def test_multi_loop_acks_and_single_tick_invariant():
    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("platform has no SO_REUSEPORT")
    crdt = DenseCrdt("ml-a", n_slots=256)
    node = str(crdt.node_id)
    reg = default_registry()
    flushes = reg.counter("crdt_tpu_ingest_flush_total")
    before = flushes.value(trigger="tick", node=node)
    loops_g = reg.gauge("crdt_tpu_serve_loops")
    with ServeTier(crdt, flush_interval=0.05, loops=2) as tier:
        assert tier.loops_effective == 2
        assert loops_g.value(node=node) == 2
        # Many connections: the kernel spreads accepts across both
        # loops, so writes (and their acks) cross the MPSC seam.
        socks = [_connect(tier) for _ in range(12)]
        try:
            for i, s in enumerate(socks):
                send_frame(s, {"op": "put", "slot": i,
                               "value": i * 10})
            for s in socks:
                assert recv_frame(
                    s, deadline=time.monotonic() + 10.0) == {"ok": True}
            # 12 writers across 2 loops, still a handful of combiner
            # ticks — never one flush per write, and the dispatch
            # ledger (runtime-asserted) saw ONE ingest_scatter per
            # tick.
            ticks = flushes.value(trigger="tick", node=node) - before
            assert 1 <= ticks <= 4
        finally:
            for s in socks:
                s.close()
    for i in range(12):
        assert crdt.get(i) == i * 10
    assert tier.dropped_sessions == 0


def test_multi_loop_binop_lane():
    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("platform has no SO_REUSEPORT")
    crdt = DenseCrdt("ml-b", n_slots=256)
    with ServeTier(crdt, flush_interval=0.01, loops=2) as tier:
        sessions = [_binop_session(tier.host, tier.port)
                    for _ in range(6)]
        try:
            for k, (sock, codec) in enumerate(sessions):
                status, _, _ = binop_round(
                    sock, [BINOP_PUT] * 4,
                    [4 * k, 4 * k + 1, 4 * k + 2, 4 * k + 3],
                    [k, k, k, k],
                    deadline=time.monotonic() + 10.0, codec=codec)
                assert list(status) == [BINOP_ST_OK] * 4
        finally:
            for sock, _ in sessions:
                sock.close()
    for k in range(6):
        for j in range(4):
            assert crdt.get(4 * k + j) == k


def test_reuseport_less_platform_falls_back_counted(monkeypatch):
    """No SO_REUSEPORT -> ONE loop, and the loop gauge says so (no
    silent downscale)."""
    monkeypatch.delattr(socket, "SO_REUSEPORT", raising=False)
    crdt = DenseCrdt("fb-a", n_slots=64)
    node = str(crdt.node_id)
    loops_g = default_registry().gauge("crdt_tpu_serve_loops")
    with ServeTier(crdt, loops=4) as tier:
        assert tier.loops_effective == 1
        assert loops_g.value(node=node) == 1
        with _connect(tier) as sock:
            assert _req(sock, {"op": "put", "slot": 1,
                               "value": 5}) == {"ok": True}
            send_frame(sock, {"op": "bye"})
    assert crdt.get(1) == 5

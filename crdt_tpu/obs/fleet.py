"""Fleet poller: N replicas' ``metrics`` ops → lag matrix + SLO verdict.

``python -m crdt_tpu.obs fleet --peers a=h:p,b=h:p [--once]`` scrapes
the existing ``metrics`` wire op (no new wire surface) across the
fleet and derives what no single replica can know:

- **Lag matrix** — per-(origin, observer) end-to-end replication lag
  from the canary beats (`crdt_tpu.obs.probe`):
  ``lag_s[origin][observer] = (newest_beat(origin) −
  observed(observer)[origin]) / 1000``. ``None`` marks a pair where
  the observer has never seen that origin's canary; ``complete`` is
  True only when every (origin, observer) pair has a value.
- **SLO verdict** — a machine-readable pass/fail over four budgets:
  serve ack p99 (`crdt_tpu_serve_ack_seconds`), worst convergence lag
  (the matrix), shed writes (`crdt_tpu_serve_shed_total` == 0), and
  replica-group primary liveness (every group visible in any
  snapshot's ``replication`` section must have a reachable member
  claiming ``role == "primary"`` — a partition with no live primary
  is DOWN for writes no matter how healthy its followers look;
  docs/REPLICATION.md). Each check is ``{"value", "budget", "ok"}``
  with ``ok=None`` when the fleet exposes no data for it (not
  measured ≠ passed ≠ failed); the top-level ``ok`` requires every
  *measured* check to pass. Bench modes emit this verdict as a
  trailing JSON line; CI gates on it.
- **Replica health** — per-group role/lease/head roll-up from the
  ``replication`` sections (`replica_health`), rendered as a table in
  the default output and as ``crdt_tpu_fleet_replica_primary`` in
  the federation exposition.
- **Federation output** — an aggregated Prometheus exposition of the
  fleet-level series (matrix, beats, per-instance SLO inputs), each
  labelled by ``instance`` so same-named per-replica series can't
  collide.

Everything below `poll_fleet` is pure (dicts in, dicts/strings out),
so bench's in-process soaks feed `lag_matrix`/`evaluate_slo` directly
from replica snapshots without sockets.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from .render import _fmt, _labels, _table
from .sketch import QuantileSketch, merge_sketches, sketch_from_sample

# Default budgets: the serve ack budget SERVE_r01 was judged against
# (p99 <= 4.25 ms), a convergence budget loose enough for WAN gossip
# but tight enough to catch a wedged peer, and a topology-change
# progress budget — a split/merge whose progress stamp stalls longer
# than this holds the federation's control lock and has frozen the
# scale loop (federation.py wedge gauges).
ACK_P99_BUDGET_S = 0.00425
CONVERGENCE_BUDGET_S = 5.0
TOPOLOGY_STALL_BUDGET_S = 30.0

# The measured SERVE_r01 steady-state ack envelope (ROADMAP item 1):
# p99 <= 14.6 ms under scatter pressure. Unexpressible as a log2
# histogram gate (the nearest bucket ceilings are 7.8 ms and 15.6 ms,
# the nearest usable *stable* boundary 31.3 ms) — this is the budget
# the sketch-backed autoscaler probe gates on (autoscale.py).
SERVE_ACK_ENVELOPE_S = 0.0146

# Instrument names the ack SLO check reads: the log2 histogram (bucket
# ceilings; every fleet exposes it) and its sketch twin (relative-
# error quantiles; fleets behind the `sketch` hello cap).
ACK_HIST_NAME = "crdt_tpu_serve_ack_seconds"
ACK_SKETCH_NAME = "crdt_tpu_serve_ack_seconds_sketch"


def parse_peers(spec: str) -> List[Tuple[str, str, int]]:
    """``"a=host:1234,b=host:1235"`` (or bare ``host:port``) →
    ``[(name, host, port), ...]``."""
    peers = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, addr = part.rpartition("=")
        host, _, port = addr.rpartition(":")
        if not host or not port:
            raise ValueError(f"peer {part!r} is not [name=]host:port")
        peers.append((name or addr, host, int(port)))
    return peers


def poll_fleet(peers: List[Tuple[str, str, int]],
               timeout: float = 5.0) -> Dict[str, dict]:
    """Scrape each peer's ``metrics`` op. Unreachable peers map to
    ``{"_scrape_error": "..."}`` — the matrix and verdict treat them
    as observers that saw nothing."""
    # Lazy: obs stays importable below net (cli.py contract).
    from ..net import SyncError, fetch_metrics
    out: Dict[str, dict] = {}
    for name, host, port in peers:
        try:
            out[name] = fetch_metrics(host, port, timeout=timeout)
        except (SyncError, OSError) as exc:
            out[name] = {"_scrape_error":
                         f"{type(exc).__name__}: {exc}"}
    return out


def _okey(origin: str):
    return (0, int(origin)) if origin.isdigit() else (1, origin)


def lag_matrix(snapshots: Dict[str, dict]) -> Dict[str, Any]:
    """Per-(origin, observer) replication lag from the ``canary``
    sections of scraped (or in-process) metrics snapshots. Pure."""
    canaries: Dict[str, dict] = {}
    for name, snap in snapshots.items():
        if not isinstance(snap, dict):
            continue
        can = snap.get("canary")
        if isinstance(can, dict) and isinstance(can.get("observed"),
                                                dict):
            canaries[name] = can
    observers = sorted(canaries)
    origin_peers: Dict[str, str] = {}
    newest: Dict[str, int] = {}
    for name, can in canaries.items():
        if can.get("origin") is not None:
            origin_peers[str(can["origin"])] = name
        for o, v in can["observed"].items():
            o = str(o)
            if v is not None and (o not in newest
                                  or int(v) > newest[o]):
                newest[o] = int(v)
    origins = sorted(newest, key=_okey)
    lag: Dict[str, Dict[str, Optional[float]]] = {}
    complete = bool(origins) and bool(observers)
    worst: Optional[float] = None
    for o in origins:
        row: Dict[str, Optional[float]] = {}
        for w in observers:
            v = canaries[w]["observed"].get(o)
            if v is None:
                row[w] = None
                complete = False
            else:
                row[w] = max(0.0, (newest[o] - int(v)) / 1000.0)
                worst = (row[w] if worst is None
                         else max(worst, row[w]))
        lag[o] = row
    return {"origins": origins, "observers": observers,
            "origin_peers": origin_peers, "lag_s": lag,
            "complete": complete, "max_lag_s": worst}


def histogram_quantile(sample: Dict[str, Any], q: float
                       ) -> Optional[float]:
    """Upper-bound quantile estimate from one log2-bucket histogram
    sample (the `Histogram.samples()` shape): the smallest bucket
    bound whose cumulative count reaches ``q``; ``inf`` when the
    quantile lands in the overflow bucket; ``None`` when empty."""
    count = sample.get("count", 0)
    if not count:
        return None
    target = q * count
    cum = 0
    for bound, n in sample.get("buckets", []):
        cum += n
        if cum >= target:
            return float(bound)
    return math.inf


def instance_sketch(snap: dict, name: str = ACK_SKETCH_NAME
                    ) -> Optional[QuantileSketch]:
    """One instance's sketch series (all label sets merged) from its
    metrics snapshot; ``None`` when the snapshot predates the sketch
    cap or carries no observations. Pure."""
    if not isinstance(snap, dict):
        return None
    samples = snap.get("sketches", {}).get(name, [])
    merged = merge_sketches(
        sk for sk in (sketch_from_sample(s) for s in samples)
        if sk is not None and sk.count > 0)
    return merged


def fleet_sketch(snapshots: Dict[str, dict],
                 name: str = ACK_SKETCH_NAME
                 ) -> Optional[QuantileSketch]:
    """Fleet-true quantile sketch: every replica's series merged into
    one. The merge is the sketch's CRDT join — commutative and
    associative with the relative-error bound preserved — so the
    result's p99 is the p99 of the *union* of all replicas' samples,
    not a max-of-ceilings. ``None`` when no replica ships sketch data
    (pre-sketch fleet). Pure."""
    return merge_sketches(
        sk for sk in (instance_sketch(snap, name)
                      for snap in snapshots.values())
        if sk is not None)


def replica_health(snapshots: Dict[str, dict]) -> Dict[str, Any]:
    """Per-group replica roll-up from the ``replication`` sections of
    scraped (or in-process) metrics snapshots: ``groups`` maps group
    → instance → {role, lease_ms, hlc_head[, followers]}, and
    ``groups_without_primary`` lists every group no reachable member
    claims to lead. A killed primary scrapes as ``_scrape_error`` and
    so cannot claim its group — the group shows up here through its
    followers and counts as primaryless until the monitor promotes
    one. Pure."""
    groups: Dict[str, Dict[str, dict]] = {}
    for name, snap in snapshots.items():
        if not isinstance(snap, dict):
            continue
        rep = snap.get("replication")
        if not isinstance(rep, dict):
            continue
        entry = {"role": rep.get("role"),
                 "lease_ms": rep.get("lease_ms"),
                 "hlc_head": rep.get("hlc_head")}
        if isinstance(rep.get("followers"), dict):
            entry["followers"] = rep["followers"]
        groups.setdefault(str(rep.get("group")), {})[name] = entry
    missing = sorted(g for g, members in groups.items()
                     if not any(m.get("role") == "primary"
                                for m in members.values()))
    return {"groups": groups, "groups_without_primary": missing}


def _check(value: Optional[float], budget: float,
           ok: Optional[bool] = None) -> Dict[str, Any]:
    if ok is None:
        ok = None if value is None else bool(value <= budget)
    return {"value": value, "budget": budget, "ok": ok}


def _gauge_max(snap: dict, name: str) -> Optional[float]:
    vals = [s["value"] for s in snap.get("gauges", {}).get(name, [])
            if s.get("value") is not None]
    return max(vals) if vals else None


def topology_stall_s(snapshots: Dict[str, dict],
                     now_ms: Optional[float] = None
                     ) -> Optional[float]:
    """Seconds since the in-flight topology change last made progress,
    0.0 when no change is in flight, None when no snapshot exposes the
    wedge gauges (pre-elastic fleets). A change is "in flight" when
    any snapshot's ``crdt_tpu_topology_change_inflight_since_ms`` is
    non-zero; staleness is measured against the matching
    ``..._progress_ms`` stamp. Pure given ``now_ms``."""
    if now_ms is None:
        from ..hlc import wall_clock_millis
        now_ms = float(wall_clock_millis())
    seen = False
    worst: Optional[float] = None
    for snap in snapshots.values():
        if not isinstance(snap, dict):
            continue
        inflight = _gauge_max(
            snap, "crdt_tpu_topology_change_inflight_since_ms")
        if inflight is None:
            continue
        seen = True
        if inflight <= 0:
            continue
        progress = _gauge_max(
            snap, "crdt_tpu_topology_change_progress_ms") or inflight
        stall = max(0.0, (now_ms - progress) / 1000.0)
        worst = stall if worst is None else max(worst, stall)
    if not seen:
        return None
    return worst if worst is not None else 0.0


def evaluate_slo(snapshots: Dict[str, dict],
                 matrix: Optional[Dict[str, Any]] = None, *,
                 ack_p99_budget_s: float = ACK_P99_BUDGET_S,
                 convergence_budget_s: float = CONVERGENCE_BUDGET_S,
                 topology_stall_budget_s: float =
                 TOPOLOGY_STALL_BUDGET_S,
                 now_ms: Optional[float] = None
                 ) -> Dict[str, Any]:
    """Machine-readable fleet SLO verdict (see module docstring)."""
    if matrix is None:
        matrix = lag_matrix(snapshots)
    ceiling: Optional[float] = None
    shed: Optional[float] = None
    for snap in snapshots.values():
        if not isinstance(snap, dict):
            continue
        hists = snap.get("histograms", {})
        for s in hists.get(ACK_HIST_NAME, []):
            v = histogram_quantile(s, 0.99)
            if v is not None:
                ceiling = v if ceiling is None else max(ceiling, v)
        ctrs = snap.get("counters", {})
        for s in ctrs.get("crdt_tpu_serve_shed_total", []):
            shed = (shed or 0.0) + s["value"]
    # Ack p99: sketch-true when any replica ships sketch data (the
    # merged fleet sketch's quantile carries a ~1% relative-error
    # bound, so an off-power-of-two budget like the 14.6 ms envelope
    # is a real gate). Pre-sketch fleets fall back to the histogram
    # bucket ceiling, *honestly*: the ceiling only proves a pass when
    # it is itself within budget, only proves a breach when even the
    # bucket's lower edge exceeds budget, and is otherwise unmeasured
    # (ok=None) — unmeasured ≠ passed, and a ceiling 2× the budget is
    # not evidence of a breach.
    fleet_ack = fleet_sketch(snapshots)
    ack_check: Dict[str, Any]
    if fleet_ack is not None:
        ack_check = _check(fleet_ack.quantile(0.99), ack_p99_budget_s)
        ack_check["source"] = "sketch"
    else:
        ack_ok: Optional[bool] = None
        if ceiling is not None:
            # crdtlint: disable=histogram-ceiling-gate -- the one legal ceiling compare: three-valued, pass only when ceiling<=budget, fail only when the bucket FLOOR breaches, else unmeasured
            if ceiling <= ack_p99_budget_s:
                ack_ok = True       # true p99 <= ceiling <= budget
            # crdtlint: disable=histogram-ceiling-gate -- bucket floor (ceiling/2) exceeding budget proves the breach without trusting the quantization
            elif ceiling / 2.0 > ack_p99_budget_s:
                ack_ok = False      # even the bucket floor breaches
        # _check() would re-derive ok from the ceiling; build the
        # dict directly so ok=None survives as "unmeasured".
        ack_check = {"value": ceiling, "budget": ack_p99_budget_s,
                     "ok": ack_ok, "source": "histogram_ceiling"}
    conv = matrix.get("max_lag_s")
    conv_ok: Optional[bool] = None
    if matrix.get("origins"):
        # An incomplete matrix is a failed convergence check even if
        # the seen pairs are fast — an unseen pair IS unbounded lag.
        conv_ok = bool(matrix.get("complete")
                       and conv is not None
                       and conv <= convergence_budget_s)
    health = replica_health(snapshots)
    missing = health["groups_without_primary"]
    # Unmeasured ≠ passed: a fleet with no replication sections gets
    # ok=None here, but a group whose members answer and none of whom
    # is primary is a hard failure — that partition is down for
    # writes regardless of every other number on this page.
    primary_ok: Optional[bool] = (None if not health["groups"]
                                  else not missing)
    checks = {
        "ack_p99_s": ack_check,
        "convergence_lag_s": _check(conv, convergence_budget_s,
                                    ok=conv_ok),
        "shed_writes": _check(shed, 0.0),
        "groups_without_primary": _check(
            float(len(missing)) if health["groups"] else None, 0.0,
            ok=primary_ok),
        # A wedged in-flight topology change is a hard failure: the
        # stalled split/merge holds the federation's control lock, so
        # promotions queue behind it and the autoscaler is frozen —
        # the fleet cannot react to anything until it clears.
        "topology_change_stall_s": _check(
            topology_stall_s(snapshots, now_ms=now_ms),
            topology_stall_budget_s),
    }
    measured = [c["ok"] for c in checks.values()
                if c["ok"] is not None]
    scrape_errors = sorted(
        name for name, snap in snapshots.items()
        if isinstance(snap, dict) and "_scrape_error" in snap)
    ok = bool(measured) and all(measured) and not scrape_errors
    return {"checks": checks, "matrix_complete":
            bool(matrix.get("complete")),
            "scrape_errors": scrape_errors,
            "replication": health, "ok": ok}


def render_federation(snapshots: Dict[str, dict],
                      matrix: Optional[Dict[str, Any]] = None) -> str:
    """Aggregated Prometheus exposition of the fleet-level series;
    every series carries an ``instance`` (or origin/observer) label so
    same-named per-replica series cannot collide."""
    if matrix is None:
        matrix = lag_matrix(snapshots)
    lines: List[str] = []
    lines.append("# TYPE crdt_tpu_fleet_up gauge")
    for name, snap in sorted(snapshots.items()):
        up = int(isinstance(snap, dict)
                 and "_scrape_error" not in snap)
        lines.append(f"crdt_tpu_fleet_up"
                     f"{_labels({'instance': name})} {up}")
    if matrix["origins"]:
        lines.append("# TYPE crdt_tpu_canary_lag_seconds gauge")
        for o in matrix["origins"]:
            for w, v in sorted(matrix["lag_s"][o].items()):
                if v is None:
                    continue
                lines.append(
                    f"crdt_tpu_canary_lag_seconds"
                    f"{_labels({'origin': o, 'observer': w})} "
                    f"{_fmt(v)}")
    emitted_type = False
    for name, snap in sorted(snapshots.items()):
        if not isinstance(snap, dict):
            continue
        for s in snap.get("histograms", {}).get(
                "crdt_tpu_serve_ack_seconds", []):
            v = histogram_quantile(s, 0.99)
            if v is None or math.isinf(v):
                continue
            if not emitted_type:
                lines.append(
                    "# TYPE crdt_tpu_fleet_ack_p99_seconds gauge")
                emitted_type = True
            lines.append(f"crdt_tpu_fleet_ack_p99_seconds"
                         f"{_labels(dict(s['labels'], instance=name))}"
                         f" {_fmt(v)}")
    # Sketch-true ack quantiles: per-instance p99 plus the merged
    # fleet summary. These sit NEXT to the bucket-ceiling gauge above
    # — the two disagreeing (ceiling 31.25 ms, sketch 16 ms) is the
    # signal the log2 family cannot express, made visible.
    emitted_type = False
    for name, snap in sorted(snapshots.items()):
        sk = instance_sketch(snap)
        if sk is None:
            continue
        v = sk.quantile(0.99)
        if v is None:
            continue
        if not emitted_type:
            lines.append(
                "# TYPE crdt_tpu_fleet_ack_p99_sketch_seconds gauge")
            emitted_type = True
        lines.append(f"crdt_tpu_fleet_ack_p99_sketch_seconds"
                     f"{_labels({'instance': name})} {_fmt(v)}")
    fleet_ack = fleet_sketch(snapshots)
    if fleet_ack is not None and fleet_ack.count > 0:
        lines.append("# TYPE crdt_tpu_fleet_ack_seconds summary")
        for q in (0.5, 0.9, 0.99):
            lines.append(
                f"crdt_tpu_fleet_ack_seconds"
                f"{_labels({'quantile': f'{q:g}'})} "
                f"{_fmt(fleet_ack.quantile(q))}")
        lines.append(f"crdt_tpu_fleet_ack_seconds_count "
                     f"{fleet_ack.count}")
        lines.append(f"crdt_tpu_fleet_ack_seconds_sum "
                     f"{_fmt(fleet_ack.sum)}")
    emitted_type = False
    for name, snap in sorted(snapshots.items()):
        if not isinstance(snap, dict):
            continue
        for s in snap.get("counters", {}).get(
                "crdt_tpu_serve_shed_total", []):
            if not emitted_type:
                lines.append(
                    "# TYPE crdt_tpu_fleet_shed_total counter")
                emitted_type = True
            lines.append(f"crdt_tpu_fleet_shed_total"
                         f"{_labels(dict(s['labels'], instance=name))}"
                         f" {_fmt(s['value'])}")
    health = replica_health(snapshots)
    if health["groups"]:
        lines.append("# TYPE crdt_tpu_fleet_replica_primary gauge")
        for g, members in sorted(health["groups"].items()):
            for inst, m in sorted(members.items()):
                lines.append(
                    f"crdt_tpu_fleet_replica_primary"
                    f"{_labels({'group': g, 'instance': inst})} "
                    f"{int(m.get('role') == 'primary')}")
    return "\n".join(lines) + ("\n" if lines else "")


def format_replicas(health: Dict[str, Any]) -> str:
    """Human-readable per-group replica table (role, lease, head);
    empty string when no snapshot carried a ``replication`` section."""
    if not health["groups"]:
        return ""
    headers = ["group", "instance", "role", "lease_ms", "hlc_head"]
    rows = []
    for g, members in sorted(health["groups"].items()):
        for inst, m in sorted(members.items()):
            lease = m.get("lease_ms")
            rows.append([g, inst, str(m.get("role")),
                         "-" if lease is None else f"{lease:.0f}",
                         str(m.get("hlc_head") or "-")])
    text = "\n".join(_table(headers, rows)) + "\n"
    missing = health["groups_without_primary"]
    if missing:
        text += ("NO LIVE PRIMARY: " + ", ".join(missing) + "\n")
    return text


def format_partitions(snapshots: Dict[str, dict]) -> str:
    """Human-readable per-partition table from the ``partition``
    sections of scraped (or in-process) metrics snapshots, ranked by
    committed-row load (rank 1 = hottest) with the last scale action
    each partition took part in — the at-a-glance view of what the
    autoscaler has been doing. Empty string when no snapshot carries
    a partition section. Pure."""
    parts = []
    for name, snap in snapshots.items():
        if isinstance(snap, dict) and isinstance(
                snap.get("partition"), dict):
            parts.append((name, snap["partition"]))
    if not parts:
        return ""
    parts.sort(key=lambda kv: (
        -(kv[1].get("rows_committed") or 0), kv[0]))
    # Both ack p99 estimates side by side: the histogram's bucket
    # ceiling and the sketch's relative-error value. When they
    # disagree (ceiling 31.25 ms vs sketch 16 ms) the gap is the
    # log2 quantization — visible here instead of silent.
    headers = ["rank", "instance", "addr", "epoch", "slots", "rows",
               "queue", "shed", "p99ceil_ms", "p99_ms", "last_scale"]
    rows = []
    for rank, (name, p) in enumerate(parts, 1):
        ls = p.get("last_scale") or {}
        last = str(ls.get("action") or "-")
        if ls.get("epoch") is not None:
            last += f"@e{ls['epoch']}"
        snap = snapshots.get(name)
        ceil = None
        if isinstance(snap, dict):
            for s in snap.get("histograms", {}).get(ACK_HIST_NAME,
                                                    []):
                v = histogram_quantile(s, 0.99)
                if v is not None:
                    ceil = v if ceil is None else max(ceil, v)
        sk = instance_sketch(snap) if isinstance(snap, dict) else None
        true_p99 = sk.quantile(0.99) if sk is not None else None
        rows.append([str(rank), name, str(p.get("addr")),
                     str(p.get("epoch")), str(p.get("slots")),
                     str(p.get("rows_committed")),
                     str(p.get("queue_depth")), str(p.get("shed")),
                     "-" if ceil is None or math.isinf(ceil)
                     else f"{ceil * 1e3:.1f}",
                     "-" if true_p99 is None
                     else f"{true_p99 * 1e3:.1f}",
                     last])
    return "\n".join(_table(headers, rows)) + "\n"


def format_storage(snapshots: Dict[str, dict]) -> str:
    """Human-readable storage-plane table: per-instance stability
    watermark (``PINNED`` when any peer is unmeasured — tombstone GC
    is parked, docs/STORAGE.md), the last purge floor, and the
    live/tombstone split of shipped transfer bytes (migrate + rejoin
    surfaces summed). The split is the payoff metric: post-GC donors
    should ship ~zero tombstone bytes. Empty string when no snapshot
    carries storage-plane data. Pure."""
    rows = []
    for name, snap in sorted(snapshots.items()):
        if not isinstance(snap, dict):
            continue
        st = snap.get("stability")
        ctrs = snap.get("counters", {})
        live = sum(s["value"] for s in ctrs.get(
            "crdt_tpu_shipped_live_bytes_total", []))
        tomb = sum(s["value"] for s in ctrs.get(
            "crdt_tpu_shipped_tombstone_bytes_total", []))
        if not isinstance(st, dict) and not live and not tomb:
            continue
        if isinstance(st, dict):
            mark = ("PINNED" if st.get("pinned")
                    else str(st.get("stability_hlc") or "-"))
            floor = str(st.get("gc_floor") or "-")
        else:
            mark, floor = "-", "-"
        rows.append([name, mark, floor,
                     str(int(live)), str(int(tomb))])
    if not rows:
        return ""
    headers = ["instance", "stability", "gc_floor",
               "shipped_live_B", "shipped_tomb_B"]
    return "\n".join(_table(headers, rows)) + "\n"


def format_matrix(matrix: Dict[str, Any]) -> str:
    """Human-readable (origin × observer) lag table, seconds."""
    if not matrix["origins"]:
        return "no canary data\n"
    headers = ["origin\\observer"] + list(matrix["observers"])
    rows = []
    for o in matrix["origins"]:
        row = [o]
        for w in matrix["observers"]:
            v = matrix["lag_s"][o].get(w)
            row.append("-" if v is None else f"{v:.3f}")
        rows.append(row)
    return "\n".join(_table(headers, rows)) + "\n"


def fleet_main(argv: Optional[List[str]] = None, out=None) -> int:
    """``python -m crdt_tpu.obs fleet`` entry point. Returns the exit
    code CI gates on: 0 iff the SLO verdict is ok (with ``--once``)."""
    import argparse
    import json
    import sys
    import time

    ap = argparse.ArgumentParser(
        prog="python -m crdt_tpu.obs fleet",
        description="scrape a replica fleet into a canary lag matrix "
                    "and SLO verdict")
    ap.add_argument("--peers", required=True,
                    help="comma list of [name=]host:port")
    ap.add_argument("--once", action="store_true",
                    help="poll once and exit (exit 1 on SLO breach)")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--json", action="store_true",
                    help="emit {matrix, slo} JSON per poll")
    ap.add_argument("--prom", action="store_true",
                    help="emit Prometheus federation text per poll")
    ap.add_argument("--ack-budget", type=float,
                    default=ACK_P99_BUDGET_S,
                    help="serve ack p99 budget, seconds")
    ap.add_argument("--lag-budget", type=float,
                    default=CONVERGENCE_BUDGET_S,
                    help="convergence lag budget, seconds")
    args = ap.parse_args(argv)
    out = sys.stdout if out is None else out
    peers = parse_peers(args.peers)

    while True:
        snapshots = poll_fleet(peers, timeout=args.timeout)
        matrix = lag_matrix(snapshots)
        verdict = evaluate_slo(
            snapshots, matrix, ack_p99_budget_s=args.ack_budget,
            convergence_budget_s=args.lag_budget)
        if args.json:
            out.write(json.dumps({"matrix": matrix,
                                  "slo": verdict}) + "\n")
        elif args.prom:
            out.write(render_federation(snapshots, matrix))
        else:
            out.write(format_matrix(matrix))
            out.write(format_replicas(verdict["replication"]))
            out.write(format_partitions(snapshots))
            out.write(format_storage(snapshots))
            out.write(f"slo ok={verdict['ok']} "
                      f"{json.dumps(verdict['checks'])}\n")
        out.flush()
        if args.once:
            return 0 if verdict["ok"] else 1
        time.sleep(args.interval)

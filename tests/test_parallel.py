"""Sharded fan-in over a virtual 8-device CPU mesh.

The sharded path must produce bit-identical store lanes and canonical
clock to the single-device `fanin_step` (crdt_tpu/parallel/fanin.py
docstring contract) for every mesh factorization of 8 devices.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crdt_tpu.ops.dense import (DenseStore,
                                empty_dense_store, fanin_step)
from crdt_tpu.parallel import (make_fanin_mesh,
                               make_multislice_fanin_mesh,
                               make_sharded_fanin, shard_changeset,
                               shard_store, sharded_delta_mask,
                               sharded_max_logical_time)

from test_dense import LOCAL, MILLIS, lt_of, make_changeset

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def random_changeset(rng, r, n, dup_free=True):
    entries = []
    for ri in range(r):
        for k in range(n):
            if rng.random() < 0.5:
                continue
            node = rng.randrange(1, 6) if dup_free else rng.randrange(0, 6)
            entries.append((ri, k,
                            lt_of(MILLIS + rng.randrange(40),
                                  rng.randrange(3)),
                            node, rng.randrange(1000), rng.random() < 0.3))
    return make_changeset(r, n, entries)


@pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2), (8, 1), (1, 8)])
@pytest.mark.parametrize("seed", [0, 1])
def test_sharded_matches_single_device(mesh_shape, seed):
    rng = random.Random(seed)
    r, n = 8, 32
    cs = random_changeset(rng, r, n)
    store = empty_dense_store(n)

    ref_store, ref_res = fanin_step(store, cs, jnp.int64(0),
                                    jnp.int32(LOCAL),
                                    jnp.int64(MILLIS + 10_000))

    mesh = make_fanin_mesh(*mesh_shape)
    step = make_sharded_fanin(mesh)
    sh_store, sh_res = step(shard_store(store, mesh),
                            shard_changeset(cs, mesh),
                            jnp.int64(0), jnp.int32(LOCAL),
                            jnp.int64(MILLIS + 10_000))

    for lane in DenseStore._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref_store, lane)),
            np.asarray(getattr(sh_store, lane)), err_msg=lane)
    assert int(sh_res.new_canonical) == int(ref_res.new_canonical)
    assert int(sh_res.win_count) == int(ref_res.win_count)
    assert not bool(sh_res.any_bad)


@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (4, 2, 1), (2, 1, 4),
                                        (1, 2, 4)])
@pytest.mark.parametrize("seed", [0, 1])
def test_multislice_matches_single_device(mesh_shape, seed):
    # (slice, replica, key) mesh: the fan-in reduces over BOTH replica
    # axes (ICI within a slice, DCN across on real hardware) and must
    # stay bit-identical to the single-device fold.
    rng = random.Random(seed + 50)
    r, n = 8, 32
    cs = random_changeset(rng, r, n)
    store = empty_dense_store(n)

    ref_store, ref_res = fanin_step(store, cs, jnp.int64(0),
                                    jnp.int32(LOCAL),
                                    jnp.int64(MILLIS + 10_000))

    mesh = make_multislice_fanin_mesh(*mesh_shape)
    step = make_sharded_fanin(mesh)
    sh_store, sh_res = step(shard_store(store, mesh),
                            shard_changeset(cs, mesh),
                            jnp.int64(0), jnp.int32(LOCAL),
                            jnp.int64(MILLIS + 10_000))

    for lane in DenseStore._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref_store, lane)),
            np.asarray(getattr(sh_store, lane)), err_msg=lane)
    assert int(sh_res.new_canonical) == int(ref_res.new_canonical)
    assert int(sh_res.win_count) == int(ref_res.win_count)
    assert not bool(sh_res.any_bad)
    assert int(sharded_max_logical_time(mesh)(sh_store)) == \
        int(ref_res.new_canonical)


def test_multislice_stable_tie_across_slice_boundary():
    # Identical (lt, node) records land on different SLICES; the lowest
    # flat replica row must still win (outer-major rank composition).
    mesh = make_multislice_fanin_mesh(2, 2, 2)
    step = make_sharded_fanin(mesh)
    n = 8
    cs = make_changeset(4, n, [
        (3, 0, lt_of(MILLIS), 3, 333, False),   # slice 1, inner row 1
        (1, 0, lt_of(MILLIS), 3, 111, False),   # slice 0, inner row 1
        (2, 0, lt_of(MILLIS), 3, 222, False),   # slice 1, inner row 0
    ])
    store, _ = step(shard_store(empty_dense_store(n), mesh),
                    shard_changeset(cs, mesh),
                    jnp.int64(0), jnp.int32(LOCAL),
                    jnp.int64(MILLIS + 10_000))
    assert int(store.val[0]) == 111


def test_sharded_identical_hlc_stable_tie():
    # Identical (lt, node) on different replica shards: lowest replica
    # index wins, even across the device boundary.
    mesh = make_fanin_mesh(4, 2)
    step = make_sharded_fanin(mesh)
    n = 8
    cs = make_changeset(4, n, [
        (2, 0, lt_of(MILLIS), 3, 222, False),
        (1, 0, lt_of(MILLIS), 3, 111, False),
        (3, 0, lt_of(MILLIS), 3, 333, False),
    ])
    store, _ = step(shard_store(empty_dense_store(n), mesh),
                    shard_changeset(cs, mesh),
                    jnp.int64(0), jnp.int32(LOCAL),
                    jnp.int64(MILLIS + 10_000))
    assert int(store.val[0]) == 111


def test_sharded_guards_fire(recwarn):
    mesh = make_fanin_mesh(2, 4)
    step = make_sharded_fanin(mesh)
    n = 8
    cs = make_changeset(2, n, [
        (1, 5, lt_of(MILLIS), LOCAL, 1, False),  # local ordinal, ahead
    ])
    _, res = step(shard_store(empty_dense_store(n), mesh),
                  shard_changeset(cs, mesh),
                  jnp.int64(0), jnp.int32(LOCAL),
                  jnp.int64(MILLIS + 10_000))
    assert bool(res.any_bad) and bool(res.any_dup) and not bool(res.any_drift)


def test_sharded_delta_and_max_lt():
    mesh = make_fanin_mesh(2, 4)
    step = make_sharded_fanin(mesh)
    n = 8
    cs = make_changeset(2, n, [
        (0, 1, lt_of(MILLIS), 1, 5, False),
        (1, 6, lt_of(MILLIS + 3), 2, 6, False),
    ])
    store, res = step(shard_store(empty_dense_store(n), mesh),
                      shard_changeset(cs, mesh),
                      jnp.int64(0), jnp.int32(LOCAL),
                      jnp.int64(MILLIS + 10_000))
    mask = sharded_delta_mask(mesh)(store, res.new_canonical)
    assert list(np.asarray(mask)) == [False, True, False, False,
                                      False, False, True, False]
    assert int(sharded_max_logical_time(mesh)(store)) == lt_of(MILLIS + 3)

"""Seeded counterexample search for the semilattice laws.

The paper's convergence guarantee reduces to three properties of the
join the merge kernels implement (PAPERS.md, "Certified Mergeable
Replicated Data Types" frames them as checkable artifacts):

    idempotence     join(s, a) twice == once
    commutativity   join(join(s, a), b) == join(join(s, b), a)
    associativity   join over [a ++ b] == join over a, then over b

We check them on the DEVICE kernels, not a model: each
:class:`LawTarget` wraps a registered merge step and a way to combine
deltas, and ``run_laws`` drives randomized record batches through it,
reporting the violating input (seed, lanes, both results) when a law
fails.

Two scoping decisions keep the check honest rather than vacuous:

- **Compared lanes** are (lt, node, val, occupied, tomb) — the CRDT
  state. ``mod_lt``/``mod_node`` stamp local apply time and are
  order-dependent BY DESIGN (stamping is bookkeeping, not lattice
  state), so they are excluded.
- **Event uniqueness**: generated batches derive ``val``
  deterministically from ``(lt, node)``. Two replicas never emit
  different values for the same HLC stamp, so value disagreement under
  reordering is a real law violation, not generator noise. Without
  this, commutativity is unfalsifiable (ties broken either way are
  both "right").

Targets whose batch semantics forbid duplicate slots within one delta
(the scatter-based steps) set ``combine=None`` and are checked for
idempotence + commutativity only — associativity's concatenation
would manufacture exactly the duplicate-slot batches the call contract
excludes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

from .findings import Finding

# Lanes that ARE lattice state; mod_lt/mod_node are stamping
# bookkeeping and order-dependent by design.
_STATE_LANES = ("lt", "node", "val", "occupied", "tomb")

_LOCAL_NODE = 0          # generated events use nodes 1..4: never the
                         # local node, so recv-side self-echo guards
                         # cannot mask a law violation
_WALL = 1 << 30          # far future => drift guard never clamps


@dataclass
class LawTarget:
    """One merge step under law checking.

    ``apply(store, batch) -> store`` runs the kernel. ``fresh()``
    builds an empty store. ``gen(rng) -> batch`` draws one randomized
    delta. ``combine(a, b) -> batch`` concatenates two deltas for the
    associativity check; None skips that law (per-call uniqueness
    contracts). ``extract(store) -> dict[lane, ndarray]`` pulls the
    compared lanes."""

    name: str
    fresh: Callable[[], object]
    gen: Callable[[object], object]
    apply: Callable[[object, object], object]
    extract: Callable[[object], dict]
    combine: Optional[Callable[[object, object], object]] = None
    notes: str = ""


def _stores_equal(a: dict, b: dict) -> bool:
    import numpy as np
    return all(np.array_equal(np.asarray(a[k]), np.asarray(b[k]))
               for k in _STATE_LANES)


def _diff_detail(a: dict, b: dict, labels: Tuple[str, str]) -> str:
    import numpy as np
    lines: List[str] = []
    for k in _STATE_LANES:
        av, bv = np.asarray(a[k]), np.asarray(b[k])
        if np.array_equal(av, bv):
            continue
        idx = np.argwhere(av != bv)
        lines.append(f"lane '{k}' differs at {len(idx)} slot(s); "
                     f"first at {tuple(int(i) for i in idx[0])}: "
                     f"{labels[0]}={av[tuple(idx[0])]} "
                     f"{labels[1]}={bv[tuple(idx[0])]}")
    return "\n".join(lines)


def _batch_repr(batch: object) -> str:
    import numpy as np
    if isinstance(batch, dict):
        items = batch.items()
    elif hasattr(batch, "__dict__"):
        items = vars(batch).items()
    else:
        return repr(batch)
    lines = []
    for k, v in items:
        arr = np.asarray(v)
        with np.printoptions(threshold=64, linewidth=100):
            lines.append(f"{k} = {arr!r}")
    return "\n".join(lines)


def check_target(target: LawTarget, seed: int) -> List[Finding]:
    """Run all applicable laws on one target with one seed."""
    import numpy as np
    rng = np.random.default_rng(seed)
    findings: List[Finding] = []
    path = f"<law:{target.name}>"
    a = target.gen(rng)
    b = target.gen(rng)

    def fail(law: str, a_res: dict, b_res: dict,
             labels: Tuple[str, str], batches: Sequence) -> None:
        detail = _diff_detail(a_res, b_res, labels)
        detail += "\nviolating input (seed={}):\n".format(seed)
        for i, batch in enumerate(batches):
            detail += f"-- batch {i} --\n{_batch_repr(batch)}\n"
        findings.append(Finding(
            rule=f"law-{law}", path=path, line=0,
            message=f"{law} violated by {target.name} "
                    f"(seed={seed}); counterexample below",
            detail=detail.rstrip()))

    # idempotence: applying the same delta twice is a no-op
    once = target.apply(target.fresh(), a)
    twice = target.apply(once, a)
    e_once, e_twice = target.extract(once), target.extract(twice)
    if not _stores_equal(e_once, e_twice):
        fail("idempotence", e_once, e_twice, ("once", "twice"), [a])

    # commutativity: delta application order must not matter
    ab = target.apply(target.apply(target.fresh(), a), b)
    ba = target.apply(target.apply(target.fresh(), b), a)
    e_ab, e_ba = target.extract(ab), target.extract(ba)
    if not _stores_equal(e_ab, e_ba):
        fail("commutativity", e_ab, e_ba, ("a,b", "b,a"), [a, b])

    # associativity: one combined delta == two sequential deltas
    if target.combine is not None:
        joint = target.apply(target.fresh(), target.combine(a, b))
        e_joint = target.extract(joint)
        if not _stores_equal(e_ab, e_joint):
            fail("associativity", e_ab, e_joint,
                 ("sequential", "combined"), [a, b])

    return findings


def run_laws(targets: Sequence[LawTarget],
             seeds: Sequence[int] = (0, 1, 2)) -> List[Finding]:
    findings: List[Finding] = []
    for target in targets:
        for seed in seeds:
            hits = check_target(target, seed)
            findings.extend(hits)
            if hits:
                break   # one counterexample per target is enough
    return findings


# --- builtin targets over the registered kernels ---

_N = 64          # store width for law batches
_R = 8           # rows per delta


def _event_lanes(rng, size) -> tuple:
    """(lt, node, val, tomb) with the event-uniqueness invariant: val
    and tomb are deterministic functions of (lt, node), so identical
    stamps can never carry different payloads — otherwise ties broken
    either way are both 'right' and commutativity is unfalsifiable."""
    import numpy as np
    millis = rng.integers(1, 1 << 20, size=size)
    counter = rng.integers(0, 4, size=size)
    lt = ((millis << 16) | counter).astype(np.int64)
    node = rng.integers(1, 5, size=size).astype(np.int32)  # != local 0
    val = ((lt * 31 + node * 7) & 0x7FFF).astype(np.int64)
    tomb = ((lt ^ node) & 1).astype(bool)
    return lt, node, val, tomb


def _gen_sparse(rng, n: int, rows: int) -> dict:
    import numpy as np
    lt, node, val, tomb = _event_lanes(rng, rows)
    return {"slot": rng.integers(0, n, size=rows).astype(np.int64),
            "lt": lt, "node": node, "val": val, "tomb": tomb,
            "valid": np.ones(rows, dtype=bool)}


def _gen_dense(rng, n: int) -> dict:
    """Full-width wire delta (one lane value per slot, valid mask)."""
    import numpy as np
    lt, node, val, tomb = _event_lanes(rng, n)
    valid = rng.integers(0, 2, size=n).astype(bool)
    return {"lt": np.where(valid, lt, 0),
            "node": np.where(valid, node, 0).astype(np.int32),
            "val": np.where(valid, val, 0),
            "tomb": valid & tomb, "valid": valid}


def _extract_store(store) -> dict:
    import numpy as np
    return {k: np.asarray(getattr(store, k)) for k in _STATE_LANES}


def make_wire_join_target(step: Callable, name: str,
                          notes: str = "") -> LawTarget:
    """LawTarget over a wire_join_step-shaped callable
    ``step(store, lt, node, val, tomb, valid, stamp_lt, local_node)``.
    Public so the broken-merge fixture (and future kernels) reuse the
    harness instead of reimplementing it."""
    from ..ops.dense import empty_dense_store

    def fresh():
        return empty_dense_store(_N)

    def gen(rng):
        return _gen_dense(rng, _N)

    def apply(store, batch):
        import numpy as np
        new_store, _win = step(
            store, batch["lt"], batch["node"], batch["val"],
            batch["tomb"], batch["valid"],
            np.int64(_WALL << 16), np.int32(_LOCAL_NODE))
        return new_store

    def combine(a, b):
        # elementwise lattice max of two wire deltas: per slot keep
        # the (lt, node)-lex greater valid event (equal stamps carry
        # equal payloads by the uniqueness invariant, so >= is safe)
        import numpy as np
        a_newer = ((a["lt"] > b["lt"])
                   | ((a["lt"] == b["lt"]) & (a["node"] >= b["node"])))
        a_wins = a["valid"] & (~b["valid"] | a_newer)
        out = {}
        for k in ("lt", "node", "val", "tomb", "valid"):
            out[k] = np.where(a_wins, a[k], b[k])
        out["valid"] = a["valid"] | b["valid"]
        return out

    return LawTarget(name=name, fresh=fresh, gen=gen, apply=apply,
                     extract=_extract_store, combine=combine,
                     notes=notes)


def builtin_targets() -> List[LawTarget]:
    """Law targets over the registered merge kernels. Imports jax-side
    modules lazily so the host linter can run without jax."""
    import numpy as np
    from ..ops import dense as dense_ops

    targets: List[LawTarget] = [
        make_wire_join_target(
            dense_ops.wire_join_step, "dense.wire_join_step",
            notes="elementwise full-width join; all three laws"),
    ]

    # sparse_fanin_step: scatter-based; call contract requires unique
    # slots per delta => no associativity (concatenation would
    # manufacture exactly the duplicate-slot batches the contract
    # excludes).
    def sparse_fresh():
        return dense_ops.empty_dense_store(_N)

    def sparse_gen(rng):
        lanes = _gen_sparse(rng, _N, _R)
        # unique slots per delta (dict-keyed deltas guarantee it in
        # production); keep the first occurrence of each slot
        _, first = np.unique(lanes["slot"], return_index=True)
        keep = np.zeros(_R, dtype=bool)
        keep[first] = True
        lanes["valid"] = lanes["valid"] & keep
        return lanes

    def sparse_apply(store, batch):
        new_store, _win = dense_ops.sparse_fanin_step(
            store, batch["slot"], batch["lt"], batch["node"],
            batch["val"], batch["tomb"], batch["valid"],
            np.int64(_WALL << 16), np.int32(_LOCAL_NODE))
        return new_store

    targets.append(LawTarget(
        name="dense.sparse_fanin_step", fresh=sparse_fresh,
        gen=sparse_gen, apply=sparse_apply, extract=_extract_store,
        combine=None,
        notes="unique-slot contract: idempotence + commutativity "
              "only"))

    # fanin_step: R-row masked fold into the store; rows may collide,
    # the fold resolves them — all three laws, combine = row concat.
    def fanin_fresh():
        return dense_ops.empty_dense_store(_N)

    def fanin_gen(rng):
        lanes = _gen_sparse(rng, _N, _R)
        return dense_ops.DenseChangeset(
            lt=_rows_to_grid(lanes, "lt", np.int64),
            node=_rows_to_grid(lanes, "node", np.int32),
            val=_rows_to_grid(lanes, "val", np.int64),
            tomb=_rows_to_grid(lanes, "tomb", bool),
            valid=_rows_to_grid(lanes, "valid", bool))

    def fanin_apply(store, cs):
        new_store, _res = dense_ops.fanin_step(
            store, cs, canonical_lt=np.int64(0),
            local_node=np.int32(_LOCAL_NODE),
            wall_millis=np.int64(_WALL))
        return new_store

    def fanin_combine(a, b):
        import numpy as np
        return dense_ops.DenseChangeset(
            lt=np.concatenate([np.asarray(a.lt), np.asarray(b.lt)]),
            node=np.concatenate([np.asarray(a.node),
                                 np.asarray(b.node)]),
            val=np.concatenate([np.asarray(a.val), np.asarray(b.val)]),
            tomb=np.concatenate([np.asarray(a.tomb),
                                 np.asarray(b.tomb)]),
            valid=np.concatenate([np.asarray(a.valid),
                                  np.asarray(b.valid)]))

    targets.append(LawTarget(
        name="dense.fanin_step", fresh=fanin_fresh, gen=fanin_gen,
        apply=fanin_apply, extract=_extract_store,
        combine=fanin_combine,
        notes="R-row masked fold; all three laws, combine=row "
              "concatenation"))

    # The pod-local collective join, driven as a 2-member group where
    # one member plays the store and the other the incoming delta; the
    # laws read member 0's joined lanes. Needs >= 2 devices
    # (tests/conftest.py and the CLI force 8 virtual CPU devices);
    # skipped otherwise — the pairwise kernels above cover the same
    # join rules on one device, and the collective≡wire property test
    # pins the two paths bit-identical.
    import jax
    if len(jax.devices()) >= 2:
        try:
            from ..parallel import collective as _pc
        except ImportError:
            _pc = None
        if _pc is not None:
            from ..ops.dense import DenseStore

            coll_mesh = _pc.make_collective_mesh(2)
            coll_step = _pc.make_collective_join(coll_mesh, False, 8)

            def coll_fresh():
                return dense_ops.empty_dense_store(_N)

            def coll_gen(rng):
                return _gen_dense(rng, _N)

            def coll_apply(store, batch):
                other = DenseStore(
                    lt=batch["lt"], node=batch["node"],
                    val=batch["val"],
                    mod_lt=np.zeros(_N, np.int64),
                    mod_node=np.zeros(_N, np.int32),
                    occupied=batch["valid"], tomb=batch["tomb"])
                stacked, _res = coll_step(
                    (store, other), np.zeros(2, np.int64),
                    np.asarray([0, 1], np.int32), np.int64(0))
                return jax.tree_util.tree_map(lambda a: a[0], stacked)

            def coll_combine(a, b):
                # same elementwise lex-max of two wire deltas as
                # make_wire_join_target: a member store IS a
                # full-width delta to the group
                a_newer = ((a["lt"] > b["lt"])
                           | ((a["lt"] == b["lt"])
                              & (a["node"] >= b["node"])))
                a_wins = a["valid"] & (~b["valid"] | a_newer)
                out = {}
                for k in ("lt", "node", "val", "tomb", "valid"):
                    out[k] = np.where(a_wins, a[k], b[k])
                out["valid"] = a["valid"] | b["valid"]
                return out

            targets.append(LawTarget(
                name="parallel.collective_join[member2]",
                fresh=coll_fresh, gen=coll_gen, apply=coll_apply,
                extract=_extract_store, combine=coll_combine,
                notes="group join as all-reduce over a 2-member mesh; "
                      "all three laws on member 0's lanes"))

    # --- storage plane (docs/STORAGE.md) ---
    #
    # dense.gc_purge: the COMPOSITE operator the deployed system runs
    # — floor-masked join (the merge-side resurrection fence, modeled
    # as its stability premise: nothing at or below the floor is
    # still in flight, so sub-floor inbound rows are masked) followed
    # by the purge kernel at the same fixed floor. The fresh store is
    # pre-seeded with sub-floor tombstones AND sub-floor live rows,
    # so the purge genuinely fires (tombs vanish, live rows survive)
    # on every law path. All three laws on the above-floor
    # sublattice.
    _FLOOR = np.int64(1) << 30

    def gc_fresh():
        lt = np.zeros(_N, np.int64)
        node = np.zeros(_N, np.int32)
        val = np.zeros(_N, np.int64)
        occ = np.zeros(_N, bool)
        tomb = np.zeros(_N, bool)
        for i in range(8):
            lt[i] = int(_FLOOR) - 1 - i
            node[i] = np.int32(1 + (i % 4))
            occ[i] = True
            tomb[i] = (i % 2 == 0)
            val[i] = 0 if tomb[i] else 100 + i
        return dense_ops.DenseStore(
            lt=lt, node=node, val=val,
            mod_lt=np.zeros(_N, np.int64),
            mod_node=np.zeros(_N, np.int32),
            occupied=occ, tomb=tomb)

    def gc_apply(store, batch):
        stability_floor = np.int64(_FLOOR)  # fixed modeled watermark
        fenced = np.asarray(batch["valid"]) \
            & (np.asarray(batch["lt"]) > stability_floor)
        joined, _win = dense_ops.wire_join_step(
            store, batch["lt"], batch["node"], batch["val"],
            batch["tomb"], fenced, np.int64(_WALL << 16),
            np.int32(_LOCAL_NODE))
        purged, _count, _mask = dense_ops.gc_purge(
            joined, stability_floor)
        return purged

    _wire = make_wire_join_target(dense_ops.wire_join_step,
                                  "dense.gc_purge")
    targets.append(LawTarget(
        name="dense.gc_purge", fresh=gc_fresh,
        gen=_wire.gen, apply=gc_apply, extract=_extract_store,
        combine=_wire.combine,
        notes="floor-masked join + purge at a fixed stability floor; "
              "all three laws on the above-floor sublattice, purge "
              "fires on the seeded sub-floor tombstones"))

    # dense.compact_remap: join laws preserved under the compaction
    # quotient — extract compares the REMAPPED lanes (full-span
    # compact), so law-equal stores must also compact identically:
    # the remap is a deterministic, slot-order-preserving function of
    # the store, never of the delivery order.
    def compact_extract(store):
        out = dense_ops.compact_remap(
            store, np.asarray([0], np.int64),
            np.asarray([_N], np.int64), None, leaf_width=8)
        new_store, _translation, _live, _levels = out
        return _extract_store(new_store)

    compacted = make_wire_join_target(
        dense_ops.wire_join_step, "dense.compact_remap",
        notes="wire join compared through the compaction quotient: "
              "the remap must be order-independent or replicas that "
              "compact diverge")
    compacted.extract = compact_extract
    targets.append(compacted)

    # The semantics registry contributes one typed wire-join target
    # per registered lane type (crdt_tpu/semantics/types.py) — a new
    # type gets law coverage by registering, zero hand-listed targets.
    from ..semantics import law_targets as _semantics_law_targets
    targets.extend(_semantics_law_targets())

    return targets


def _rows_to_grid(lanes: dict, key: str, dtype):
    """Scatter R sparse rows into an [R, N] one-event-per-row grid —
    the DenseChangeset layout fanin_step folds over."""
    import numpy as np
    rows = len(lanes["slot"])
    grid = np.zeros((rows, _N), dtype=dtype)
    r = np.arange(rows)
    grid[r, lanes["slot"]] = lanes[key] if key != "valid" \
        else lanes["valid"]
    mask = np.zeros((rows, _N), dtype=bool)
    mask[r, lanes["slot"]] = lanes["valid"]
    if key != "valid":
        grid = np.where(mask, grid, np.zeros_like(grid))
    return grid.astype(dtype)

"""Deliberately BROKEN merge kernel — crdtlint self-test fixture.

A max→mean corruption of `ops.dense.wire_join_step`: where the real
kernel ADOPTS the winning remote logical time, this one stores the
AVERAGE of local and remote lt on a win. Averaging is not a lattice
join (it is neither idempotent nor commutative once the LWW compare
reads the damaged lt back), so the law search must find a
counterexample and print the violating input:

    python -m crdt_tpu.analysis --law-fixture tests/fixtures/broken_merge.py

The lt lane (not val) is averaged on purpose: a val-only corruption
would slide under the idempotence check, because the UNDAMAGED lt
lane still blocks re-adoption on the second apply. Averaging lt makes
the store's own compare input wrong, so the breakage is visible to
every law.
"""

import jax
import jax.numpy as jnp

from crdt_tpu.analysis.lattice_laws import make_wire_join_target
from crdt_tpu.ops.dense import DenseStore, _NEG


@jax.jit
def mean_join_step(store: DenseStore, lt, node, val, tomb, valid,
                   stamp_lt, local_node):
    """wire_join_step with the max→mean bug planted."""
    lt = jnp.where(valid, lt, _NEG)
    node = node.astype(jnp.int32)
    val = val.astype(jnp.int64)
    remote_newer = ((lt > store.lt) |
                    ((lt == store.lt) & (node > store.node)))
    win = valid & (~store.occupied | remote_newer)
    # BUG: mean instead of max — not a semilattice join.
    mean_lt = (store.lt + lt) // 2
    new_store = DenseStore(
        lt=jnp.where(win, mean_lt, store.lt),
        node=jnp.where(win, node, store.node),
        val=jnp.where(win, val, store.val),
        mod_lt=jnp.where(win, stamp_lt, store.mod_lt),
        mod_node=jnp.where(win, local_node, store.mod_node),
        occupied=store.occupied | win,
        tomb=jnp.where(win, tomb, store.tomb),
    )
    return new_store, win


LAW_TARGETS = [
    make_wire_join_target(mean_join_step, "broken-mean-join",
                          notes="max→mean planted bug"),
]

"""Resilient gossip runtime: long-running anti-entropy over flaky links.

The reference's replication story assumes a cooperative, always-up
peer — its example mocks the remote with a function returning a JSON
string (example/crdt_example.dart:21-25) — and :func:`sync_over_tcp`
inherits that: one socket error aborts the round and nothing retries.
This module turns the one-shot round into a runtime that keeps
converging through drops, delays, truncations and crashes:

- **Bounded retry** with exponential backoff + FULL jitter on
  transport faults. Rounds are idempotent lattice joins, so replaying
  one is always safe; jitter spreads uncoordinated replicas retrying
  a shared peer instead of synchronizing them into a thundering herd.
- A per-peer **circuit breaker**: open after N consecutive failed
  rounds, half-open probe after a cool-down, close again on success —
  a dead peer costs one probe per reset window, not a retry storm.
- **Pooled sessions**: each peer keeps one `net.PeerConnection` — a
  keep-alive framed session with hello capability negotiation —
  instead of paying a fresh TCP connect (and a fresh zlib
  negotiation) every round. Any round error RESETS the session and
  the normal retry machinery reconnects; `stop()` says ``bye``.
- **Graceful wire-form degradation**: peers aim at the fastest wire
  form the local replica speaks (``packed`` O(k) columnar, then the
  ``dense`` kernel form, then universal JSON) and downgrade (sticky)
  one step the moment the peer rejects an op. Capability selection
  is separate and free: a session whose hello did not advertise
  ``packed`` simply isn't offered it — no rejection round-trip, no
  ``fallbacks`` count, and the peer's aim is retried on reconnect.
- **Pipelined sweeps**: `run_round` overlaps round N+1's device-side
  ``pack_since`` with round N's socket I/O (double-buffered through a
  one-worker executor), so a multi-peer sweep hides pack latency
  behind the wire instead of paying pack→send→recv→merge serially.
- **Durable watermarks** (`checkpoint.save_gossip_state`): the
  per-peer delta watermark survives a crash, so a restarted node
  resumes DELTA sync instead of re-pulling full peer state. (The
  replica contents persist separately — `checkpoint.save_json` /
  `load_json`, or a durable backend like `SqliteCrdt`.)
- **Per-peer counters** (`utils.stats.PeerSyncStats`): rounds,
  retries, fallbacks, pull kinds, bytes, breaker transitions — a
  fault-injection soak can prove its faults actually fired.

Time sources are injectable (``clock``/``sleep``/``rng``) so tests
drive the breaker and backoff deterministically; production uses the
defaults. The fault-injection counterpart lives in
`crdt_tpu.testing_faults` (a TCP proxy that drops, delays, truncates,
corrupts and duplicates on a seeded schedule).
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .analysis.concurrency import make_lock
from .checkpoint import load_gossip_state, save_gossip_state
from .crdt import Crdt
from .hlc import Hlc
from .net import (PeerConnection, SyncProtocolError, SyncServer,
                  SyncTransportError, WireTally, _pack_for_peer,
                  sync_dense_over_conn, sync_merkle_over_conn,
                  sync_over_conn, sync_packed_over_conn)
from .obs.lag import health_status, lag_entry
from .obs.registry import default_registry
from .obs.trace import tracer
from .utils.stats import PeerSyncStats


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff and full jitter:
    ``sleep = uniform(0, min(max_delay, base_delay * 2**attempt))``.
    Full jitter (rather than equal or decorrelated) because gossiping
    replicas share peers — a deterministic backoff ladder would march
    every client of a briefly-down peer back in lockstep."""

    max_attempts: int = 4      # total tries per round, first included
    base_delay: float = 0.05   # seconds; the cap grows base * 2^n
    max_delay: float = 2.0

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        return rng.uniform(0.0, min(self.max_delay,
                                    self.base_delay * (2 ** attempt)))


@dataclass(frozen=True)
class BreakerPolicy:
    failure_threshold: int = 5   # consecutive failed ROUNDS to open
    reset_timeout: float = 30.0  # seconds open before one probe


class CircuitBreaker:
    """CLOSED → (N consecutive round failures) → OPEN →
    (reset_timeout elapses) → HALF_OPEN → one probe round →
    success: CLOSED / failure: OPEN again.

    Failures are counted per ROUND (after the retry budget is spent),
    not per attempt — a peer that needs one retry per round is slow,
    not down, and must not trip the breaker. Transitions are counted
    into the owning peer's :class:`PeerSyncStats` and, when the
    process tracer is enabled, emitted as ``breaker`` trace events."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, policy: BreakerPolicy,
                 clock: Callable[[], float] = time.monotonic,
                 stats: Optional[PeerSyncStats] = None,
                 name: str = ""):
        self.policy = policy
        self._clock = clock
        self._stats = stats
        self.name = name           # owning peer, for trace events
        self.state = self.CLOSED
        self.failures = 0          # consecutive, resets on success
        self._opened_at = 0.0

    def _transition(self, state: str) -> None:
        self.state = state
        ring = tracer()
        if ring.enabled:
            ring.emit("breaker", peer=self.name, state=state,
                      failures=self.failures)

    def allow(self) -> bool:
        """May a round be attempted now? Flips OPEN → HALF_OPEN when
        the cool-down has elapsed (the probe is the caller's round)."""
        if self.state == self.OPEN:
            if self._clock() - self._opened_at \
                    < self.policy.reset_timeout:
                return False
            self._transition(self.HALF_OPEN)
            if self._stats is not None:
                self._stats.breaker_half_open += 1
        return True

    def record_success(self) -> None:
        self.failures = 0
        if self.state != self.CLOSED:
            self._transition(self.CLOSED)
            if self._stats is not None:
                self._stats.breaker_closed += 1

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == self.HALF_OPEN \
                or (self.state == self.CLOSED
                    and self.failures >= self.policy.failure_threshold):
            self._transition(self.OPEN)
            self._opened_at = self._clock()
            if self._stats is not None:
                self._stats.breaker_opened += 1


# Wire modes a peer can aim at, fastest first. Downgrades are sticky
# and one-way: merkle -> packed -> dense -> json. "merkle" is packed
# sync plus digest-tree anti-entropy for rounds with no usable
# watermark (docs/ANTIENTROPY.md) — a cold or long-partitioned peer
# walks divergence in O(log n) probes instead of full-scanning.
_MODES = ("merkle", "packed", "dense", "json")


class Peer:
    """One gossip neighbour: address, pooled session, current wire
    mode, delta watermark, breaker, counters. ``name`` is the durable
    identity the watermark persists under — keep it stable across
    restarts."""

    def __init__(self, name: str, host: str, port: int, *,
                 mode: str,
                 breaker: CircuitBreaker,
                 stats: PeerSyncStats,
                 watermark: Optional[Hlc] = None,
                 timeout: float = 30.0,
                 collective: bool = False):
        if mode not in _MODES:
            raise ValueError(f"unknown wire mode {mode!r}")
        self.name = name
        self.host = host
        self.port = port
        self.mode = mode              # sticky: downgraded on rejection
        self.conn = PeerConnection(host, port, timeout=timeout)
        self.breaker = breaker
        self.stats = stats
        self.watermark = watermark
        self.last_error: Optional[Exception] = None
        self.last_attempt = mode      # wire form of the newest round
        # Mesh-co-located (this node's CollectiveGroup declares the
        # peer's address): rounds ride the single-dispatch collective
        # join, not a socket (docs/COLLECTIVE.md). ``mode`` stays the
        # negotiated socket ladder — the fallback when a join fails.
        self.collective = collective

    @property
    def dense(self) -> bool:
        """Back-compat view of :attr:`mode`: any binary form counts
        as dense (the pre-packed API exposed only that split)."""
        return self.mode != "json"

    @dense.setter
    def dense(self, value: bool) -> None:
        # Mode-preserving: `dense = True` only UPGRADES a json peer to
        # the dense floor of the binary ladder — a peer already at
        # dense/packed/merkle keeps its (faster) mode, where the old
        # `mode = "dense"` collapse would silently downgrade it.
        # `dense = False` still forces json, the legacy escape hatch.
        if value:
            if self.mode == "json":
                self.mode = "dense"
        else:
            self.mode = "json"

    def __repr__(self) -> str:
        return (f"Peer({self.name!r}, {self.host}:{self.port}, "
                f"{self.mode}, "
                f"breaker={self.breaker.state}, "
                f"watermark={self.watermark})")


# Protocol codes that mean "this peer does not speak the dense wire
# form" — downgrade to JSON and retry the round immediately. Any other
# rejection (e.g. a clock guard) would fail identically on JSON, so it
# is terminal for the round. "rejected" is the default code replies
# from pre-taxonomy servers map to.
_DENSE_FALLBACK_CODES = frozenset(
    {"dense_rejected", "unknown_op", "rejected"})

# Codes that mean "this peer will not take the packed columnar form"
# even though its session advertised (or predated) the capability —
# drop one step, to dense, and rerun. A session that never advertised
# "packed" is handled earlier and cheaper: `_one_round` simply never
# offers the form (capability selection, not a rejection — no
# fallback counted, no wasted round-trip).
_PACKED_FALLBACK_CODES = frozenset(
    {"packed_rejected", "unknown_op", "rejected"})

# Codes that mean "this peer will not walk digest trees" — geometry
# mismatch, a digest surface the peer's replica lacks, or a
# pre-merkle server. Drop one step, to packed, and rerun: a full
# packed round is always a correct (just wider) substitute for an
# anti-entropy walk.
_MERKLE_FALLBACK_CODES = frozenset(
    {"merkle_rejected", "unknown_op", "rejected"})


class GossipNode:
    """A replica + its :class:`SyncServer` + a set of :class:`Peer`s,
    run as a resilient long-lived gossip participant.

    >>> node = GossipNode(crdt, state_path="/var/lib/app/gossip.json")
    >>> node.add_peer("b", "10.0.0.2", 7000)
    >>> node.start(gossip_interval=1.0)   # background anti-entropy
    ... # or drive rounds yourself:
    >>> node.sync_peer("b")               # 'ok' | 'skipped' | 'failed'
    >>> node.stop()

    Local writes from other threads must hold :attr:`lock` (the
    server's replica lock) — the same contract as `SyncServer`.
    `sync_peer`/`run_round` themselves are not re-entrant; drive them
    from one thread (the built-in loop, or your own)."""

    # crdtlint lock-discipline contract: the peer registry is touched
    # only under self._peers_lock (enforced statically by
    # crdt_tpu.analysis.host_lint).
    _CRDTLINT_GUARDED = {"_peers_lock": ("peers",)}
    # Checked by analysis/concurrency.py: peers-registry lock before
    # the server's replica lock. In the shipped tree they are only
    # ever taken SEQUENTIALLY (lag_snapshot releases one before the
    # other) — the declaration pins the permitted direction should a
    # future path nest them.
    _CRDTLINT_LOCK_ORDER = ("_peers_lock", ("server.lock",
                                            "SyncServer.lock"))

    def __init__(self, crdt: Crdt, host: str = "127.0.0.1",
                 port: int = 0, *,
                 state_path: Optional[str] = None,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[BreakerPolicy] = None,
                 prefer_dense: Optional[bool] = None,
                 round_timeout: float = 30.0,
                 key_encoder=None, value_encoder=None,
                 key_decoder=None, value_decoder=None,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 group=None,
                 **server_kwargs):
        if group is not None and not group.contains(crdt):
            raise ValueError(
                "collective group does not contain this node's "
                "replica — declare membership with the live replica "
                "object, not a copy")
        self.crdt = crdt
        # Pod-local replica group (crdt_tpu.collective.CollectiveGroup):
        # peers whose address the group declares skip sockets entirely
        # and converge through the single-dispatch collective join.
        self._group = group
        self.retry = retry or RetryPolicy()
        self.breaker_policy = breaker or BreakerPolicy()
        # Dense binary wire form only when the local replica speaks it.
        self.prefer_dense = (hasattr(crdt, "export_split_delta")
                             if prefer_dense is None else prefer_dense)
        self.round_timeout = round_timeout
        self._codecs = dict(key_encoder=key_encoder,
                            value_encoder=value_encoder,
                            key_decoder=key_decoder,
                            value_decoder=value_decoder)
        self._rng = rng or random.Random()
        self._clock = clock
        self._sleep = sleep
        self.server = SyncServer(crdt, host, port,
                                 **self._codecs, **server_kwargs)
        # Client-side wire bytes across all peers, node lifetime
        # (per-peer splits live in each PeerSyncStats). The server's
        # metrics op folds our per-peer lag table into its snapshot.
        self.wire = WireTally()
        default_registry().attach("wire", self.wire, replace=True,
                                  role="client", node=str(crdt.node_id))
        self.server.metrics_extra = self._metrics_extra
        # Flight-recorder context (obs/recorder.py): incident bundles
        # dumped by this process carry the same node/lag/routing/
        # partition sections the metrics op shows a live poller.
        # Weakly held — a test's short-lived node never pins itself.
        from .obs.recorder import default_recorder
        default_recorder().attach_source(self._metrics_extra)
        # Guards the peer REGISTRY (the dict itself): add_peer may run
        # from any thread while the gossip loop iterates. Per-peer
        # mutable state stays single-writer (the gossip thread).
        self._peers_lock = make_lock("GossipNode._peers_lock", 38)
        self.peers: Dict[str, Peer] = {}
        self._state_path = state_path
        # Crash resume: watermarks persisted by a previous incarnation
        # seed add_peer — the first round after restart is a DELTA
        # pull, not a full re-pull.
        self._saved_marks = ({} if state_path is None else
                             load_gossip_state(state_path,
                                               crdt.node_id))
        self._gossip_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Fleet canary probe (obs/probe.py): enabled explicitly via
        # enable_canary — user stores must never lose slots silently.
        self._canary = None
        # Federated routing view (routing.PartitionRouter): attached
        # via attach_router so the routing table + epoch gossip on the
        # metrics/health surfaces pre-federation clients already poll.
        self._router = None
        # Replica-group membership view (replication.ReplicaGroup's
        # ServeTier): attached via attach_replication so role/lease
        # ride the same metrics surface (docs/REPLICATION.md).
        self._replica_tier = None

    # --- topology ---

    @property
    def host(self) -> str:
        return self.server.host

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def lock(self) -> threading.Lock:
        """The replica lock (the server's): hold it around any local
        write from outside the gossip thread."""
        return self.server.lock

    def _default_mode(self, binary: bool) -> str:
        """Fastest wire form the LOCAL replica can speak. What the
        peer accepts is discovered per session (hello caps) and per
        round (sticky rejection downgrade)."""
        if not binary:
            return "json"
        if hasattr(self.crdt, "pack_since") \
                and hasattr(self.crdt, "merge_packed"):
            # "merkle" = packed plus digest-tree anti-entropy for
            # watermark-less rounds; steady-state behavior (and the
            # pipelined fast lane) is identical to "packed".
            if callable(getattr(self.crdt, "digest_tree", None)):
                return "merkle"
            return "packed"
        return "dense"

    def add_peer(self, name: str, host: str, port: int,
                 dense: Optional[bool] = None, *,
                 mode: Optional[str] = None) -> Peer:
        """Register (or re-address) a peer. A persisted watermark for
        ``name`` is resumed. ``mode`` pins the starting wire form
        ('merkle' | 'packed' | 'dense' | 'json'); the older ``dense``
        flag keeps
        meaning "binary if True, JSON if False", with binary resolving
        to the fastest form the local replica speaks."""
        if mode is None:
            mode = self._default_mode(
                self.prefer_dense if dense is None else dense)
        stats = PeerSyncStats().register(
            node=str(self.crdt.node_id), peer=name)
        # Topology detection: an address the local CollectiveGroup
        # declares is a mesh-co-located member — its rounds take the
        # collective lane; `mode` stays negotiated as the fallback.
        collective = (self._group is not None
                      and f"{host}:{port}"
                      in self._group.member_addresses())
        peer = Peer(
            name, host, port,
            mode=mode,
            breaker=CircuitBreaker(self.breaker_policy,
                                   clock=self._clock, stats=stats,
                                   name=name),
            stats=stats,
            watermark=self._saved_marks.get(name),
            timeout=self.round_timeout,
            collective=collective)
        with self._peers_lock:
            old = self.peers.get(name)
            self.peers[name] = peer
        if old is not None:
            old.conn.reset()     # re-addressed: drop the old session
        return peer

    # --- lifecycle ---

    def start(self, gossip_interval: Optional[float] = None
              ) -> "GossipNode":
        """Serve the replica; with ``gossip_interval`` also run
        `run_round` on a background loop every that many seconds."""
        self.server.start()
        if gossip_interval is not None:
            self._stop.clear()

            def loop() -> None:
                while not self._stop.is_set():
                    self.run_round()
                    self._stop.wait(gossip_interval)

            self._gossip_thread = threading.Thread(
                target=loop, daemon=True,
                name=f"gossip-{self.crdt.node_id}")
            self._gossip_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._gossip_thread is not None:
            self._gossip_thread.join(timeout=60)
            self._gossip_thread = None
        with self._peers_lock:
            conns = [p.conn for p in self.peers.values()]
        for conn in conns:
            conn.close(self.wire)    # polite bye, best-effort
        self.server.stop()

    def __enter__(self) -> "GossipNode":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- rounds ---

    def run_round(self) -> Dict[str, str]:
        """One gossip sweep: sync every peer once, in a shuffled order
        (uncoordinated nodes must not all visit peers in registration
        order). Returns ``{peer name: outcome}``.

        Peers on the packed fast path with an already-negotiated
        healthy session run PIPELINED: peer N+1's ``pack_since``
        (device work, under the replica lock) overlaps peer N's
        socket round on a one-worker executor, so the sweep hides
        pack latency behind the wire. Everything else — first
        contact, legacy/dense/JSON peers, open or probing breakers —
        takes the plain sequential path."""
        if self._canary is not None:
            # One canary beat per sweep, BEFORE the watermark reads:
            # the beat rides this very sweep's deltas, so the fleet
            # matrix measures write->replicate->observe end to end.
            try:
                self._canary.beat()
            except Exception:
                pass   # a failed beat must never stall gossip
        with self._peers_lock:
            names = list(self.peers)
        self._rng.shuffle(names)
        with self._peers_lock:
            peers = {n: self.peers[n] for n in names
                     if n in self.peers}
        fast: List[str] = []
        results: Dict[str, str] = {}
        # Topology-aware fast lane first: every mesh-co-located peer in
        # this sweep converges through ONE collective join (a single
        # device dispatch, zero wire bytes); only on a failed join do
        # those peers rerun below on the socket ladder — counted, never
        # silent.
        co = [n for n in names
              if peers[n].collective and self._group is not None]
        if co:
            done = self._collective_sweep(co, peers)
            if done is not None:
                results.update(done)
                names = [n for n in names if n not in done]
        for name in names:
            p = peers[name]
            # A merkle peer WITH a watermark runs the same packed
            # incremental round (the digest walk is only for
            # watermark-less rounds), so it pipelines identically.
            if ((p.mode == "packed"
                 or (p.mode == "merkle" and p.watermark is not None))
                    and p.conn.connected
                    and "packed" in p.conn.caps
                    and p.breaker.state == CircuitBreaker.CLOSED):
                fast.append(name)
            else:
                results[name] = self.sync_peer(name)
        if len(fast) < 2:
            for name in fast:
                results[name] = self.sync_peer(name)
            return results
        default_registry().counter(
            "crdt_tpu_gossip_pipelined_rounds_total",
            "gossip sweeps that overlapped device pack with "
            "network I/O").inc(node=str(self.crdt.node_id))
        with ThreadPoolExecutor(max_workers=1) as ex:
            prev_name, fut = "", None
            for name in fast:
                p = peers[name]
                with self.server.lock:
                    # Drain any ingest-window backlog BEFORE reading
                    # the watermark: pack_since drains internally, but
                    # that flush advances the canonical AFTER a
                    # watermark read here — the stale watermark would
                    # re-send every flushed row next round.
                    drain = getattr(self.crdt, "drain_ingest", None)
                    if drain is not None:
                        drain()
                    watermark = self.crdt.canonical_time
                    # The fast lane requires a live negotiated session
                    # (checked above), so the caps are authoritative:
                    # the sem tag lane rides iff this peer agreed to
                    # "semantics" in its hello.
                    packed, ids = _pack_for_peer(
                        self.crdt, p.watermark,
                        "semantics" in p.conn.caps)
                # The worker is still (possibly) mid-round on the
                # previous peer — that socket wait is what the pack
                # above just overlapped. Collect it before
                # dispatching this one.
                if fut is not None:
                    results[prev_name] = fut.result()
                prev_name = name
                fut = ex.submit(self.sync_peer, name,
                                (watermark, packed, ids))
            if fut is not None:
                results[prev_name] = fut.result()
        return results

    def sync_peer(self, name: str,
                  _prepacked: Optional[Tuple] = None) -> str:
        """One resilient anti-entropy round against a peer.

        Returns ``'ok'`` (round completed, watermark advanced and
        persisted), ``'skipped'`` (breaker open — no network attempt),
        or ``'failed'`` (retry budget exhausted on transport faults,
        or the peer rejected the round; see ``peer.last_error``).
        Failures never raise — a long-running mesh must keep gossiping
        with its healthy peers."""
        ring = tracer()
        if not ring.enabled:
            return self._sync_peer(name, _prepacked)
        start = time.perf_counter()
        outcome = self._sync_peer(name, _prepacked)
        dur = time.perf_counter() - start
        with self.server.lock:
            stamp = str(self.crdt.canonical_time)
        ring.emit("gossip_round", hlc=stamp, peer=name,
                  outcome=outcome, dur_s=dur)
        default_registry().histogram(
            "crdt_tpu_gossip_round_seconds",
            "anti-entropy round wall time, retries included"
        ).observe(dur, peer=name, outcome=outcome)
        return outcome

    def _sync_peer(self, name: str,
                   _prepacked: Optional[Tuple] = None) -> str:
        with self._peers_lock:
            peer = self.peers[name]
        # Co-located peer: the collective lane, checked BEFORE the
        # breaker — the breaker guards the peer's socket, and the
        # collective join never touches it. A failed join is counted
        # as a fallback and the round reruns on the ladder below.
        if peer.collective and self._group is not None:
            done = self._collective_sweep([name], {name: peer})
            if done is not None:
                return done[name]
        if not peer.breaker.allow():
            peer.stats.skipped += 1
            return "skipped"
        was_full = peer.watermark is None
        attempt = 0
        while True:
            try:
                mark = self._one_round(peer, _prepacked)
            except SyncProtocolError as e:
                # A rejected round means the pre-pack is for the
                # wrong wire form; a transport fault means the store
                # may have moved during the backoff. Either way the
                # rerun re-packs fresh.
                _prepacked = None
                tried = peer.last_attempt
                if tried == "merkle" \
                        and e.code in _MERKLE_FALLBACK_CODES:
                    # The peer advertised merkle but won't walk
                    # (geometry mismatch, digest surface missing):
                    # downgrade (sticky) one step — a full packed
                    # round is a correct, wider substitute.
                    peer.stats.fallbacks += 1
                    peer.mode = "packed"
                    continue
                if tried == "packed" \
                        and e.code in _PACKED_FALLBACK_CODES:
                    # The peer advertised packed but won't take it:
                    # downgrade (sticky) one step and rerun on the
                    # dense split form. Not a link fault — no
                    # backoff, and the retry budget is untouched.
                    peer.stats.fallbacks += 1
                    peer.mode = "dense"
                    continue
                if tried == "dense" and peer.mode != "json" \
                        and e.code in _DENSE_FALLBACK_CODES:
                    # No binary form at all: downgrade (sticky) to
                    # the universal JSON path and rerun.
                    peer.stats.fallbacks += 1
                    peer.mode = "json"
                    continue
                return self._round_failed(peer, e)
            except SyncTransportError as e:
                _prepacked = None
                attempt += 1
                if attempt >= self.retry.max_attempts:
                    return self._round_failed(peer, e)
                peer.stats.retries += 1
                peer.last_error = e
                self._sleep(self.retry.delay(attempt, self._rng))
                continue
            if was_full:
                peer.stats.full_pulls += 1
            else:
                peer.stats.delta_pulls += 1
            peer.stats.rounds_ok += 1
            peer.last_error = None
            peer.breaker.record_success()
            peer.watermark = mark
            self._persist()
            return "ok"

    def _collective_sweep(self, names: List[str],
                          peers: Dict[str, Peer]
                          ) -> Optional[Dict[str, str]]:
        """One collective join converges EVERY co-located member, so a
        sweep charges all its collective peers to a single dispatch
        (docs/COLLECTIVE.md). Returns per-peer outcomes, or ``None``
        when the join failed — the downgrade is counted per peer in
        ``crdt_tpu_collective_fallback_total`` (a co-located round
        landing on sockets is a topology regression someone must see;
        crdtlint: collective-socket-fallback-silent) and the caller
        reruns those peers on the socket ladder."""
        group = self._group
        with self.server.lock:
            drain = getattr(self.crdt, "drain_ingest", None)
            if drain is not None:
                drain()
            # The pre-join canonical: exactly the `since` the join
            # seeds each member's pack cache under, so a later socket
            # round (a member left the mesh) delta-packs from a warm
            # hit instead of a full re-pull.
            watermark = self.crdt.canonical_time
        start = time.perf_counter()
        try:
            with self.server.lock:
                group.join()
        except Exception as e:
            fb = default_registry().counter(
                "crdt_tpu_collective_fallback_total",
                "co-located rounds downgraded from the collective "
                "lane to the socket path, by reason")
            for name in names:
                p = peers[name]
                p.stats.fallbacks += 1
                p.last_error = e
                fb.inc(reason=type(e).__name__,
                       node=str(self.crdt.node_id), peer=name)
            return None
        dur = time.perf_counter() - start
        with self.server.lock:
            stamp = str(self.crdt.canonical_time)
        ring = tracer()
        hist = default_registry().histogram(
            "crdt_tpu_gossip_round_seconds",
            "anti-entropy round wall time, retries included")
        results: Dict[str, str] = {}
        for name in names:
            p = peers[name]
            p.last_attempt = "collective"
            p.stats.rounds_ok += 1
            p.stats.delta_pulls += 1
            p.last_error = None
            p.breaker.record_success()
            p.watermark = watermark
            results[name] = "ok"
            if ring.enabled:
                ring.emit("gossip_round", hlc=stamp, peer=name,
                          outcome="ok", dur_s=dur, lane="collective")
            hist.observe(dur, peer=name, outcome="ok")
        self._persist()
        return results

    def _one_round(self, peer: Peer,
                   prepacked: Optional[Tuple] = None) -> Hlc:
        """One wire round on the peer's pooled session, byte-tallied.

        The form actually attempted may sit BELOW ``peer.mode`` for
        this round: a session whose hello did not advertise the
        ``packed`` capability (including pre-hello legacy peers) is
        never offered it. That is capability selection, not a
        rejection — ``fallbacks`` stays untouched, ``peer.mode``
        keeps aiming high, and a future session that does advertise
        the cap gets the fast path back. Dense stays rejection-based
        on purpose: pre-hello servers may well speak it, and hello
        caps can't prove they don't."""
        tally = WireTally()
        try:
            conn = peer.conn
            if (conn.host, conn.port) != (peer.host, peer.port):
                # The peer was re-pointed in place (failover): drop
                # the old session and follow the address.
                conn.reset()
                conn.host, conn.port = peer.host, peer.port
            conn.ensure(tally)
            mode = peer.mode
            if mode == "merkle":
                if "merkle" not in conn.caps:
                    # Capability selection, like packed below: a
                    # session that never advertised merkle is never
                    # offered the walk — no fallback counted.
                    mode = "packed"
                elif peer.watermark is not None or prepacked is not None:
                    # Warm session: the watermark-bounded incremental
                    # round is strictly cheaper than a digest walk.
                    # Merkle is the cold/partitioned-join half; the
                    # mode keeps aiming at it so a dropped watermark
                    # (restart without state, explicit reset) walks
                    # again.
                    mode = "packed"
            if mode == "packed" and "packed" not in conn.caps:
                mode = ("dense"
                        if hasattr(self.crdt, "export_split_delta")
                        else "json")
            peer.last_attempt = mode
            if mode == "merkle":
                return sync_merkle_over_conn(
                    self.crdt, conn, lock=self.server.lock,
                    tally=tally, fused_repack=True)
            if mode == "packed":
                # Gossip relays take the fused merge+repack dispatch:
                # the pulled delta's join also seeds the next round's
                # pack under this round's watermark.
                return sync_packed_over_conn(
                    self.crdt, conn, since=peer.watermark,
                    lock=self.server.lock, tally=tally,
                    _prepacked=prepacked, fused_repack=True)
            if mode == "dense":
                return sync_dense_over_conn(
                    self.crdt, conn, since=peer.watermark,
                    lock=self.server.lock, tally=tally)
            return sync_over_conn(
                self.crdt, conn, since=peer.watermark,
                lock=self.server.lock, tally=tally, **self._codecs)
        finally:
            peer.stats.bytes_sent += tally.sent
            peer.stats.bytes_received += tally.received
            self.wire.sent += tally.sent
            self.wire.received += tally.received
            self.wire.z_raw += tally.z_raw
            self.wire.z_wire += tally.z_wire

    def _round_failed(self, peer: Peer, exc: Exception) -> str:
        peer.last_error = exc
        peer.stats.rounds_failed += 1
        peer.breaker.record_failure()
        return "failed"

    def _persist(self) -> None:
        if self._state_path is not None:
            with self._peers_lock:
                entries = list(self.peers.items())
            save_gossip_state(
                self._state_path, self.crdt.node_id,
                {name: p.watermark for name, p in entries})

    # --- observability ---

    def stats_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-peer counter snapshot plus breaker state — cheap, no
        replica access, safe to poll from a monitoring thread."""
        with self._peers_lock:
            entries = list(self.peers.items())
        return {name: {**p.stats.as_dict(),
                       "breaker": p.breaker.state,
                       "dense": p.dense,
                       "mode": p.mode,
                       "connects": p.conn.connects,
                       "watermark": None if p.watermark is None
                       else str(p.watermark)}
                for name, p in entries}

    def lag_snapshot(self, include_pending: bool = True
                     ) -> Dict[str, Dict[str, Any]]:
        """Per-peer convergence lag: how far each peer's last
        completed round is behind the local HLC head.

        ``lag_ms`` is ``local_head.millis - watermark.millis`` (the
        watermark is the local canonical time captured at the start of
        the peer's last completed round, so this measures sync
        staleness, not network latency); ``pending_records`` counts
        local records modified since that watermark — the backlog the
        next delta round would push. Never-synced peers report
        ``synced: False`` with null lag. ``include_pending=False``
        skips the replica scan (and its lock) for cheap polling."""
        with self._peers_lock:
            entries = list(self.peers.items())
        with self.server.lock:
            head = self.crdt.canonical_time
            pending = {}
            if include_pending:
                for name, p in entries:
                    pending[name] = self.crdt.count_modified_since(
                        p.watermark)
        return {name: lag_entry(head, p.watermark,
                                pending=pending.get(name),
                                breaker=p.breaker.state,
                                dense=p.dense,
                                last_error=p.last_error)
                for name, p in entries}

    def health(self, include_pending: bool = True,
               stale_after_ms: int = 60_000) -> Dict[str, Any]:
        """One-call node health: identity, HLC head, per-peer lag, and
        an overall ``status`` — ``"degraded"`` when any peer is
        never-synced, breaker-impaired, or staler than
        ``stale_after_ms``; else ``"ok"``."""
        peers = self.lag_snapshot(include_pending=include_pending)
        with self.server.lock:
            head = self.crdt.canonical_time
        out = {"node_id": str(self.crdt.node_id),
               "hlc_head": str(head),
               "head_millis": head.millis,
               "status": health_status(peers,
                                       stale_after_ms=stale_after_ms),
               "peers": peers}
        router = self._router
        if router is not None and router.epoch is not None:
            out["routing_epoch"] = router.epoch
        return out

    # --- tombstone GC (docs/STORAGE.md) ---

    def stability_hlc(self) -> Optional[Hlc]:
        """Fleet stability watermark: the min over every configured
        peer's delivery watermark (the PR 3 `lag_snapshot` signal —
        the local canonical captured when that peer's last round
        completed, i.e. everything this node holds below it has been
        offered to the peer) and, when a replica-group tier is
        attached, the group's durable floor
        (`ServeTier.stability_hlc`). A tombstone below this mark has
        been delivered everywhere, so purging it can never be
        observed. ANY unmeasured input — a never-synced peer, a
        follower without a durable head — pins the watermark to
        ``None``: unmeasured ≠ safe-to-purge, the same discipline as
        the autoscaler's degraded freeze. With no peers and no tier,
        this node is the fleet, and its own head is the watermark.
        Raw watermark — `DenseCrdt.gc_purge` applies the HLC drift
        slack."""
        with self._peers_lock:
            peers = list(self.peers.values())
        marks = []
        for p in peers:
            if p.watermark is None:
                return None
            marks.append(p.watermark)
        tier = self._replica_tier
        if tier is not None:
            t = tier.stability_hlc()
            if t is None:
                return None
            marks.append(t)
        if not marks:
            with self.server.lock:
                return self.crdt.canonical_time
        return min(marks)

    def gc_pass(self, drift_slack_ms: Optional[int] = None) -> int:
        """One epoch-GC pass: fold the fleet stability watermark and
        purge tombstones it has passed (`DenseCrdt.gc_purge`, one
        dispatch — zero when the watermark hasn't advanced). Returns
        slots purged; 0 when the watermark is pinned or the replica
        has no dense GC surface (record-dict backends purge nothing).
        Call it from the sweep cadence — GC is idempotent and cheap
        when idle, so over-calling is safe."""
        from .obs.registry import default_registry
        stability = self.stability_hlc()
        if stability is None:
            default_registry().counter(
                "crdt_tpu_gc_pinned_total",
                "GC passes skipped on a pinned stability watermark"
            ).inc(surface="gossip")
            return 0
        if not hasattr(self.crdt, "gc_purge"):
            return 0
        with self.server.lock:
            return self.crdt.gc_purge(stability,
                                      drift_slack_ms=drift_slack_ms)

    def attach_group(self, group) -> None:
        """Declare (or replace, or with ``None`` detach) this node's
        pod-local replica group after construction — the usual order,
        since member server ports are only known once every node has
        started. Registered peers are re-scanned for co-location, so
        `add_peer` order relative to this call does not matter."""
        if group is not None and not group.contains(self.crdt):
            raise ValueError(
                "collective group does not contain this node's "
                "replica — declare membership with the live replica "
                "object, not a copy")
        self._group = group
        addrs = (frozenset() if group is None
                 else group.member_addresses())
        with self._peers_lock:
            for p in self.peers.values():
                p.collective = f"{p.host}:{p.port}" in addrs

    def attach_router(self, router) -> None:
        """Bind a `routing.PartitionRouter` so this node's metrics op
        and `health()` carry the federated routing table/epoch — the
        gossip leg of table distribution: any peer or poller that
        already fetches metrics learns the newest table without a
        federation-aware session (docs/FEDERATION.md)."""
        self._router = router

    def attach_replication(self, tier) -> None:
        """Bind a replica-group member `ServeTier` so this node's
        metrics op carries its group/role/lease state — the gossip
        leg of replica-health distribution: the fleet poller learns
        which member is primary without a group-aware session
        (docs/REPLICATION.md)."""
        self._replica_tier = tier

    def _metrics_extra(self) -> Dict[str, Any]:
        """Folded into the server's ``metrics`` op reply (called
        WITHOUT the server lock held — lag_snapshot takes it)."""
        with self.server.lock:
            node = {"node_id": str(self.crdt.node_id),
                    "hlc_head": str(self.crdt.canonical_time)}
        extra = {"node": node, "lag": self.lag_snapshot()}
        if self._canary is not None:
            extra["canary"] = self._canary.snapshot()
        router = self._router
        if router is not None and router.table is not None:
            extra["routing"] = router.table.to_json()
        tier = self._replica_tier
        if tier is not None and tier.role is not None:
            extra["replication"] = {
                "group": tier.group_name, "role": tier.role,
                "lease_ms": tier._lease_ms()}
        if tier is not None:
            # Per-partition load roll-up for the fleet table
            # (obs/fleet.py format_partitions) — present only when
            # the tier is a federated partition.
            part = tier.partition_info()
            if part is not None:
                extra["partition"] = part
        # Stability watermark (docs/STORAGE.md): gossiped so peers and
        # the fleet poller see each node's GC posture — the watermark
        # it would purge at (or the pin), and the armed floor.
        stability = self.stability_hlc()
        gc: Dict[str, Any] = {
            "stability_hlc": (None if stability is None
                              else str(stability)),
            "pinned": stability is None}
        floor = getattr(self.crdt, "gc_floor", None)
        if floor:
            gc["gc_floor"] = int(floor)
        extra["stability"] = gc
        return extra

    # --- fleet canary (obs/probe.py) ---

    def enable_canary(self, origin: int, n_origins: int,
                      base_slot: Optional[int] = None):
        """Join the fleet's canary protocol: reserve ``n_origins``
        slots (the top of the store unless ``base_slot`` is given),
        beat slot ``base_slot + origin`` each gossip sweep, and expose
        last-seen beats per origin in the ``canary`` section of the
        ``metrics`` op — the fleet poller's lag-matrix feed
        (docs/OBSERVABILITY.md). Returns the probe."""
        from .obs.probe import CanaryProbe
        self._canary = CanaryProbe(self.crdt, origin, n_origins,
                                   base_slot=base_slot,
                                   lock=self.server.lock)
        return self._canary

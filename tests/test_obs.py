"""Unified telemetry (crdt_tpu.obs): metrics registry, HLC-stamped
trace ring, convergence-lag monitor, the ``metrics`` wire op, and the
``python -m crdt_tpu.obs`` CLI — plus the crdtlint gate over the obs
package itself.

The registry under test is usually a FRESH ``MetricsRegistry`` (unit
scope); end-to-end tests go through the process-wide default registry
and therefore filter snapshots by label instead of asserting global
counts (other tests' backends live in the same process).
"""

import io
import json
import random
import threading

import pytest

from crdt_tpu import (DenseCrdt, GossipNode, Hlc, MapCrdt, Record,
                      RetryPolicy, SqliteCrdt, fetch_metrics)
from crdt_tpu.obs import (default_registry, metrics_snapshot, span,
                          tracer)
from crdt_tpu.obs.lag import health_status, lag_entry, lag_millis
from crdt_tpu.obs.registry import (Counter, Gauge, Histogram,
                                   MetricsRegistry)
from crdt_tpu.obs.render import (format_phase_table, render_prometheus,
                                 render_summary, summarize_trace)
from crdt_tpu.obs.trace import TraceRing
from crdt_tpu.testing import FakeClock, FaultProxy, FaultSchedule
from crdt_tpu.utils.stats import MergeStats

pytestmark = pytest.mark.obs

NO_SLEEP = lambda _s: None


# ---------------------------------------------------------------- registry


def test_counter_inc_value_and_labels():
    c = Counter("reqs_total", "requests")
    c.inc()
    c.inc(2, route="a")
    c.inc(route="a")
    assert c.value() == 1
    assert c.value(route="a") == 3
    assert c.value(route="never") == 0
    by_labels = {tuple(sorted(s["labels"].items())): s["value"]
                 for s in c.samples()}
    assert by_labels == {(): 1, (("route", "a"),): 3}


def test_counter_rejects_negative_increment():
    c = Counter("n", "")
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_and_add():
    g = Gauge("depth", "")
    g.set(5, q="x")
    g.add(-2, q="x")
    assert g.value(q="x") == 3


def test_histogram_log2_buckets_and_overflow():
    h = Histogram("lat", "", low_exp=-2, high_exp=2)
    assert h.bounds == (0.25, 0.5, 1.0, 2.0, 4.0)
    h.observe(0.2)     # <= 0.25 -> first bucket
    h.observe(0.25)    # boundary lands in its own bucket (le=0.25)
    h.observe(3.0)     # <= 4.0 -> last finite bucket
    h.observe(100.0)   # overflow (+Inf)
    (s,) = h.samples()
    assert s["count"] == 4
    assert s["sum"] == pytest.approx(103.45)
    assert s["overflow"] == 1
    counts = dict(s["buckets"])
    assert counts[0.25] == 2
    assert counts[4.0] == 1


def test_registry_get_or_create_and_kind_clash():
    reg = MetricsRegistry()
    c1 = reg.counter("a_total", "help")
    c2 = reg.counter("a_total")
    assert c1 is c2
    with pytest.raises(ValueError):
        reg.gauge("a_total")


def test_registry_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("c_total").inc(7)
    reg.gauge("g").set(1.5)
    reg.histogram("h", low_exp=0, high_exp=1).observe(1.0)
    snap = reg.snapshot()
    assert snap["counters"]["c_total"][0]["value"] == 7
    assert snap["gauges"]["g"][0]["value"] == 1.5
    assert snap["histograms"]["h"][0]["count"] == 1


def test_stats_collectors_absorbed_and_weakly_held():
    import gc
    reg = MetricsRegistry()
    ms = MergeStats()
    ms.merges = 3
    reg.attach("merge", ms, backend="X", node="n1")
    entries = reg.snapshot()["stats"]["merge"]
    assert entries == [{"labels": {"backend": "X", "node": "n1"},
                        "values": ms.as_dict()}]
    del ms
    gc.collect()
    assert reg.snapshot()["stats"].get("merge", []) == []


def test_backends_register_with_default_registry():
    crdt = SqliteCrdt("obs-reg-node")
    crdt.merge({"k": Record(Hlc(1_700_000_000_000, 0, "peer"), 1,
                            Hlc(1_700_000_000_000, 0, "peer"))})
    merge_rows = metrics_snapshot()["stats"]["merge"]
    (row,) = [e for e in merge_rows
              if e["labels"].get("node") == "obs-reg-node"]
    assert row["labels"]["backend"] == "SqliteCrdt"
    assert row["values"]["merges"] == 1
    assert row["values"]["records_seen"] == 1
    assert row["values"]["records_adopted"] == 1


# ---------------------------------------------------------------- trace ring


def test_ring_disabled_is_noop_and_lazy_hlc_not_evaluated():
    ring = TraceRing()
    calls = []
    ring.emit("merge", hlc=lambda: calls.append(1))
    assert ring.events() == [] and calls == []


def test_ring_bounded_and_ordered():
    ring = TraceRing(capacity=3)
    ring.enabled = True
    for i in range(5):
        ring.emit("k", i=i)
    assert [e["i"] for e in ring.events()] == [2, 3, 4]
    assert [e["seq"] for e in ring.events()] == [3, 4, 5]


def test_ring_jsonl_sink_and_hlc_stamp(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    ring = TraceRing()
    ring.enable(jsonl_path=path)
    ring.emit("merge", hlc=lambda: Hlc(1_700_000_000_000, 2, "a"),
              n=1)
    ring.disable()
    (line,) = open(path).read().splitlines()
    event = json.loads(line)
    assert event["kind"] == "merge" and event["n"] == 1
    assert event["hlc"] == str(Hlc(1_700_000_000_000, 2, "a"))


def test_span_emits_duration_and_histogram_sample():
    ring = tracer()
    ring.enable()
    ring.clear()
    try:
        with span("obs.test.phase", kind="bench_phase"):
            pass
        (event,) = ring.events("bench_phase")
        assert event["span"] == "obs.test.phase"
        assert event["dur_s"] >= 0
        hist = default_registry().histogram("crdt_tpu_span_seconds")
        assert any(s["labels"] == {"span": "obs.test.phase"}
                   for s in hist.samples())
    finally:
        ring.disable()
        ring.clear()


# ---------------------------------------------------------------- lag math


def test_lag_millis_and_entry():
    head = Hlc(1_700_000_060_000, 0, "a")
    mark = Hlc(1_700_000_000_000, 3, "a")
    assert lag_millis(head, mark) == 60_000
    assert lag_millis(head, None) is None
    assert lag_millis(mark, head) == 0    # clamped, never negative
    entry = lag_entry(head, mark, pending=4, breaker="closed",
                      dense=True)
    assert entry["synced"] and entry["lag_ms"] == 60_000
    assert entry["pending_records"] == 4 and entry["dense"]
    never = lag_entry(head, None)
    assert not never["synced"] and never["lag_ms"] is None


def test_health_status_rules():
    head = Hlc(1_700_000_060_000, 0, "a")
    ok = {"b": lag_entry(head, Hlc(1_700_000_059_000, 0, "a"),
                         breaker="closed")}
    assert health_status(ok) == "ok"
    assert health_status(ok, stale_after_ms=500) == "degraded"
    assert health_status(
        {"b": lag_entry(head, None)}) == "degraded"
    open_breaker = {"b": lag_entry(head, head, breaker="open")}
    assert health_status(open_breaker) == "degraded"
    assert health_status({}) == "ok"


# ------------------------------------------------- count_modified_since


def _mk_since(crdt):
    crdt.put("k1", 1)
    since = crdt.canonical_time
    crdt.put("k2", 2)
    crdt.put("k3", 3)
    return since


def test_count_modified_since_map():
    crdt = MapCrdt("a", wall_clock=FakeClock())
    since = _mk_since(crdt)
    # Inclusive bound (map_crdt.dart:44-45): the record at the watermark
    # itself still counts, so k1 is in the backlog along with k2/k3.
    assert crdt.count_modified_since(since) == 3
    assert crdt.count_modified_since(None) == 3
    assert crdt.count_modified_since(since) == \
        len(crdt.record_map(modified_since=since))


def test_count_modified_since_sqlite():
    crdt = SqliteCrdt("a", wall_clock=FakeClock())
    since = _mk_since(crdt)
    assert crdt.count_modified_since(since) == 3
    assert crdt.count_modified_since(None) == 3
    # matches the record_map view it summarizes
    assert crdt.count_modified_since(since) == \
        len(crdt.record_map(modified_since=since))


def test_count_modified_since_dense():
    crdt = DenseCrdt("a", 16, wall_clock=FakeClock())
    crdt.put_batch([1], [10])
    since = crdt.canonical_time
    crdt.put_batch([2], [20])
    crdt.delete_batch([1])   # tombstones count: they still need shipping
    assert crdt.count_modified_since(since) == 2
    assert crdt.count_modified_since(None) == 2


# -------------------------------------------------- metrics wire op / e2e


def _node(crdt, **kw):
    kw.setdefault("rng", random.Random(7))
    kw.setdefault("sleep", NO_SLEEP)
    return GossipNode(crdt, **kw)


def test_metrics_wire_op_end_to_end():
    clk = FakeClock()
    a = _node(MapCrdt("obs-a", wall_clock=clk))
    b = _node(MapCrdt("obs-b", wall_clock=clk))
    with a, b:
        a.add_peer("b", b.host, b.port)
        with a.lock:
            a.crdt.put("x", 1)
            a.crdt.put("y", 2)
        assert a.run_round() == {"b": "ok"}
        snap = fetch_metrics(a.host, a.port)

    assert snap["node"]["node_id"] == "obs-a"
    assert "hlc_head" in snap["node"]
    # per-peer HLC lag, from the node that owns the peers
    entry = snap["lag"]["b"]
    assert entry["synced"] is True
    assert entry["lag_ms"] is not None and entry["lag_ms"] >= 0
    assert entry["pending_records"] is not None
    assert entry["breaker"] == "closed"
    # per-peer gossip counters
    (peer_row,) = [e for e in snap["stats"]["peer_sync"]
                   if e["labels"].get("node") == "obs-a"]
    assert peer_row["labels"]["peer"] == "b"
    assert peer_row["values"]["rounds_ok"] == 1
    assert peer_row["values"]["bytes_sent"] > 0
    # merge counters from the remote replica's ingest
    merge_rows = [e for e in snap["stats"]["merge"]
                  if e["labels"].get("node") == "obs-b"]
    assert merge_rows and merge_rows[0]["values"]["records_seen"] >= 2
    # wire bytes, both roles
    roles = {e["labels"]["role"] for e in snap["stats"]["wire"]}
    assert {"server", "client"} <= roles
    client_rows = [e for e in snap["stats"]["wire"]
                   if e["labels"] == {"role": "client",
                                      "node": "obs-a"}]
    assert client_rows[0]["values"]["sent"] > 0

    # the snapshot renders in both formats without loss
    prom = render_prometheus(snap)
    assert 'crdt_tpu_peer_synced{node="obs-a",peer="b"} 1' in prom
    assert "crdt_tpu_merge_merges_total" in prom
    assert "crdt_tpu_wire_sent_bytes_total" in prom
    human = render_summary(snap)
    assert "obs-a" in human and "b" in human


def test_metrics_op_on_bare_sync_server():
    """A SyncServer without a GossipNode still answers: registry
    snapshot plus its own node identity, no lag section."""
    from crdt_tpu.net import SyncServer
    crdt = MapCrdt("obs-bare", wall_clock=FakeClock())
    server = SyncServer(crdt)
    server.start()
    try:
        snap = fetch_metrics(server.host, server.port)
    finally:
        server.stop()
    assert snap["node"]["node_id"] == "obs-bare"
    assert "lag" not in snap
    assert "stats" in snap


def test_unknown_op_still_rejected():
    """The metrics op must not have loosened the op whitelist."""
    from crdt_tpu.net import (SyncProtocolError, SyncServer,
                              recv_frame, send_frame)
    import socket
    import time
    crdt = MapCrdt("obs-unknown", wall_clock=FakeClock())
    server = SyncServer(crdt)
    server.start()
    try:
        with socket.create_connection((server.host, server.port),
                                      timeout=5) as sock:
            send_frame(sock, {"op": "metricz"})
            reply = recv_frame(sock, deadline=time.monotonic() + 5)
        assert reply["code"] == "unknown_op"
    finally:
        server.stop()


# --------------------------------- satellite: partitioned-peer lag growth


def test_three_node_lag_grows_under_partition_and_heals():
    """Hub node `a` gossips with a healthy peer `b` and a peer `c`
    behind an all-drop fault proxy. After one clean sync everywhere,
    the partition begins: c's lag (local head minus its watermark)
    grows with every local write while b's stays near zero, health
    degrades once c is staler than the threshold — then the proxy
    heals, one round collapses c's lag, and health returns to ok."""
    clk = FakeClock()
    a = _node(MapCrdt("a", wall_clock=clk),
              retry=RetryPolicy(max_attempts=1, base_delay=0.001))
    b = _node(MapCrdt("b", wall_clock=clk))
    c = _node(MapCrdt("c", wall_clock=clk))
    with a, b, c:
        drop_all = FaultSchedule(rate=1.0, kinds={"drop": 1})
        with FaultProxy(c.host, c.port, drop_all) as proxy:
            proxy.passthrough = True          # healthy to begin with
            a.add_peer("b", b.host, b.port)
            a.add_peer("c", proxy.host, proxy.port)
            with a.lock:
                a.crdt.put("k0", 0)
            assert a.run_round() == {"b": "ok", "c": "ok"}
            lag0 = a.lag_snapshot()
            assert lag0["c"]["synced"] and lag0["b"]["synced"]

            proxy.passthrough = False         # partition begins
            samples = []
            for i in range(3):
                clk.advance(10_000)
                with a.lock:
                    a.crdt.put(f"p{i}", i)
                outcome = a.run_round()
                assert outcome["b"] == "ok"
                assert outcome["c"] == "failed"
                snap = a.lag_snapshot()
                samples.append(snap["c"]["lag_ms"])
                # healthy peer keeps re-syncing: watermark tracks head
                assert snap["b"]["lag_ms"] < snap["c"]["lag_ms"]
            # monotone growth while partitioned
            assert samples == sorted(samples)
            assert samples[-1] > samples[0] >= 10_000
            assert snap["c"]["pending_records"] >= 3
            health = a.health(stale_after_ms=15_000)
            assert health["status"] == "degraded"

            proxy.passthrough = True          # heal
            assert a.sync_peer("c") == "ok"
            healed = a.lag_snapshot()["c"]
            assert healed["lag_ms"] < samples[0]
            assert a.health(stale_after_ms=15_000)["status"] == "ok"
    assert a.crdt.map == c.crdt.map


# ---------------------------------------------------------------- CLI


def test_cli_once_summary_json_and_prom():
    from crdt_tpu.obs.cli import main as obs_main
    clk = FakeClock()
    a = _node(MapCrdt("obs-cli", wall_clock=clk))
    b = _node(MapCrdt("obs-cli-b", wall_clock=clk))
    with a, b:
        a.add_peer("b", b.host, b.port)
        with a.lock:
            a.crdt.put("x", 1)
        assert a.run_round() == {"b": "ok"}
        target = f"{a.host}:{a.port}"

        out = io.StringIO()
        assert obs_main([target, "--once"], out=out) == 0
        assert "obs-cli" in out.getvalue()

        out = io.StringIO()
        assert obs_main([target, "--once", "--json"], out=out) == 0
        snap = json.loads(out.getvalue())
        assert snap["node"]["node_id"] == "obs-cli"
        assert snap["lag"]["b"]["synced"] is True

        out = io.StringIO()
        assert obs_main([target, "--once", "--prom"], out=out) == 0
        assert "crdt_tpu_peer_synced" in out.getvalue()


def test_cli_poll_failure_returns_nonzero():
    import socket
    from crdt_tpu.obs.cli import main as obs_main
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    assert obs_main([f"127.0.0.1:{port}", "--once"],
                    out=io.StringIO()) == 1


def test_cli_trace_summary_table(tmp_path):
    from crdt_tpu.obs.cli import main as obs_main
    path = str(tmp_path / "trace.jsonl")
    with open(path, "w") as f:
        for dur in (0.010, 0.020, 0.030):
            f.write(json.dumps({"kind": "merge", "span": "merge",
                                "dur_s": dur}) + "\n")
        f.write(json.dumps({"kind": "gossip_round",
                            "dur_s": 0.5}) + "\n")
        f.write(json.dumps({"kind": "breaker"}) + "\n")  # no dur_s
        f.write("{corrupt json\n")                       # tail line
    out = io.StringIO()
    assert obs_main(["--trace", path], out=out) == 0
    table = out.getvalue()
    assert "merge" in table and "gossip_round" in table
    assert "breaker" not in table


def test_summarize_trace_percentiles():
    events = [{"kind": "merge", "span": "m", "dur_s": d / 100}
              for d in range(1, 101)]
    summary = summarize_trace(events)
    stats = summary["m"]
    assert stats["count"] == 100
    assert stats["p50_s"] == pytest.approx(0.50)
    assert stats["p95_s"] == pytest.approx(0.95)
    assert stats["max_s"] == pytest.approx(1.00)
    table = format_phase_table(summary)
    assert "m" in table
    assert format_phase_table({}) == "no span events\n"


# ----------------------------------------------- breaker trace events


def test_breaker_transitions_emit_trace_events():
    from crdt_tpu import BreakerPolicy, CircuitBreaker
    clock = [100.0]
    br = CircuitBreaker(BreakerPolicy(failure_threshold=1,
                                      reset_timeout=5.0),
                        clock=lambda: clock[0], name="peer-x")
    ring = tracer()
    ring.enable()
    ring.clear()
    try:
        br.record_failure()                   # -> open
        clock[0] += 6.0
        assert br.allow()                     # -> half_open
        br.record_success()                   # -> closed
        states = [e["state"] for e in ring.events("breaker")
                  if e["peer"] == "peer-x"]
        assert states == ["open", "half_open", "closed"]
    finally:
        ring.disable()
        ring.clear()


# ------------------------------------------------ satellite: lint gate


@pytest.mark.analysis
def test_crdtlint_clean_on_obs_package():
    import os
    from crdt_tpu.analysis.cli import main as lint_main
    obs_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "crdt_tpu", "obs")
    assert lint_main(["--lint", obs_dir, "--json"]) == 0


# ------------------------- fleet plane: registry attach semantics


def test_attach_rejects_duplicate_live_label_set():
    reg = MetricsRegistry()
    a, b = MergeStats(), MergeStats()
    reg.attach("merge", a, backend="X", node="n")
    with pytest.raises(ValueError, match="duplicate"):
        reg.attach("merge", b, backend="X", node="n")
    # a different label set is a different series: fine
    reg.attach("merge", b, backend="X", node="m")
    assert len(reg.snapshot()["stats"]["merge"]) == 2


def test_attach_replace_supersedes_live_entry():
    reg = MetricsRegistry()
    a, b = MergeStats(), MergeStats()
    a.merges, b.merges = 1, 2
    reg.attach("merge", a, node="n")
    reg.attach("merge", b, node="n", replace=True)
    (entry,) = reg.snapshot()["stats"]["merge"]
    assert entry["values"]["merges"] == 2
    del a   # keep the superseded object alive until after the check


def test_attach_reuses_dead_entry_without_replace():
    import gc
    reg = MetricsRegistry()
    a = MergeStats()
    reg.attach("merge", a, node="n")
    del a
    gc.collect()
    b = MergeStats()
    reg.attach("merge", b, node="n")          # no raise: referent died
    assert len(reg.snapshot()["stats"]["merge"]) == 1


def test_gossip_restart_same_node_id_does_not_raise():
    """The restart idiom: a node re-created under the same node id
    while the prior incarnation is still weakly reachable must
    supersede its collectors, not raise (replace=True at every
    identity-collector site)."""
    clk = FakeClock()
    first = _node(MapCrdt("obs-restart", wall_clock=clk))
    second = _node(MapCrdt("obs-restart", wall_clock=clk))
    rows = [e for e in metrics_snapshot()["stats"]["wire"]
            if e["labels"] == {"role": "client",
                               "node": "obs-restart"}]
    assert len(rows) == 1
    del first, second


# ------------------------- fleet plane: exposition escaping


def test_prometheus_escapes_label_values():
    reg = MetricsRegistry()
    c = reg.counter("crdt_tpu_esc_total", "escape regression")
    c.inc(peer='quo"te', path="back\\slash", msg="line\nbreak")
    text = render_prometheus(reg.snapshot())
    assert 'peer="quo\\"te"' in text
    assert 'path="back\\\\slash"' in text
    assert 'msg="line\\nbreak"' in text
    # no raw newline may survive inside any sample line
    for line in text.splitlines():
        assert "\n" not in line


def test_prometheus_renders_seconds_behind():
    head = Hlc(1_700_000_060_000, 0, "a")
    mark = Hlc(1_700_000_000_000, 0, "a")
    snap = {"node": {"node_id": "sb-a"},
            "lag": {"b": lag_entry(head, mark)}}
    text = render_prometheus(snap)
    assert ('crdt_tpu_peer_seconds_behind{node="sb-a",peer="b"} 60'
            in text)


def test_lag_entry_seconds_behind():
    head = Hlc(1_700_000_060_000, 0, "a")
    mark = Hlc(1_700_000_000_000, 3, "a")
    assert lag_entry(head, mark)["seconds_behind"] == \
        pytest.approx(60.0)
    assert lag_entry(head, None)["seconds_behind"] is None


# ------------------------- fleet plane: bounded trace sink


def test_trace_sink_rotates_at_byte_budget(tmp_path):
    import os
    path = str(tmp_path / "trace.jsonl")
    ring = TraceRing()
    ring.enable(jsonl_path=path, max_sink_bytes=512)
    for i in range(64):
        ring.emit("soak", i=i, pad="x" * 40)
    ring.disable()
    rolled = path + ".1"
    assert os.path.exists(rolled)
    # one generation: live file + rolled file bound the disk footprint
    # to ~2x the budget, however long the soak ran
    line_len = len(json.dumps({"kind": "soak", "mono_s": 0.0,
                               "i": 1, "pad": "x" * 40,
                               "seq": 1})) + 1
    assert os.path.getsize(path) <= 512 + line_len
    assert os.path.getsize(rolled) <= 512 + line_len
    # both generations hold intact JSONL (no torn lines at the roll)
    for p in (path, rolled):
        for line in open(p).read().splitlines():
            json.loads(line)


def test_round_id_unique_and_node_prefixed():
    from crdt_tpu.obs import round_id
    a, b = round_id("n1"), round_id("n1")
    assert a != b
    assert a.startswith("n1.r") and b.startswith("n1.r")
    assert round_id().startswith("r")


# ------------------------- fleet plane: canary probe + lag matrix


def test_canary_probe_beat_observed_and_matrix():
    from crdt_tpu.obs import CanaryProbe, evaluate_slo, lag_matrix
    from crdt_tpu.sync import sync_packed
    a = DenseCrdt("can-a", 32, wall_clock=FakeClock())
    b = DenseCrdt("can-b", 32, wall_clock=FakeClock())
    pa = CanaryProbe(a, origin=0, n_origins=2)
    pb = CanaryProbe(b, origin=1, n_origins=2)
    assert (pa.slot, pb.slot) == (30, 31)     # top of the store
    pa.beat(1_000_000)
    pb.beat(1_002_500)
    sync_packed(a, b, since=None)
    snaps = {"a": {"canary": pa.snapshot()},
             "b": {"canary": pb.snapshot()}}
    m = lag_matrix(snaps)
    assert m["origins"] == ["0", "1"] and m["complete"]
    assert m["max_lag_s"] == 0.0
    # origin 0 beats again without replicating: b falls 60s behind
    pa.beat(1_060_000)
    snaps = {"a": {"canary": pa.snapshot()},
             "b": {"canary": pb.snapshot()}}
    m = lag_matrix(snaps)
    assert m["complete"]                       # pair seen, just stale
    assert m["lag_s"]["0"]["b"] == pytest.approx(60.0)
    assert m["lag_s"]["0"]["a"] == 0.0
    verdict = evaluate_slo(snaps, m)
    assert verdict["checks"]["convergence_lag_s"]["ok"] is False
    assert verdict["ok"] is False


def test_canary_probe_validates_range():
    from crdt_tpu.obs import CanaryProbe
    crdt = DenseCrdt("can-v", 16, wall_clock=FakeClock())
    with pytest.raises(ValueError):
        CanaryProbe(crdt, origin=2, n_origins=2)
    with pytest.raises(ValueError):
        CanaryProbe(crdt, origin=0, n_origins=32)


def test_lag_matrix_incomplete_pair_fails_convergence():
    from crdt_tpu.obs import evaluate_slo, lag_matrix
    snaps = {"a": {"canary": {"origin": 0, "n_origins": 2,
                              "base_slot": 30,
                              "observed": {"0": 1000, "1": None}}},
             "b": {"canary": {"origin": 1, "n_origins": 2,
                              "base_slot": 30,
                              "observed": {"0": 1000, "1": 2000}}}}
    m = lag_matrix(snaps)
    assert not m["complete"]
    assert m["lag_s"]["1"]["a"] is None
    verdict = evaluate_slo(snaps, m)
    # an unseen pair IS unbounded lag, whatever the seen pairs say
    assert verdict["checks"]["convergence_lag_s"]["ok"] is False


def test_histogram_quantile_bounds():
    import math
    from crdt_tpu.obs.fleet import histogram_quantile
    h = Histogram("crdt_tpu_hq", "", low_exp=-2, high_exp=2)
    for v in (0.2, 0.2, 3.0):
        h.observe(v)
    (s,) = h.samples()
    assert histogram_quantile(s, 0.5) == 0.25
    assert histogram_quantile(s, 0.99) == 4.0
    assert histogram_quantile({"count": 0}, 0.5) is None
    h2 = Histogram("crdt_tpu_hq2", "", low_exp=-2, high_exp=2)
    h2.observe(100.0)                          # overflow bucket
    (s2,) = h2.samples()
    assert math.isinf(histogram_quantile(s2, 0.99))


def test_parse_peers_forms():
    from crdt_tpu.obs.fleet import parse_peers
    assert parse_peers("a=h:1, b=h2:2") == [("a", "h", 1),
                                            ("b", "h2", 2)]
    assert parse_peers("127.0.0.1:9") == \
        [("127.0.0.1:9", "127.0.0.1", 9)]
    with pytest.raises(ValueError):
        parse_peers("nope")


def test_evaluate_slo_unmeasured_and_scrape_errors():
    from crdt_tpu.obs import evaluate_slo
    v = evaluate_slo({})
    assert all(c["ok"] is None for c in v["checks"].values())
    assert v["ok"] is False                    # nothing measured
    v2 = evaluate_slo({"a": {"_scrape_error": "ConnectionError: x"}})
    assert v2["scrape_errors"] == ["a"] and v2["ok"] is False


def test_render_federation_series():
    from crdt_tpu.obs.fleet import render_federation
    snaps = {"a": {"canary": {"origin": 0, "n_origins": 1,
                              "base_slot": 31,
                              "observed": {"0": 5000}}},
             "down": {"_scrape_error": "refused"}}
    text = render_federation(snaps)
    assert 'crdt_tpu_fleet_up{instance="a"} 1' in text
    assert 'crdt_tpu_fleet_up{instance="down"} 0' in text
    assert ('crdt_tpu_canary_lag_seconds{observer="a",origin="0"} 0'
            in text)


def test_fleet_poller_end_to_end():
    """Two live GossipNodes with canary probes: the fleet poller
    scrapes the real metrics wire op into a complete matrix, and
    ``python -m crdt_tpu.obs fleet --once --json`` gates on it."""
    from crdt_tpu.obs.cli import main as obs_main
    from crdt_tpu.obs.fleet import lag_matrix, poll_fleet
    clk = FakeClock()
    a = _node(DenseCrdt("fleet-a", 32, wall_clock=clk))
    b = _node(DenseCrdt("fleet-b", 32, wall_clock=clk))
    with a, b:
        a.enable_canary(0, 2)
        b.enable_canary(1, 2)
        a.add_peer("b", b.host, b.port)
        b.add_peer("a", a.host, a.port)
        for _ in range(2):                     # beats cross both ways
            assert a.run_round() == {"b": "ok"}
            assert b.run_round() == {"a": "ok"}
        peers = [("a", a.host, a.port), ("b", b.host, b.port)]
        snaps = poll_fleet(peers)
        m = lag_matrix(snaps)
        assert m["origins"] == ["0", "1"]
        assert m["observers"] == ["a", "b"]
        assert m["complete"], m
        assert m["origin_peers"] == {"0": "a", "1": "b"}

        out = io.StringIO()
        spec = f"a={a.host}:{a.port},b={b.host}:{b.port}"
        rc = obs_main(["fleet", "--peers", spec, "--once", "--json",
                       "--lag-budget", "1e9"], out=out)
        doc = json.loads(out.getvalue())
        assert doc["matrix"]["complete"] is True
        assert doc["slo"]["checks"]["convergence_lag_s"]["ok"] is True
        assert rc == 0


def test_fleet_poller_marks_unreachable_peer():
    import socket
    from crdt_tpu.obs.fleet import poll_fleet
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    snaps = poll_fleet([("dead", "127.0.0.1", port)], timeout=2.0)
    assert "_scrape_error" in snaps["dead"]


# ------------------------- fleet plane: cross-replica trace rounds


def test_trace_round_ids_correlate_across_wire():
    """Initiator sync span and responder merge span carry the SAME
    round id — both ends live in this process, so both land in the
    one ring."""
    from crdt_tpu.net import (PeerConnection, SyncServer,
                              sync_packed_over_conn)
    a = DenseCrdt("tr-a", 32, wall_clock=FakeClock())
    b = DenseCrdt("tr-b", 32, wall_clock=FakeClock())
    a.put_batch([1, 2], [10, 20])
    ring = tracer()
    ring.enable()
    ring.clear()
    try:
        with SyncServer(b) as server:
            with PeerConnection(server.host, server.port,
                                timeout=5.0) as conn:
                sync_packed_over_conn(a, conn, since=None)
        (sync_span,) = [e for e in ring.events("sync")
                        if e.get("span") == "sync_packed"]
        rid = sync_span["rid"]
        assert rid.startswith("tr-a.r")
        recv = [e for e in ring.events("sync_recv")
                if e.get("rid") == rid]
        assert recv and recv[0]["origin"] == "tr-a"
        assert recv[0]["span"] == "push_packed_recv"
        assert "hlc_hi" in recv[0]
        # the responder's wire_frame events carry the rid too
        framed = [e for e in ring.events("wire_frame")
                  if e.get("rid") == rid]
        assert framed
    finally:
        ring.disable()
        ring.clear()


def test_in_process_sync_spans_carry_round_ids():
    from crdt_tpu.sync import sync_merkle
    a = DenseCrdt("ip-a", 64, wall_clock=FakeClock())
    b = DenseCrdt("ip-b", 64, wall_clock=FakeClock())
    a.put_batch([3], [30])
    ring = tracer()
    ring.enable()
    ring.clear()
    try:
        sync_merkle(a, b)
        (e,) = [e for e in ring.events("sync")
                if e.get("span") == "sync_merkle"]
        assert e["rid"].startswith("ip-a.r")
        assert e["peer"] == "ip-b"
    finally:
        ring.disable()
        ring.clear()


def test_replica_health_rollup_and_primaryless_groups():
    from crdt_tpu.obs.fleet import replica_health
    snaps = {
        "r0": {"replication": {"group": "g0", "role": "primary",
                               "lease_ms": 120.0, "hlc_head": "h0",
                               "followers": {"r1": {"durable": "h1"}}}},
        "r1": {"replication": {"group": "g0", "role": "follower",
                               "lease_ms": None, "hlc_head": "h1"}},
        "q0": {"replication": {"group": "g1", "role": "follower",
                               "lease_ms": None, "hlc_head": "h2"}},
        "plain": {"counters": {}},          # no replication section
        "dead": "_not_a_dict_",
    }
    health = replica_health(snaps)
    assert set(health["groups"]) == {"g0", "g1"}
    assert health["groups"]["g0"]["r0"]["role"] == "primary"
    assert "followers" in health["groups"]["g0"]["r0"]
    assert health["groups_without_primary"] == ["g1"]


def test_evaluate_slo_fails_group_without_live_primary():
    from crdt_tpu.obs.fleet import evaluate_slo
    snaps = {
        "r0": {"replication": {"group": "g0", "role": "follower",
                               "lease_ms": None, "hlc_head": "h0"}},
        "r1": {"replication": {"group": "g0", "role": "follower",
                               "lease_ms": None, "hlc_head": "h1"}},
    }
    verdict = evaluate_slo(snaps)
    check = verdict["checks"]["groups_without_primary"]
    assert check["value"] == 1.0 and check["ok"] is False
    assert verdict["ok"] is False
    assert verdict["replication"]["groups_without_primary"] == ["g0"]
    # promotion heals the verdict
    snaps["r1"]["replication"]["role"] = "primary"
    verdict = evaluate_slo(snaps)
    assert verdict["checks"]["groups_without_primary"]["ok"] is True
    assert verdict["ok"] is True


def test_format_replicas_surfaces_health_and_missing_primary():
    from crdt_tpu.obs.fleet import format_replicas, replica_health
    snaps = {
        "r0": {"replication": {"group": "g0", "role": "primary",
                               "lease_ms": 87.5, "hlc_head": "h0"}},
        "q0": {"replication": {"group": "g1", "role": "follower",
                               "lease_ms": None, "hlc_head": "h1"}},
    }
    out = format_replicas(replica_health(snaps))
    assert "primary" in out and "r0" in out
    assert "NO LIVE PRIMARY" in out and "g1" in out


# ------------------------------------------------ elastic topology health


def _wedge_snap(inflight, progress):
    return {"gauges": {
        "crdt_tpu_topology_change_inflight_since_ms":
            [{"labels": {}, "value": inflight}],
        "crdt_tpu_topology_change_progress_ms":
            [{"labels": {}, "value": progress}],
    }}


def test_topology_stall_unmeasured_on_pre_elastic_fleets():
    from crdt_tpu.obs.fleet import evaluate_slo, topology_stall_s
    snaps = {"r0": {"gauges": {}}}
    assert topology_stall_s(snaps, now_ms=1000.0) is None
    check = evaluate_slo(snaps)["checks"]["topology_change_stall_s"]
    assert check["value"] is None and check["ok"] is None


def test_topology_stall_zero_while_idle():
    from crdt_tpu.obs.fleet import evaluate_slo, topology_stall_s
    snaps = {"r0": _wedge_snap(0.0, 0.0)}
    assert topology_stall_s(snaps, now_ms=99_000.0) == 0.0
    check = evaluate_slo(snaps)["checks"]["topology_change_stall_s"]
    assert check["ok"] is True


def test_topology_stall_wedge_hard_fails_the_verdict():
    from crdt_tpu.obs.fleet import evaluate_slo, topology_stall_s
    # In flight since t=1s, last progress at t=2s, now t=40s: the
    # change has been stuck for 38 s — past the 30 s budget.
    snaps = {"r0": _wedge_snap(1_000.0, 2_000.0),
             "r1": _wedge_snap(0.0, 0.0)}
    assert topology_stall_s(snaps, now_ms=40_000.0) == 38.0
    verdict = evaluate_slo(snaps, now_ms=40_000.0)
    check = verdict["checks"]["topology_change_stall_s"]
    assert check["ok"] is False
    assert verdict["ok"] is False
    # a change making progress within budget passes
    verdict = evaluate_slo({"r0": _wedge_snap(1_000.0, 39_000.0)},
                           now_ms=40_000.0)
    assert verdict["checks"]["topology_change_stall_s"]["ok"] is True


def test_format_partitions_ranks_by_load():
    from crdt_tpu.obs.fleet import format_partitions
    snaps = {
        "p0": {"partition": {"addr": "h:1", "epoch": 4, "slots": 64,
                             "rows_committed": 10, "queue_depth": 0,
                             "shed": 0,
                             "last_scale": {"action": "split-donor",
                                            "epoch": 3,
                                            "peer": "h:2"}}},
        "p1": {"partition": {"addr": "h:2", "epoch": 4, "slots": 192,
                             "rows_committed": 900, "queue_depth": 2,
                             "shed": 0, "last_scale": None}},
        "stale": {"_scrape_error": "ConnectionError: x"},
    }
    out = format_partitions(snaps)
    lines = [ln for ln in out.splitlines() if ln.strip()]
    # hottest first: p1 (900 rows) outranks p0 (10 rows)
    assert lines[1].split()[0] == "1" and "p1" in lines[1]
    assert lines[2].split()[0] == "2" and "p0" in lines[2]
    assert "split-donor@e3" in out
    # no partition sections at all -> empty, not a header-only table
    assert format_partitions({"x": {"gauges": {}}}) == ""


def test_serve_snapshot_carries_partition_section():
    from crdt_tpu import FederatedTier
    with FederatedTier(64, partitions=2,
                       flush_interval=0.002) as fed:
        tier = fed.tiers[0]
        snap = tier._metrics_snapshot()
        part = snap["partition"]
        assert part["addr"] == tier.router.addr
        assert part["epoch"] == fed.table.epoch
        assert part["slots"] == fed.table.slots_of(tier.router.addr)
        assert part["rows_committed"] == 0
        # an unfederated tier has no partition identity to report
        from crdt_tpu import DenseCrdt, ServeTier
        with ServeTier(DenseCrdt("solo", 64)) as solo:
            assert "partition" not in solo._metrics_snapshot()

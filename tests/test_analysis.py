"""crdtlint self-tests: suppressions, host linter, lattice law search,
jaxpr audit goldens, CLI gate, and the runtime sanitizer.

The CLI smoke tests run ``python -m crdt_tpu.analysis`` exactly as CI
does (subprocess, fresh interpreter) — the shipped tree must come back
clean, and both planted fixtures must fail loudly.
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

from crdt_tpu.analysis.findings import (
    Finding, apply_suppressions, parse_suppressions)
from crdt_tpu.analysis.host_lint import lint_file, lint_source
from crdt_tpu.analysis import sanitizer

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "crdt_tpu.analysis", *argv],
        cwd=REPO, capture_output=True, text=True, timeout=300)


# ---------------------------------------------------------------- findings


def test_suppression_parsing_covers_own_and_next_line():
    src = (
        "x = 1\n"
        "# crdtlint: disable=wall-clock-read -- build artifact reaping\n"
        "t = time.time()\n"
        "u = time.time()\n")
    supp = parse_suppressions(src)
    assert supp.covers("wall-clock-read", 2)
    assert supp.covers("wall-clock-read", 3)
    assert not supp.covers("wall-clock-read", 4)
    assert not supp.covers("record-mutation", 3)
    assert supp.unexplained == []


def test_suppression_without_reason_is_its_own_finding():
    src = "# crdtlint: disable=socket-no-timeout\nconnect()\n"
    supp = parse_suppressions(src)
    assert supp.unexplained == [1]
    kept = apply_suppressions(
        [Finding(rule="socket-no-timeout", path="f.py", line=2,
                 message="m")], supp, "f.py")
    rules = {f.rule for f in kept}
    # a reasonless suppression is inert: the original finding survives
    # AND the malformed comment is flagged
    assert rules == {"socket-no-timeout", "suppression-without-reason"}


# --------------------------------------------------------------- host lint


def test_racy_gossip_fixture_trips_every_planted_rule():
    findings = lint_file(os.path.join(FIXTURES, "racy_gossip.py"))
    rules = sorted({f.rule for f in findings})
    assert rules == [
        "add-batch-unique-keys",
        "hlc-wall-compare",
        "lock-discipline",
        "record-mutation",
        "socket-no-timeout",
        "wall-clock-read",
    ]
    # both undisciplined registry touches, not just one
    assert sum(f.rule == "lock-discipline" for f in findings) == 2


def test_donated_buffer_reuse_flagged():
    src = (
        "def f(store, cs):\n"
        "    out = put_scatter(store, cs, t, me, donate=True)\n"
        "    return store.lt + out.lt\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "donated-buffer-reuse" in rules


def test_donated_buffer_rebind_not_flagged():
    src = (
        "def f(store, cs):\n"
        "    store = put_scatter(store, cs, t, me, donate=True)\n"
        "    return store.lt\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "donated-buffer-reuse" not in rules


def test_peer_connection_idle_timeout_none_flagged():
    src = (
        "from crdt_tpu.net import PeerConnection\n"
        "conn = PeerConnection('h', 1, idle_timeout=None)\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "socket-no-timeout"]
    assert len(findings) == 1
    assert "idle_timeout" in findings[0].message


def test_peer_connection_with_idle_timeout_not_flagged():
    src = (
        "from crdt_tpu.net import PeerConnection\n"
        "a = PeerConnection('h', 1)\n"
        "b = PeerConnection('h', 1, idle_timeout=5.0)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "socket-no-timeout" not in rules


def test_combiner_bypass_flagged_without_gate():
    src = (
        "def commit(self, slots, vals, t, me):\n"
        "    self._store = put_scatter(self._store, slots, vals, t, me)\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "scatter-combiner-bypass"]
    assert len(findings) == 1
    assert "drain" in findings[0].message


def test_combiner_bypass_gate_must_precede_the_write():
    # Draining AFTER the scatter is the bug, not the fix: the staged
    # backlog still commits over the direct write.
    src = (
        "def commit(self, slots, vals, t, me):\n"
        "    self._store = delete_scatter(self._store, slots, t, me)\n"
        "    self.drain_ingest()\n")
    rules = [f.rule for f in lint_source(src, "snippet.py")]
    assert "scatter-combiner-bypass" in rules


def test_combiner_bypass_drain_gate_passes():
    src = (
        "def put_slot_records(self, recs):\n"
        "    self.drain_ingest()\n"
        "    self._store = record_scatter(self._store, recs)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "scatter-combiner-bypass" not in rules


def test_collective_fallback_silent_flagged():
    src = (
        "class Node:\n"
        "    def __init__(self, group):\n"
        "        self._group = group\n"
        "    def round(self):\n"
        "        try:\n"
        "            self._group.join()\n"
        "        except Exception:\n"
        "            return self.socket_round()\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "collective-socket-fallback-silent"]
    assert len(findings) == 1
    assert "crdt_tpu_collective_fallback_total" in findings[0].message


def test_collective_fallback_counted_passes():
    src = (
        "class Node:\n"
        "    def __init__(self, group):\n"
        "        self._group = group\n"
        "    def round(self):\n"
        "        try:\n"
        "            self._group.join()\n"
        "        except Exception:\n"
        "            self.counter('crdt_tpu_collective_fallback_total')"
        ".inc()\n"
        "            return self.socket_round()\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "collective-socket-fallback-silent" not in rules


def test_collective_fallback_reraise_passes():
    # Loud is fine: a handler that re-raises never hides the downgrade.
    src = (
        "class Node:\n"
        "    def __init__(self, group):\n"
        "        self._group = group\n"
        "    def round(self):\n"
        "        try:\n"
        "            self._group.join()\n"
        "        except Exception:\n"
        "            raise\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "collective-socket-fallback-silent" not in rules


def test_collective_fallback_outside_grouped_class_not_flagged():
    # Without a pod-local group on the class, a .join() in a try is
    # unrelated (thread.join, path join on an object named group_dir).
    src = (
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._threads = []\n"
        "    def stop(self):\n"
        "        try:\n"
        "            self.group_thread.join()\n"
        "        except Exception:\n"
        "            pass\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "collective-socket-fallback-silent" not in rules


def test_combiner_bypass_staging_branch_passes():
    # put_batch's shape: branch on the staging handle, fall through to
    # the direct scatter only when no window is open.
    src = (
        "def put_batch(self, slots, vals, t, me):\n"
        "    if self._ingest is not None:\n"
        "        return self._ingest.stage(slots, vals, None)\n"
        "    self._store = put_scatter(self._store, slots, vals, t, me)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "scatter-combiner-bypass" not in rules


def test_combiner_bypass_suppressible_with_reason():
    src = (
        "def flush(self, owner):\n"
        "    # crdtlint: disable=scatter-combiner-bypass -- the flush"
        " IS the barrier\n"
        "    owner._store = ingest_scatter(owner._store, s, lt, v)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "scatter-combiner-bypass" not in rules
    assert "suppression-without-reason" not in rules


def test_pack_path_copy_flagged():
    # All three copy shapes the zero-copy refactor removed: a bytes()
    # staging copy, an np.asarray re-materialization, a .tobytes().
    src = (
        "def pack_rows(delta):\n"
        "    blob = bytes(delta.lt)\n"
        "    a = np.asarray(delta.slots, np.int32)\n"
        "    return blob + a.tobytes()\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "pack-path-extra-copy"]
    assert len(findings) == 3
    assert all("crdt_tpu_pack_copy_bytes_total" in f.message
               for f in findings)


def test_pack_path_rule_skips_unpack_and_merge():
    # The wire-IN side legitimately materializes host arrays — the
    # rule covers only the device→wire direction.
    src = (
        "def unpack_rows(meta, blob):\n"
        "    return bytes(blob)\n"
        "def merge_packed(self, packed, ids):\n"
        "    lanes = np.asarray(packed.lt)\n"
        "    return lanes.tobytes()\n"
        "def scatter_rows(x):\n"
        "    return bytes(x)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "pack-path-extra-copy" not in rules


def test_pack_path_rule_covers_frame_layer_names():
    # `encode` / `send_bytes_frame` don't contain "pack" but ARE the
    # pack path's last hop — covered by exact name.
    src = (
        "def send_bytes_frame(sock, bufs):\n"
        "    sock.sendall(bytes(bufs[0]))\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "pack-path-extra-copy" in rules


def test_pack_path_copy_suppressible_with_reason():
    src = (
        "def pack_rows(delta):\n"
        "    # crdtlint: disable=pack-path-extra-copy -- foreign-lane"
        " normalization, counted in the copy-bytes counter\n"
        "    a = np.ascontiguousarray(delta.slots, np.int32)\n"
        "    return a\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "pack-path-extra-copy" not in rules
    assert "suppression-without-reason" not in rules


def test_async_blocking_call_flags_all_three_families():
    # The three blocking families the serving tier must never touch
    # from a coroutine: time.sleep, a raw socket ctor, and the sync
    # frame helpers (which block on sendall/recv under the hood).
    src = (
        "async def handle(reader, writer):\n"
        "    time.sleep(0.01)\n"
        "    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)\n"
        "    send_frame(s, {'op': 'hello'}, None)\n"
        "    reply = recv_frame(s, None)\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "async-blocking-call"]
    assert len(findings) == 4
    assert all("coroutine handle()" in f.message for f in findings)


def test_async_blocking_call_ignores_sync_functions():
    # The exact same calls in a plain def are the NORMAL sync path
    # (net.py is built from them) — only coroutines are in scope.
    src = (
        "def handle(conn):\n"
        "    time.sleep(0.01)\n"
        "    send_frame(conn, {'op': 'hello'}, None)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "async-blocking-call" not in rules


def test_async_blocking_call_skips_nested_sync_def_and_executor_refs():
    # A sync helper DEFINED inside the coroutine is executor bait —
    # its body runs off-loop. Passing a frame helper by reference to
    # run_in_executor never calls it on the loop either.
    src = (
        "async def serve(loop, conn, frame):\n"
        "    def _pump():\n"
        "        send_frame(conn, frame, None)\n"
        "        time.sleep(0)\n"
        "    await loop.run_in_executor(None, _pump)\n"
        "    await loop.run_in_executor(None, recv_frame, conn, None)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "async-blocking-call" not in rules


def test_async_blocking_call_flags_blocking_socket_methods():
    src = (
        "async def relay(sock, blob):\n"
        "    sock.sendall(blob)\n"
        "    return sock.recv(4)\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "async-blocking-call"]
    assert len(findings) == 2


def test_async_blocking_call_awaited_calls_pass():
    # Directly-awaited calls are async APIs whatever their name —
    # asyncio's own loop.sock_connect / connect coroutines must pass.
    src = (
        "async def dial(loop, sock, addr, conn):\n"
        "    await loop.sock_connect(sock, addr)\n"
        "    await conn.connect()\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "async-blocking-call" not in rules


def test_async_blocking_call_suppressible_with_reason():
    src = (
        "async def shutdown(self, sock):\n"
        "    # crdtlint: disable=async-blocking-call -- teardown path,"
        " loop already draining\n"
        "    sock.sendall(b'bye')\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "async-blocking-call" not in rules
    assert "suppression-without-reason" not in rules


def test_metric_name_unprefixed_flagged():
    src = (
        "def setup(reg):\n"
        "    reg.counter('requests_total', 'h')\n"
        "    reg.gauge('depth')\n"
        "    reg.histogram('lat_seconds', 'h')\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "metric-name-unprefixed"]
    assert len(findings) == 3
    assert {f.line for f in findings} == {2, 3, 4}
    assert "namespace" in findings[0].message


def test_metric_name_prefixed_passes():
    src = (
        "def setup(reg):\n"
        "    c = reg.counter('crdt_tpu_requests_total', 'h')\n"
        "    c.inc(op='put', node=node)\n"
        "    reg.histogram('crdt_tpu_lat_seconds').observe(\n"
        "        0.5, trigger=trigger, peer=name)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "metric-name-unprefixed" not in rules


def test_metric_label_from_user_key_flagged():
    src = (
        "def record(c, h, key, slot):\n"
        "    c.inc(key=str(key))\n"
        "    h.observe(0.1, shard=slot % 4)\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "metric-name-unprefixed"]
    assert {f.line for f in findings} == {2, 3}
    assert "cardinality" in findings[0].message


def test_metric_label_rule_skips_jax_at_set():
    # jax's .at[slots].set(values, mode='drop') is not a metric sink:
    # the cardinality scan only inspects keyword values, and mode= is
    # a constant
    src = (
        "def commit(store, slots, values):\n"
        "    return store.at[slots].set(values, mode='drop')\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "metric-name-unprefixed" not in rules


def test_metric_name_suppressible_with_reason():
    src = (
        "def bridge(reg):\n"
        "    # crdtlint: disable=metric-name-unprefixed --"
        " exporting a foreign exporter's series verbatim\n"
        "    reg.counter('up', 'h')\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "metric-name-unprefixed" not in rules
    assert "suppression-without-reason" not in rules


def test_router_bypass_ungated_enqueue_flagged():
    src = (
        "class Tier:\n"
        "    def __init__(self, router=None):\n"
        "        self.router = router\n"
        "        self._q = []\n"
        "    def handle(self, msg, slot, value):\n"
        "        self._q.append((slot, value))\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "router-epoch-bypass"]
    assert len(findings) == 1
    assert findings[0].line == 6
    assert "handle()" in findings[0].message


def test_router_bypass_enqueue_before_gate_flagged():
    src = (
        "class Tier:\n"
        "    def __init__(self, router=None):\n"
        "        self.router = router\n"
        "        self._q = []\n"
        "    def handle(self, msg, slot, value):\n"
        "        self._q.append((slot, value))\n"
        "        verdict = self.router.check(slot, msg.get('epoch'),"
        " True)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "router-epoch-bypass" in rules


def test_router_bypass_gated_enqueue_clean():
    src = (
        "class Tier:\n"
        "    def __init__(self, router=None):\n"
        "        self.router = router\n"
        "        self._q = []\n"
        "    async def handle(self, msg, slot, value):\n"
        "        routed = await self._route_verdict(msg, slot, True)\n"
        "        if routed is not None:\n"
        "            return routed\n"
        "        self._q.append((slot, value))\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "router-epoch-bypass" not in rules


def test_router_bypass_ignores_routerless_classes():
    # a queue-owning class with no router carries no partition
    # ownership contract — nothing to gate
    src = (
        "class Combiner:\n"
        "    def __init__(self):\n"
        "        self._q = []\n"
        "    def push(self, item):\n"
        "        self._q.append(item)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "router-epoch-bypass" not in rules


def test_router_bypass_covers_mpsc_push_spelling():
    # the MPSC-era enqueue (self._q.push) carries the same routing
    # contract as the list-era append, and the batched admission call
    # (check_batch) gates it
    src = (
        "class Tier:\n"
        "    def __init__(self, router=None):\n"
        "        self.router = router\n"
        "        self._q = MpscQueue()\n"
        "    def ungated(self, slot, value):\n"
        "        self._q.push(('j', slot, value))\n"
        "    def gated(self, slots, epoch):\n"
        "        admit = self.router.check_batch(slots, epoch, True)\n"
        "        self._q.push(('b', slots))\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "router-epoch-bypass"]
    assert len(findings) == 1
    assert findings[0].line == 6
    assert "ungated()" in findings[0].message


def test_combiner_enqueue_bare_append_flagged():
    src = (
        "class Tier:\n"
        "    def __init__(self, crdt):\n"
        "        self._q = MpscQueue()\n"
        "        self._wc = None\n"
        "    def handle(self, slot, value, fut):\n"
        "        self._q.append((slot, value, fut))\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "combiner-enqueue-unsafe"]
    assert len(findings) == 1
    assert findings[0].line == 6
    assert "handle()" in findings[0].message
    assert ".push" in findings[0].message


def test_combiner_enqueue_mpsc_push_clean():
    src = (
        "class Tier:\n"
        "    def __init__(self, crdt):\n"
        "        self._q = MpscQueue()\n"
        "        self._wc = None\n"
        "    def handle(self, slot, value, fut):\n"
        "        self._q.push((slot, value, fut))\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "combiner-enqueue-unsafe" not in rules


def test_combiner_enqueue_ignores_non_combiner_classes():
    # no self._wc in __init__ -> not a combiner owner; a plain list
    # queue drained on the same thread carries no MPSC contract
    src = (
        "class Hub:\n"
        "    def __init__(self):\n"
        "        self._q = []\n"
        "    def handle(self, item):\n"
        "        self._q.append(item)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "combiner-enqueue-unsafe" not in rules


def test_combiner_enqueue_init_exempt_and_inner_targets_flagged():
    # __init__ is construction (happens-before publication); any
    # deeper self._q... target (a stripe's raw list) is still a
    # bypass of the MPSC gate
    src = (
        "class Tier:\n"
        "    def __init__(self, crdt):\n"
        "        self._q = MpscQueue()\n"
        "        self._q.append = None\n"
        "        self._wc = None\n"
        "    def sneak(self, entry):\n"
        "        self._q._stripes[0].items.append(entry)\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "combiner-enqueue-unsafe"]
    assert len(findings) == 1
    assert findings[0].line == 7
    assert "sneak()" in findings[0].message


def test_combiner_enqueue_shipped_serve_tier_clean():
    # pin: the real serving tier routes every producer through the
    # MPSC gate — this is the tree-level guarantee the rule exists for
    import crdt_tpu.serve as serve_mod
    findings = [f for f in lint_file(serve_mod.__file__)
                if f.rule == "combiner-enqueue-unsafe"]
    assert findings == []


def test_ack_before_replicate_ungated_ack_flagged():
    src = (
        "class Tier:\n"
        "    def __init__(self, replicator=None):\n"
        "        self.replicator = replicator\n"
        "    def tick(self, futs):\n"
        "        for fut in futs:\n"
        "            fut.set_result('acked')\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "ack-before-replicate"]
    assert len(findings) == 1
    assert findings[0].line == 6
    assert "write-concern barrier" in findings[0].message


def test_ack_before_replicate_ack_before_barrier_flagged():
    # barrier is consulted, but only AFTER the ack already resolved
    src = (
        "class Tier:\n"
        "    def __init__(self, replicator=None):\n"
        "        self.replicator = replicator\n"
        "    def tick(self, fut):\n"
        "        fut.set_result('acked')\n"
        "        ok, why = self.replicator.barrier()\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "ack-before-replicate" in rules


def test_ack_before_replicate_barrier_first_clean():
    src = (
        "class Tier:\n"
        "    def __init__(self, replicator=None):\n"
        "        self.replicator = replicator\n"
        "    def tick(self, fut):\n"
        "        rep = self.replicator\n"
        "        if rep is not None:\n"
        "            ok, why = rep.barrier()\n"
        "        fut.set_result('acked')\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "ack-before-replicate" not in rules


def test_ack_before_replicate_ignores_replicatorless_classes():
    # a future-resolving class with no replicator carries no write-
    # concern contract — nothing to gate
    src = (
        "class Combiner:\n"
        "    def __init__(self):\n"
        "        self._q = []\n"
        "    def flush(self, fut):\n"
        "        fut.set_result(len(self._q))\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "ack-before-replicate" not in rules


def test_scale_fence_missing_epoch_check_flagged():
    src = (
        "class Scaler:\n"
        "    def __init__(self, fed):\n"
        "        self.fed = fed\n"
        "        self._inflight = None\n"
        "    def act(self, dec):\n"
        "        if self._inflight is not None:\n"
        "            return False\n"
        "        self.fed.split_hot(src=dec['src'])\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "scale-decision-unfenced"]
    assert len(findings) == 1
    assert findings[0].line == 8
    assert "table-epoch fence" in findings[0].message


def test_scale_fence_missing_inflight_guard_flagged():
    src = (
        "class Scaler:\n"
        "    def __init__(self, fed):\n"
        "        self.fed = fed\n"
        "    def act(self, dec):\n"
        "        if self.fed.table.epoch != dec['epoch']:\n"
        "            return False\n"
        "        self.fed.merge_cold(src=dec['src'])\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "scale-decision-unfenced"]
    assert len(findings) == 1
    assert "in-flight guard" in findings[0].message


def test_scale_fence_both_fences_first_clean():
    src = (
        "class Scaler:\n"
        "    def __init__(self, fed):\n"
        "        self.fed = fed\n"
        "        self._inflight = None\n"
        "    def act(self, dec):\n"
        "        if self._inflight is not None:\n"
        "            return False\n"
        "        if self.fed.table.epoch != dec['epoch']:\n"
        "            return False\n"
        "        self._inflight = dec['action']\n"
        "        self.fed.merge_cold(src=dec['src'])\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "scale-decision-unfenced" not in rules


def test_scale_fence_ignores_fedless_classes():
    # a test harness poking split_hot directly owns no federation
    # handle — no controller contract to enforce
    src = (
        "class Driver:\n"
        "    def __init__(self):\n"
        "        self.runs = 0\n"
        "    def kick(self, fed):\n"
        "        fed.split_hot(src=0)\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "scale-decision-unfenced" not in rules


def test_shipped_tree_lints_clean():
    from crdt_tpu.analysis.host_lint import lint_package
    import crdt_tpu
    pkg_root = os.path.dirname(os.path.abspath(crdt_tpu.__file__))
    findings = lint_package(pkg_root)
    assert findings == [], "\n".join(f.format() for f in findings)


# -------------------------------------------------------------- law search


def test_broken_mean_join_fixture_fails_all_three_laws():
    from crdt_tpu.analysis.lattice_laws import run_laws
    from tests.fixtures.broken_merge import LAW_TARGETS
    findings = run_laws(LAW_TARGETS, seeds=(0, 1, 2))
    rules = {f.rule for f in findings}
    assert rules == {"law-idempotence", "law-commutativity",
                     "law-associativity"}
    # every counterexample must carry the reproducible input
    for f in findings:
        assert "violating input (seed=" in (f.detail or "")


def test_builtin_law_targets_hold():
    from crdt_tpu.analysis.lattice_laws import builtin_targets, run_laws
    findings = run_laws(builtin_targets(), seeds=(0,))
    assert findings == [], "\n".join(f.format() for f in findings)


# ------------------------------------------------------------- jaxpr audit


def test_jaxpr_audit_builtin_targets_clean():
    from crdt_tpu.analysis.jaxpr_audit import audit_all, builtin_targets
    reports, findings = audit_all(builtin_targets())
    assert findings == [], "\n".join(f.format() for f in findings)
    assert len(reports) >= 11


def test_pallas_fanin_block_matches_golden():
    from crdt_tpu.analysis.jaxpr_audit import audit_all, builtin_targets
    targets = [t for t in builtin_targets()
               if t.name == "parallel.pallas_fanin_block[per-shard]"]
    assert targets, "per-shard Pallas fan-in audit target missing"
    reports, findings = audit_all(targets)
    assert findings == []
    with open(os.path.join(REPO, "tests", "goldens",
                           "fanin_pallas_audit.json")) as fh:
        golden = json.load(fh)
    assert reports[0].golden() == golden


# --------------------------------------------------------------- sanitizer


def test_sanitizer_enabled_reads_env_live(monkeypatch):
    monkeypatch.delenv("CRDT_TPU_SANITIZE", raising=False)
    assert not sanitizer.enabled()
    monkeypatch.setenv("CRDT_TPU_SANITIZE", "0")
    assert not sanitizer.enabled()
    monkeypatch.setenv("CRDT_TPU_SANITIZE", "1")
    assert sanitizer.enabled()


def test_sanitizer_sparse_join_accepts_dominating_store():
    store = types.SimpleNamespace(
        lt=np.array([10, 20, 30], np.int64),
        node=np.array([2, 1, 3], np.int32))
    sanitizer.check_dense_sparse_join(
        store, slots=np.array([0, 2]), lt=np.array([10, 5]),
        node=np.array([1, 9]))


def test_sanitizer_sparse_join_raises_on_lost_update():
    store = types.SimpleNamespace(
        lt=np.array([10, 20], np.int64),
        node=np.array([2, 1], np.int32))
    with pytest.raises(sanitizer.LatticeViolation, match="slot 1"):
        sanitizer.check_dense_sparse_join(
            store, slots=np.array([0, 1]), lt=np.array([10, 20]),
            node=np.array([1, 4]))


def test_sanitizer_dense_join_raises_on_dropped_row():
    store = types.SimpleNamespace(
        lt=np.array([5, 5], np.int64), node=np.array([0, 0], np.int32))
    cs = types.SimpleNamespace(
        lt=np.array([[5, 9]], np.int64),
        node=np.array([[0, 1]], np.int32),
        valid=np.array([[True, True]]))
    with pytest.raises(sanitizer.LatticeViolation, match="slot 1"):
        sanitizer.check_dense_join(store, cs)


def test_sanitizer_catches_merge_that_drops_writes(monkeypatch):
    """End-to-end: a scalar CRDT whose merge silently drops remote
    winners trips check_scalar_join under CRDT_TPU_SANITIZE=1."""
    monkeypatch.setenv("CRDT_TPU_SANITIZE", "1")
    from crdt_tpu.models.map_crdt import MapCrdt
    a = MapCrdt("a")
    b = MapCrdt("b")
    b.put("k", 1)
    payload = b.record_map()
    # sanity: an honest merge passes with the sanitizer armed
    honest = MapCrdt("c")
    honest.merge(dict(payload))
    # now drop the winner write on its way to storage
    monkeypatch.setattr(a, "put_records", lambda record_map: None)
    with pytest.raises(sanitizer.LatticeViolation):
        a.merge(payload)


# --------------------------------------------------------------------- CLI


def test_cli_json_clean_on_shipped_tree():
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    names = {r["target"] for r in payload["jaxpr_reports"]}
    assert "parallel.pallas_fanin_block[per-shard]" in names
    # The fast-path completeness gate's required kernels are present
    # (their absence would have failed the run above).
    assert "dense.merge_repack_step" in names
    assert "pallas.ingest_scatter_tiles[interpret]" in names


def test_fastpath_completeness_gate_fails_on_missing_kernel():
    from crdt_tpu.analysis.cli import _fastpath_completeness
    findings = _fastpath_completeness(
        ["dense.merge_repack_step",
         "parallel.collective_join[member2]"])
    assert [f.rule for f in findings] == ["fastpath-kernel-unregistered"]
    assert "ingest_scatter_tiles" in findings[0].message
    assert _fastpath_completeness(
        ["dense.merge_repack_step",
         "pallas.ingest_scatter_tiles[interpret]",
         "parallel.collective_join[member2]"]) == []


def test_fastpath_completeness_requires_collective_on_multidevice():
    # The collective-join audit target only exists on >= 2 devices
    # (the shard_map needs a member mesh); under the 8-virtual-device
    # test platform its absence must be a finding like any other
    # required kernel's.
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("single-device host: requirement is exempt")
    from crdt_tpu.analysis.cli import _fastpath_completeness
    findings = _fastpath_completeness(
        ["dense.merge_repack_step",
         "pallas.ingest_scatter_tiles[interpret]"])
    assert [f.rule for f in findings] == ["fastpath-kernel-unregistered"]
    assert "collective_join" in findings[0].message


def test_ledger_completeness_gate_fails_on_missing_kernel():
    from crdt_tpu.analysis.cli import (_LEDGER_REQUIRED,
                                       _ledger_completeness)
    missing = set(_LEDGER_REQUIRED) - {"dense.merge_repack_step"}
    findings = _ledger_completeness(registered=missing)
    assert [f.rule for f in findings] == ["dispatch-ledger-unregistered"]
    assert "dense.merge_repack_step" in findings[0].message
    # an unregistered extra never trips the gate; the full set is clean
    assert _ledger_completeness(
        registered=set(_LEDGER_REQUIRED) | {"extra.kernel"}) == []


def test_ledger_completeness_gate_clean_on_shipped_tree():
    # no `registered=`: the gate imports the instrumented modules and
    # reads the live default ledger — exactly what the default run does
    from crdt_tpu.analysis.cli import _ledger_completeness
    assert _ledger_completeness() == []


def test_cli_nonzero_with_counterexample_on_broken_fixture():
    proc = _run_cli("--law-fixture",
                    os.path.join(FIXTURES, "broken_merge.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "law-idempotence" in proc.stdout
    assert "violating input (seed=" in proc.stdout


def test_cli_nonzero_on_racy_fixture():
    proc = _run_cli("--lint", os.path.join(FIXTURES, "racy_gossip.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "lock-discipline" in proc.stdout
    assert "socket-no-timeout" in proc.stdout


# ---------------------------------------------------- concurrency analyzer


def test_deadlock_cycle_fixture_exact_findings():
    from crdt_tpu.analysis.concurrency import analyze_paths
    findings = analyze_paths(
        [os.path.join(FIXTURES, "deadlock_cycle.py")])
    assert [f.rule for f in findings] == [
        "lock-order-cycle", "lock-order-undeclared"], findings
    cycle, undeclared = findings
    # the cycle is pinned at the offending (inverted) acquisition and
    # the witness path walks through the helper the edge hides in
    assert "PairStore._a" in cycle.message
    assert "PairStore._b" in cycle.message
    assert "_grab_a" in cycle.detail
    assert "Indexer._idx" in undeclared.message
    assert "Journal._j" in undeclared.message


def test_blocking_hold_fixture_exact_findings():
    from crdt_tpu.analysis.concurrency import analyze_paths
    findings = analyze_paths(
        [os.path.join(FIXTURES, "blocking_hold.py")])
    assert [f.rule for f in findings] == [
        "blocking-under-lock", "blocking-under-lock"], findings
    socket_f, sleep_f = findings
    assert "sendall" in socket_f.message
    assert "Shipper._lock" in socket_f.message
    assert "time.sleep" in sleep_f.message
    # the sleep lives in a helper: interprocedural witness required
    assert "_backoff" in sleep_f.detail


def test_cli_nonzero_on_deadlock_fixture():
    proc = _run_cli("--lint",
                    os.path.join(FIXTURES, "deadlock_cycle.py"),
                    "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert [f["rule"] for f in payload["findings"]] == [
        "lock-order-cycle", "lock-order-undeclared"]


def test_cli_nonzero_on_blocking_hold_fixture():
    proc = _run_cli("--lint",
                    os.path.join(FIXTURES, "blocking_hold.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert proc.stdout.count("blocking-under-lock") >= 2


def test_shipped_tree_concurrency_clean():
    from crdt_tpu.analysis.concurrency import analyze_package
    import crdt_tpu
    pkg_root = os.path.dirname(os.path.abspath(crdt_tpu.__file__))
    assert analyze_package(pkg_root) == []


def test_concurrency_suppression_honored():
    from crdt_tpu.analysis.concurrency import analyze_source
    src = (
        "import threading, time\n"
        "class C:\n"
        "    _CRDTLINT_LOCK_ORDER = ('_l',)\n"
        "    def f(self):\n"
        "        with self._l:\n"
        "            # crdtlint: disable=blocking-under-lock -- bounded\n"
        "            time.sleep(0.01)\n")
    assert analyze_source(src, "c.py") == []
    # without the comment the finding is real
    assert [f.rule for f in analyze_source(
        src.replace("            # crdtlint: disable="
                    "blocking-under-lock -- bounded\n", ""),
        "c.py")] == ["blocking-under-lock"]


def test_contract_only_cycle_reported_at_declaration():
    from crdt_tpu.analysis.concurrency import analyze_source
    # two contracts that admit a cycle with no witnessing site
    src = (
        "class A:\n"
        "    _CRDTLINT_LOCK_ORDER = ('_x', ('peer_y', 'B._y'))\n"
        "class B:\n"
        "    _CRDTLINT_LOCK_ORDER = ('_y', ('peer_x', 'A._x'))\n")
    findings = analyze_source(src, "c.py")
    assert [f.rule for f in findings] == ["lock-order-cycle"]
    assert "mutually inconsistent" in findings[0].message


def test_acquire_call_counts_as_hold():
    from crdt_tpu.analysis.concurrency import analyze_source
    src = (
        "import threading, time\n"
        "class C:\n"
        "    _CRDTLINT_LOCK_ORDER = ('_l',)\n"
        "    def f(self):\n"
        "        self._l.acquire()\n"
        "        try:\n"
        "            time.sleep(0.01)\n"
        "        finally:\n"
        "            self._l.release()\n")
    assert [f.rule for f in analyze_source(src, "c.py")] == [
        "blocking-under-lock"]


def test_async_with_is_not_a_thread_lock_acquisition():
    from crdt_tpu.analysis.concurrency import analyze_source
    src = (
        "class C:\n"
        "    _CRDTLINT_LOCK_ORDER = ('_l',)\n"
        "    async def f(self):\n"
        "        async with self._l:\n"
        "            import time\n"
        "            time.sleep(0.01)\n")
    # the asyncio lock orders the event loop, not threads — the
    # concurrency pass must not treat it as a held thread lock
    assert analyze_source(src, "c.py") == []


def test_thread_unnamed_flagged_and_named_clean():
    flagged = lint_source(
        "import threading\n"
        "t = threading.Thread(target=f, daemon=True)\n", "t.py")
    assert [f.rule for f in flagged] == ["thread-unnamed"]
    named = lint_source(
        "import threading\n"
        "t = threading.Thread(target=f, daemon=True, name='worker')\n",
        "t.py")
    assert named == []


def test_async_sync_with_contract_lock_flagged():
    src = (
        "class C:\n"
        "    _CRDTLINT_LOCK_ORDER = ('_l',)\n"
        "    async def f(self):\n"
        "        with self._l:\n"
        "            return 1\n")
    findings = lint_source(src, "c.py")
    assert [f.rule for f in findings] == ["async-blocking-call"]
    assert "_l" in findings[0].message
    # a non-contract with block stays exempt (ordinary context
    # managers are not locks) ...
    assert lint_source(src.replace("('_l',)", "()"), "c.py") == []
    # ... and so does `async with` on the same attribute
    assert lint_source(src.replace("with self._l:",
                                   "pass\n"
                                   "    async def g(self):\n"
                                   "        async with self._l:"),
                       "c.py") == []


# ---------------------------------------------------- runtime lock sanitizer


def test_make_lock_is_plain_lock_when_disabled(monkeypatch):
    import threading
    monkeypatch.delenv("CRDT_TPU_SANITIZE", raising=False)
    from crdt_tpu.analysis.concurrency import OrderedLock, make_lock
    plain = make_lock("T.l", 10)
    assert isinstance(plain, type(threading.Lock()))
    reentrant = make_lock("T.r", 10, rlock=True)
    assert not isinstance(reentrant, OrderedLock)
    with reentrant:
        with reentrant:  # RLock semantics preserved
            pass


def test_runtime_sanitizer_catches_inversion_without_hang(monkeypatch):
    import threading
    monkeypatch.setenv("CRDT_TPU_SANITIZE", "1")
    from crdt_tpu.analysis.concurrency import OrderedLock, make_lock
    from crdt_tpu.obs.registry import default_registry
    from crdt_tpu.obs.trace import tracer

    a = make_lock("InvA.a", 10)
    b = make_lock("InvB.b", 20)
    assert isinstance(a, OrderedLock)

    ring = tracer()
    was_enabled = ring.enabled
    ring.enabled = True
    try:
        ok = threading.Event()

        def conforming():
            with a:
                with b:
                    ok.set()

        t1 = threading.Thread(target=conforming, name="inv-good")
        t1.start()
        t1.join(timeout=10)
        assert ok.is_set() and not t1.is_alive()

        def inverted():
            with b:
                with a:   # rank 10 while holding rank 20
                    pass

        t2 = threading.Thread(target=inverted, name="inv-bad")
        t2.start()
        t2.join(timeout=10)
        # the sanitizer reports, it never blocks differently — the
        # inverted thread must COMPLETE
        assert not t2.is_alive()

        counter = default_registry().counter(
            "crdt_tpu_lock_order_violations_total")
        assert counter.value(held="InvB.b", acquiring="InvA.a") == 1
        # the conforming order produced no count
        assert counter.value(held="InvA.a", acquiring="InvB.b") == 0

        events = [e for e in ring.events()
                  if e.get("kind") == "lock_order_violation"]
        assert events, "no trace event emitted"
        assert events[-1]["held"] == "InvB.b"
        assert events[-1]["acquiring"] == "InvA.a"
        assert events[-1]["thread"] == "inv-bad"
    finally:
        ring.enabled = was_enabled


def test_ordered_lock_rlock_reentry_is_not_a_violation(monkeypatch):
    monkeypatch.setenv("CRDT_TPU_SANITIZE", "1")
    from crdt_tpu.analysis.concurrency import OrderedLock, make_lock
    from crdt_tpu.obs.registry import default_registry

    r = make_lock("Reent.r", 30, rlock=True)
    assert isinstance(r, OrderedLock)
    with r:
        with r:
            pass
    counter = default_registry().counter(
        "crdt_tpu_lock_order_violations_total")
    assert counter.value(held="Reent.r", acquiring="Reent.r") == 0


# --- histogram-ceiling-gate (PR 18) ---

def test_histogram_ceiling_gate_direct_compare_flagged():
    src = (
        "def decide(snap, budget_s):\n"
        "    if histogram_quantile(snap, 0.99) > budget_s:\n"
        "        split()\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "histogram-ceiling-gate" in rules


def test_histogram_ceiling_gate_taint_through_max_fold_flagged():
    # The realistic controller shape: quantile folded through an
    # assignment and a max() before the gate — the taint must follow.
    src = (
        "def decide(samples, ack_p99_budget_s):\n"
        "    ceil = None\n"
        "    for s in samples:\n"
        "        v = histogram_quantile(s, 0.99)\n"
        "        ceil = v if ceil is None else max(ceil, v)\n"
        "    if ceil is not None and ceil > ack_p99_budget_s:\n"
        "        return 'split'\n")
    findings = [f for f in lint_source(src, "snippet.py")
                if f.rule == "histogram-ceiling-gate"]
    assert findings
    # pinned to the gate line, not the fold
    assert findings[0].line == 6


def test_histogram_ceiling_gate_display_only_not_flagged():
    # Rendering the ceiling (no budget in sight) is fine — ceilings
    # are display-only; so are non-budget compares like the None /
    # inf guards.
    src = (
        "def render(samples):\n"
        "    rows = []\n"
        "    for s in samples:\n"
        "        v = histogram_quantile(s, 0.99)\n"
        "        if v is not None and v != float('inf'):\n"
        "            rows.append(v * 1e3)\n"
        "    return rows\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "histogram-ceiling-gate" not in rules


def test_histogram_ceiling_gate_sketch_gate_not_flagged():
    # The migration target: gating the same budget on the sketch
    # quantile must stay clean even with a ceiling computed alongside
    # for display.
    src = (
        "def decide(snapshots, ack_p99_budget_s):\n"
        "    ceil = histogram_quantile(snapshots[0], 0.99)\n"
        "    sk = fleet_sketch(snapshots)\n"
        "    p99 = sk.quantile(0.99)\n"
        "    show(ceil)\n"
        "    return p99 is not None and p99 <= ack_p99_budget_s\n")
    rules = {f.rule for f in lint_source(src, "snippet.py")}
    assert "histogram-ceiling-gate" not in rules


def test_histogram_ceiling_gate_shipped_fleet_fallback_suppressed():
    # fleet.py's pre-sketch fallback compares the ceiling against the
    # budget on purpose (three-valued: pass / floor-breach / None) —
    # it must stay suppressed with a reason, not exempted silently.
    import crdt_tpu.obs.fleet as fleet
    findings = [f for f in lint_file(fleet.__file__)
                if f.rule in ("histogram-ceiling-gate",
                              "suppression-without-reason")]
    assert findings == []

"""Native runtime components (host side).

The TPU compute path is JAX/XLA/Pallas; the host-side wire boundary
(JSON codec, crdt_json.dart:8-37) is scalar string work where CPython
is the bottleneck, so its hot primitive — the per-record HLC string
codec — has a C implementation (`hlccodec.c`), compiled on first use
with the system C compiler and cached next to the source.

Everything degrades silently: no compiler, a failed build, or
``CRDT_TPU_NO_NATIVE=1`` all fall back to the pure-Python codec
(semantics are identical; the C path only accepts the canonical wire
shape and defers everything else to Python per-item).
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
from typing import Optional

_mod = None
_tried = False


def load() -> Optional[object]:
    """The `_hlccodec` extension module, or None when unavailable."""
    global _mod, _tried
    if _tried:
        return _mod
    _tried = True
    if os.environ.get("CRDT_TPU_NO_NATIVE"):
        return None
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "hlccodec.c")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    try:
        # The cache key is the SOURCE CONTENT, not mtimes: archive
        # extraction (sdist/wheel upgrades) preserves timestamps, so a
        # stale .so compiled from an older source could otherwise load
        # and miss newer symbols (AttributeError instead of the
        # documented silent degradation).
        import hashlib
        with open(src, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:12]
        so = os.path.join(here, f"_hlccodec_{tag}{suffix}")
        if not os.path.exists(so):
            cc = (os.environ.get("CC") or sysconfig.get_config_var("CC")
                  or "cc").split()[0]
            include = sysconfig.get_paths()["include"]
            # Build to a private temp path and rename into place:
            # os.rename is atomic, so a concurrent process never dlopens
            # a half-written .so (it sees either the old file or the
            # complete new one).
            tmp = f"{so}.build{os.getpid()}"
            subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", f"-I{include}", src,
                 "-o", tmp],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
            # Content-hash naming leaves one stale binary behind per
            # source update; reap siblings with a different tag so
            # upgrades don't accumulate .so files without bound.
            # Unlinking a file another process has dlopen'd is safe on
            # POSIX (the mapping holds the inode); best-effort only.
            # Only reap AGED files: two long-lived processes running
            # different source versions would otherwise delete each
            # other's fresh binary and recompile on every load
            # (load-after-unlink is the unsafe half).
            import time
            # crdtlint: disable=wall-clock-read -- file-age reaping of stale build artifacts, nowhere near HLC clock paths
            cutoff = time.time() - 24 * 3600
            for name in os.listdir(here):
                if (name.startswith("_hlccodec_")
                        and name.endswith(suffix)
                        and name != os.path.basename(so)):
                    try:
                        path = os.path.join(here, name)
                        if os.path.getmtime(path) < cutoff:
                            os.unlink(path)
                    except OSError:
                        pass
        spec = importlib.util.spec_from_file_location(
            "crdt_tpu.native._hlccodec", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _mod = mod
    except Exception:
        _mod = None
    return _mod

"""Property-based tests (hypothesis): the clock algebra and codecs
hold for ALL inputs, not just the reference's golden vectors.

Complements the ported golden tests (test_hlc.py) and the seeded
merge-algebra checks in the conformance kit with generated cases —
the SURVEY §4 "what the reference lacks" layer.
"""

import string

import pytest

# Collection must not die on hosts without hypothesis (the tier-1
# harness previously leaned on --continue-on-collection-errors here).
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from crdt_tpu import Hlc, MapCrdt, Record
from crdt_tpu.native import load as load_native
from crdt_tpu.testing import FakeClock

settings.register_profile("crdt", max_examples=60, deadline=None)
settings.load_profile("crdt")

# The reference parse scans for the first dash after the LAST colon
# (hlc.dart:40-44), so a node id containing ':' is unparseable there
# too — same constraint here. Dashes in node ids ARE supported.
NODE_ALPHABET = string.ascii_letters + string.digits + "-_."
nodes = st.text(NODE_ALPHABET, min_size=1, max_size=16).filter(
    lambda s: not s.startswith("-"))
# Year range 1-9999 (the wire codec's fail-fast window).
millis_vals = st.integers(min_value=-62_135_596_800_000,
                          max_value=253_402_300_799_999)
counters = st.integers(min_value=0, max_value=0xFFFF)
hlcs = st.builds(Hlc, millis_vals, counters, nodes)


class TestHlcCodecs:
    @given(hlcs)
    def test_string_roundtrip(self, h):
        assert Hlc.parse(str(h)) == h

    @given(st.builds(Hlc, st.integers(min_value=0, max_value=(1 << 45)),
                     counters, nodes))
    def test_pack_roundtrip(self, h):
        # pack() is defined for non-negative millis (the reference's
        # base36 rjust encoding has no sign slot, hlc.dart:110-121).
        back = Hlc.unpack(h.pack())
        assert (back.millis, back.counter, str(back.node_id)) == \
            (h.millis, h.counter, str(h.node_id))

    @given(hlcs)
    def test_logical_time_roundtrip(self, h):
        back = Hlc.from_logical_time(h.logical_time, h.node_id)
        assert back == h and back.millis == h.millis \
            and back.counter == h.counter

    @given(st.lists(hlcs, min_size=2, max_size=8, unique_by=str))
    def test_string_order_matches_pack_order(self, hs):
        # pack() is the fixed-width SORTABLE codec (hlc.dart:110-121):
        # sorting packed strings == sorting Hlcs, whenever node ids are
        # strings of equal length (the reference's randomNodeId shape).
        hs = [Hlc(h.millis, h.counter, str(h.node_id)[:1].ljust(4, "x"))
              for h in hs if h.millis >= 0]
        assert sorted(hs) == sorted(hs, key=lambda h: h.pack())


class TestClockAlgebra:
    @given(hlcs, st.integers(min_value=0, max_value=1 << 45))
    def test_send_advances(self, canonical, wall):
        try:
            out = Hlc.send(canonical, millis=wall)
        except Exception:
            return  # drift/overflow guards may fire; that's their job
        assert out > canonical or out.millis >= canonical.millis
        assert out.node_id == canonical.node_id
        assert out.logical_time > canonical.logical_time

    @given(hlcs, hlcs)
    def test_recv_absorbs(self, canonical, remote):
        wall = max(canonical.millis, remote.millis)
        if str(canonical.node_id) == str(remote.node_id):
            return  # duplicate-node guard domain, tested elsewhere
        try:
            out = Hlc.recv(canonical, remote, millis=wall)
        except Exception:
            return
        # Canonical never regresses and ends >= the remote time seen.
        assert out.logical_time >= canonical.logical_time
        assert out.logical_time >= remote.logical_time
        assert out.node_id == canonical.node_id

    @given(hlcs, hlcs, hlcs)
    def test_total_order(self, a, b, c):
        key = lambda h: (h.logical_time, str(h.node_id))
        assert (a < b) == (key(a) < key(b))
        assert (a == b) == (key(a) == key(b))
        if a <= b and b <= c:
            assert a <= c


def _record(ms, c, n):
    # The value is a FUNCTION of the HLC: real systems can only repeat
    # an HLC for the same event (the node id is inside it), so
    # identical HLCs must mean identical records — without this
    # invariant the LWW local-wins-tie rule makes merge legitimately
    # order-dependent and the algebra properties are false.
    h = Hlc(ms, c, n)
    value = None if (ms + c) % 4 == 0 else (ms * 31 + c) % 997
    return Record(h, value, h)


record_maps = st.dictionaries(
    st.text(string.ascii_lowercase, min_size=1, max_size=4),
    st.builds(
        _record,
        st.integers(min_value=1_700_000_000_000,
                    max_value=1_700_000_000_040),
        counters, st.sampled_from(["nodeA", "nodeB", "nodeZ"])),
    max_size=6)


def _state(crdt):
    """Converged-state snapshot: (hlc, value) per key; `modified` is
    local-only and excluded (record.dart:34-35)."""
    return {k: (r.hlc, r.value) for k, r in crdt.record_map().items()}


class TestMergeAlgebra:
    def fresh(self):
        return MapCrdt("local",
                       wall_clock=FakeClock(start=1_700_000_000_050))

    def state(self, crdt):
        return _state(crdt)

    @given(record_maps, record_maps)
    def test_commutative(self, m1, m2):
        a, b = self.fresh(), self.fresh()
        a.merge(dict(m1)); a.merge(dict(m2))
        b.merge(dict(m2)); b.merge(dict(m1))
        assert self.state(a) == self.state(b)

    @given(record_maps, record_maps, record_maps)
    def test_associative_grouping(self, m1, m2, m3):
        a, b = self.fresh(), self.fresh()
        a.merge(dict(m1)); a.merge(dict(m2)); a.merge(dict(m3))
        merged = dict(m1)
        for m in (m2, m3):
            for k, r in m.items():
                if k not in merged or merged[k].hlc < r.hlc:
                    merged[k] = r
        b.merge(merged)
        assert self.state(a) == self.state(b)

    @given(record_maps)
    def test_idempotent(self, m):
        a = self.fresh()
        a.merge(dict(m))
        snap = self.state(a)
        a.merge(dict(m))
        assert self.state(a) == snap


class TestWireProperties:
    @given(record_maps)
    def test_wire_roundtrip_preserves_state(self, m):
        # record state survives to_json -> merge_json into a fresh
        # replica: every record keeps its hlc and value (modified is
        # local-only and re-stamped, record.dart:28-31).
        src = MapCrdt("src", wall_clock=FakeClock(start=1_700_000_000_050))
        src.merge(dict(m))
        dst = MapCrdt("dst", wall_clock=FakeClock(start=1_700_000_000_060))
        dst.merge_json(src.to_json())
        assert _state(src) == _state(dst)

    @given(record_maps, record_maps)
    def test_bidirectional_sync_converges(self, m1, m2):
        # An anti-entropy round (test/map_crdt_test.dart:273-279) is a
        # FULL push plus an inclusive DELTA pull. One round does not
        # always converge — hypothesis found the counterexample: if
        # the puller's pre-sync canonical is ahead of the remote's
        # `modified` stamps (recv ADOPTS remote times, hlc.dart:96, so
        # merging old data stamps old `modified`s), the delta pull
        # misses those records. That is reference-faithful: the delta
        # is an optimization; the full-state PUSH is the convergence
        # backstop. So the guaranteed property is one round in EACH
        # direction.
        from crdt_tpu.sync import sync
        clk = FakeClock(start=1_700_000_000_050)
        a = MapCrdt("aa", wall_clock=clk)
        b = MapCrdt("bb", wall_clock=clk)
        a.merge(dict(m1))
        b.merge(dict(m2))
        sync(a, b)
        sync(b, a)
        assert _state(a) == _state(b)
        assert a.map == b.map

    @given(record_maps)
    def test_one_round_converges_fresh_puller(self, m2):
        # The one-round case the reference's own tests exercise: a
        # puller whose canonical is NOT ahead of the remote's modified
        # stamps (fresh replica, canonical 0 before capture) gets
        # everything in a single round.
        from crdt_tpu.sync import sync
        a = MapCrdt("aa", wall_clock=FakeClock(start=1_700_000_000_050))
        b = MapCrdt("bb", wall_clock=FakeClock(start=1_700_000_000_050))
        b.merge(dict(m2))
        sync(a, b)
        assert a.map == b.map


import os as _os
import pytest as _pytest


@_pytest.mark.skipif(bool(_os.environ.get("CRDT_TPU_NO_NATIVE")),
                     reason="native codec disabled for this run")
class TestNativeCodecProperties:
    @given(st.lists(hlcs, min_size=1, max_size=20))
    def test_batch_parse_matches_python(self, hs):
        codec = load_native()
        assert codec is not None
        strings = [str(h) for h in hs]
        millis_l, counter_l, node_l = codec.parse_hlc_batch(strings)
        for h, ms, c, node in zip(hs, millis_l, counter_l, node_l):
            assert ms is not None
            assert Hlc(ms, c, node) == h

    @given(st.lists(hlcs, min_size=1, max_size=20))
    def test_batch_format_matches_python(self, hs):
        codec = load_native()
        out = codec.format_hlc_batch([h.millis for h in hs],
                                     [h.counter for h in hs],
                                     [str(h.node_id) for h in hs])
        for h, s in zip(hs, out):
            assert s == str(h)


json_values = st.recursive(
    st.none() | st.booleans()
    | st.integers(min_value=-(10 ** 18), max_value=10 ** 18)
    | st.floats(allow_nan=False, allow_infinity=True)
    | st.text(max_size=12),
    lambda children: st.lists(children, max_size=3)
    | st.dictionaries(st.text(max_size=6), children, max_size=3),
    max_leaves=6)


# Lane-safe millis: (millis << 16) must fit int64 (the columnar
# backends' packing range); the full year-9999 range only the scalar
# oracle supports.
lane_hlcs = st.builds(
    Hlc,
    st.integers(min_value=-62_135_596_800_000,   # year 1 (wire floor)
                max_value=(1 << 47) - 1),        # lt fits int64
    counters, nodes)




def _veq(a, b):
    """Strict-type value equality: True != 1, 1 != 1.0 — a codec that
    coerces types must fail; NaN equals NaN."""
    if type(a) is not type(b):
        return False
    if isinstance(a, float):
        return a == b or (a != a and b != b)
    if isinstance(a, list):
        return len(a) == len(b) and all(
            _veq(x, y) for x, y in zip(a, b))
    if isinstance(a, dict):
        return a.keys() == b.keys() and all(_veq(a[k], b[k]) for k in a)
    return a == b

class TestWireScannerProperties:
    @given(st.dictionaries(st.text(max_size=8),
                           st.tuples(lane_hlcs, json_values),
                           min_size=0, max_size=30))
    def test_scan_matches_json_loads_path(self, payload_map):
        """Random wire payloads (arbitrary unicode keys, full JSON value
        space, random HLCs): the C one-pass scan must be exactly the
        json.loads-based column build."""
        import json as json_mod

        import numpy as np

        from crdt_tpu import crdt_json
        from crdt_tpu.hlc import SHIFT

        payload = json_mod.dumps(
            {k: {"hlc": str(h), "value": v}
             for k, (h, v) in payload_map.items()},
            separators=(",", ":"), ensure_ascii=False)
        keys, lt, nds, values = crdt_json.decode_columns(payload)
        raw = json_mod.loads(payload)
        assert keys == list(raw.keys())
        assert values == [v.get("value") for v in raw.values()]
        for i, k in enumerate(keys):
            h = Hlc.parse(raw[k]["hlc"])
            assert int(lt[i]) == (h.millis << SHIFT) + h.counter
            assert nds[i] == h.node_id

    @given(st.dictionaries(st.text(max_size=8),
                           st.tuples(lane_hlcs, json_values),
                           min_size=0, max_size=30))
    def test_scan_with_ensure_ascii_escapes(self, payload_map):
        """Same exactness when the producer escaped non-ASCII (the
        json.dumps default) — every unicode key/value arrives as
        \\uXXXX escapes, exercising the C unescaper."""
        import json as json_mod

        from crdt_tpu import crdt_json

        payload = json_mod.dumps(
            {k: {"hlc": str(h), "value": v}
             for k, (h, v) in payload_map.items()},
            separators=(",", ":"), ensure_ascii=True)
        keys, lt, nds, values = crdt_json.decode_columns(payload)
        raw = json_mod.loads(payload)
        assert keys == list(raw.keys())
        assert values == [v.get("value") for v in raw.values()]

    @given(st.integers(min_value=(1 << 47),
                       max_value=253_402_300_799_999))
    def test_beyond_lane_range_raises_not_wraps(self, ms):
        """millis >= 2^47 (years beyond ~6429) cannot be packed into
        the int64 lt lane. Both the C-scanner and pure paths must
        raise OverflowError — never silently wrap into a WRONG
        merge-winning timestamp. The scalar oracle still handles the
        full year-9999 wire range."""
        import json as json_mod

        import pytest as pytest_mod

        import crdt_tpu.crdt_json as crdt_json_mod

        h = Hlc(ms, 0, "n")
        payload = json_mod.dumps({"k": {"hlc": str(h), "value": 1}},
                                 separators=(",", ":"))
        with pytest_mod.raises(OverflowError):
            crdt_json_mod.decode_columns(payload)
        # the scalar decode keeps working (big-int Python path)
        rec = crdt_json_mod.decode(payload, Hlc(0, 0, "local"),
                                   now_millis=0)
        assert rec["k"].hlc == h

    @staticmethod
    def _assert_fast_matches_pure(junk):
        """Differential harness: the native scan of ``junk`` must have
        the same outcome as the pure path — same exception type, or
        equal columns (keys, lt, nodes, values) — never a crash or a
        silent wrong answer."""
        from unittest import mock

        import numpy as np

        import crdt_tpu.crdt_json as cj

        def run():
            try:
                return cj.decode_columns(junk), None
            except Exception as e:
                return None, type(e)

        fast, fast_exc = run()
        with mock.patch.object(cj.native, "load", lambda: None):
            slow, slow_exc = run()
        assert fast_exc == slow_exc
        if fast is not None:
            assert fast[0] == slow[0]
            assert np.array_equal(fast[1], slow[1])
            assert list(fast[2]) == list(slow[2])
            assert len(fast[3]) == len(slow[3])
            assert all(_veq(a, b) for a, b in zip(fast[3], slow[3]))

    @given(st.text(max_size=200))
    def test_scanner_never_crashes_on_junk(self, junk):
        self._assert_fast_matches_pure(junk)

    @given(st.text(alphabet='{}[]",:\\ \t\n0123456789.eE+-truefalsn'
                            'hlcvalue\ud800é',
                   max_size=120))
    def test_scanner_never_crashes_on_jsonish_junk(self, junk):
        """Biased toward JSON-structural characters (braces, quotes,
        escapes, literals, surrogates) so valid-payload fragments are
        actually reachable."""
        self._assert_fast_matches_pure(junk)

    @given(st.dictionaries(st.text(max_size=6), json_values,
                           max_size=10))
    def test_assembler_roundtrips_arbitrary_values(self, kv):
        """encode -> decode round trip over the full JSON value space
        (C assembly on the way out, C scan on the way back)."""
        from crdt_tpu import MapCrdt
        from crdt_tpu.testing import FakeClock
        src = MapCrdt("src", wall_clock=FakeClock(
            start=1_700_000_000_000))
        src.put_all(kv)
        dst = MapCrdt("dst", wall_clock=FakeClock(
            start=1_700_000_000_500))
        dst.merge_json(src.to_json())
        assert dst.map.keys() == src.map.keys()
        assert all(_veq(dst.map[k], src.map[k]) for k in src.map)

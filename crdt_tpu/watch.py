"""Change-notification streams (C13 — watch/reactivity).

The reference exposes a Dart broadcast stream of ``MapEntry(key, value)``
change events (map_crdt.dart:11,27-39,48-49; contract crdt.dart:162-164).
This is the Python equivalent: a synchronous broadcast hub with
filterable subscriptions. Device backends emit events host-side after
kernel writes land — reactivity never lives inside jit (SURVEY.md §7
hard part 6).

Inside a `DenseCrdt.ingest()` window (models/ingest.py), staged writes
do NOT emit as they are staged: change events fire at COMMIT time, one
event per distinct slot carrying the winning post-dedup value (a slot
staged twice in one window emits once, with the last value). Ordering
across flushes follows commit order, which is also HLC order.
"""

from __future__ import annotations

import asyncio
import threading
import weakref
from collections import deque
from typing import Any, Callable, List, NamedTuple, Optional

from .analysis.concurrency import make_lock


class ChangeEvent(NamedTuple):
    """A (key, value) change notification — value is None for deletes."""
    key: Any
    value: Any


_ANY_KEY = object()   # sentinel: stream not filtered to a single key

# Change events delivered to live subscribers, process-wide. Created
# lazily so importing watch.py never drags in the obs package; touched
# only when some stream is actually listening, so the nobody-watching
# bulk path stays zero-cost.
_WATCH_COUNTER = None


def _watch_counter():
    global _WATCH_COUNTER
    if _WATCH_COUNTER is None:
        from .obs.registry import default_registry
        _WATCH_COUNTER = default_registry().counter(
            "crdt_tpu_watch_events_total",
            "change events fanned out to live watch subscribers")
    return _WATCH_COUNTER


class _EventBatch(NamedTuple):
    """A recorded batch held UNMATERIALIZED in a stream buffer: a 1M
    merge with a recording subscriber must not allocate 1M ChangeEvent
    objects on the merge path — `events` expands batches on read
    (inspection-time cost, not merge-time)."""
    keys: Any
    values: Any


class ChangeStream:
    """A filtered view over a :class:`ChangeHub`.

    Supports callback subscription (``listen``), buffered collection for
    tests (``record`` + ``events``), and further filtering (``where``).
    """

    def __init__(self, hub: "ChangeHub",
                 predicate: Optional[Callable[[ChangeEvent], bool]] = None,
                 key_filter: Any = _ANY_KEY):
        self._hub = hub
        self._predicate = predicate
        # When the stream is exactly a single-key filter (the common
        # `watch(key=...)` shape), the key is kept structurally so
        # batch emission can answer it in O(1) instead of scanning the
        # batch; `where()` chains fall back to the per-event path.
        self._key_filter = key_filter
        self._buffer: List[ChangeEvent] = []
        self._recording = False
        # Each subscription is a single-element list token so duplicate
        # callbacks unsubscribe independently.
        self._callbacks: List[List[Callable[[ChangeEvent], None]]] = []
        hub._streams.append(self)

    def _emit(self, event: ChangeEvent) -> None:
        if self._predicate is not None and not self._predicate(event):
            return
        if self._recording:
            self._buffer.append(event)
        for token in list(self._callbacks):
            token[0](event)

    def _emit_many(self, keys, values) -> None:
        """Batch emission: an unfiltered recording-only stream appends
        ONE batch marker (zero per-event work on the merge path; the
        `events` read expands it); anything with a predicate or
        callbacks takes the per-event path. Batches are retained by
        reference — the `ChangeHub.add_batch` contract requires
        callers to hand over snapshots they will not mutate."""
        if self._predicate is None and not self._callbacks:
            if self._recording:
                self._buffer.append(_EventBatch(keys, values))
            return
        for k, v in zip(keys, values):
            self._emit(ChangeEvent(k, v))

    def listen(self, callback: Callable[[ChangeEvent], None]
               ) -> Callable[[], None]:
        """Subscribe; returns an idempotent unsubscribe function. The
        last unsubscribe detaches the stream from its hub (so transient
        watch/listen/unsubscribe cycles don't accumulate dead streams);
        a later listen() re-attaches."""
        if self not in self._hub._streams:
            self._hub._streams.append(self)
        token = [callback]
        self._callbacks.append(token)

        def unsubscribe() -> None:
            if token in self._callbacks:
                self._callbacks.remove(token)
                if not self._callbacks and not self._recording:
                    self.cancel()

        return unsubscribe

    def record(self) -> "ChangeStream":
        """Start buffering events into ``events`` (test helper)."""
        self._recording = True
        return self

    @property
    def events(self) -> List[ChangeEvent]:
        out: List[ChangeEvent] = []
        for item in self._buffer:
            if type(item) is _EventBatch:
                out.extend(map(ChangeEvent, item.keys, item.values))
            else:
                out.append(item)
        return out

    def where(self, predicate: Callable[[ChangeEvent], bool]
              ) -> "ChangeStream":
        prev = self._predicate
        combined = (predicate if prev is None
                    else (lambda e: prev(e) and predicate(e)))
        # a custom predicate can't be answered structurally
        return ChangeStream(self._hub, combined)

    def cancel(self) -> None:
        if self in self._hub._streams:
            self._hub._streams.remove(self)

    def aiter(self) -> "AsyncChangeIterator":
        """Async iteration over future events — the Dart ``await for``
        shape (map_crdt.dart:48-49 streams are async there natively).

        Events emitted before the first ``await`` are buffered; call
        ``close()`` (or use ``async with``) to end iteration.
        """
        return AsyncChangeIterator(self)


class AsyncChangeIterator:
    """Bridges the synchronous ChangeHub to an ``async for`` consumer.

    Emission may happen on any thread (device backends emit host-side
    after kernel writes); a lock serializes the pending-buffer → queue
    handoff, after which delivery is marshalled onto the consuming
    event loop with ``call_soon_threadsafe``.

    Detach deterministically with ``close()`` / ``async with`` /
    ``await aclose()`` (works with ``contextlib.aclosing``); a dropped
    iterator also detaches on garbage collection so a bare
    ``async for ... break`` cannot leak the hub subscription forever.
    """

    # crdtlint lock-discipline contract: the pending buffer is touched
    # only under self._lock (enforced by crdt_tpu.analysis.host_lint).
    _CRDTLINT_GUARDED = {"_lock": ("_pending",)}
    # Checked by analysis/concurrency.py: singleton leaf — no other
    # lock is ever taken inside the handoff critical section.
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    _CLOSE = object()

    def __init__(self, stream: ChangeStream):
        self._pending: deque = deque()
        self._queue: Optional[asyncio.Queue] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._lock = make_lock("AsyncChangeIterator._lock", 60)
        self._closed = False
        # Subscribe through a weak shim: a bound-method callback would
        # make the iterator reachable FROM the hub (hub -> stream ->
        # callback -> iterator), so an abandoned iterator could never
        # be collected and __del__ could never detach it.
        ref = weakref.ref(self)

        def shim(event, _ref=ref):
            it = _ref()
            if it is not None:
                it._on_event(event)

        self._unsubscribe = stream.listen(shim)

    def _on_event(self, event) -> None:
        with self._lock:
            if self._queue is None:
                self._pending.append(event)
                return
            loop, queue = self._loop, self._queue
        try:
            loop.call_soon_threadsafe(queue.put_nowait, event)
        except RuntimeError:
            pass  # consuming loop already closed; drop quietly

    def close(self) -> None:
        """Stop receiving; pending events still drain, then iteration
        raises StopAsyncIteration."""
        if self._closed:
            return
        self._closed = True
        self._unsubscribe()
        self._on_event(self._CLOSE)

    async def aclose(self) -> None:
        self.close()

    def __del__(self) -> None:
        try:
            if not self._closed:
                self._unsubscribe()
                self._closed = True
        except Exception:
            pass  # interpreter shutdown / partial construction

    def __aiter__(self) -> "AsyncChangeIterator":
        return self

    async def __anext__(self) -> ChangeEvent:
        if self._queue is None:
            # crdtlint: disable=async-blocking-call -- bounded handoff: the critical section is a few deque ops, and emitters never block inside it
            with self._lock:
                self._loop = asyncio.get_running_loop()
                self._queue = asyncio.Queue()
                while self._pending:
                    self._queue.put_nowait(self._pending.popleft())
        event = await self._queue.get()
        if event is self._CLOSE:
            raise StopAsyncIteration
        return event

    async def __aenter__(self) -> "AsyncChangeIterator":
        return self

    async def __aexit__(self, *exc) -> None:
        self.close()


class WatchIndex:
    """Slot-interest bookkeeping for the serving tier's push-on-flush
    fan-out (docs/FEDERATION.md): who subscribed to which slots, and —
    given the slots a flush tick touched — which watchers get the
    pack. Watchers are opaque handles (the serve loop uses session
    writer records); slot sets are held both ways so registration,
    removal and the per-tick interest query all stay proportional to
    the watcher's own subscriptions, never to the watcher count.

    Single-threaded by design: every call site lives on the tier's
    serve loop, matching the loop's no-lock threading model.
    """

    __slots__ = ("_by_slot", "_slots_of", "_all")

    def __init__(self) -> None:
        self._by_slot: dict = {}     # slot -> set of watchers
        self._slots_of: dict = {}    # watcher -> frozenset of slots
        self._all: set = set()       # whole-keyspace watchers

    def __len__(self) -> int:
        return len(self._slots_of) + len(self._all)

    @property
    def empty(self) -> bool:
        return not self._slots_of and not self._all

    def add(self, watcher, slots=None) -> None:
        """Register ``watcher`` for ``slots`` (an iterable of ints) or
        the whole keyspace (None). Re-adding replaces the previous
        subscription."""
        self.remove(watcher)
        if slots is None:
            self._all.add(watcher)
            return
        fs = frozenset(int(s) for s in slots)
        self._slots_of[watcher] = fs
        for s in fs:
            self._by_slot.setdefault(s, set()).add(watcher)

    def remove(self, watcher) -> None:
        """Idempotent deregistration (session close, backpressure
        shed)."""
        self._all.discard(watcher)
        fs = self._slots_of.pop(watcher, None)
        if fs:
            for s in fs:
                group = self._by_slot.get(s)
                if group is not None:
                    group.discard(watcher)
                    if not group:
                        del self._by_slot[s]

    def watchers(self) -> set:
        """Every live watcher regardless of slot interest — the set a
        partition retire must re-home (`ServeTier.rehome_watchers`)."""
        return self._all | set(self._slots_of)

    def touched(self, slots) -> set:
        """Watchers interested in ANY of ``slots`` — the fan-out set
        for one flush tick's pack. Whole-keyspace watchers are always
        included; slot-filtered watchers join via the per-slot index,
        so a tick touching k slots costs O(k + matches)."""
        out = set(self._all)
        by_slot = self._by_slot
        if by_slot:
            for s in slots:
                group = by_slot.get(int(s))
                if group:
                    out.update(group)
        return out


class ChangeHub:
    """Broadcast source owned by a storage backend."""

    def __init__(self) -> None:
        self._streams: List[ChangeStream] = []

    @property
    def active(self) -> bool:
        """True when some stream actually wants events (a live callback
        or an active recording) — lets bulk backends skip per-record
        host emission entirely when nobody is listening, including
        after every subscriber unsubscribed."""
        return any(s._recording or s._callbacks for s in self._streams)

    def add(self, key: Any, value: Any) -> None:
        event = ChangeEvent(key, value)
        for stream in list(self._streams):
            stream._emit(event)
        if self.active:
            _watch_counter().inc()

    def add_batch(self, pairs,
                  get: Optional[Callable[[Any], tuple]] = None) -> None:
        """Emit a whole batch of (key, value) changes.

        Equivalent to ``add`` per pair, but bulk backends stay
        vectorized: ``pairs`` is ``(keys, values)`` or a zero-arg
        callable producing it, materialized at most once and ONLY if
        some stream needs the full batch — single-key-filtered
        streams are answered via ``get(key) -> (present, value)``,
        the caller's O(1) lookup into the batch, without touching it.
        Unfiltered recording streams extend their buffers in one
        pass; predicate/callback streams take the per-event path.

        ``get`` answers a key AT MOST ONCE per batch; callers whose
        batch may repeat a key (raw slot arrays, not dict-keyed
        payloads) must pass ``get=None`` so keyed streams see every
        occurrence like everyone else.

        Ownership: materialized ``(keys, values)`` may be RETAINED by
        recording streams (expanded lazily on ``events`` reads) —
        callers hand over snapshots they will not mutate afterwards
        (every in-tree caller builds fresh lists or passes decode
        products that are never written again)."""
        mat = None
        keyed_hits = 0
        for stream in list(self._streams):
            if not (stream._recording or stream._callbacks):
                continue   # no sink: never materialize on its behalf
            k = stream._key_filter
            if k is not _ANY_KEY and get is not None:
                present, v = get(k)
                if present:
                    stream._emit(ChangeEvent(k, v))
                    keyed_hits += 1
                continue
            if mat is None:
                mat = pairs() if callable(pairs) else pairs
            stream._emit_many(*mat)
        if mat is not None:
            _watch_counter().inc(len(mat[0]))
        elif keyed_hits:
            _watch_counter().inc(keyed_hits)

    def stream(self, key: Any = None) -> ChangeStream:
        if key is None:
            return ChangeStream(self)
        return ChangeStream(self, lambda e: e.key == key,
                            key_filter=key)

"""Deliberately RACY/undisciplined gossip stub — crdtlint self-test
fixture. Never imported by production code; every construct below
exists to be flagged:

    python -m crdt_tpu.analysis --lint tests/fixtures/racy_gossip.py

Expected findings: lock-discipline (peer registry touched outside the
declared lock), socket-no-timeout (unbounded connect), wall-clock-read
+ hlc-wall-compare (HLC ordered against time.time), record-mutation
(in-place hlc overwrite), add-batch-unique-keys (keyed get with a
repeat-capable batch).
"""

import socket
import threading
import time


class RacyGossipStub:
    """Declares the same lock contract as GossipNode, then breaks it."""

    _CRDTLINT_GUARDED = {"_lock": ("peers",)}

    def __init__(self):
        self._lock = threading.Lock()
        self.peers = {}

    def add_peer(self, name, host, port):
        # RACE: registry write outside self._lock.
        self.peers[name] = (host, port)

    def run_round(self):
        with self._lock:
            names = list(self.peers)          # disciplined (not flagged)
        for name in names:
            self.sync_peer(name)

    def sync_peer(self, name):
        # RACE: registry read outside self._lock.
        host, port = self.peers[name]
        # UNBOUNDED: no timeout= and no settimeout on the result — a
        # silent peer stalls the round forever.
        conn = socket.create_connection((host, port))
        try:
            conn.sendall(b"sync")
        finally:
            conn.close()

    def expire_stale(self, record):
        # HLC MISUSE: wall-clock compared against HLC state. HLCs
        # order by (logical_time, node) — not wall time.
        if record.hlc.millis < time.time() * 1000:
            # MUTATION: records are shared by reference with merge and
            # watch machinery; they must be replaced, not edited.
            record.hlc = None

    def emit(self, hub, slots, values):
        # CONTRACT: slots may repeat (raw payload order), but a keyed
        # get callback answers each key AT MOST ONCE per batch.
        hub.add_batch(lambda: (slots, values),
                      lambda k: (k in slots, values[slots.index(k)]))

"""Benchmark: N-replica fan-in merge throughput (BASELINE.json north star).

Headline config: 1M-key × 1024-replica changesets through the fused
fan-in lattice join, streamed in replica chunks, on whatever
accelerator jax selects (the driver runs this on real TPU hardware).
Target: >100M record-merges/sec (BASELINE.json; the reference itself
publishes no numbers — its merge is a single-thread O(n) Dart loop,
crdt.dart:77-94).

Measurement protocol: after warmup, `--repeats` full 1024-replica
fan-ins are enqueued back-to-back with the canonical clock threaded
from each run into the next (a real data dependency; the device
executes them sequentially), then a single scalar readback fences the
timing. This measures steady-state merge throughput; the ~100ms
host<->device round trip of this environment's remote-proxied chip is
paid once rather than per run. Merges are counted over valid lanes
only.

Prints exactly ONE JSON line per metric:
    {"metric": ..., "value": N, "unit": "merges/s", "vs_baseline": N,
     "path": ..., "platform": ...}
``vs_baseline`` is value / 100e6 (the north-star target), since the
reference has no published numbers to compare against (BASELINE.md).

Stream mode also re-runs the workload once with the `crdt_tpu.obs`
trace ring enabled and prints a SECOND JSON line
(``{"metric": "<name>_phases", "phases": {...}}``) breaking the run
into pack (changeset manufacture) / dispatch (enqueue loop) / fetch
(scalar readback) spans, plus the measured tracing overhead against
the untraced number — the observability layer's ≤5% hot-path budget,
checked where it matters. The main metric line always comes first.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time

import jax
import jax.numpy as jnp

from crdt_tpu.hlc import SHIFT
from crdt_tpu.ops.dense import DenseChangeset, empty_dense_store, fanin_step
from crdt_tpu.ops.pallas_merge import (TILE, pallas_fanin_batch,
                                       pallas_fanin_stream,
                                       split_changeset, split_store)

TARGET = 100e6  # merges/s north star (BASELINE.json)
_MILLIS = 1_700_000_000_000


def make_changeset(rc: int, n: int, seed: int, tomb_ratio: float = 0.3,
                   millis_spread: int = 1000, counter_spread: int = 4,
                   fill: float = 0.8) -> DenseChangeset:
    """Device-generated random changeset. Defaults model the realistic
    sparse-delta shape (mixed writers, 30% tombstones, 80% fill); the
    knobs produce the BASELINE.json stress configs:

    - ``tomb_ratio=0.5`` — tombstone-heavy merge (config 3).
    - ``millis_spread=1, counter_spread=2`` — HLC tie-break stress: most
      records collide on logicalTime and resolve via the node ordinal
      (config 4, hlc.dart:158-161).
    """
    k = jax.random.split(jax.random.key(seed), 5)
    lt = ((_MILLIS + jax.random.randint(k[0], (rc, n), 0, millis_spread,
                                        jnp.int64))
          << SHIFT) + jax.random.randint(k[1], (rc, n), 0, counter_spread,
                                         jnp.int64)
    return DenseChangeset(
        lt=lt,
        node=jax.random.randint(k[2], (rc, n), 1, 9, jnp.int32),
        val=lt,  # payload content doesn't affect the join cost
        tomb=jax.random.uniform(k[3], (rc, n)) < tomb_ratio,
        valid=jax.random.uniform(k[4], (rc, n)) < fill,
    )


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("rc", "n"))
def make_changeset_fast(rc: int, n: int, seed) -> DenseChangeset:
    """`make_changeset` defaults from ONE uint32 random draw per lane
    pair, as ONE fused jit — for the e2e rows, where input manufacture
    sits INSIDE the timed loop (the 1024 distinct batches cannot be
    HBM-resident at once) and would otherwise dominate the number.
    Jitting matters as much as the single draw: the eager form
    dispatched ~15 separate 128M-element ops and MATERIALIZED every
    intermediate to HBM (~225 ms/batch vs ~25 fused). Same
    distributions: ~1000-ms millis spread, 4 counter values, 8
    writers, ~30% tombstones, ~80% fill."""
    bits = jax.random.bits(jax.random.key(seed), (2, rc, n), jnp.uint32)
    b1 = bits[0]
    b2 = bits[1]
    lt = ((_MILLIS + (b1 % 1000).astype(jnp.int64)) << SHIFT)         + (b2 & 3).astype(jnp.int64)
    return DenseChangeset(
        lt=lt,
        node=(1 + ((b2 >> 2) & 7)).astype(jnp.int32),
        val=lt,  # payload content doesn't affect the join cost
        tomb=((b2 >> 5) & 0xFF) < 77,        # ~30%
        valid=((b2 >> 13) & 0xFF) < 205,     # ~80%
    )


def build_stream_fn(n_chunks: int):
    """fori_loop of XLA-fold fan-in steps; each chunk's clocks advance
    by 1ms so every round has genuine winners (steady-state write
    path)."""

    @jax.jit
    def run(store, cs, canonical, local_node, wall):
        def body(i, carry):
            st, canon = carry
            cs_i = cs._replace(lt=cs.lt + (i << SHIFT))
            st2, res = fanin_step(st, cs_i, canon, local_node, wall)
            return (st2, res.new_canonical)

        return jax.lax.fori_loop(0, n_chunks, body, (store, canonical))

    return run


def build_pallas_stream_fn(n_chunks: int):
    """ONE fused multi-chunk kernel launch (`pallas_fanin_stream`) — the
    TPU fast path: split 32-bit lanes (no int64 emulation) and the store
    block VMEM-resident across the chunk grid dimension, so HBM sees
    each store/changeset lane once per row block instead of once per
    chunk. Chunk clocks advance by 1ms per chunk with the canonical
    clock threaded through — store lanes bit-identical to the XLA fold
    loop (tests/test_pallas_merge.py::test_stream_matches_sequential_folds).
    Guards run in optimistic "fast" mode: closed-form superset flags
    with zero per-row cost; any trip would hand off to the exact
    host-side recompute (the model-layer contract) — this workload
    never trips either mode, and the flag executor does not change the
    store results."""

    @jax.jit
    def run(store, cs, canonical, local_node, wall):
        sstore = split_store(store)
        scs = split_changeset(cs)
        st2, res = pallas_fanin_stream(sstore, scs, canonical, local_node,
                                       wall, n_chunks=n_chunks,
                                       guards="fast")
        return st2, res.new_canonical

    return run


# BASELINE.json stress configs as changeset knobs (see make_changeset).
CONFIGS = {
    "fanin": dict(),
    "tombstone": dict(tomb_ratio=0.5),
    "tiebreak": dict(millis_spread=1, counter_spread=2),
}


def _traced_phases(run, args, cs_spec, repeats: int, metric: str,
                   untraced_elapsed: float) -> dict:
    """One extra traced pass of the stream workload, broken into
    pack / dispatch / fetch spans via the `crdt_tpu.obs` trace ring.
    Overhead is judged on dispatch+fetch (the phases the untraced
    timed loop actually covers; pack happens outside it there)."""
    from crdt_tpu.obs import span, summarize_trace, tracer
    ring = tracer()
    ring.enable()
    ring.clear()
    with span("bench.pack", kind="bench_phase"):
        cs = make_changeset(*cs_spec[:2], seed=0, **CONFIGS[cs_spec[2]])
        jax.block_until_ready(cs)
    canon = args[2]
    with span("bench.dispatch", kind="bench_phase"):
        for _ in range(repeats):
            _, canon = run(args[0], cs, canon, args[3], args[4])
    with span("bench.fetch", kind="bench_phase"):
        int(jax.device_get(canon))
    phases = summarize_trace(ring.events("bench_phase"))
    ring.disable()
    ring.clear()
    traced = (phases["bench.dispatch"]["total_s"]
              + phases["bench.fetch"]["total_s"])
    return {"metric": f"{metric}_phases", "phases": phases,
            "traced_elapsed_s": round(traced, 6),
            "untraced_elapsed_s": round(untraced_elapsed, 6),
            "trace_overhead_frac": (
                round(max(0.0, traced / untraced_elapsed - 1.0), 4)
                if untraced_elapsed else None)}


def bench(n_keys: int, n_replicas: int, chunk_replicas: int,
          repeats: int = 64, path: str = "auto",
          config: str = "fanin", with_phases: bool = False) -> dict:
    platform = jax.devices()[0].platform
    # The kernel path is the default on ANY accelerator platform (the
    # driver's chip reports a plugin platform name, not "tpu"); when
    # auto-selected it falls back to the XLA fold if the kernel fails
    # to compile/run there.
    auto = path == "auto"
    if auto:
        path = ("pallas" if platform != "cpu" and n_keys % TILE == 0
                else "xla")
    n_chunks = n_replicas // chunk_replicas
    store = empty_dense_store(n_keys)
    cs = make_changeset(chunk_replicas, n_keys, seed=0, **CONFIGS[config])
    # Honest accounting: only valid lanes are record-merges (fill < 1
    # pads the changeset with invalid entries that cost no join work).
    merges = int(jnp.sum(cs.valid)) * n_chunks
    args = (store, cs, jnp.int64(_MILLIS << SHIFT), jnp.int32(0),
            jnp.int64(_MILLIS + 10_000))

    def compile_and_warm(p: str):
        run = (build_pallas_stream_fn if p == "pallas"
               else build_stream_fn)(n_chunks)
        # Force completion with a scalar readback: under remote-proxied
        # backends block_until_ready can return at enqueue time, which
        # would fake multi-T/s numbers.
        _, canon = run(*args)
        int(jax.device_get(canon))
        return run

    if path == "pallas" and auto:
        try:
            run = compile_and_warm("pallas")
        except Exception as e:  # Mosaic/compile failure on this platform
            print(f"pallas path failed ({type(e).__name__}: {e}); "
                  "falling back to xla", file=sys.stderr)
            path = "xla"
            run = compile_and_warm("xla")
    else:
        run = compile_and_warm(path)

    # Steady-state throughput: enqueue `repeats` runs back-to-back with
    # the canonical clock threaded run-to-run (a true data dependency —
    # runs execute sequentially on device), then ONE scalar readback.
    # Dispatches are async, so the ~100ms host<->device round trip is
    # paid once instead of per run; per-run cost is identical whether or
    # not rounds have fresh winners (branchless selects).
    t0 = time.perf_counter()
    canon = args[2]
    for _ in range(repeats):
        _, canon = run(args[0], args[1], canon, args[3], args[4])
    int(jax.device_get(canon))
    elapsed = time.perf_counter() - t0

    suffix = "" if config == "fanin" else f"_{config}"
    # Honest metric name: this is a WRITE-STREAM replay — one
    # chunk_replicas-row changeset applied n_chunks times with per-chunk
    # +1ms clock offsets (a steady-state ingest model), NOT n_replicas
    # distinct changesets resident at once. The distinct-data workload
    # is the `distinct` mode / `bench_distinct` row.
    out = result_dict(
        f"record_merges_per_sec_{n_keys // 1000}k_keys_"
        f"x{chunk_replicas}_replicas_stream{n_chunks}{suffix}",
        merges * repeats, elapsed, path=path, platform=platform)
    out["repeats"] = repeats  # protocol transparency: rows at different
    #                           amortization levels must be comparable
    if with_phases:
        out["_phases"] = _traced_phases(
            run, args, (chunk_replicas, n_keys, config), repeats,
            out["metric"], elapsed)
    return out


def bench_distinct(n_keys: int, n_rows: int, loops: int = 48,
                   interpret: bool = False,
                   value_width: int = 64) -> dict:
    """GENUINELY DISTINCT replica rows: one [n_rows, n_keys] changeset
    resident in HBM — every record independent random data — merged by
    `pallas_fanin_batch` walking n_rows/8 distinct row groups per pass
    (the BASELINE.md:26 north-star workload shape, bounded by what HBM
    holds: [128, 1M] int64 lanes ≈ 2.8 GB + split lanes ≈ 3 GB).

    ``loops`` chains passes with the canonical clock threaded so the
    one-off dispatch round trip amortizes. Unlike the stream-replay
    kernel (whose changeset tile is VMEM-resident across chunks),
    every counted merge here pays its full HBM read: each chunk walks
    a DIFFERENT row group, so per-merge memory traffic is identical in
    every loop — this row is the honest HBM-bound number."""
    platform = jax.devices()[0].platform
    store = empty_dense_store(n_keys)
    cs = make_changeset(n_rows, n_keys, seed=0)
    merges = int(jnp.sum(cs.valid))
    # The HBM-resident wire format IS the split form, PRE-TILED to the
    # kernel's (r, rows, lane) layout: convert once outside the timed
    # loop (paying the int64 emulation per pass would measure the
    # conversion; a per-call reshape to the tile layout is a physical
    # ~2.4 GB relayout copy that cost ~7 of the old 15 ms — resident
    # batches store pre-tiled, `ops.pallas_merge.tile_changeset`).
    # value_width=32 takes the value-ref lanes (int32 payloads/table
    # indices, 15 B/merge).
    from crdt_tpu.ops.pallas_merge import tile_changeset
    if value_width == 32:
        from crdt_tpu.ops.pallas_merge import split_changeset_narrow
        scs, overflow = split_changeset_narrow(
            cs._replace(val=cs.val & 0x7FFFFFFF))
        assert not bool(overflow)
    else:
        scs = split_changeset(cs)
    scs = tile_changeset(scs)
    jax.block_until_ready(scs)
    del cs

    @jax.jit
    def run(store, scs, canonical, local_node, wall):
        st2, res = pallas_fanin_batch(
            split_store(store), scs, canonical,
            local_node, wall, chunk_rows=16, interpret=interpret)
        return st2, res.new_canonical

    suffix = "" if value_width == 64 else "_valref32"

    args = (store, scs, jnp.int64(_MILLIS << SHIFT), jnp.int32(0),
            jnp.int64(_MILLIS + 10_000))
    _, canon = run(*args)
    int(jax.device_get(canon))  # compile + warm, fenced

    t0 = time.perf_counter()
    canon = args[2]
    for _ in range(loops):
        _, canon = run(args[0], args[1], canon, args[3], args[4])
    int(jax.device_get(canon))
    elapsed = time.perf_counter() - t0

    out = result_dict(
        f"record_merges_per_sec_{n_keys // 1000}k_keys_"
        f"x{n_rows}_distinct_replicas{suffix}", merges * loops, elapsed,
        path="pallas-batch", platform=platform)
    out["loops"] = loops  # every loop re-reads all rows from HBM
    return out


def bench_e2e_1024(n_keys: int, rows_per_pass: int = 128,
                   passes: int = 8, through_model: bool = True) -> dict:
    """THE north-star workload, end to end: 1M keys × 1024 DISTINCT
    replica rows, as ``passes`` freshly device-generated
    ``rows_per_pass``-row changesets — no replay, every counted merge
    pays full HBM traffic AND the generation cost of its data (the
    batches cannot all be HBM-resident at once; generating in-loop is
    disclosed in the protocol fields and can only make the number
    worse).

    ``through_model=True`` drives `DenseCrdt.merge` inside a
    ``pipelined()`` window (real model API: ordinal remap, fit_slots,
    stats, device clock threading, guard accumulation — zero host
    syncs until the closing flush). ``False`` runs the identical loop
    shape against the raw kernel (gen → split → `pallas_fanin_batch`,
    canonical threaded by hand) — the pair isolates model-API overhead
    at the headline scale."""
    from crdt_tpu import DenseCrdt
    platform = jax.devices()[0].platform
    ids = [f"n{i}" for i in range(9)]   # make_changeset ordinals 1..8
    n_rows_total = rows_per_pass * passes

    # Valid-lane counts per pass, computed OUTSIDE the timed loop.
    merges = 0
    for p in range(passes):
        cs = make_changeset_fast(rows_per_pass, n_keys, seed=p)
        merges += int(jnp.sum(cs.valid))
        del cs

    if through_model:
        crdt = DenseCrdt("n0", n_keys, node_ids=ids)
        # Warm the whole path with TWO passes, then rebuild: the lazy
        # stats accumulators first run their scalar device adds on the
        # SECOND merge, and on remote-proxied backends every first
        # compile — even a scalar add — costs a ~0.6 s remote compile
        # RPC that must not land inside the timed window.
        with crdt.pipelined():
            for p in range(2):
                crdt.merge(
                    make_changeset_fast(rows_per_pass, n_keys, seed=p),
                    ids)
        crdt = DenseCrdt("n0", n_keys, node_ids=ids)
        t0 = time.perf_counter()
        with crdt.pipelined():   # exit = ONE fenced readback
            for p in range(passes):
                crdt.merge(
                    make_changeset_fast(rows_per_pass, n_keys, seed=p),
                    ids)
        elapsed = time.perf_counter() - t0
        path = ("model-pipelined-" +
                ("pallas" if crdt._use_pallas() else "xla"))
    else:
        from crdt_tpu.ops.pallas_merge import (pallas_fanin_batch,
                                               split_changeset,
                                               split_store)
        store = split_store(empty_dense_store(n_keys))
        wall = jnp.int64(_MILLIS + 10_000)

        @jax.jit
        def step(store, cs, canonical):
            st2, res = pallas_fanin_batch(
                store, split_changeset(cs), canonical, jnp.int32(0),
                wall, chunk_rows=16)
            return st2, res.new_canonical

        canonical = jnp.int64(0)
        for p in range(2):               # warm (protocol symmetry
            store, canonical = step(store, make_changeset_fast(
                rows_per_pass, n_keys, seed=p), canonical)
        int(jax.device_get(canonical))   # with the model row) + fence
        store = split_store(empty_dense_store(n_keys))
        canonical = jnp.int64(0)
        t0 = time.perf_counter()
        for p in range(passes):
            store, canonical = step(
                store, make_changeset_fast(rows_per_pass, n_keys,
                                           seed=p), canonical)
        int(jax.device_get(canonical))
        elapsed = time.perf_counter() - t0
        path = "raw-kernel"

    out = result_dict(
        f"record_merges_per_sec_{n_keys // 1000}k_keys_"
        f"x{n_rows_total}_distinct_replicas_e2e_"
        f"{'model' if through_model else 'kernel'}",
        merges, elapsed, path=path, platform=platform)
    out["protocol"] = {
        "passes": passes, "rows_per_pass": rows_per_pass,
        "fresh_device_generated_batches": True,
        "includes_generation_cost": True,
        "api": ("DenseCrdt.merge in a pipelined() window"
                if through_model else
                "pallas_fanin_batch loop, hand-threaded canonical")}
    return out


def bench_e2e_generator_only(n_keys: int, rows_per_pass: int = 128,
                             passes: int = 8) -> dict:
    """The e2e protocol with the merge replaced by a minimal consumer:
    same ``passes`` fresh device-generated batches (separate gen jit,
    like the e2e rows), each consumed by one jitted per-lane full
    reduce whose carried scalar is fenced at the end — the cheapest
    consumption that still forces every lane to materialize (a dropped
    output would let XLA dead-code-eliminate the generator wholesale).
    The e2e rows then decompose: e2e = generation(+reduce) + framework;
    the suite also reports the subtracted merge-only figure."""
    platform = jax.devices()[0].platform
    merges = 0
    for p in range(passes):
        cs = make_changeset_fast(rows_per_pass, n_keys, seed=p)
        merges += int(jnp.sum(cs.valid))
        del cs

    @jax.jit
    def consume(acc, cs):
        return (acc + jnp.max(cs.lt) + jnp.max(cs.val)
                + jnp.sum(cs.valid.astype(jnp.int64))
                + jnp.sum(cs.tomb.astype(jnp.int64))
                + jnp.max(cs.node).astype(jnp.int64))

    acc = jnp.int64(0)
    for p in range(2):   # warm both jits, fenced (protocol symmetry)
        acc = consume(acc, make_changeset_fast(rows_per_pass, n_keys,
                                               seed=p))
    int(jax.device_get(acc))
    acc = jnp.int64(0)
    t0 = time.perf_counter()
    for p in range(passes):
        acc = consume(acc, make_changeset_fast(rows_per_pass, n_keys,
                                               seed=p))
    int(jax.device_get(acc))
    elapsed = time.perf_counter() - t0

    out = result_dict(
        f"record_merges_per_sec_{n_keys // 1000}k_keys_"
        f"x{rows_per_pass * passes}_distinct_replicas_e2e_generator_only",
        merges, elapsed, path="generator+reduce-consumer",
        platform=platform)
    out["protocol"] = {
        "passes": passes, "rows_per_pass": rows_per_pass,
        "fresh_device_generated_batches": True,
        "consumer": "per-lane full reduces, carried scalar (no merge)"}
    return out


def _bytes_to_wire(crdt, write, rounds: int):
    """Median device→wire latency of one real delta: a fresh write
    invalidates the pack cache, so every timed round pays the honest
    full path — device delta mask + `device_get` gather into the pack
    arena (`pack_since`), arena framing (`pack_rows`), and the
    vectored frame send handing the arena views to the kernel.
    Also returns the `crdt_tpu_pack_copy_bytes_total{stage=
    "pack_rows"}` delta across all rounds — 0 means the zero-copy
    invariant held for every frame (docs/FASTPATH.md)."""
    import socket as _sk
    import statistics
    import threading
    from crdt_tpu.net import recv_bytes_frame, send_bytes_frame
    from crdt_tpu.obs.registry import default_registry
    from crdt_tpu.ops.packing import pack_rows

    ctr = default_registry().counter("crdt_tpu_pack_copy_bytes_total",
                                     "")
    tx, rx = _sk.socketpair()

    def drain():
        while recv_bytes_frame(rx) is not None:
            pass

    th = threading.Thread(target=drain, daemon=True,
                          name="bench-serve-drain")
    th.start()
    write(0)
    crdt.pack_since(None)          # compile the mask program, fenced
    c0 = ctr.value(stage="pack_rows")
    times = []
    try:
        for i in range(rounds):
            write(i + 1)
            t0 = time.perf_counter()
            packed, _ = crdt.pack_since(None)
            _, bufs = pack_rows(packed)
            send_bytes_frame(tx, bufs)
            times.append(time.perf_counter() - t0)
    finally:
        tx.close()
        th.join(5)
        rx.close()
    copies = ctr.value(stage="pack_rows") - c0
    return round(statistics.median(times) * 1e3, 3), int(copies)


def _ledger_overhead(workload, budget_s: float = 2.0) -> dict:
    """Differential cost of the dispatch ledger (obs.device): the same
    workload in GC-paused alternated pairs with the ledger enabled vs
    disabled, fastest-of-3 floors — the bench_antientropy
    tracer-overhead idiom, so slow drift cancels within a pair and
    preemption spikes drop out of the floor. The acceptance budget is
    5% (ISSUE 12): the ledger rides every device dispatch, so its cost
    must stay invisible next to the dispatches it counts."""
    import gc
    from crdt_tpu.obs.device import default_ledger

    led = default_ledger()
    was_enabled = led.enabled
    on_ts: list = []
    off_ts: list = []
    workload()                        # warm jit caches outside pairs
    deadline = time.perf_counter() + budget_s
    pairs = 0
    try:
        while pairs < 8 or (pairs < 24
                            and time.perf_counter() < deadline):
            gc.collect()
            gc.disable()
            try:
                # Alternate order within pairs: the first run after a
                # collect pays allocator/cache warmup, and always
                # giving it to the same side reads as fake overhead.
                order = ((True, False) if pairs % 2 == 0
                         else (False, True))
                for state in order:
                    led.enabled = state
                    t0 = time.perf_counter()
                    workload()
                    dt = time.perf_counter() - t0
                    (on_ts if state else off_ts).append(dt)
            finally:
                gc.enable()
            pairs += 1
    finally:
        led.enabled = was_enabled

    def floor(ts, j=4):
        best = sorted(ts)[:j]
        return sum(best) / len(best)

    overhead = max(0.0, floor(on_ts) / floor(off_ts) - 1.0)
    return {"ledger_overhead_frac": round(overhead, 4),
            "ledger_overhead_budget_frac": 0.05,
            "ledger_overhead_within_budget": overhead < 0.05}


def _sanitize_lock_overhead(workload, budget_s: float = 2.0) -> dict:
    """Differential cost of the CRDT_TPU_SANITIZE lock wrapper: the
    same lock-taking ingest workload with an
    `analysis.concurrency.OrderedLock` (exactly what `make_lock`
    returns with the sanitizer env set) vs the plain `threading.Lock`
    the production build gets, GC-paused alternated pairs and
    fastest-of-4 floors — the `_ledger_overhead` method, so slow
    drift cancels within a pair. ``workload(lock)`` must take ``lock``
    where the serve tier takes its store lock, so the measured delta
    is the wrapper's per-acquisition bookkeeping and nothing else.
    Budget 5% (ISSUE 17): held-set tracking rides every control-plane
    acquisition under sanitize, and it must stay invisible next to
    the device work the locks guard.

    Estimator: each GC-paused pair runs ABBA (linear drift cancels
    exactly inside the pair), pairs alternate ABBA/BAAB (convex
    position bias — allocator pressure rising across the four runs of
    a paused window — cancels across pair parity), and the overhead
    is the MEDIAN of per-pair ratios: a preemption spike lands in one
    pair and the median discards it, where an independent-floors
    comparison (the ledger probe's shape) would need the spike to
    miss the floor samples of exactly one arm."""
    import gc
    import statistics
    import threading
    from crdt_tpu.analysis.concurrency import OrderedLock

    on_lock = OrderedLock("bench.sanitize_probe", 50)
    off_lock = threading.Lock()
    # Warm BOTH arms outside the pairs: jit caches, and the
    # OrderedLock's thread-local held-stack setup — first-touch costs
    # must not land inside a timed run.
    workload(off_lock)
    workload(on_lock)
    ratios: list = []
    deadline = time.perf_counter() + budget_s
    pairs = 0
    while pairs < 16 or (pairs < 48
                         and time.perf_counter() < deadline):
        gc.collect()
        gc.disable()
        try:
            t_on = t_off = 0.0
            order = ((True, False, False, True) if pairs % 2 == 0
                     else (False, True, True, False))
            for state in order:
                lock = on_lock if state else off_lock
                t0 = time.perf_counter()
                workload(lock)
                dt = time.perf_counter() - t0
                if state:
                    t_on += dt
                else:
                    t_off += dt
        finally:
            gc.enable()
        ratios.append(t_on / t_off)
        pairs += 1

    overhead = max(0.0, statistics.median(ratios) - 1.0)
    return {"sanitize_lock_overhead_frac": round(overhead, 4),
            "sanitize_lock_overhead_budget_frac": 0.05,
            "sanitize_lock_within_budget": overhead < 0.05}


def _sketch_overhead(ack_mean_s, budget_s: float = 1.5) -> dict:
    """Differential cost of quantile-sketch recording on the serve
    ack path: the tier's per-ack metric sequence (histogram observe)
    with vs without the sketch twin's observe, GC-paused alternated
    pairs and fastest-of-4 floors — the `_ledger_overhead` idiom, so
    slow drift cancels within a pair. The per-ack marginal cost is
    then expressed as a fraction of the bench's own measured mean ack
    latency; budget 5% (ISSUE 18): the sketch rides every ack, so it
    must stay invisible next to the tick the ack waits on.

    Standalone instruments, not the process registry — the probe's
    synthetic series must never pollute the `_slo` verdict or the
    fleet sketch roll-up."""
    import gc
    from crdt_tpu.obs.registry import Histogram, Sketch

    hist = Histogram("bench_sketch_probe_hist")
    sk = Sketch("bench_sketch_probe_sketch")
    # Deterministic latency-shaped values (0.5..40 ms) spanning many
    # γ-buckets, so the sketch pays realistic dict churn, not one hot
    # bucket.
    vals = [0.0005 * (1.0 + (i * 37 % 79)) for i in range(512)]

    def run(with_sketch: bool) -> None:
        if with_sketch:
            for v in vals:
                hist.observe(v, node="probe")
                sk.observe(v, node="probe")
        else:
            for v in vals:
                hist.observe(v, node="probe")

    run(True)                        # warm both arms outside pairs
    run(False)
    on_ts: list = []
    off_ts: list = []
    deadline = time.perf_counter() + budget_s
    pairs = 0
    while pairs < 8 or (pairs < 24
                        and time.perf_counter() < deadline):
        gc.collect()
        gc.disable()
        try:
            order = ((True, False) if pairs % 2 == 0
                     else (False, True))
            for state in order:
                t0 = time.perf_counter()
                run(state)
                dt = time.perf_counter() - t0
                (on_ts if state else off_ts).append(dt)
        finally:
            gc.enable()
        pairs += 1

    def floor(ts, j=4):
        best = sorted(ts)[:j]
        return sum(best) / len(best)

    per_record_s = max(0.0, (floor(on_ts) - floor(off_ts))
                       / len(vals))
    frac = (per_record_s / ack_mean_s
            if ack_mean_s else None)
    return {"sketch_record_cost_us": round(per_record_s * 1e6, 4),
            "sketch_overhead_frac_of_ack": (round(frac, 5)
                                            if frac is not None
                                            else None),
            "sketch_overhead_budget_frac": 0.05,
            "sketch_within_ack_budget": (frac is not None
                                         and frac < 0.05)}


def bench_sync(n_slots: int = 1 << 14, k: int = 256,
               rounds: int = 32) -> dict:
    """End-to-end two-replica sync over the pooled packed fast path.

    Spins up two `GossipNode`s (real sockets on loopback) and reports,
    in one JSON line, the three acceptance signals of the fast path:
    a pooled round vs a fresh-connect round on wall-clock, wire bytes
    for k- vs 2k-row deltas (proportional to the change, not the
    store), and a steady-state no-change round's pack-cache counters
    (zero misses == zero device packs) — plus the negotiated zlib
    compression ratio off the node's `WireTally`."""
    import statistics
    import numpy as np
    from crdt_tpu.gossip import GossipNode
    from crdt_tpu.models.dense_crdt import DenseCrdt
    from crdt_tpu.net import PeerConnection, sync_packed_over_conn
    from crdt_tpu.obs.registry import default_registry

    a = GossipNode(DenseCrdt("a", n_slots=n_slots))
    b = GossipNode(DenseCrdt("b", n_slots=n_slots))
    rng = np.random.default_rng(7)
    cache = default_registry().counter("crdt_tpu_pack_cache_total", "")
    med = statistics.median
    out = {"metric": "e2e_sync", "unit": "s/round",
           "n_slots": n_slots, "rows_per_round": k,
           "platform": jax.devices()[0].platform}
    with a, b:
        peer = a.add_peer("b", b.host, b.port)

        def write(node, n):
            slots = rng.choice(n_slots, size=n, replace=False)
            with node.lock:
                node.crdt.put_batch(
                    slots.tolist(), [int(s) % 1000 for s in slots])

        def round_pooled():
            t0 = time.perf_counter()
            outcome = a.sync_peer("b")
            assert outcome == "ok", outcome
            return time.perf_counter() - t0

        write(a, k)
        write(b, k)
        round_pooled()                # first contact: connect + hello

        pooled = []
        for _ in range(rounds):
            write(a, k)
            pooled.append(round_pooled())

        fresh = []                    # connect + hello paid every round
        for _ in range(rounds):
            write(a, k)
            t0 = time.perf_counter()
            fc = PeerConnection(b.host, b.port, timeout=10.0)
            try:
                mark = sync_packed_over_conn(
                    a.crdt, fc, since=peer.watermark, lock=a.lock)
            finally:
                fc.close()
            fresh.append(time.perf_counter() - t0)
            peer.watermark = mark

        def round_bytes(n):
            write(a, n)
            before = peer.stats.bytes_sent + peer.stats.bytes_received
            round_pooled()
            return (peer.stats.bytes_sent + peer.stats.bytes_received
                    - before)

        bytes_k = round_bytes(k)
        bytes_2k = round_bytes(2 * k)

        for _ in range(6):            # settle: clocks still, caches warm
            round_pooled()
        miss0 = (cache.value(outcome="miss", node="a")
                 + cache.value(outcome="miss", node="b"))
        hit0 = cache.value(outcome="hit", node="a")
        nochange_s = round_pooled()
        miss_delta = (cache.value(outcome="miss", node="a")
                      + cache.value(outcome="miss", node="b")
                      - miss0)
        hit_delta = cache.value(outcome="hit", node="a") - hit0

        out.update({
            "pooled_round_s": round(med(pooled), 6),
            "fresh_round_s": round(med(fresh), 6),
            "pooled_speedup": round(med(fresh) / med(pooled), 3),
            "bytes_round_k": int(bytes_k),
            "bytes_round_2k": int(bytes_2k),
            "bytes_growth": round(bytes_2k / bytes_k, 3),
            "z_ratio": round(a.wire.z_ratio, 4),
            "nochange_round_s": round(nochange_s, 6),
            "nochange_pack_misses": int(miss_delta),
            "nochange_pack_hits": int(hit_delta),
            "pooled_connects": peer.conn.connects,
        })

    # --- cold peer: empty watermark, merkle walk vs full-scan pack ---
    # The anti-entropy acceptance shape (docs/ANTIENTROPY.md): a
    # 4096-slot pair that converged once, lost the watermark, and
    # diverged in <= 1% of slots. The full-scan reference is a real
    # packed round with since=None over its own socket, so both
    # numbers are post-compression wire bytes. "clustered" is the
    # headline (slots are handed out in interning order, so real
    # divergence is contiguous); "scattered" is the honest worst case
    # (every divergent slot in its own leaf).
    cold_n = min(n_slots, 4096)
    out["cold_peer"] = {
        "n_slots": cold_n,
        "divergent_slots": max(1, cold_n // 100),
        "round_trip_budget": _cold_round_budget(cold_n),
        "clustered": _cold_peer_scenario(cold_n, "clustered"),
        "scattered": _cold_peer_scenario(cold_n, "scattered"),
    }

    # --- device→wire: zero-copy pack + vectored frame, k fresh rows ---
    w = DenseCrdt("w", n_slots=n_slots)

    def fresh_write(i):
        slots = rng.choice(n_slots, size=k, replace=False)
        w.put_batch(slots.tolist(), [int(s) % 1000 for s in slots])

    btw_ms, copies = _bytes_to_wire(w, fresh_write, rounds)
    out["bytes_to_wire_ms"] = btw_ms
    out["copies"] = copies

    # --- ledger overhead: dispatch-dense in-process replica pair ---
    la = DenseCrdt("la", n_slots=n_slots)
    lb = DenseCrdt("lb", n_slots=n_slots)

    def ledger_workload():
        for _ in range(4):
            slots = rng.choice(n_slots, size=k, replace=False)
            la.put_batch(slots.tolist(),
                         [int(s) % 1000 for s in slots])
            packed, ids = la.pack_since(None)
            lb.merge_packed(packed, ids)

    out.update(_ledger_overhead(ledger_workload))
    return out


def _cold_round_budget(n_slots: int) -> int:
    """The ISSUE's digest round-trip acceptance bound:
    log2(n_slots) + 2."""
    import math
    return int(math.log2(n_slots)) + 2


def _cold_peer_scenario(n_slots: int, pattern: str) -> dict:
    """One cold-peer (empty-watermark) sync: two replicas converge,
    then diverge in ~1% of slots, then re-sync twice over real sockets
    — once through the merkle walk, once through the full-scan packed
    round a watermark-less peer otherwise pays. Both byte counts are
    wire bytes off a `WireTally` (same compression, same framing)."""
    import numpy as np
    from crdt_tpu.models.dense_crdt import DenseCrdt
    from crdt_tpu.net import (PeerConnection, SyncServer, WireTally,
                              sync_merkle_over_conn,
                              sync_packed_over_conn)

    k_div = max(1, n_slots // 100)
    src = DenseCrdt("cold_src", n_slots=n_slots)
    ids = list(range(n_slots))
    src.put_batch(ids, [i % 1000 for i in ids])
    packed, pids = src.pack_since(None)
    # two identical stale twins: one re-syncs by walk, one by full scan
    merkle_dst = DenseCrdt("cold_m", n_slots=n_slots)
    scan_dst = DenseCrdt("cold_f", n_slots=n_slots)
    merkle_dst.merge_packed(packed, pids)
    scan_dst.merge_packed(packed, pids)
    if pattern == "clustered":
        div = list(range(n_slots // 2, n_slots // 2 + k_div))
    else:
        div = np.random.default_rng(23).choice(
            n_slots, size=k_div, replace=False).tolist()
    src.put_batch(div, [7] * k_div)

    stats = {}
    m_tally, f_tally = WireTally(), WireTally()
    with SyncServer(src) as server:
        with PeerConnection(server.host, server.port,
                            timeout=10.0) as conn:
            sync_merkle_over_conn(merkle_dst, conn, tally=m_tally,
                                  _stats=stats)
        with PeerConnection(server.host, server.port,
                            timeout=10.0) as conn:
            sync_packed_over_conn(scan_dst, conn, since=None,
                                  tally=f_tally)
    assert merkle_dst.digest_tree().root == src.digest_tree().root
    merkle_bytes = m_tally.sent + m_tally.received
    full_bytes = f_tally.sent + f_tally.received
    return {
        "pattern": pattern,
        "merkle_bytes": int(merkle_bytes),
        "full_scan_bytes": int(full_bytes),
        "bytes_ratio": round(merkle_bytes / full_bytes, 4),
        "digest_round_trips": stats["rounds"],
        "digests_fetched": stats["digests"],
        "divergent_ranges": len(stats["ranges"]),
        "rows_reshipped": stats["pulled_rows"],
    }


def bench_collective(n_slots: int = 1 << 14, k: int = 256,
                     rounds: int = 32, members: int = 4) -> dict:
    """Pod-local collective join vs the same-host `sync_packed`
    loopback (docs/COLLECTIVE.md).

    One `CollectiveGroup.join` converges ``members`` replicas in ONE
    device dispatch with zero wire bytes; the loopback baseline is
    bench_sync's pooled packed round — a real socket on 127.0.0.1,
    the fastest thing the wire path can do on one host. Reports both
    wall times, runtime-asserts the per-round dispatch count and the
    pack-copy-bytes invariant off the live ledger/registry, and
    re-reads the dispatch floor (benchmarks/sharded_scale.py's probe)
    over one member store so the collective number decomposes into
    floor + join work.

    Honest-downscale caveat: on CPU the "mesh" is virtual devices on
    ONE core — members time-slice the join instead of running it in
    parallel over ICI, so the collective number here is an upper
    bound; the dispatch/bytes invariants are the portable signal.
    """
    import statistics
    import numpy as np
    from crdt_tpu.collective import CollectiveGroup
    from crdt_tpu.gossip import GossipNode
    from crdt_tpu.models.dense_crdt import DenseCrdt
    from crdt_tpu.obs.device import default_ledger
    from crdt_tpu.obs.registry import default_registry
    from crdt_tpu.obs.trajectory import host_class

    members = min(members, jax.device_count())
    if members < 2:
        raise SystemExit("--mode collective needs >= 2 devices "
                         "(set xla_force_host_platform_device_count)")
    med = statistics.median
    rng = np.random.default_rng(7)
    led = default_ledger()
    copies = default_registry().counter("crdt_tpu_pack_copy_bytes_total",
                                        "")

    def pack_copy_bytes():
        return sum(s["value"] for s in copies.samples())

    def write(crdt, n):
        slots = rng.choice(n_slots, size=n, replace=False)
        crdt.put_batch(slots.tolist(), [int(s) % 1000 for s in slots])

    # --- collective lane: G members, one dispatch per round ---
    reps = [DenseCrdt(f"m{i}", n_slots=n_slots) for i in range(members)]
    group = CollectiveGroup(reps)
    for r in reps:
        write(r, k)
    group.join()                        # first join warms the jit cache

    coll, disp_per_round = [], []
    bytes_before = pack_copy_bytes()
    for _ in range(rounds):
        for r in reps:
            write(r, k)
        d0 = led.dispatches(kernel="parallel.collective_join")
        t0 = time.perf_counter()
        report = group.join()
        coll.append(time.perf_counter() - t0)
        disp_per_round.append(
            led.dispatches(kernel="parallel.collective_join") - d0)
        assert report.bytes_to_wire == 0
    # The PR's runtime-asserted invariant: intra-pod anti-entropy is
    # exactly ONE dispatch and moves zero bytes onto the pack path.
    assert set(disp_per_round) == {1}, disp_per_round
    assert pack_copy_bytes() == bytes_before

    t0 = time.perf_counter()
    nochange_report = group.join()
    nochange_s = time.perf_counter() - t0
    assert nochange_report.adopted == 0

    # --- dispatch-floor re-read (MULTICHIP_SCALE probe shape) ---
    @jax.jit
    def _touch(store):
        return type(store)(*((ln if ln.dtype == bool else ln + 0)
                             for ln in store))
    st = reps[0]._store
    jax.block_until_ready(_touch(st))
    floor = float("inf")
    for _ in range(max(4, rounds // 2)):
        t0 = time.perf_counter()
        jax.block_until_ready(_touch(st))
        floor = min(floor, time.perf_counter() - t0)

    # --- loopback baseline: pooled packed rounds over a real socket ---
    a = GossipNode(DenseCrdt("a", n_slots=n_slots))
    b = GossipNode(DenseCrdt("b", n_slots=n_slots))
    loop, loop_bytes = [], 0
    with a, b:
        peer = a.add_peer("b", b.host, b.port)
        write(a.crdt, k)
        write(b.crdt, k)
        assert a.sync_peer("b") == "ok"   # first contact: connect+hello
        for _ in range(rounds):
            write(a.crdt, k)
            t0 = time.perf_counter()
            assert a.sync_peer("b") == "ok"
            loop.append(time.perf_counter() - t0)
        loop_bytes = peer.stats.bytes_sent + peer.stats.bytes_received

    coll_s, loop_s = med(coll), med(loop)
    return {
        "metric": "collective_join", "unit": "s/round",
        "n_slots": n_slots, "rows_per_round": k, "members": members,
        "platform": jax.devices()[0].platform,
        "collective_round_s": round(coll_s, 6),
        "collective_nochange_s": round(nochange_s, 6),
        "collective_dispatches_per_round": 1,
        "collective_bytes_to_wire": 0,
        "loopback_round_s": round(loop_s, 6),
        "loopback_bytes_total": int(loop_bytes),
        "collective_speedup_vs_loopback": round(loop_s / coll_s, 3),
        "dispatch_floor_ms": round(floor * 1e3, 3),
        "round_over_floor_ms": round((coll_s - floor) * 1e3, 3),
        # Downscale honesty (satellite: trajectory records must carry
        # it): the member mesh is virtual devices on shared cores, so
        # wall time is an upper bound for a real ICI pod.
        "_host_class": host_class() + "-virtualmesh",
        "downscale_caveat": (
            f"{members}-member mesh is "
            f"xla_force_host_platform_device_count virtual devices "
            "time-slicing one host CPU, not ICI-linked chips; "
            "dispatch and byte counts are exact, wall time is an "
            "upper bound"),
    }


def bench_antientropy(replicas: int = 64, divergent: int = 8,
                      store_sizes=(1 << 10, 1 << 12, 1 << 14),
                      max_ring_sweeps: int = 8) -> dict:
    """Topology soak for the merkle anti-entropy path: ``replicas``
    in-process `DenseCrdt`s (no sockets — `sync.sync_merkle` keeps the
    same walk/range accounting the wire path reports) converge from a
    common seed, each writes ``divergent`` slots of its own, and the
    mesh heals through star and ring sweeps. The scaling table re-runs
    the star soak at growing store sizes with the SAME divergence —
    the acceptance claim is that total anti-entropy traffic tracks the
    divergence column, not the store-size column (full-scan traffic,
    shown alongside, tracks store size)."""
    from crdt_tpu.models.dense_crdt import DenseCrdt
    from crdt_tpu.sync import _packed_nbytes, sync_merkle

    def build_mesh(n_slots):
        nodes = [DenseCrdt(f"r{i}", n_slots=n_slots)
                 for i in range(replicas)]
        seed_ids = list(range(0, n_slots, 2))
        nodes[0].put_batch(seed_ids, [i % 997 for i in seed_ids])
        packed, pids = nodes[0].pack_since(None)
        for node in nodes[1:]:
            node.merge_packed(packed, pids)
        # partition-era writes: every replica touches its own window
        for i, node in enumerate(nodes):
            lo = (i * divergent) % (n_slots - divergent)
            node.put_batch(list(range(lo, lo + divergent)),
                           [i * 1000 + j for j in range(divergent)])
        return nodes

    def converged(nodes):
        root = nodes[0].digest_tree().root
        return all(n.digest_tree().root == root for n in nodes[1:])

    def soak(nodes, edges_per_sweep, max_sweeps):
        acc = {"sweeps": 0, "syncs": 0, "total_bytes": 0,
               "digest_bytes": 0, "payload_bytes": 0,
               "max_walk_rounds": 0}
        for _ in range(max_sweeps):
            acc["sweeps"] += 1
            for a, b in edges_per_sweep(nodes):
                rep = sync_merkle(a, b)
                acc["syncs"] += 1
                acc["total_bytes"] += rep.total_bytes
                acc["digest_bytes"] += rep.digest_bytes
                acc["payload_bytes"] += rep.payload_bytes
                acc["max_walk_rounds"] = max(acc["max_walk_rounds"],
                                             rep.rounds)
            if converged(nodes):
                break
        acc["converged"] = converged(nodes)
        return acc

    def star_edges(nodes):
        return [(nodes[0], s) for s in nodes[1:]]

    def ring_edges(nodes):
        return [(nodes[i], nodes[(i + 1) % len(nodes)])
                for i in range(len(nodes))]

    base_n = store_sizes[len(store_sizes) // 2]
    out = {"metric": "merkle_antientropy_soak", "unit": "bytes",
           "replicas": replicas,
           "divergent_slots_per_replica": divergent,
           "platform": jax.devices()[0].platform,
           "star": soak(build_mesh(base_n), star_edges, 3),
           "ring": soak(build_mesh(base_n), ring_edges,
                        max_ring_sweeps)}
    out["star"]["n_slots"] = out["ring"]["n_slots"] = base_n

    scaling = []
    for n_slots in store_sizes:
        nodes = build_mesh(n_slots)
        full_scan = _packed_nbytes(nodes[0].pack_since(None)[0])
        row = soak(nodes, star_edges, 3)
        scaling.append({"n_slots": n_slots,
                        "star_total_bytes": row["total_bytes"],
                        "star_payload_bytes": row["payload_bytes"],
                        "one_full_scan_bytes": int(full_scan),
                        "converged": row["converged"]})
    out["scaling"] = scaling
    lo, hi = scaling[0], scaling[-1]
    out["store_growth"] = round(hi["n_slots"] / lo["n_slots"], 1)
    out["traffic_growth"] = round(
        hi["star_total_bytes"] / lo["star_total_bytes"], 3)
    out["full_scan_growth"] = round(
        hi["one_full_scan_bytes"] / lo["one_full_scan_bytes"], 3)

    # Canary convergence matrix + trace overhead (docs/OBSERVABILITY
    # .md): every replica beats its reserved canary slot once, the
    # star soak heals the mesh, and the SAME pure fleet math the
    # network poller uses (crdt_tpu.obs.fleet) turns the converged
    # snapshots into a per-(origin, observer) lag matrix and an SLO
    # verdict — no sockets, so the soak exercises the math at mesh
    # scale. The soak is also re-timed with the trace ring enabled
    # (every sync_merkle emits a round-id'd span) to pin the tracing
    # overhead against the observability layer's 5% budget; min-of-
    # repeats on both sides keeps host scheduling noise out of the
    # ratio.
    from crdt_tpu.obs.fleet import evaluate_slo, lag_matrix
    from crdt_tpu.obs.probe import CanaryProbe
    from crdt_tpu.obs.trace import tracer

    def canary_soak():
        nodes = build_mesh(base_n)
        probes = [CanaryProbe(node, i, replicas)
                  for i, node in enumerate(nodes)]
        for p in probes:
            p.beat()
        t0 = time.perf_counter()
        rep = soak(nodes, star_edges, 3)
        dt = time.perf_counter() - t0
        snaps = {f"r{i}": {"canary": p.snapshot()}
                 for i, p in enumerate(probes)}
        return dt, rep, snaps

    plain_dt, rep, snaps = canary_soak()
    was_on = tracer().enabled
    plain_ts, traced_ts = [plain_dt], []
    try:
        # Alternated untraced/traced samples, each a fresh-mesh soak
        # (the honest workload — a converged mesh re-soaks for near
        # free). A single soak is ~tens of ms, the same order as this
        # host's scheduling jitter, so the ratio comes from the mean
        # of each side's 3 fastest samples: pairing cancels slow
        # drift, the fastest-k floor drops preemption spikes, and the
        # pair count adapts to a wall-clock budget so full-size
        # meshes don't pay smoke-size repetition. GC is paused inside
        # each pair (collected at the seam): a soak this small emits
        # only ~a hundred events, so a collector pass landing in one
        # sample but not its twin would otherwise dominate the very
        # per-event cost being measured.
        import gc
        deadline = time.perf_counter() + 3.0
        pairs = 0
        while pairs < 4 or (pairs < 8
                            and time.perf_counter() < deadline):
            gc.collect()
            gc.disable()
            try:
                tracer().enable(capacity=4096)
                traced_ts.append(canary_soak()[0])
                if not was_on:
                    tracer().disable()
                plain_ts.append(canary_soak()[0])
            finally:
                gc.enable()
            pairs += 1
    finally:
        if not was_on:
            tracer().disable()

    def floor(ts, k=3):
        best = sorted(ts)[:k]
        return sum(best) / len(best)

    overhead = max(0.0, floor(traced_ts) / floor(plain_ts) - 1.0)

    matrix = lag_matrix(snaps)
    verdict = evaluate_slo(snaps, matrix)
    out["canary"] = {
        "origins": len(matrix["origins"]),
        "observers": len(matrix["observers"]),
        "matrix_complete": matrix["complete"],
        "max_lag_s": matrix["max_lag_s"],
        "soak_converged": rep["converged"],
    }
    out["trace_overhead_frac"] = round(overhead, 4)
    out["trace_overhead_budget_frac"] = 0.05
    out["trace_overhead_within_budget"] = overhead < 0.05
    out["_slo"] = verdict
    return out


def bench_serve(sessions: int = 10000, rate_hz: float = 1.0,
                duration: float = 10.0, warmup: float = 3.0,
                n_slots: int = 1 << 14,
                flush_interval: float = 0.002,
                connect_batch: int = 500) -> dict:
    """Open-loop serving-tier load: ``sessions`` concurrent client
    sessions multiplexed onto ONE `ServeTier` (docs/SERVING.md), each
    issuing framed ``put`` ops on its own fixed schedule of
    ``rate_hz`` ops/s. The schedule is ABSOLUTE (open loop): a slow
    ack does not delay the next send's timestamp, and every latency is
    measured from the op's scheduled time — so queueing delay shows up
    in the percentiles instead of being coordinated-omission'd away.

    The fleet runs on its own asyncio loop in the bench thread while
    the tier serves from its loop thread; both are in-process, so the
    number includes both sides' Python framing cost (conservative).
    Reports p50/p99 write-ack latency, aggregate acked ops/s, writes
    per combiner flush (the tentpole ratio: N clients -> one batched
    stamp + one scatter per tick), and the shed/dropped counters —
    the acceptance gate is p99 within 5x the PR 5 single-client flush
    p50 (0.85 ms -> 4.25 ms budget) with zero sessions dropped below
    the admission watermark."""
    import asyncio
    import resource
    import struct as _struct
    from crdt_tpu import DenseCrdt, ServeTier
    from crdt_tpu.net import (BINOP_PUT, BINOP_ST_OK,
                              decode_binop_reply, encode_binop_request)
    from crdt_tpu.obs.fleet import evaluate_slo
    from crdt_tpu.obs.registry import default_registry
    from crdt_tpu.serve import read_bytes_frame_async, read_frame_async

    # fd budget: the tier process holds ONE server-side fd per
    # session; the fleet runs in a forked child whose client-side fds
    # count against a SEPARATE limit — that split is what seats 10k
    # sessions under a 20k per-process fd cap that an in-process
    # fleet (2 fds/session) would blow through.
    need = sessions + 512
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < need:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(need, hard), hard))
        except (ValueError, OSError):
            pass
    soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    requested = sessions
    if soft < need:
        sessions = max(1, soft - 512)

    head = _struct.Struct(">I")

    async def session(reader, writer, k, start, warm_end, end,
                      lats, counters, interval, n_sess):
        loop = asyncio.get_running_loop()
        slot = k % n_slots
        # Sessions phase uniformly across one interval so the offered
        # load is flat, not a thundering herd at each schedule edge.
        t0 = start + (k / max(1, n_sess)) * interval
        i = 0
        try:
            while True:
                sched = t0 + i * interval
                if sched >= end:
                    return
                now = loop.time()
                if sched > now:
                    await asyncio.sleep(sched - now)
                body = json.dumps({"op": "put", "slot": slot,
                                   "value": i}).encode()
                writer.write(head.pack(len(body)) + body)
                await writer.drain()
                reply = await read_frame_async(reader)
                if not (isinstance(reply, dict) and reply.get("ok")):
                    counters["errors"] += 1
                    return
                counters["acked"] += 1
                if sched >= warm_end:
                    lats.append(loop.time() - sched)
                i += 1
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            counters["errors"] += 1
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def fleet(host, port, n_sess, rate, warm, dur):
        loop = asyncio.get_running_loop()
        interval = 1.0 / rate
        lats: list = []
        counters = {"acked": 0, "errors": 0, "connect_failures": 0}
        conns = []
        for base in range(0, n_sess, connect_batch):
            n = min(connect_batch, n_sess - base)
            res = await asyncio.gather(
                *(asyncio.open_connection(host, port)
                  for _ in range(n)),
                return_exceptions=True)
            for r in res:
                if isinstance(r, BaseException):
                    counters["connect_failures"] += 1
                else:
                    conns.append(r)
        start = loop.time() + 1.0
        warm_end = start + warm
        end = warm_end + dur
        await asyncio.gather(*(
            session(r, w, k, start, warm_end, end, lats, counters,
                    interval, n_sess)
            for k, (r, w) in enumerate(conns)))
        return lats, counters, len(conns)

    def pct_ms(xs, p):
        if not xs:
            return None
        return round(xs[min(len(xs) - 1,
                            int(p * (len(xs) - 1)))] * 1e3, 3)

    crdt = DenseCrdt("srv", n_slots=n_slots)
    ticks_c = default_registry().counter(
        "crdt_tpu_ingest_flush_total",
        "write-combiner flushes by trigger")
    with ServeTier(crdt, max_sessions=sessions + 64,
                   flush_interval=flush_interval) as tier:
        # Warm the padded-commit jit buckets first: a tick batch pads
        # to the next power of two, and a first-contact bucket compile
        # (~200 ms on CPU) inside the measured window would read as a
        # fake p99 spike that no steady-state server ever pays.
        with tier.lock:
            for sz in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                       2048, 4096):
                sz = min(sz, n_slots)
                crdt.put_batch(list(range(sz)), [0] * sz)
                crdt.drain_ingest()
        # Same-platform yardstick: ONE session through the same tier
        # (tick wait + commit, nothing queued behind anyone). The PR 5
        # 0.85 ms flush p50 was measured on the driver's accelerator;
        # this run's honest 5x comparison is against THIS host.
        base_lats, _, _ = asyncio.run(
            fleet(tier.host, tier.port, 1, 50.0, 0.5, 2.0))
        base_lats.sort()
        single_p50 = pct_ms(base_lats, 0.50)
        ticks0 = ticks_c.value(trigger="tick", node="srv")

        # Ack attribution: the tier decomposes every acked write into
        # queue_wait / stamp / scatter / ack_write phase observations
        # (crdt_tpu_serve_ack_phase_seconds); the per-phase histogram
        # SUM deltas across the measured run must reconstruct the ack
        # histogram's sum delta — the 10% acceptance bound from the
        # PR 11 issue. Deltas, not absolutes: the jit warm loop and
        # the single-session yardstick above already observed.
        def _hist_sums(h, key=None):
            out = {}
            for s in h.samples():
                if s["labels"].get("node") != "srv":
                    continue
                out[s["labels"].get(key, "")] = (s["count"], s["sum"])
            return out

        ack_h = default_registry().histogram(
            "crdt_tpu_serve_ack_seconds")
        phase_h = default_registry().histogram(
            "crdt_tpu_serve_ack_phase_seconds")
        ack0 = _hist_sums(ack_h)
        phase0 = _hist_sums(phase_h, "phase")
        # The fleet forks: client fds land in the child's own limit.
        # Fork start method, so the closures need no pickling; only
        # the result crosses back (the child never touches jax or the
        # replica — pure asyncio socket work).
        import multiprocessing
        ctx = multiprocessing.get_context("fork")
        rq = ctx.SimpleQueue()

        def _fleet_child():
            try:
                # The forked heap (jax, the tier, ...) is dead weight
                # to the fleet; freeze it so the child's GC never
                # stalls every in-flight op scanning it.
                import gc
                gc.freeze()
                rq.put(asyncio.run(fleet(
                    tier.host, tier.port, sessions, rate_hz, warmup,
                    duration)))
            except BaseException as e:  # surfaced in the parent
                rq.put({"error": f"{type(e).__name__}: {e}"})

        proc = ctx.Process(target=_fleet_child, daemon=True)
        proc.start()
        res = rq.get()
        proc.join(timeout=60)
        if isinstance(res, dict):
            raise RuntimeError(f"serve fleet failed: {res['error']}")
        lats, counters, connected = res

        def _delta(after, before):
            return {k: (c - before.get(k, (0, 0.0))[0],
                        s - before.get(k, (0, 0.0))[1])
                    for k, (c, s) in after.items()}

        # Snapshot the open-loop run's deltas BEFORE the lane
        # scenario below adds its own ticks and ack observations.
        ticks = int(ticks_c.value(trigger="tick", node="srv") - ticks0)
        ack_d = _delta(_hist_sums(ack_h), ack0)
        phase_d = _delta(_hist_sums(phase_h, "phase"), phase0)

        # --- dual-lane scenario (docs/WIRE.md): the SAME tier, the
        # same open-loop frame schedule, equal seated sessions —
        # JSON one-op-per-frame vs the negotiated binary lane at
        # `lane_batch` ops per frame. The per-seat frame budget is
        # what a real client fleet holds constant (its send loop), so
        # acked-ops/s ratio IS the lane's per-host ceiling gain, and
        # it is only achieved if the tier actually keeps up: a decode
        # stall or shed session shows up as lane errors and a ratio
        # below the x5 acceptance gate. Byte counts are whole-wire
        # (header + body, both directions) per ACKED op.
        lane_sessions = min(sessions, 1000)
        lane_batch = 16
        lane_rate = 2.0
        lane_warm = min(warmup, 1.0)
        lane_dur = min(duration, 5.0)

        async def lane_session(reader, writer, k, start, end,
                               ctrs, interval, n_sess, lane):
            loop = asyncio.get_running_loop()
            slot0 = (k * lane_batch) % n_slots
            try:
                if lane == "bin":
                    hello = json.dumps({"op": "hello", "proto": 1,
                                        "caps": ["binop"]}).encode()
                    writer.write(head.pack(len(hello)) + hello)
                    await writer.drain()
                    reply = await read_frame_async(reader)
                    if not (isinstance(reply, dict)
                            and reply.get("ok")
                            and "binop" in reply.get("caps", ())):
                        ctrs["errors"] += 1
                        return
                t0 = start + (k / max(1, n_sess)) * interval
                i = 0
                while True:
                    sched = t0 + i * interval
                    if sched >= end:
                        return
                    now = loop.time()
                    if sched > now:
                        await asyncio.sleep(sched - now)
                    if lane == "bin":
                        # Post-hello framing is codec-tagged: one
                        # 0x00 raw tag ahead of the binop body.
                        slots = [(slot0 + j) % n_slots
                                 for j in range(lane_batch)]
                        body = b"\x00" + b"".join(
                            bytes(p) for p in encode_binop_request(
                                [BINOP_PUT] * lane_batch, slots,
                                [i] * lane_batch))
                        writer.write(head.pack(len(body)) + body)
                        await writer.drain()
                        raw = await read_bytes_frame_async(reader)
                        if raw is None:
                            ctrs["errors"] += 1
                            return
                        status, _, _ = decode_binop_reply(raw[1:])
                        if not (status == BINOP_ST_OK).all():
                            ctrs["errors"] += 1
                            return
                        ctrs["acked"] += lane_batch
                        ctrs["bytes"] += 8 + len(body) + len(raw)
                    else:
                        body = json.dumps({"op": "put", "slot": slot0,
                                           "value": i}).encode()
                        writer.write(head.pack(len(body)) + body)
                        await writer.drain()
                        raw = await read_bytes_frame_async(reader)
                        reply = (None if raw is None
                                 else json.loads(raw))
                        if not (isinstance(reply, dict)
                                and reply.get("ok")):
                            ctrs["errors"] += 1
                            return
                        ctrs["acked"] += 1
                        ctrs["bytes"] += 8 + len(body) + len(raw)
                    i += 1
            except (ConnectionError, OSError,
                    asyncio.IncompleteReadError):
                ctrs["errors"] += 1
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def lane_fleet(lane):
            loop = asyncio.get_running_loop()
            interval = 1.0 / lane_rate
            ctrs = {"acked": 0, "errors": 0, "bytes": 0,
                    "connect_failures": 0}
            conns = []
            for base in range(0, lane_sessions, connect_batch):
                m = min(connect_batch, lane_sessions - base)
                got = await asyncio.gather(
                    *(asyncio.open_connection(tier.host, tier.port)
                      for _ in range(m)),
                    return_exceptions=True)
                for r in got:
                    if isinstance(r, BaseException):
                        ctrs["connect_failures"] += 1
                    else:
                        conns.append(r)
            start = loop.time() + 0.5 + lane_warm
            end = start + lane_dur
            await asyncio.gather(*(
                lane_session(r, w, k, start, end, ctrs, interval,
                             lane_sessions, lane)
                for k, (r, w) in enumerate(conns)))
            return ctrs

        copy_c = default_registry().counter(
            "crdt_tpu_pack_copy_bytes_total",
            "bytes copied between pack and frame (zero on the "
            "arena fast path)")

        def _copy_total():
            return sum(s["value"] for s in copy_c.samples())

        json_ctrs = asyncio.run(lane_fleet("json"))
        copy0 = _copy_total()
        bin_ctrs = asyncio.run(lane_fleet("bin"))
        pack_copy_delta = int(_copy_total() - copy0)
        shed, dropped = tier.shed_count, tier.dropped_sessions
    ack_n, ack_sum = ack_d.get("", (0, 0.0))
    phase_sum = sum(s for _, s in phase_d.values())
    attribution = (phase_sum / ack_sum) if ack_sum else None

    lats.sort()
    n = len(lats)
    p99 = pct_ms(lats, 0.99)

    # Server-side quantile plane (PR 18): the ack histogram's log2
    # bucket ceiling next to the sketch-true p99 from the same run.
    # Two separate trajectory keys — "ceiling" is a skip token
    # (obs/trajectory.py), so the quantized upper bound is recorded
    # but never regression-gated, while the sketch key is honest
    # enough to gate.
    from crdt_tpu.obs.fleet import histogram_quantile
    ack_ceiling_s = None
    for s in ack_h.samples():
        if s.get("labels", {}).get("node") == "srv":
            q = histogram_quantile(s, 0.99)
            if q is not None and q != float("inf"):
                ack_ceiling_s = q
    ack_sk = default_registry().sketch(
        "crdt_tpu_serve_ack_seconds_sketch")
    ack_sk_p99_s = ack_sk.quantile(0.99, node="srv")
    sketch_probe = _sketch_overhead(
        (ack_sum / ack_n) if ack_n else None)
    lane_sk = default_registry().sketch(
        "crdt_tpu_serve_ack_lane_seconds_sketch")
    json_lane_p99_s = lane_sk.quantile(0.99, lane="json", node="srv")
    bin_lane_p99_s = lane_sk.quantile(0.99, lane="bin", node="srv")
    json_lane_ops_s = json_ctrs["acked"] / lane_dur
    bin_lane_ops_s = bin_ctrs["acked"] / lane_dur
    lane_ratio = (bin_lane_ops_s / json_lane_ops_s
                  if json_lane_ops_s else None)
    return {
        "metric": "serve_open_loop", "unit": "ops/s",
        "platform": jax.devices()[0].platform,
        "sessions": requested, "sessions_connected": connected,
        "rate_per_session_hz": rate_hz,
        "flush_interval_ms": flush_interval * 1e3,
        "n_slots": n_slots,
        "warmup_s": warmup, "duration_s": duration,
        "ops_s": round(n / duration, 1),
        "ops_measured": n,
        "ops_acked_total": counters["acked"],
        "p50_ms": pct_ms(lats, 0.50), "p90_ms": pct_ms(lats, 0.90),
        "p99_ms": p99, "max_ms": pct_ms(lats, 1.0),
        "combiner_ticks": ticks,
        "writes_per_flush": (round(counters["acked"] / ticks, 2)
                             if ticks else None),
        "shed_count": shed,
        "dropped_sessions": dropped,
        "session_errors": counters["errors"],
        "connect_failures": counters["connect_failures"],
        # Per-phase mean over the measured run (docs/OBSERVABILITY.md
        # "Ack attribution"); the phase sums must reconstruct the ack
        # histogram's sum to within 10% or the attribution is lying.
        "ack_phase_mean_ms": {
            k: (round(1e3 * s / c, 4) if c else None)
            for k, (c, s) in sorted(phase_d.items())},
        "ack_mean_ms": (round(1e3 * ack_sum / ack_n, 4)
                        if ack_n else None),
        "ack_phase_sum_vs_ack": (round(attribution, 4)
                                 if attribution is not None else None),
        "attribution_within_10pct": (
            attribution is not None
            and abs(attribution - 1.0) <= 0.10),
        "baseline_single_client_flush_p50_ms": 0.85,
        "write_ack_p99_budget_ms": 4.25,
        "within_budget": (p99 is not None and p99 <= 4.25),
        "single_session_p50_ms": single_p50,
        "p99_vs_single_session_p50": (
            round(p99 / single_p50, 3)
            if p99 is not None and single_p50 else None),
        "within_5x_single_session": (
            p99 is not None and bool(single_p50)
            and p99 <= 5 * single_p50),
        # Server-side ack p99 both ways: the log2 histogram's bucket
        # ceiling ("ceiling" = trajectory skip token, recorded not
        # gated) and the sketch-true quantile (~1% relative error,
        # gated like any other latency key). Note these time the ack
        # from server dequeue, not the client's scheduled send, so
        # they sit below the open-loop p99_ms above.
        "ack_p99_ceiling_ms": (round(ack_ceiling_s * 1e3, 4)
                               if ack_ceiling_s is not None else None),
        "ack_p99_sketch_ms": (round(ack_sk_p99_s * 1e3, 4)
                              if ack_sk_p99_s is not None else None),
        # Dual-lane scenario (docs/WIRE.md): JSON per-op vs binary
        # batched frames through the same tier at equal seated
        # sessions and one frame schedule. The ops/s ratio is the
        # per-host ceiling gain the binary lane buys a seat-bound
        # fleet; the x5 gate only passes when the tier acks every
        # batch (lane errors collapse the ratio). bytes_per_op is
        # whole-wire both directions; pack_copy_delta_bytes proves
        # the binary ack/read path stayed on the arena discipline
        # (zero copy-counter movement across the entire bin run).
        "lane_sessions": lane_sessions,
        "lane_batch": lane_batch,
        "lane_rate_hz": lane_rate,
        "json_lane_ops_s": round(json_lane_ops_s, 1),
        "bin_lane_ops_s": round(bin_lane_ops_s, 1),
        "bin_vs_json_ops": (round(lane_ratio, 2)
                            if lane_ratio is not None else None),
        "binop_speedup_ok": (lane_ratio is not None
                             and lane_ratio >= 5.0),
        "json_bytes_per_op": (round(json_ctrs["bytes"]
                                    / json_ctrs["acked"], 1)
                              if json_ctrs["acked"] else None),
        "bin_bytes_per_op": (round(bin_ctrs["bytes"]
                                   / bin_ctrs["acked"], 1)
                             if bin_ctrs["acked"] else None),
        "json_lane_errors": json_ctrs["errors"],
        "bin_lane_errors": bin_ctrs["errors"],
        "json_lane_ack_p99_sketch_ms": (
            round(json_lane_p99_s * 1e3, 4)
            if json_lane_p99_s is not None else None),
        "bin_lane_ack_p99_sketch_ms": (
            round(bin_lane_p99_s * 1e3, 4)
            if bin_lane_p99_s is not None else None),
        "pack_copy_delta_bytes": pack_copy_delta,
        **sketch_probe,
        # Fleet SLO verdict over this process's own registry snapshot
        # (same evaluator the network poller runs); main() prints it
        # as the trailing JSON line CI gates on. Since PR 18 the ack
        # check is sketch-sourced (source="sketch"): true p99 within
        # ~1% relative error, not the log2 bucket ceiling.
        "_slo": evaluate_slo({"srv": default_registry().snapshot()}),
    }


def bench_federate(sessions: int = 100000, partitions: int = 4,
                   rate_hz: float = 0.25, duration: float = 10.0,
                   warmup: float = 3.0, n_slots: int = 1 << 14,
                   flush_interval: float = 0.002,
                   connect_batch: int = 500,
                   split_at_frac: float = 0.4,
                   offered_cap_ops_s: float = 1000.0,
                   recovery_s: float = 3.0) -> dict:
    """Federated serving under a live partition split
    (docs/FEDERATION.md): ``sessions`` open-loop client sessions
    spread across ``partitions`` ServeTier partitions behind one
    `FederatedTier`, each session pinned to one slot and connected to
    that slot's owner. Mid-run (``split_at_frac`` of the way through
    the measured window) the hot partition is split live: the donor
    streams the migrating range while writes keep flowing, then the
    routing epoch flips and every affected session absorbs one
    ``moved`` redirect and reconnects to the new owner.

    Sessions are federation-aware (hello cap), so a redirect is a
    typed retry, never a drop: the acceptance gate is attempts ==
    acked with zero session errors across the flip, and STEADY-STATE
    post-split ack p99 (acks later than ``recovery_s`` after the
    flip) within the SERVE_r01 envelope (14.6 ms). Latencies are
    measured from the op's SCHEDULED time (open loop), so redirect
    and reconnect cost lands in the percentiles instead of being
    coordinated-omission'd away — which is also why the flip
    transient is reported separately: an epoch flip hands every
    session one `moved` inside one round-trip window, and that burst
    is a real, bounded cost the full post-split percentile would
    otherwise smear over the steady state.

    The nominal shape is 4x25k sessions; like bench_serve, the run
    downsizes honestly to the host's fd ceiling — and further to the
    host's measured serving envelope (``offered_cap_ops_s``; this
    host class saturates near 1k ops/s once five tiers, the fleet
    child, and the split streaming share one core) — and reports
    both the requested and the seated counts."""
    import asyncio
    import resource
    import struct as _struct
    import zlib as _zlib
    from crdt_tpu import FederatedTier
    from crdt_tpu.obs.fleet import evaluate_slo
    from crdt_tpu.obs.registry import default_registry

    # fd budget: the parent holds ONE server-side fd per session
    # (spread across the partition tiers, which all live in this
    # process); the forked fleet child holds the client side against
    # its own limit. Redirect reconnects close-then-open, so the
    # split does not move the high-water mark.
    need = sessions + 1024
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < need:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(need, hard), hard))
        except (ValueError, OSError):
            pass
    soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    requested = sessions
    if soft < need:
        sessions = max(1, soft - 1024)
    # Host-envelope cap, applied after the fd cap: the SERVE_r01 host
    # class (single core) tops out near 2.5k acked ops/s through the
    # serving path — offering more measures the host's own saturation
    # (a seconds-deep open-loop backlog that also keeps the migrating
    # range too hot for a split to ever settle), not the federation.
    # Both the requested and the seated counts are reported.
    cap = max(1, int(offered_cap_ops_s / max(rate_hz, 1e-9)))
    sessions = min(sessions, cap)

    head = _struct.Struct(">I")

    async def _recv(reader, tagged):
        hd = await reader.readexactly(4)
        body = await reader.readexactly(head.unpack(hd)[0])
        if tagged:
            tag, body = body[:1], body[1:]
            if tag == b"\x01":
                body = _zlib.decompress(body)
        return json.loads(body)

    async def _send(writer, obj, tagged):
        body = json.dumps(obj).encode()
        if tagged:
            body = b"\x00" + body
        writer.write(head.pack(len(body)) + body)
        await writer.drain()

    async def _dial(addr):
        host, _, port = addr.rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        # Federation-aware session: the hello cap is what turns a
        # cross-partition op into a `moved` redirect instead of a
        # server-side proxy hop.
        await _send(writer, {"op": "hello",
                             "caps": ["federation", "semantics"]},
                    tagged=False)
        await _recv(reader, tagged=False)   # pre-codec hello reply
        return reader, writer

    async def _hangup(writer):
        try:
            writer.close()
            await writer.wait_closed()
        except Exception:
            pass

    async def session(k, conn, start, warm_end, end, lats, counters,
                      interval, n_sess, route):
        loop = asyncio.get_running_loop()
        # Stride the fleet across the WHOLE keyspace — k % n_slots
        # would pile every session into the low partition whenever
        # sessions < n_slots and the "federation" under test would
        # secretly be one tier plus idle bystanders.
        slot = (k * n_slots) // max(1, n_sess)
        owner = route["table"].owner_of(slot)
        epoch = route["table"].epoch
        t0 = start + (k / max(1, n_sess)) * interval
        reader, writer = conn
        i = 0
        try:
            while True:
                sched = t0 + i * interval
                if sched >= end:
                    return
                now = loop.time()
                if sched > now:
                    await asyncio.sleep(sched - now)
                counters["attempts"] += 1
                tries = 0
                while True:
                    tries += 1
                    if tries > 64:
                        counters["errors"] += 1
                        return
                    try:
                        if writer is None:
                            reader, writer = await _dial(owner)
                        await _send(writer,
                                    {"op": "put", "slot": slot,
                                     "value": i, "epoch": epoch},
                                    tagged=True)
                        reply = await _recv(reader, tagged=True)
                    except (ConnectionError, OSError,
                            asyncio.IncompleteReadError):
                        counters["reconnects"] += 1
                        if writer is not None:
                            await _hangup(writer)
                        writer = None
                        await asyncio.sleep(0.01)
                        continue
                    if reply.get("ok"):
                        counters["acked"] += 1
                        break
                    code = reply.get("code")
                    if code == "moved":
                        # Typed redirect: the reply names this slot's
                        # owner under the fresh epoch — exactly what a
                        # single-slot session needs; no table re-fetch
                        # round trip. An epoch flip sends every
                        # session one moved, but only sessions whose
                        # range actually migrated change owner — the
                        # rest retry on the SAME connection with the
                        # new epoch, so the flip is not a reconnect
                        # herd.
                        counters["moved"] += 1
                        new_owner = reply.get("owner") or owner
                        epoch = reply.get("epoch", epoch)
                        if new_owner != owner:
                            owner = new_owner
                            await _hangup(writer)
                            writer = None
                    elif code == "busy":
                        counters["busy"] += 1
                        await asyncio.sleep(0.01)
                    else:
                        counters["errors"] += 1
                        return
                ack_t = loop.time()
                if sched >= warm_end:
                    lats.append((ack_t, ack_t - sched))
                i += 1
        finally:
            try:
                if writer is not None:
                    writer.close()
            except Exception:
                pass

    async def fleet(seed_addr, n_sess, rate, warm, dur, started):
        loop = asyncio.get_running_loop()
        from crdt_tpu.routing import RoutingTable
        # One pre-hello route fetch seeds every session's owner map.
        r, w = await asyncio.open_connection(
            *seed_addr.rpartition(":")[::2])
        await _send(w, {"op": "route"}, tagged=False)
        rep = await _recv(r, tagged=False)
        w.close()
        route = {"table": RoutingTable.from_json(rep["routing"])}
        interval = 1.0 / rate
        lats: list = []
        counters = {"attempts": 0, "acked": 0, "moved": 0, "busy": 0,
                    "reconnects": 0, "errors": 0,
                    "connect_failures": 0}

        # Dial (and hello) in bounded batches BEFORE the schedule
        # starts, like bench_serve — an all-at-once 19k dial storm
        # puts the fleet seconds behind its own open-loop schedule
        # and the catch-up flood poisons every percentile.
        async def _dial_k(k):
            try:
                return await _dial(route["table"].owner_of(
                    (k * n_slots) // max(1, n_sess)))
            except OSError:
                return None
        conns: list = []
        for base in range(0, n_sess, connect_batch):
            res = await asyncio.gather(
                *(_dial_k(k)
                  for k in range(base,
                                 min(base + connect_batch, n_sess))),
                return_exceptions=True)
            for r in res:
                if r is None or isinstance(r, BaseException):
                    counters["connect_failures"] += 1
                    conns.append(None)
                else:
                    conns.append(r)
        start = loop.time() + 1.0
        warm_end = start + warm
        end = warm_end + dur
        # Monotonic clocks are system-wide: the parent uses this
        # timestamp to fire the split mid-window and to segment the
        # latency series into pre/post-flip populations.
        started.put(start)
        await asyncio.gather(*(
            session(k, conn, start, warm_end, end, lats, counters,
                    interval, n_sess, route)
            for k, conn in enumerate(conns) if conn is not None))
        connected = n_sess - counters["connect_failures"]
        return lats, counters, connected

    def pct_ms(xs, p):
        if not xs:
            return None
        return round(xs[min(len(xs) - 1,
                            int(p * (len(xs) - 1)))] * 1e3, 3)

    fed = FederatedTier(n_slots, partitions=partitions,
                        flush_interval=flush_interval,
                        max_sessions=sessions + 64)
    with fed:
        # Pre-warm the padded-commit jit buckets once — the cache is
        # process-global, so one tier's warm pass covers every
        # partition AND the split recipient spawned mid-run (a
        # first-contact compile inside the measured window would read
        # as a fake post-split p99 spike).
        tier0 = fed.tiers[0]
        with tier0.lock:
            for sz in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                       2048, 4096):
                sz = min(sz, n_slots)
                tier0.crdt.put_batch(list(range(sz)), [0] * sz)
                tier0.crdt.drain_ingest()
        # ... and the pack/merge buckets the split's range streaming
        # hits (donor pack_since under its lock, recipient
        # merge_packed): a first-contact compile while the donor lock
        # is held stalls every in-flight ack behind the split.
        from crdt_tpu import DenseCrdt as _DC
        wa = _DC("warm-a", n_slots=n_slots)
        wb = _DC("warm-b", n_slots=n_slots)
        for sz in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                   2048, 4096):
            sz = min(sz, n_slots)
            wa.put_batch(list(range(sz)), [1] * sz)
            wa.drain_ingest()
            packed, ids = wa.pack_since(None, sem_mode="include",
                                        ranges=((0, n_slots),))
            wb.merge_packed(packed, ids)
        del wa, wb

        import multiprocessing
        ctx = multiprocessing.get_context("fork")
        rq = ctx.SimpleQueue()
        sq = ctx.SimpleQueue()
        seed = fed.addrs()[0]

        def _fleet_child():
            try:
                import gc
                gc.freeze()
                # Refcounting covers the fleet's per-op churn; a gen2
                # cycle pass over 4k live session coroutines is a
                # multi-ms stop-the-world that lands straight in an
                # open-loop percentile.
                gc.disable()
                rq.put(asyncio.run(fleet(seed, sessions, rate_hz,
                                         warmup, duration, sq)))
            except BaseException as e:  # surfaced in the parent
                rq.put({"error": f"{type(e).__name__}: {e}"})

        proc = ctx.Process(target=_fleet_child, daemon=True)
        proc.start()
        start_t = sq.get()
        if isinstance(start_t, dict):  # child died before the signal
            raise RuntimeError(f"federate fleet failed: "
                               f"{start_t['error']}")
        target = start_t + warmup + duration * split_at_frac
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t_arm = time.monotonic()
        split = fed.split_hot()
        t_flip = time.monotonic()
        res = rq.get()
        proc.join(timeout=60)
        if isinstance(res, dict):
            raise RuntimeError(f"federate fleet failed: "
                               f"{res['error']}")
        lats, counters, connected = res
        partitions_after = len(fed.tiers)
        shed = sum(t.shed_count for t in fed.tiers)
        dropped = sum(t.dropped_sessions for t in fed.tiers)

    pre = sorted(l for (t, l) in lats if t < t_arm)
    post = sorted(l for (t, l) in lats if t >= t_flip)
    steady = sorted(l for (t, l) in lats
                    if t >= t_flip + recovery_s)
    allv = sorted(l for (_, l) in lats)
    steady_p99 = pct_ms(steady, 0.99)
    zero_dropped = (counters["errors"] == 0
                    and counters["acked"] == counters["attempts"])
    return {
        "metric": "federate_live_split", "unit": "ops/s",
        "platform": jax.devices()[0].platform,
        "sessions": requested, "sessions_connected": connected,
        "partitions": partitions, "partitions_after": partitions_after,
        "rate_per_session_hz": rate_hz,
        "flush_interval_ms": flush_interval * 1e3,
        "n_slots": n_slots,
        "warmup_s": warmup, "duration_s": duration,
        "ops_s": round(len(allv) / duration, 1),
        "ops_attempted": counters["attempts"],
        "ops_acked": counters["acked"],
        "moved_redirects": counters["moved"],
        "busy_retries": counters["busy"],
        "reconnects": counters["reconnects"],
        "session_errors": counters["errors"],
        "connect_failures": counters["connect_failures"],
        "shed_count": shed,
        "dropped_sessions": dropped,
        "zero_dropped_writes": zero_dropped,
        "p50_ms": pct_ms(allv, 0.50), "p99_ms": pct_ms(allv, 0.99),
        "pre_split_p50_ms": pct_ms(pre, 0.50),
        "pre_split_p99_ms": pct_ms(pre, 0.99),
        # Full post-flip population (includes the one-round-trip
        # moved burst every session absorbs at the epoch flip) vs the
        # steady state the tier settles back into.
        "post_split_p50_ms": pct_ms(post, 0.50),
        "post_split_p99_ms": pct_ms(post, 0.99),
        "recovery_window_s": recovery_s,
        "post_split_steady_p50_ms": pct_ms(steady, 0.50),
        "post_split_steady_p99_ms": steady_p99,
        "split": {
            "src": split.get("src"), "range": split.get("range"),
            "rounds": split.get("rounds"),
            "rows_migrated": split.get("migrated_rows"),
            "seconds": split.get("seconds"),
            "epoch": split.get("epoch"),
        },
        # SERVE_r01 envelope: the single-tier 10k-session run acked
        # at p99 14.6 ms on this host class; a live split must
        # settle the post-flip steady state back inside the same
        # envelope with zero dropped writes.
        "post_split_ack_p99_budget_ms": 14.6,
        "within_budget": (zero_dropped and steady_p99 is not None
                          and steady_p99 <= 14.6),
        # SLO over this process's registry, with the ack budget set
        # to the federate envelope (14.6 ms, SERVE_r01's p99): the
        # histogram includes every redirect-burst ack around the
        # flip, which the single-tier 4.25 ms steady-state budget was
        # never meant to cover.
        "_slo": evaluate_slo(
            {"federation": default_registry().snapshot()},
            ack_p99_budget_s=0.0146),
    }


def bench_failover(replicas: int = 3, ack_replicas: int = 1,
                   writers: int = 8, slots_per_writer: int = 8,
                   kills: int = 3, rate_hz: float = 100.0,
                   n_slots: int = 1 << 10,
                   flush_interval: float = 0.002,
                   heartbeat_interval: float = 0.03,
                   lease_misses: int = 3,
                   mttr_budget_s: float = 2.5,
                   settle_s: float = 8.0) -> dict:
    """Chaos bench: kill the primary of a replica group under a
    sustained client write storm, ``kills`` times in a row.

    One `ReplicaGroup` (docs/REPLICATION.md) serves a single-arc
    keyspace; ``writers`` client threads write monotone values to
    disjoint slots through the routed `FederatedClient` retry loop.
    Each cycle abruptly kills the live primary (RST, no drain),
    measures client-observed MTTR (kill -> first acked write at a
    bumped routing epoch), verifies every write acked before the
    kill is still readable from the new primary, then rejoins the
    corpse as a follower. Gates: zero acked writes lost, the routing
    epoch advances on every failover, all MTTRs within budget, and
    all replicas end digest-root convergent."""
    import threading

    from crdt_tpu import FederatedClient
    from crdt_tpu.obs.fleet import evaluate_slo, poll_fleet
    from crdt_tpu.obs.trajectory import host_class
    from crdt_tpu.replication import ReplicaGroup

    assert writers * slots_per_writer < n_slots - 1

    # Pre-warm the jit caches every measured path hits (process-
    # global, so one pass covers all replicas and every rejoin
    # generation): padded-commit buckets for the flush tick,
    # pack/merge for the write-concern barrier ship, digest_tree for
    # election tie-breaks and the rejoin merkle walk. A first-contact
    # compile inside a failover window would read as fake MTTR and a
    # fake ack p99 spike.
    from crdt_tpu import DenseCrdt as _DC
    wa = _DC("warm-a", n_slots=n_slots)
    wb = _DC("warm-b", n_slots=n_slots)
    for sz in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024):
        sz = min(sz, n_slots)
        wa.put_batch(list(range(sz)), [1] * sz)
        wa.drain_ingest()
        packed, ids = wa.pack_since(None, sem_mode="include",
                                    ranges=((0, n_slots),))
        wb.merge_packed(packed, ids)
    int(wa.digest_tree().root)
    int(wb.digest_tree().root)
    del wa, wb

    group = ReplicaGroup(
        n_slots, replicas=replicas, ack_replicas=ack_replicas,
        flush_interval=flush_interval,
        heartbeat_interval=heartbeat_interval,
        heartbeat_timeout=heartbeat_interval * 5,
        lease_misses=lease_misses)
    group.start()
    seeds = group.member_addrs()

    stop = threading.Event()
    lock = threading.Lock()
    acks: list = []           # (t_mono, routing_epoch) append-only
    last_acked: dict = {}     # slot -> highest acked value
    counters = {"attempted": 0, "acked": 0, "retried": 0}
    writer_errors: list = []

    def writer(w: int) -> None:
        cli = FederatedClient(seeds, timeout=5.0)
        my = [w * slots_per_writer + j
              for j in range(slots_per_writer)]
        interval = 1.0 / rate_hz
        i = 0
        try:
            while not stop.is_set():
                slot = my[i % len(my)]
                val = i + 1
                with lock:
                    counters["attempted"] += 1
                try:
                    cli.put(slot, val)
                except (ConnectionError, ValueError):
                    # Retry budget exhausted mid-failover. The write
                    # was never acked, so it is NOT counted as loss;
                    # the storm just re-offers on the next loop.
                    with lock:
                        counters["retried"] += 1
                    time.sleep(0.05)
                    continue
                now = time.monotonic()
                with lock:
                    counters["acked"] += 1
                    last_acked[slot] = val
                    acks.append((now, cli.table.epoch))
                i += 1
                time.sleep(interval)
        except Exception as exc:  # pragma: no cover - surfaced below
            writer_errors.append(
                f"writer{w}: {type(exc).__name__}: {exc}")
        finally:
            cli.close()

    def read_floor(check: dict, whom: str) -> int:
        """Count acked writes no longer readable (the zero-loss
        gate): every slot must read back >= its last acked value."""
        reader = FederatedClient(seeds, timeout=5.0)
        try:
            lost = 0
            for slot, val in check.items():
                got = reader.get(slot)
                if got is None or int(got) < val:
                    lost += 1
            return lost
        finally:
            reader.close()

    cycles: list = []
    lost_total = 0
    converged = False
    try:
        threads = [threading.Thread(target=writer, args=(w,),
                                    daemon=True,
                                    name=f"bench-writer-{w}")
                   for w in range(writers)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with lock:
                if counters["acked"] >= writers:
                    break
            time.sleep(0.01)

        for cycle in range(kills):
            epoch_before = group.table.epoch
            with lock:
                checkpoint = dict(last_acked)
                scan_from = len(acks)
            dead = group.kill_primary()
            t_kill = time.monotonic()

            # Client-observed MTTR: first ack whose routing epoch is
            # newer than the table the dead primary owned.
            t_rec = None
            deadline = t_kill + 30.0
            while t_rec is None and time.monotonic() < deadline:
                with lock:
                    tail = acks[scan_from:]
                for t, epoch in tail:
                    if epoch > epoch_before:
                        t_rec = t
                        break
                if t_rec is None:
                    time.sleep(0.01)
            if t_rec is None:
                raise RuntimeError(
                    f"cycle {cycle}: no acked write at a new epoch "
                    f"within 30s of killing {dead.name}")
            mttr = t_rec - t_kill
            lost = read_floor(checkpoint, dead.name)
            lost_total += lost
            epoch_after = group.table.epoch
            group.rejoin(dead.index)
            cycles.append({
                "cycle": cycle, "killed": dead.name,
                "mttr_s": round(mttr, 4),
                "detect_promote_s": round(group.last_failover_s, 4),
                "epoch_before": epoch_before,
                "epoch_after": epoch_after,
                "acked_writes_lost": lost,
            })

        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        lost_total += read_floor(dict(last_acked), "final")

        # Convergence: nudge writes re-arm the flush tick so the
        # replicator ships every follower to head, then all live
        # replicas must agree on one digest root.
        nudge = FederatedClient(seeds, timeout=5.0)
        try:
            deadline = time.monotonic() + settle_s
            bump = 0
            while time.monotonic() < deadline:
                bump += 1
                nudge.put(n_slots - 1, bump)
                time.sleep(max(flush_interval * 4, 0.02))
                roots = []
                for m in group.members:
                    tier = m.tier
                    if m.role == "down" or tier is None or tier.killed:
                        continue
                    with tier.lock:
                        roots.append(int(tier.crdt.digest_tree().root))
                if len(roots) == replicas and len(set(roots)) == 1:
                    converged = True
                    break
        finally:
            nudge.close()

        peers = []
        for m in group.members:
            if m.addr is not None and m.role != "down":
                host, port = m.addr.rsplit(":", 1)
                peers.append((m.name, host, int(port)))
        snapshots = poll_fleet(peers)
        # Chaos-envelope ack budget (0.5 s, one log2 bucket above the
        # replicate timeout): the p99 window deliberately contains
        # every kill and every rejoin, and a rejoin's full-range
        # merkle walk is served under the primary's store lock — a
        # brief ack stall is the design, losing the write would be
        # the bug. The steady-state 14.6 ms federate budget was never
        # meant to price a catch-up walk.
        slo = evaluate_slo(snapshots, ack_p99_budget_s=0.5)
        # Chaos-window ack p99 both ways (PR 18): fleet-merged
        # sketch-true quantile vs the worst log2 bucket ceiling
        # ("ceiling" = trajectory skip token, recorded not gated).
        from crdt_tpu.obs.fleet import (ACK_HIST_NAME, fleet_sketch,
                                        histogram_quantile)
        fleet_sk = fleet_sketch(snapshots)
        sk_p99 = (fleet_sk.quantile(0.99)
                  if fleet_sk is not None else None)
        ceil_p99 = None
        for snap in snapshots.values():
            if not isinstance(snap, dict):
                continue
            for s in (snap.get("histograms", {})
                      .get(ACK_HIST_NAME, [])):
                q = histogram_quantile(s, 0.99)
                if q is not None and q != float("inf"):
                    ceil_p99 = (q if ceil_p99 is None
                                else max(ceil_p99, q))
    finally:
        stop.set()
        group.stop()

    mttrs = [c["mttr_s"] for c in cycles]
    epochs_advanced = all(c["epoch_after"] > c["epoch_before"]
                          for c in cycles)
    return {
        "metric": "failover_mttr", "unit": "s",
        "platform": jax.devices()[0].platform,
        "replicas": replicas, "ack_replicas": ack_replicas,
        "writers": writers, "rate_per_writer_hz": rate_hz,
        "kills": kills, "failovers": group.failovers,
        "ops_attempted": counters["attempted"],
        "ops_acked": counters["acked"],
        "ops_retried": counters["retried"],
        "mttr_s": mttrs,
        "mttr_max_s": max(mttrs),
        "detect_promote_s": [c["detect_promote_s"] for c in cycles],
        "epoch_final": group.table.epoch,
        "epoch_advanced_each_kill": epochs_advanced,
        "acked_writes_lost": lost_total,
        "rejoined_convergent": converged,
        "writer_errors": writer_errors,
        "cycles": cycles,
        "mttr_budget_s": mttr_budget_s,
        "within_budget": (lost_total == 0 and epochs_advanced
                          and converged and not writer_errors
                          and max(mttrs) <= mttr_budget_s),
        "ack_p99_sketch_s": (round(sk_p99, 6)
                             if sk_p99 is not None else None),
        "ack_p99_ceiling_s": (round(ceil_p99, 6)
                              if ceil_p99 is not None else None),
        "_slo": slo,
        # All replicas time-slice one host's cores over loopback —
        # detection and promotion pay no real network RTT, so this
        # MTTR never gates against a real multi-host deployment.
        "_host_class": host_class() + "-colocated",
        "downscale_caveat": (
            "replica group colocated on one host (loopback, shared "
            "cores); MTTR excludes real network + scheduling jitter"),
    }


def bench_elastic(period_s: float = 6.0, cycles: int = 2,
                  peak_hz: float = 600.0, trough_hz: float = 30.0,
                  writers: int = 4, slots_per_writer: int = 8,
                  n_slots: int = 1 << 10,
                  max_partitions: int = 4,
                  split_rows_per_s: float = 250.0,
                  merge_rows_per_s: float = 60.0,
                  scaler_interval: float = 0.2,
                  cooldown_s: float = 0.8,
                  ack_p99_budget_s: float = 0.0146,
                  recovery_s: float = 0.5,
                  settle_s: float = 1.5) -> dict:
    """Elastic autoscaling bench: a sine-wave write load against a
    `FederatedTier` driven by the `Autoscaler` daemon (ROADMAP item
    1 / docs/FEDERATION.md). Offered load swings trough -> peak ->
    trough over ``period_s``, ``cycles`` times; the controller must
    split partitions in on the rising edge and merge them away on the
    falling edge, live, while every acked write survives.

    Gates: the partition count tracks the load (>= ``cycles`` up-
    transitions AND >= ``cycles`` down-transitions), zero acked
    writes lost across every split/merge, and the steady-state
    client-observed ack p99 — excluding ``recovery_s`` after each
    routing-epoch flip, which is priced separately as flip recovery —
    within the SERVE_r01 federate envelope (14.6 ms)."""
    import threading

    from crdt_tpu import Autoscaler, FederatedClient, FederatedTier
    from crdt_tpu.obs.fleet import evaluate_slo
    from crdt_tpu.obs.registry import default_registry
    from crdt_tpu.obs.trajectory import host_class

    assert writers * slots_per_writer < n_slots - 1

    # Same jit pre-warm as bench_failover: a first-contact compile
    # inside a flip window would read as fake recovery latency.
    from crdt_tpu import DenseCrdt as _DC
    wa = _DC("warm-a", n_slots=n_slots)
    wb = _DC("warm-b", n_slots=n_slots)
    for sz in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024):
        sz = min(sz, n_slots)
        wa.put_batch(list(range(sz)), [1] * sz)
        wa.drain_ingest()
        packed, ids = wa.pack_since(None, sem_mode="include",
                                    ranges=((0, n_slots),))
        wb.merge_packed(packed, ids)
    int(wa.digest_tree().root)
    int(wb.digest_tree().root)
    del wa, wb

    def offered(t: float) -> float:
        """Total offered puts/s at elapsed ``t`` — a raised cosine
        that starts and ends at the trough."""
        t = min(t, period_s * cycles)
        swing = 0.5 - 0.5 * math.cos(2.0 * math.pi * t / period_s)
        return trough_hz + (peak_hz - trough_hz) * swing

    def probe() -> dict:
        # Sketch-sourced SLO probe (PR 18). Before the quantile
        # sketch, the log2 ack histogram forced this gate up to the
        # 31.3 ms bucket boundary: a true p99 anywhere in (7.8, 15.6]
        # ms reports as the bucket CEILING (15.625 ms), which a
        # 14.6 ms budget reads as breached forever — phantom split
        # pressure. The serve tiers now record a DDSketch twin next
        # to every histogram, so evaluate_slo answers the TRUE p99
        # within ~1% relative error and the controller gates at the
        # exact SERVE_r01 envelope — the same 14.6 ms the client-side
        # samples are held to below.
        return evaluate_slo({"local": default_registry().snapshot()},
                            ack_p99_budget_s=ack_p99_budget_s)

    duration = period_s * cycles + settle_s
    stop = threading.Event()
    lock = threading.Lock()
    last_acked: dict = {}          # slot -> highest acked value
    samples: list = []             # (t_done, ack_latency_s)
    counters = {"attempted": 0, "acked": 0, "retried": 0}
    writer_errors: list = []

    # Unreplicated tiers, like bench_federate: the 14.6 ms envelope
    # this bench gates against was measured without write-concern
    # follower ships (a CPU-host ship is ~50 ms of pack+merge per
    # ack, a different envelope entirely — bench_failover prices
    # that one). Replicated elasticity is the chaos drills' job
    # (tests/test_serve_federation.py -m soak).
    fed = FederatedTier(n_slots, partitions=1,
                        flush_interval=0.002)
    fed.start()
    seeds = fed.addrs()
    # Serve-path warmup: the first ops through a fresh federation pay
    # session setup plus any residual first-contact compiles, and the
    # registry ack histogram is cumulative — the spikes recorded here
    # must be diluted below the 99th percentile before the run starts,
    # or the controller's SLO probe reads the fleet as breached at the
    # trough and splits against phantom pressure.
    warm = FederatedClient(seeds, timeout=5.0)
    try:
        for i in range(800):
            warm.put(n_slots - 1, i + 1)
    finally:
        warm.close()
    t0 = time.monotonic()

    def writer(w: int) -> None:
        cli = FederatedClient(seeds, timeout=5.0)
        # Disjoint per-writer slots, strided across the WHOLE
        # keyspace so a split actually redistributes this load.
        total = writers * slots_per_writer
        my = [((w * slots_per_writer + j) * n_slots) // total
              for j in range(slots_per_writer)]
        i = 0
        try:
            while not stop.is_set():
                slot = my[i % len(my)]
                val = i + 1
                with lock:
                    counters["attempted"] += 1
                t_op = time.monotonic()
                try:
                    cli.put(slot, val)
                except (ConnectionError, ValueError):
                    # Retry budget exhausted mid-flip: never acked,
                    # so not loss — the storm re-offers next loop.
                    with lock:
                        counters["retried"] += 1
                    time.sleep(0.02)
                    continue
                now = time.monotonic()
                with lock:
                    counters["acked"] += 1
                    last_acked[slot] = val
                    samples.append((now - t0, now - t_op))
                i += 1
                time.sleep(writers / max(offered(now - t0), 1e-3))
        except Exception as exc:  # pragma: no cover - surfaced below
            writer_errors.append(
                f"writer{w}: {type(exc).__name__}: {exc}")
        finally:
            cli.close()

    trace: list = []               # (t, offered_hz, partitions, epoch)

    def sampler() -> None:
        while not stop.is_set():
            t = time.monotonic() - t0
            table = fed.table
            trace.append((round(t, 3), round(offered(t), 1),
                          len(fed.tiers),
                          0 if table is None else table.epoch))
            time.sleep(0.05)

    scaler = Autoscaler(
        fed, interval=scaler_interval, min_partitions=1,
        max_partitions=max_partitions,
        split_rows_per_s=split_rows_per_s,
        merge_rows_per_s=merge_rows_per_s,
        hysteresis_ticks=2, cooldown_s=cooldown_s,
        ack_p99_budget_s=ack_p99_budget_s, slo_probe=probe)

    lost = 0
    try:
        threads = [threading.Thread(target=writer, args=(w,),
                                    daemon=True,
                                    name=f"bench-writer-{w}")
                   for w in range(writers)]
        threads.append(threading.Thread(target=sampler, daemon=True,
                                        name="bench-slo-sampler"))
        for t in threads:
            t.start()
        with scaler:
            time.sleep(duration)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)

        # Zero-loss floor: every slot reads back >= its last acked
        # value, through a fresh client against the final topology —
        # seeded from the LIVE address list, the original seed tier
        # may itself have been merged away.
        reader = FederatedClient(fed.addrs(), timeout=5.0)
        try:
            with lock:
                frozen = dict(last_acked)
            for slot, val in frozen.items():
                got = reader.get(slot)
                if got is None or int(got) < val:
                    lost += 1
        finally:
            reader.close()
        slo = probe()
        # Server-side p99 both ways (PR 18): the sketch-true quantile
        # the probe gates on, and the log2 bucket ceiling it replaced
        # ("ceiling" is a trajectory skip token — recorded, not
        # gated).
        from crdt_tpu.obs.fleet import (ACK_HIST_NAME, fleet_sketch,
                                        histogram_quantile)
        snap_final = default_registry().snapshot()
        sk = fleet_sketch({"local": snap_final})
        srv_sketch_p99 = (sk.quantile(0.99)
                          if sk is not None else None)
        srv_ceiling = None
        for s in (snap_final.get("histograms", {})
                  .get(ACK_HIST_NAME, [])):
            q = histogram_quantile(s, 0.99)
            if q is not None and q != float("inf"):
                srv_ceiling = (q if srv_ceiling is None
                               else max(srv_ceiling, q))
    finally:
        stop.set()
        fed.stop()

    # Partition-count transitions, and the flip times that open each
    # recovery window.
    ups = downs = 0
    flips: list = []
    for (ta, _, pa, ea), (tb, _, pb, eb) in zip(trace, trace[1:]):
        if pb > pa:
            ups += 1
        elif pb < pa:
            downs += 1
        if eb != ea:
            flips.append(tb)
    partition_counts = sorted({p for _, _, p, _ in trace})

    def p99(lat: list) -> float:
        lat = sorted(lat)
        return lat[int(0.99 * (len(lat) - 1))] if lat else float("nan")

    steady = [dt for (ts, dt) in samples
              if not any(f <= ts <= f + recovery_s for f in flips)]
    recovering = [dt for (ts, dt) in samples
                  if any(f <= ts <= f + recovery_s for f in flips)]
    steady_p99 = p99(steady)
    decisions: dict = {}
    for d in scaler.decisions:
        key = f"{d['action']}:{d['reason']}"
        decisions[key] = decisions.get(key, 0) + 1

    tracked = ups >= cycles and downs >= cycles
    p99_ok = steady_p99 <= ack_p99_budget_s
    return {
        "metric": "elastic_ack_p99", "unit": "s",
        "value": round(steady_p99, 6),
        "platform": jax.devices()[0].platform,
        "period_s": period_s, "cycles": cycles,
        "peak_hz": peak_hz, "trough_hz": trough_hz,
        "writers": writers,
        "ops_attempted": counters["attempted"],
        "ops_acked": counters["acked"],
        "ops_retried": counters["retried"],
        "partition_counts_seen": partition_counts,
        "up_transitions": ups, "down_transitions": downs,
        "tracked_load": tracked,
        "epoch_final": 0 if fed.table is None else fed.table.epoch,
        "flips": len(flips),
        "acked_writes_lost": lost,
        "steady_ack_p99_s": round(steady_p99, 6),
        "steady_samples": len(steady),
        "recovery_ack_p99_s": (round(p99(recovering), 6)
                               if recovering else None),
        "recovery_samples": len(recovering),
        "ack_p99_budget_s": ack_p99_budget_s,
        "slo_probe_budget_s": ack_p99_budget_s,
        "srv_ack_p99_sketch_s": (round(srv_sketch_p99, 6)
                                 if srv_sketch_p99 is not None
                                 else None),
        "srv_ack_p99_ceiling_s": (round(srv_ceiling, 6)
                                  if srv_ceiling is not None
                                  else None),
        "recovery_window_s": recovery_s,
        "autoscale_decisions": decisions,
        "writer_errors": writer_errors,
        "within_budget": (tracked and lost == 0 and p99_ok
                          and not writer_errors),
        "_slo": slo,
        # Partitions, replicas, controller and clients all time-slice
        # one host's cores over loopback: the elasticity and the
        # zero-loss gates are real, the latency envelope is not a
        # multi-host number.
        "_host_class": host_class() + "-colocated",
        "downscale_caveat": (
            "federation colocated on one host (loopback, shared "
            "cores); ack p99 excludes real network + scheduling "
            "jitter, and flip recovery windows are priced "
            "separately"),
    }


def bench_ingest(n_slots: int = 1 << 14, rows: int = 1024,
                 batches: int = 64, repeats: int = 24) -> dict:
    """Write-path fast lane: staged ingest() vs unbatched put_batch.

    One JSON line with the three acceptance signals of the write
    combiner (docs/INGEST.md): staged vs unbatched puts/sec through
    the model API (same random batches, device-fenced), a flush
    latency histogram for a 1024-row commit on a single device, and
    the same flush on a sharded store against the pre-combiner
    put_batch baseline (MULTICHIP_SCALE_r05.json: sharded 4.81 ms /
    single 1.73 ms, dispatch floors 2.132 / 0.856) with the measured
    dispatch floor subtracted so the scatter's own cost is visible."""
    import statistics
    import numpy as np
    from crdt_tpu.models.dense_crdt import DenseCrdt, ShardedDenseCrdt
    from crdt_tpu.parallel import make_fanin_mesh

    platform = jax.devices()[0].platform
    med = statistics.median
    rng = np.random.default_rng(11)
    data = [rng.choice(n_slots, size=rows, replace=False)
            for _ in range(batches)]
    vals = [(s % 1000).astype(np.int64) for s in data]
    total = rows * batches

    def fence(crdt):
        jax.block_until_ready(crdt._store.lt)

    # --- throughput: one scatter per call vs one fused flush ---
    def run_unbatched():
        c = DenseCrdt("i", n_slots=n_slots)
        c.put_batch(data[0], vals[0])     # compile the per-call scatter
        fence(c)
        t0 = time.perf_counter()
        for s, v in zip(data, vals):
            c.put_batch(s, v)
        fence(c)
        return time.perf_counter() - t0

    def run_staged():
        c = DenseCrdt("i", n_slots=n_slots)
        t0 = time.perf_counter()
        with c.ingest() as wc:
            for s, v in zip(data, vals):
                c.put_batch(s, v)
        fence(c)
        return time.perf_counter() - t0, wc.flushes

    run_staged()                          # compile the fused flush
    staged_s, flushes = run_staged()
    unbatched_s = run_unbatched()

    # --- flush latency: 1024 staged rows to committed, fenced ---
    def flush_hist(crdt):
        times = []
        with crdt.ingest() as wc:
            for i in range(repeats + 2):
                crdt.put_batch(data[i % batches], vals[i % batches])
                t0 = time.perf_counter()
                wc.flush()
                fence(crdt)
                if i >= 2:                # first two warm the jit
                    times.append(time.perf_counter() - t0)
        return times

    def floor_ms(crdt):
        # What merely RUNNING a trivial program over this store costs
        # (benchmarks/sharded_scale.py's dispatch-floor probe) — the
        # irreducible per-dispatch overhead under the flush number.
        @jax.jit
        def _touch(store):
            return type(store)(*((ln if ln.dtype == bool else ln + 0)
                                 for ln in store))
        st = crdt._store
        jax.block_until_ready(_touch(st))
        best = float("inf")
        for _ in range(max(4, repeats // 2)):
            t0 = time.perf_counter()
            jax.block_until_ready(_touch(st))
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    single = DenseCrdt("i", n_slots=n_slots)
    hist = flush_hist(single)
    single_floor = floor_ms(single)

    # --- the same flush on a sharded store (largest mesh that fits) ---
    d = jax.device_count()
    if d >= 8:
        mesh = make_fanin_mesh(2, 4)
    else:
        ks = 1
        while ks * 2 <= d and n_slots % (ks * 2) == 0:
            ks *= 2
        mesh = make_fanin_mesh(1, ks)
    sharded = ShardedDenseCrdt("i", n_slots, mesh)
    sh_hist = flush_hist(sharded)
    sh_floor = floor_ms(sharded)

    def ms(xs):
        xs = sorted(xs)
        return {"min": round(xs[0] * 1e3, 3),
                "p50": round(med(xs) * 1e3, 3),
                "p90": round(xs[int(0.9 * (len(xs) - 1))] * 1e3, 3),
                "max": round(xs[-1] * 1e3, 3)}

    # --- device→wire for a freshly flushed delta off the same store ---
    def fresh_write(i):
        single.put_batch(data[i % batches], vals[i % batches])

    btw_ms, copies = _bytes_to_wire(single, fresh_write,
                                    max(4, repeats // 2))

    # --- ledger overhead: staged flush ticks on the warm store ---
    def ledger_workload():
        with single.ingest() as wc:
            for i in range(4):
                single.put_batch(data[i % batches], vals[i % batches])
                wc.flush()
        fence(single)

    ledger = _ledger_overhead(ledger_workload)

    # --- sanitize lock wrapper overhead on the same staged ticks ---
    def sanitize_workload(lock):
        with single.ingest() as wc:
            for i in range(4):
                with lock:
                    single.put_batch(data[i % batches],
                                     vals[i % batches])
                    wc.flush()
        fence(single)

    sanitize = _sanitize_lock_overhead(sanitize_workload)

    sh_min_ms = min(sh_hist) * 1e3
    return {
        **ledger,
        **sanitize,
        "metric": "ingest_fast_lane", "unit": "puts/s",
        "n_slots": n_slots, "rows_per_batch": rows, "batches": batches,
        "platform": platform,
        "unbatched_puts_per_sec": round(total / unbatched_s, 1),
        "staged_puts_per_sec": round(total / staged_s, 1),
        "staged_speedup": round(unbatched_s / staged_s, 3),
        "staged_flushes": flushes,
        "flush_ms": ms(hist),
        "bytes_to_wire_ms": btw_ms,
        "copies": copies,
        "single_dispatch_floor_ms": round(single_floor, 3),
        "sharded": {
            "mesh": f"(replica={mesh.shape['replica']}, "
                    f"key={mesh.shape['key']})",
            "flush_1024_ms": round(sh_min_ms, 3),
            "flush_hist_ms": ms(sh_hist),
            "dispatch_floor_ms": round(sh_floor, 3),
            "flush_over_floor_ms": round(sh_min_ms - sh_floor, 3),
            "baseline_put_batch_1024_ms": {"sharded": 4.81,
                                           "single_device": 1.73},
            "baseline_dispatch_floor_ms": {"sharded": 2.132,
                                           "single_device": 0.856},
            "vs_sharded_put_batch_baseline": round(sh_min_ms / 4.81, 3),
        },
    }


def bench_types(n_slots: int = 1 << 10, loops: int = 16,
                rounds: int = 3) -> dict:
    """Per-semantics merge throughput over the typed inbound path.

    For every entry in the semantics registry (`crdt_tpu.semantics`)
    this types a writer's whole 1024-slot store with that semantics,
    packs the full delta once with the sem lane included, and times
    `merge_packed` replaying it into a same-typed receiver — tag
    validation plus the per-tag sub-semilattice join, the exact path a
    typed sync round exercises. One JSON line with merges/s per
    semantics, single-device and (when >= 8 devices are visible)
    sharded over the 2x4 fan-in mesh, so regressions in any one type's
    join kernel show up against this baseline."""
    import numpy as np
    from crdt_tpu.models.dense_crdt import DenseCrdt, ShardedDenseCrdt
    from crdt_tpu.parallel import make_fanin_mesh
    from crdt_tpu.semantics import all_semantics
    from crdt_tpu.semantics.types import MVREG_MAX, ORSET_UNIVERSE

    platform = jax.devices()[0].platform
    slots = list(range(n_slots))

    def payload(spec, slot):
        # Type-canonical lane values, distinct per slot so the join
        # does real work on every row.
        if spec.name == "lww":
            return slot % 1000
        if spec.name == "pncounter":
            return spec.encode(slot - n_slots // 2)
        if spec.name == "orset":
            return spec.encode({slot % ORSET_UNIVERSE})
        if spec.name == "mvreg":
            return spec.encode(1 + slot % MVREG_MAX)
        return spec.encode(slot % 1000)   # gcounter and future types

    def measure(make_receiver):
        rates = {}
        for spec in all_semantics():
            w = DenseCrdt("w", n_slots=n_slots)
            if spec.name != "lww":
                w.set_semantics(slots, spec.name)
            w.put_batch(slots, [payload(spec, s) for s in slots])
            pk, ids = w.pack_since(None, sem_mode="include")
            r = make_receiver(spec)
            r.merge_packed(pk, ids)       # compile + first join, fenced
            jax.block_until_ready(r._store)
            best = None
            for _ in range(rounds):
                t0 = time.perf_counter()
                for _ in range(loops):
                    r.merge_packed(pk, ids)
                jax.block_until_ready(r._store)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            rates[spec.name] = round(n_slots * loops / best, 1)
        return rates

    def single(spec):
        r = DenseCrdt("r", n_slots=n_slots)
        if spec.name != "lww":
            r.set_semantics(slots, spec.name)
        return r

    out = {"metric": "typed_merges_per_sec_1024_slots",
           "unit": "merges/s", "n_slots": n_slots, "loops": loops,
           "platform": platform,
           "single_device": measure(single)}
    if len(jax.devices()) >= 8:
        mesh = make_fanin_mesh(2, 4)

        def sharded(spec):
            r = ShardedDenseCrdt("r", n_slots, mesh)
            if spec.name != "lww":
                r.set_semantics(slots, spec.name)
            return r

        out["sharded"] = measure(sharded)
    else:
        out["sharded"] = None
    return out


def bench_churn(live: int = 4096, cycles: int = 5,
                drift_budget: float = 0.05) -> dict:
    """Churn soak: tombstone epoch GC + online compaction keep a
    steady live-set workload at CONSTANT footprint (docs/STORAGE.md).

    Each cycle puts ``live`` never-before-seen keys through a
    `KeyedDenseCrdt`, deletes the previous cycle's keys, exercises the
    pack + digest surfaces (so their caches are live), then runs one
    GC pass (own canonical head — single node, so the fleet stability
    watermark IS the local head) and one `compact`. Without the
    storage plane every cycle grows the store by ``live`` slots and
    the digest tree gains depth; with it, store bytes, digest depth,
    pack-cache entries and slot capacity must all be FLAT across the
    post-warmup cycles (<= ``drift_budget`` relative spread). The flat
    checks are returned as booleans AND enforced with a nonzero exit
    via ``churn_flat_ok`` so the smoke gate fails loudly, and the
    byte metrics use the trajectory's lower-is-better override names
    (``store_bytes_hwm``, ``bytes_per_live_row``) so a footprint
    regression gates like a latency regression."""
    import numpy as np
    from crdt_tpu.models.dense_crdt import DenseCrdt
    from crdt_tpu.models.keyed_dense import KeyedDenseCrdt

    platform = jax.devices()[0].platform
    kc = KeyedDenseCrdt(DenseCrdt("churn", n_slots=2 * live))

    def store_bytes():
        return int(sum(ln.nbytes for ln in kc.dense._store))

    prev_keys: list = []
    series = []
    gc_ms = []
    t_total = time.perf_counter()
    for cycle in range(cycles):
        keys = [f"c{cycle}:{i}" for i in range(live)]
        kc.put_all({k: (cycle * live + i) % 100000
                    for i, k in enumerate(keys)})
        for k in prev_keys:
            kc.delete(k)
        # Populate the caches the flat checks watch; the entry count
        # is read HERE, at its per-cycle high-water (compact clears
        # the cache via the store swap) — the check is that it never
        # accumulates across cycles.
        kc.dense.pack_since(None)
        depth = kc.digest_tree().depth
        pack_entries = len(kc.dense._pack_cache)
        t0 = time.perf_counter()
        stability = kc.canonical_time   # single node: head == fleet min
        purged = kc.gc_purge(stability, drift_slack_ms=0)
        retained = kc.compact()
        gc_ms.append((time.perf_counter() - t0) * 1e3)
        series.append({
            "cycle": cycle, "purged": purged, "retained": retained,
            "store_bytes": store_bytes(), "digest_depth": depth,
            "pack_cache_entries": pack_entries,
            "capacity_slots": kc.dense.n_slots})
        prev_keys = keys
    total_s = time.perf_counter() - t_total

    # Read-back oracle: the live set must survive GC + remap intact.
    sample = prev_keys[:: max(1, live // 64)]
    reads_ok = all(
        kc.get(k) == ((cycles - 1) * live + i * max(1, live // 64))
        % 100000 for i, k in enumerate(sample))

    # Flatness over the post-warmup cycles (cycle 0 has no deletes to
    # purge, so it's warmup; >= 3 measured cycles by construction).
    tail = series[1:]

    def flat(key):
        vals = [c[key] for c in tail]
        lo, hi = min(vals), max(vals)
        return lo > 0 and (hi - lo) / lo <= drift_budget

    checks = {k: flat(k) for k in ("store_bytes", "digest_depth",
                                   "pack_cache_entries",
                                   "capacity_slots")}
    purge_ok = all(c["purged"] == live for c in tail)
    ok = all(checks.values()) and purge_ok and reads_ok
    hwm = max(c["store_bytes"] for c in series)
    return {
        "metric": "churn_constant_footprint", "unit": "bytes",
        "platform": platform, "live_rows": live, "cycles": cycles,
        "keys_churned_total": live * cycles,
        "churn_keys_per_sec": round(live * cycles / total_s, 1),
        "gc_compact_ms_p50": round(sorted(gc_ms)[len(gc_ms) // 2], 3),
        "store_bytes_hwm": hwm,
        "bytes_per_live_row": round(hwm / live, 2),
        "digest_depth": tail[-1]["digest_depth"],
        "pack_cache_entries": tail[-1]["pack_cache_entries"],
        "capacity_slots": tail[-1]["capacity_slots"],
        "purged_per_cycle_ok": purge_ok,
        "reads_ok": reads_ok,
        "flat": checks,
        "churn_flat_ok": ok,
        "cycles_detail": series,
    }


def result_dict(metric: str, merges: int, secs: float,
                path: str = None, platform: str = None) -> dict:
    """The one-line JSON contract shared by bench.py and the suite.
    ``path``/``platform`` record which executor produced the number so
    it stays verifiable after the fact."""
    out = {"metric": metric, "value": round(merges / secs, 1),
           "unit": "merges/s",
           "vs_baseline": round(merges / secs / TARGET, 3)}
    if path is not None:
        out["path"] = path
    if platform is not None:
        out["platform"] = platform
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for a fast correctness smoke")
    ap.add_argument("--keys", type=int, default=None)
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--chunk", type=int, default=None)
    ap.add_argument("--path", choices=("auto", "xla", "pallas"),
                    default="auto")
    ap.add_argument("--config", choices=tuple(CONFIGS), default="fanin")
    ap.add_argument("--repeats", type=int, default=64,
                    help="chained timed runs (one readback at the end)")
    ap.add_argument("--mode",
                    choices=("stream", "distinct", "e2e", "e2e-kernel",
                             "sync", "ingest", "types", "antientropy",
                             "serve", "federate", "failover",
                             "collective", "elastic", "churn"),
                    default="stream",
                    help="stream: write-stream replay (chunk replayed "
                         "with +1ms offsets); distinct: HBM-resident "
                         "independent replica rows (north-star shape); "
                         "e2e: 1024 fresh distinct rows through the "
                         "model API (pipelined); e2e-kernel: same loop "
                         "against the raw kernel; sync: two-replica "
                         "gossip over loopback sockets — pooled vs "
                         "fresh-connect latency, delta bytes, "
                         "compression ratio, pack-cache hits; ingest: "
                         "write-combiner fast lane — staged vs "
                         "unbatched puts/sec, flush latency histogram, "
                         "sharded flush vs the pre-combiner put_batch "
                         "baseline; types: per-semantics merge_packed "
                         "replay at 1024 slots, single-device and "
                         "sharded — the type-zoo baseline; "
                         "antientropy: merkle star/ring topology soak "
                         "over in-process replicas (--replicas, "
                         "default 64) — anti-entropy traffic vs "
                         "divergence vs store size; serve: open-loop "
                         "serving-tier load — --sessions concurrent "
                         "client sessions multiplexed onto one "
                         "ServeTier, p50/p99 write-ack latency and "
                         "acked ops/s; federate: the serve fleet "
                         "spread over --partitions consistent-hash "
                         "partitions behind a FederatedTier, with a "
                         "live hot-partition split fired mid-run — "
                         "zero-dropped-writes and post-split ack p99 "
                         "are the gates; failover: chaos bench — "
                         "kill a replica group's primary under a "
                         "client write storm, >=3 cycles; gates are "
                         "zero acked writes lost, epoch advance per "
                         "failover, MTTR within budget, root-"
                         "convergent rejoin; collective: pod-local "
                         "single-dispatch group join over a virtual "
                         "member mesh vs the same-host sync_packed "
                         "loopback — wall time, dispatches-per-round "
                         "(asserted == 1), bytes-to-wire (asserted "
                         "== 0), dispatch-floor re-read; elastic: "
                         "sine-wave load against the Autoscaler "
                         "daemon — partition count must track the "
                         "load for >= 2 full cycles (splits on the "
                         "rise, merges on the fall) with zero acked "
                         "writes lost and steady ack p99 within the "
                         "federate envelope; churn: tombstone-GC + "
                         "compaction soak — unique-key churn with a "
                         "constant live set; store bytes, digest "
                         "depth, pack-cache entries and capacity "
                         "must stay flat across >= 3 GC cycles "
                         "(exit 1 otherwise)")
    ap.add_argument("--sessions", type=int, default=None,
                    help="serve/federate mode: concurrent client "
                         "sessions (serve default 10000, federate "
                         "100000 nominal — both fd-capped; smoke 200)")
    ap.add_argument("--partitions", type=int, default=4,
                    help="federate mode: initial partition count")
    ap.add_argument("--rows", type=int, default=128,
                    help="distinct mode: replica rows resident in HBM")
    ap.add_argument("--trajectory", metavar="JSONL", default=None,
                    help="append this run as one normalized record to "
                         "the given trajectory file (default: "
                         "benchmarks/history/trajectory.jsonl)")
    ap.add_argument("--no-trajectory", action="store_true",
                    help="skip the trajectory append")
    ap.add_argument("--loops", type=int, default=48,
                    help="distinct mode: chained full passes (the "
                         "one-off dispatch/fence round trip is ~100ms "
                         "on remote-proxied chips; more loops keep it "
                         "out of the steady-state number)")
    args = ap.parse_args()

    if args.smoke:
        n_keys, n_replicas, chunk = 1 << 16, 16, 8
    else:
        n_keys, n_replicas, chunk = 1 << 20, 1024, 8
    n_keys = args.keys or n_keys
    n_replicas = args.replicas or n_replicas
    chunk = args.chunk or chunk

    if args.mode == "antientropy":
        result = bench_antientropy(
            replicas=args.replicas or (8 if args.smoke else 64),
            divergent=4 if args.smoke else 8,
            store_sizes=((1 << 8, 1 << 9, 1 << 10) if args.smoke
                         else (1 << 10, 1 << 12, 1 << 14)))
    elif args.mode == "serve":
        # Full shape: 10k concurrent sessions at 0.25 op/s each —
        # 2.5k ops/s offered load, sized so a single-core host is
        # measuring the tier's multiplexing, not its own saturation.
        result = bench_serve(
            sessions=args.sessions or (200 if args.smoke else 10000),
            rate_hz=2.0 if args.smoke else 0.25,
            duration=2.0 if args.smoke else 10.0,
            warmup=1.0 if args.smoke else 3.0,
            n_slots=1 << 10 if args.smoke else 1 << 14)
    elif args.mode == "federate":
        # Nominal shape: 4 partitions x 25k sessions. The bench
        # downsizes to the host's fd ceiling and records both counts.
        result = bench_federate(
            sessions=args.sessions or (200 if args.smoke else 100000),
            partitions=2 if args.smoke else args.partitions,
            rate_hz=2.0 if args.smoke else 0.25,
            duration=3.0 if args.smoke else 12.0,
            warmup=1.0 if args.smoke else 3.0,
            recovery_s=1.0 if args.smoke else 3.0,
            n_slots=1 << 10 if args.smoke else 1 << 14)
    elif args.mode == "failover":
        # >=3 kill cycles even in smoke: the acceptance gate is
        # consecutive failovers, not throughput.
        result = bench_failover(
            replicas=args.replicas or 3,
            writers=4 if args.smoke else 8,
            slots_per_writer=4 if args.smoke else 8,
            kills=3 if args.smoke else 5,
            rate_hz=50.0 if args.smoke else 100.0,
            n_slots=1 << 10 if args.smoke else 1 << 14)
    elif args.mode == "elastic":
        # >= 2 full sine cycles even in smoke: the acceptance gate is
        # the partition count tracking the load both ways, not
        # throughput.
        result = bench_elastic(
            period_s=3.0 if args.smoke else 6.0,
            cycles=2,
            peak_hz=500.0 if args.smoke else 600.0,
            trough_hz=25.0 if args.smoke else 30.0,
            writers=4,
            max_partitions=args.partitions,
            scaler_interval=0.15 if args.smoke else 0.2,
            cooldown_s=0.5 if args.smoke else 0.8,
            settle_s=1.2 if args.smoke else 1.5,
            n_slots=1 << 10 if args.smoke else 1 << 14)
    elif args.mode == "churn":
        result = bench_churn(
            live=256 if args.smoke else 4096,
            cycles=4 if args.smoke else 6)
    elif args.mode == "types":
        result = bench_types(n_slots=1 << 10,
                             loops=4 if args.smoke else 16,
                             rounds=1 if args.smoke else 3)
    elif args.mode == "ingest":
        result = bench_ingest(
            n_slots=1 << 10 if args.smoke else 1 << 14,
            rows=128 if args.smoke else 1024,
            batches=4 if args.smoke else 64,
            repeats=4 if args.smoke else 24)
    elif args.mode == "sync":
        result = bench_sync(
            n_slots=1 << 10 if args.smoke else 1 << 14,
            k=32 if args.smoke else 256,
            rounds=4 if args.smoke else 32)
    elif args.mode == "collective":
        result = bench_collective(
            n_slots=1 << 10 if args.smoke else 1 << 14,
            k=32 if args.smoke else 256,
            rounds=4 if args.smoke else 32,
            members=args.replicas or (2 if args.smoke else 4))
    elif args.mode in ("e2e", "e2e-kernel"):
        result = bench_e2e_1024(
            n_keys,
            rows_per_pass=16 if args.smoke else args.rows,
            passes=2 if args.smoke else 8,
            through_model=args.mode == "e2e")
    elif args.mode == "distinct":
        result = bench_distinct(n_keys, 16 if args.smoke else args.rows,
                                loops=args.loops)
    else:
        result = bench(n_keys, n_replicas, chunk, path=args.path,
                       config=args.config, repeats=args.repeats,
                       with_phases=True)
    phases = result.pop("_phases", None)
    slo = result.pop("_slo", None)
    # Modes measured on a downscaled stand-in (virtual mesh on shared
    # cores) override the trajectory host_class so the series never
    # reads them as comparable to real-hardware points.
    host_override = result.pop("_host_class", None)
    print(json.dumps(result))
    if phases is not None:
        print(json.dumps(phases))
    if slo is not None:
        # Trailing machine-readable SLO verdict (same shape as
        # `python -m crdt_tpu.obs fleet --json`'s "slo"); CI gates on
        # the last line of serve/antientropy bench output.
        print(json.dumps({"slo": slo}))
    if not args.no_trajectory:
        # Every mode appends ONE normalized record so the bench series
        # reads as a trajectory (`python -m crdt_tpu.obs bench`).
        from crdt_tpu.obs import trajectory as _traj
        rec = dict(result)
        if slo is not None:
            rec["slo"] = slo
        _traj.append_record(
            _traj.normalize_record(args.mode, rec, smoke=args.smoke,
                                   host=host_override),
            args.trajectory or _traj.TRAJECTORY_PATH)
    if result.get("churn_flat_ok") is False:
        # The churn soak's acceptance IS the flatness; a growing
        # footprint must fail CI, not just log (docs/STORAGE.md).
        sys.exit(1)


if __name__ == "__main__":
    main()

"""Planted AB/BA deadlock fixture for the concurrency analyzer.

Expected findings, exactly:

- ``lock-order-cycle`` in ``PairStore.backward`` — the declared order
  is ``_a`` before ``_b``, ``forward()`` conforms, but ``backward()``
  holds ``_b`` while reaching ``_a`` through the ``_grab_a`` helper
  (the interprocedural edge), completing the classic inversion.
- ``lock-order-undeclared`` in ``Indexer.reindex`` — a cross-class
  nesting (``_idx`` held while taking a Journal's ``_j``) that no
  contract declares in either direction.

Every lock is deliberately never contended at runtime — the planted
bugs must be caught purely statically (the file is never imported by
the shipped tree).
"""

import threading


class PairStore:
    _CRDTLINT_LOCK_ORDER = ("_a", "_b")

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.hot = {}
        self.cold = {}

    def forward(self, key, value):
        # conforms to the declared order: _a then _b
        with self._a:
            self.hot[key] = value
            with self._b:
                self.cold[key] = value

    def _grab_a(self, key):
        with self._a:
            return self.hot.get(key)

    def backward(self, key):
        # PLANTED: holds _b, then reaches _a through the helper
        with self._b:
            if key in self.cold:
                return self._grab_a(key)
            return None


class Journal:
    _CRDTLINT_LOCK_ORDER = ("_j",)

    def __init__(self):
        self._j = threading.Lock()
        self.entries = []

    def append(self, entry):
        with self._j:
            self.entries.append(entry)


class Indexer:
    _CRDTLINT_LOCK_ORDER = ("_idx",)

    def __init__(self):
        self._idx = threading.Lock()
        self.index = {}

    def reindex(self, journal):
        # PLANTED: nests a foreign contract lock with no declared
        # order between _idx and Journal._j
        with self._idx:
            with journal._j:
                for i, entry in enumerate(journal.entries):
                    self.index[entry] = i

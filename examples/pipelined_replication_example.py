"""Pipelined replication: merging a stream of peer changesets with
zero per-merge host synchronization.

The scenario: a dense replica ingesting deltas from many peers in a
tight loop — the steady-state of an anti-entropy mesh. Unpipelined,
every `merge` fetches its guard flags and canonical clock from the
device (a full host↔device round trip per call — the dominant cost on
remote-proxied accelerators). Inside a `DenseCrdt.pipelined()` window
the canonical clock threads as a device scalar, guard flags
accumulate, and ONE readback at the window's end settles everything.

Store lanes and the canonical clock are bit-identical to the same
merges issued unpipelined — this example proves it by running both.
"""

from crdt_tpu import DenseCrdt, PipelinedGuardError
from crdt_tpu.testing import FakeClock, assert_dense_stores_equal

BASE = 1_700_000_000_000
N = 4096


def make_peers(k: int):
    peers = []
    for i in range(k):
        p = DenseCrdt(f"peer{i}", N,
                      wall_clock=FakeClock(start=BASE + i * 13))
        p.put_batch(list(range(i, N, i + 3)),
                    [i * 1000 + s for s in range(i, N, i + 3)])
        p.delete_batch([i, i + 11])
        peers.append(p)
    return peers


def main() -> None:
    batches = [p.export_delta() for p in make_peers(6)]

    pipelined = DenseCrdt("local", N, wall_clock=FakeClock(start=BASE))
    with pipelined.pipelined():          # one readback, at exit
        for cs, ids in batches:
            pipelined.merge(cs, ids)

    plain = DenseCrdt("local", N, wall_clock=FakeClock(start=BASE))
    for cs, ids in batches:              # one readback PER merge
        plain.merge(cs, ids)

    assert_dense_stores_equal(pipelined.store, plain.store)
    assert pipelined.canonical_time == plain.canonical_time
    print(f"pipelined == unpipelined: {len(pipelined.record_map())} "
          "records, identical lanes and clock ✓")

    # The trade: a guard violation (here, a peer claiming OUR node id)
    # reports at the window's end, coarsely — and the merges have
    # already landed (the lattice join is monotone either way).
    rogue = DenseCrdt("local", N,        # duplicate node id!
                      wall_clock=FakeClock(start=BASE + 10_000))
    rogue.put_batch([0], [1])
    cs, ids = rogue.export_delta()
    try:
        with pipelined.pipelined():
            pipelined.merge(cs, ids)
    except PipelinedGuardError as e:
        print(f"deferred guard report: {e}")


if __name__ == "__main__":
    main()

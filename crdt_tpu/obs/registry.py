"""Process-wide metrics registry: counters, gauges, log2 histograms,
and mergeable quantile sketches.

Design constraints, in order:

1. **The merge hot path stays lock-free.** `MergeStats` accumulates
   plain host ints (and lazy device scalars) exactly as before; it
   *attaches* to the registry as a weak-referenced collector and is
   only read at snapshot time. Registry locks are paid on scrape and
   on genuinely cold paths (gossip rounds, checkpoints, watch fanout),
   never per record.
2. **Thread-safe by declaration.** Every instrument and the registry
   itself guard their mutable state behind one lock each, declared via
   ``_CRDTLINT_GUARDED`` so the crdtlint lock-discipline rule enforces
   the contract statically.
3. **No global leak.** Collectors are held by ``weakref`` — a test
   that builds ten thousand replicas does not grow the registry past
   their lifetimes; dead entries are pruned on snapshot.

Histograms use **fixed log2 buckets**: bucket ``e`` counts
observations ``<= 2**e`` for ``e`` in a fixed exponent range, plus an
overflow bucket. Log-spaced bounds cover µs..minutes latencies with
~26 integers and merge trivially across processes (the bounds are the
same everywhere by construction).
"""

from __future__ import annotations

import bisect
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.concurrency import make_lock
from .sketch import (DEFAULT_MAX_BINS, DEFAULT_RELATIVE_ACCURACY,
                     QuantileSketch)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    # Single-label fast path: most hot-path series carry exactly one
    # label (span name, trigger, outcome), and the sorted() generator
    # round trip is pure overhead there — this sits under every
    # counter.inc/histogram.observe in the tree.
    if len(labels) == 1:
        for k, v in labels.items():
            return ((str(k), str(v)),)
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing counter with optional labels."""

    kind = "counter"

    # crdtlint lock-discipline contract (see module docstring).
    _CRDTLINT_GUARDED = {"_lock": ("_values",)}
    # analysis/concurrency.py: leaf singleton, nothing nests inside.
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = make_lock("Counter._lock", 90)
        self._values: Dict[_LabelKey, float] = {}

    def inc(self, amount: float = 1, **labels: Any) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels: Any) -> float:
        key = _label_key(labels)
        with self._lock:
            return self._values.get(key, 0)

    def samples(self) -> List[dict]:
        with self._lock:
            items = list(self._values.items())
        return [{"labels": dict(k), "value": v} for k, v in items]

    def _state(self) -> Dict[_LabelKey, float]:
        with self._lock:
            return dict(self._values)

    def _restore(self, state: Dict[_LabelKey, float]) -> None:
        with self._lock:
            self._values = dict(state)


class Gauge:
    """Point-in-time value with optional labels (set or add)."""

    kind = "gauge"

    _CRDTLINT_GUARDED = {"_lock": ("_values",)}
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = make_lock("Gauge._lock", 90)
        self._values: Dict[_LabelKey, float] = {}

    def set(self, value: float, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = value

    def add(self, amount: float, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels: Any) -> float:
        key = _label_key(labels)
        with self._lock:
            return self._values.get(key, 0)

    def samples(self) -> List[dict]:
        with self._lock:
            items = list(self._values.items())
        return [{"labels": dict(k), "value": v} for k, v in items]

    def _state(self) -> Dict[_LabelKey, float]:
        with self._lock:
            return dict(self._values)

    def _restore(self, state: Dict[_LabelKey, float]) -> None:
        with self._lock:
            self._values = dict(state)


class Histogram:
    """Histogram over fixed log2 buckets.

    Bucket ``i`` counts observations ``<= 2**exponents[i]``; one extra
    overflow bucket catches the rest. The default range (2**-20 ..
    2**5 seconds, ~1 µs .. 32 s) suits the latencies this codebase
    emits; pass ``low_exp``/``high_exp`` for other units.
    """

    kind = "histogram"

    _CRDTLINT_GUARDED = {"_lock": ("_series",)}
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self, name: str, help: str = "",
                 low_exp: int = -20, high_exp: int = 5):
        if high_exp <= low_exp:
            raise ValueError("need high_exp > low_exp")
        self.name = name
        self.help = help
        self.bounds: Tuple[float, ...] = tuple(
            2.0 ** e for e in range(low_exp, high_exp + 1))
        self._lock = make_lock("Histogram._lock", 90)
        # label key -> [bucket counts (len(bounds)+1, last=overflow),
        #               total count, running sum]
        self._series: Dict[_LabelKey, list] = {}

    def observe(self, value: float, **labels: Any) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        key = _label_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = [[0] * (len(self.bounds) + 1), 0, 0.0]
                self._series[key] = series
            series[0][idx] += 1
            series[1] += 1
            series[2] += value

    def samples(self) -> List[dict]:
        with self._lock:
            items = [(k, [list(s[0]), s[1], s[2]])
                     for k, s in self._series.items()]
        return [{"labels": dict(k),
                 "buckets": [[b, c] for b, c in zip(self.bounds,
                                                    counts)],
                 "overflow": counts[len(self.bounds)],
                 "count": count, "sum": total}
                for k, (counts, count, total) in items]

    def _state(self) -> Dict[_LabelKey, list]:
        with self._lock:
            return {k: [list(s[0]), s[1], s[2]]
                    for k, s in self._series.items()}

    def _restore(self, state: Dict[_LabelKey, list]) -> None:
        with self._lock:
            self._series = {k: [list(s[0]), s[1], s[2]]
                            for k, s in state.items()}


class Sketch:
    """Labelled relative-error quantile sketch (obs/sketch.py).

    The histogram's complement, not its replacement: log2 buckets
    answer "how many under 2**e" cheaply, but their quantiles are
    bucket *ceilings* — a true p99 of 16 ms reads as 31.25 ms. A
    sketch series records the same observations into γ-indexed log
    buckets whose quantile estimates carry a configurable relative
    error (~1% default), merge commutatively/associatively across
    replicas, and so can gate an SLO envelope that does not sit on a
    power of two (docs/OBSERVABILITY.md).
    """

    kind = "sketch"

    _CRDTLINT_GUARDED = {"_lock": ("_series",)}
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self, name: str, help: str = "",
                 relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY,
                 max_bins: int = DEFAULT_MAX_BINS):
        self.name = name
        self.help = help
        self.relative_accuracy = float(relative_accuracy)
        self.max_bins = int(max_bins)
        self._lock = make_lock("Sketch._lock", 90)
        self._series: Dict[_LabelKey, QuantileSketch] = {}

    def observe(self, value: float, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            sk = self._series.get(key)
            if sk is None:
                sk = QuantileSketch(self.relative_accuracy,
                                    self.max_bins)
                self._series[key] = sk
            sk.record(value)

    def quantile(self, q: float, **labels: Any) -> Optional[float]:
        """Per-series quantile; ``None`` when that label set has no
        observations (unmeasured ≠ zero)."""
        key = _label_key(labels)
        with self._lock:
            sk = self._series.get(key)
            return None if sk is None else sk.quantile(q)

    def merged(self) -> Optional[QuantileSketch]:
        """All label sets folded into one fresh sketch; ``None`` when
        the instrument has never observed."""
        with self._lock:
            sketches = [sk.copy() for sk in self._series.values()]
        out: Optional[QuantileSketch] = None
        for sk in sketches:
            out = sk if out is None else out.merge(sk)
        return out

    def samples(self) -> List[dict]:
        with self._lock:
            items = [(k, sk.copy()) for k, sk in self._series.items()]
        return [{"labels": dict(k), "count": sk.count, "sum": sk.sum,
                 "sketch": sk.to_dict()} for k, sk in items]

    def _state(self) -> Dict[_LabelKey, QuantileSketch]:
        with self._lock:
            return {k: sk.copy() for k, sk in self._series.items()}

    def _restore(self, state: Dict[_LabelKey, QuantileSketch]) -> None:
        with self._lock:
            self._series = {k: sk.copy() for k, sk in state.items()}


class MetricsRegistry:
    """Named instruments plus weak-referenced stat collectors.

    ``counter``/``gauge``/``histogram`` get-or-create by name (the
    same name always yields the same instrument; a kind clash raises).
    ``attach(kind, obj, **labels)`` registers any object exposing
    ``as_dict()`` as a collector — its live values land under
    ``snapshot()["stats"][kind]`` with the given labels. Collectors
    are weakly referenced and pruned once their owner is collected.
    """

    _CRDTLINT_GUARDED = {"_lock": ("_instruments", "_collectors")}
    # analysis/concurrency.py: scrape takes the registry lock, then
    # each instrument's — never the reverse (registry rank 86 orders
    # before the instruments' 90 under the runtime sanitizer).
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self) -> None:
        self._lock = make_lock("MetricsRegistry._lock", 86)
        self._instruments: Dict[str, Any] = {}
        self._collectors: List[Tuple[str, Dict[str, str],
                                     weakref.ref]] = []

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, **kwargs)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"instrument {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  low_exp: int = -20, high_exp: int = 5) -> Histogram:
        return self._get_or_create(Histogram, name, help,
                                   low_exp=low_exp, high_exp=high_exp)

    def sketch(self, name: str, help: str = "",
               relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY,
               max_bins: int = DEFAULT_MAX_BINS) -> Sketch:
        return self._get_or_create(Sketch, name, help,
                                   relative_accuracy=relative_accuracy,
                                   max_bins=max_bins)

    def attach(self, kind: str, obj: Any, *, replace: bool = False,
               **labels: Any) -> Any:
        """Register ``obj`` (anything with ``as_dict()``) as a live
        stats collector; returns ``obj`` for chaining. Weakly held.

        A ``(kind, label-set)`` pair identifies one exposition series.
        Attaching a second live collector under an already-live pair
        raises ``ValueError`` — duplicate label sets must never reach
        the Prometheus exposition, where they are undefined. Pass
        ``replace=True`` to supersede the prior entry instead: the
        restart idiom (a replica re-created under the same node id
        while the old object is still weakly reachable) keeps exactly
        one series, the newest. Entries whose referent died are always
        fair game for reuse.
        """
        label_map = {str(k): str(v) for k, v in labels.items()}
        key = (kind, _label_key(label_map))
        entry = (kind, label_map, weakref.ref(obj))
        with self._lock:
            kept = []
            for c in self._collectors:
                if c[2]() is None:
                    continue  # dead — prune opportunistically
                if (c[0], _label_key(c[1])) == key:
                    if not replace:
                        raise ValueError(
                            f"duplicate collector label set: "
                            f"kind={kind!r} labels={label_map!r} "
                            f"(pass replace=True to supersede)")
                    continue  # superseded by the new entry
                kept.append(c)
            kept.append(entry)
            self._collectors = kept
        return obj

    def snapshot(self) -> dict:
        """Self-describing JSON-safe snapshot of every instrument and
        every live collector. Dead collector entries are pruned."""
        with self._lock:
            instruments = list(self._instruments.values())
            collectors = list(self._collectors)
            self._collectors = [c for c in collectors
                                if c[2]() is not None]
        # "sketches" sits before "stats" so a wire layer that strips
        # it for a pre-sketch peer (net.py metrics op) leaves a dict
        # whose key order — hence serialized bytes — is identical to
        # what a pre-sketch server produced.
        out = {"counters": {}, "gauges": {}, "histograms": {},
               "sketches": {}, "stats": {}}
        section = {"counter": "counters", "gauge": "gauges",
                   "histogram": "histograms", "sketch": "sketches"}
        for inst in instruments:
            out[section[inst.kind]][inst.name] = inst.samples()
        for kind, labels, ref in collectors:
            obj = ref()
            if obj is None:
                continue
            try:
                values = obj.as_dict()
            except Exception:
                # A collector mid-teardown must not break the scrape.
                continue
            out["stats"].setdefault(kind, []).append(
                {"labels": labels, "values": values})
        return out


    def state_snapshot(self) -> Tuple[Dict[str, Any], List]:
        """Deep copy of every instrument's accumulated samples plus
        the collector list — pair with :meth:`restore_state` to fence
        a window of activity off from the rest of the process.

        The intended consumer is test isolation: the registry is a
        process-global, so (say) serve-tier ack latencies observed by
        one test module would otherwise leak into another module's
        fleet-poller SLO verdict, making outcomes depend on collection
        order. Instruments themselves are never dropped on restore —
        code holds direct references to them — only their sample state
        is rolled back (instruments born inside the window restore to
        empty)."""
        with self._lock:
            instruments = dict(self._instruments)
            collectors = list(self._collectors)
        return ({name: inst._state()
                 for name, inst in instruments.items()}, collectors)

    def restore_state(self, snap: Tuple[Dict[str, Any], List]) -> None:
        """Roll every instrument back to a :meth:`state_snapshot`.
        Instruments registered since the snapshot stay registered
        (cached references elsewhere must keep working) but lose their
        samples; collectors attached since are detached."""
        inst_state, collectors = snap
        with self._lock:
            instruments = dict(self._instruments)
            self._collectors = list(collectors)
        for name, inst in instruments.items():
            inst._restore(inst_state.get(name, {}))


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every in-tree instrument attaches to."""
    return _DEFAULT

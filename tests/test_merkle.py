"""Merkle anti-entropy (docs/ANTIENTROPY.md): on-device digest trees,
the O(log n) subtree walk, the slot-range pack it feeds, and the
fourth gossip wire mode — over real sockets, with the fault proxy
partitioning the link the walk claims to recover from.

The acceptance checks the ISSUE pins live here: a cold or partitioned
peer converges shipping bytes proportional to DIVERGENCE (asserted
against the full-scan pack it replaces), range packs are bit-identical
slices of the full pack, legacy peers downgrade cleanly in both
directions, and an unchanged store answers digest_tree() from cache
with zero new dispatches."""

import numpy as np
import pytest

from crdt_tpu import (DenseCrdt, GossipNode, RetryPolicy, SyncServer,
                      PeerConnection, SyncProtocolError, WireTally,
                      sync_merkle, sync_merkle_over_conn)
from crdt_tpu.gossip import Peer
from crdt_tpu.obs.registry import default_registry
from crdt_tpu.ops.digest import (PREFETCH_LEVELS, coalesce_leaf_ranges,
                                 walk_divergent_leaves)
from crdt_tpu.sync import _packed_nbytes
from crdt_tpu.testing import (FakeClock, FaultProxy, ScriptedSchedule)

pytestmark = pytest.mark.merkle

BASE = 1_700_000_000_000
NO_SLEEP = lambda _s: None


def _make(node="n", n_slots=64, **kw):
    return DenseCrdt(node, n_slots=n_slots,
                     wall_clock=FakeClock(start=BASE), **kw)


def _node(crdt, **kw):
    kw.setdefault("sleep", NO_SLEEP)
    return GossipNode(crdt, **kw)


def _stores_equal(a, b):
    # Replicated lanes only: node/mod_* are replica-local ordinals and
    # bookkeeping — converged stores legitimately differ there (which
    # is exactly why the digest excludes them).
    for lane in ("lt", "val", "tomb", "occupied"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.store, lane)),
            np.asarray(getattr(b.store, lane)), err_msg=lane)


class _LegacyDense(DenseCrdt):
    """A pre-merkle replica: packs, but has no digest surface, so its
    server never advertises the 'merkle' cap."""
    digest_tree = None


class _BrokenDigestDense(DenseCrdt):
    """Advertises merkle (digest_tree is callable) but every walk
    fails server-side — the sticky-downgrade trigger."""

    def digest_tree(self):
        raise RuntimeError("digest surface wedged")


# ------------------------------------------------ digest tree + walk

def test_walk_localizes_single_slot_divergence():
    a = _make("a", 256)
    b = _make("b", 256)
    ids = list(range(0, 256, 2))
    a.put_batch(ids, [i * 10 for i in ids])
    packed, pids = a.pack_since(None)
    b.merge_packed(packed, pids)
    ta, tb = a.digest_tree(), b.digest_tree()
    assert ta.levels[0][0] == tb.levels[0][0]      # converged: equal roots
    b.put_batch([37], [999])
    tb = b.digest_tree()
    leaves, rounds, fetched = walk_divergent_leaves(ta, tb.values)
    # single-level fetch (the pre-prefetch wire op): one round/level
    assert rounds == ta.depth
    spans = coalesce_leaf_ranges(leaves, ta.leaf_width, ta.n_slots)
    assert len(spans) == 1
    lo, hi = spans[0]
    assert lo <= 37 < hi and hi - lo == ta.leaf_width
    # the walk touches one path, not the whole bottom level
    assert fetched < 3 * ta.depth
    # batched frontier prefetch: PREFETCH_LEVELS levels per round
    # trip, same leaves, and the speculative fan-out stays bounded by
    # (2^P - 1) digests per frontier node per round
    leaves_p, rounds_p, fetched_p = walk_divergent_leaves(
        ta, None, fetch_levels=tb.values_levels)
    assert sorted(leaves_p) == sorted(leaves)
    assert rounds_p == -(-ta.depth // PREFETCH_LEVELS)
    assert fetched_p <= rounds_p * 2 * (2 ** PREFETCH_LEVELS - 1)


def test_clean_walk_costs_one_round():
    a = _make("a", 128)
    a.put_batch([1, 2, 3], [10, 20, 30])
    t = a.digest_tree()
    leaves, rounds, fetched = walk_divergent_leaves(t, t.values)
    assert leaves == [] and rounds == 1 and fetched == 1
    # prefetch: still ONE round trip — matching roots end the walk at
    # level 0; the speculative descendants rode along (2^l digests at
    # each prefetched level l) and were simply unused
    leaves, rounds, fetched = walk_divergent_leaves(
        t, None, fetch_levels=t.values_levels)
    assert leaves == [] and rounds == 1
    assert fetched == sum(
        2 ** l for l in range(min(PREFETCH_LEVELS, t.depth)))


# ------------------------------------------------ range pack

def test_full_range_pack_bit_identical_to_pack_since():
    c = _make("c", 96)
    c.put_batch(list(range(0, 90, 3)), list(range(100, 190, 3)))
    c.delete_batch([6, 12])
    full, fids = c.pack_since(None)
    ranged, rids = c.pack_since(None, ranges=((0, 96),))
    assert fids == rids
    for lf, lr in zip(full, ranged):
        if lf is None:
            assert lr is None
        else:
            assert lf.dtype == lr.dtype
            np.testing.assert_array_equal(np.asarray(lf),
                                          np.asarray(lr))


def test_subrange_packs_union_to_full_convergence():
    src = _make("src", 128)
    src.put_batch(list(range(128)), list(range(1000, 1128)))
    via_full = _make("rf", 128)
    via_ranges = _make("rr", 128)
    packed, ids = src.pack_since(None)
    via_full.merge_packed(packed, ids)
    for span in ((0, 40), (40, 128)):
        p, i = src.pack_since(None, ranges=(span,))
        via_ranges.merge_packed(p, i)
    _stores_equal(via_full, via_ranges)


def test_range_validation_rejects_out_of_bounds():
    c = _make("c", 32)
    with pytest.raises(ValueError):
        c.pack_since(None, ranges=((0, 33),))
    with pytest.raises(ValueError):
        c.pack_since(None, ranges=((-1, 4),))


# ------------------------------------------------ digest cache

def test_unchanged_store_answers_digest_from_cache():
    ctr = default_registry().counter("crdt_tpu_digest_cache_total", "")
    c = _make("cache", 64)
    c.put_batch([1, 2], [11, 22])
    m0 = ctr.value(outcome="miss", node="cache")
    h0 = ctr.value(outcome="hit", node="cache")
    t1 = c.digest_tree()
    assert ctr.value(outcome="miss", node="cache") == m0 + 1
    t2 = c.digest_tree()
    # the exact cached object — no rebuild, no new digest dispatch
    assert t2 is t1
    assert ctr.value(outcome="hit", node="cache") == h0 + 1
    c.put_batch([3], [33])                       # store moved: invalidated
    t3 = c.digest_tree()
    assert t3 is not t1
    assert ctr.value(outcome="miss", node="cache") == m0 + 2


def test_restart_answers_first_walk_from_persisted_digest(tmp_path):
    """Digest-tree persistence: `DenseCrdt.save` writes the tree under
    its cache key; `load` re-seeds the cache, so the restarted
    replica's FIRST digest_tree() is a cache hit — zero digest
    dispatches before the first walk — and the tree is level-for-level
    identical to the one saved."""
    ctr = default_registry().counter("crdt_tpu_digest_cache_total", "")
    c = _make("boot", 64)
    c.put_batch(list(range(0, 64, 4)), list(range(16)))
    c.delete_batch([8])
    t_saved = c.digest_tree()
    path = str(tmp_path / "snap.npz")
    c.save(path)
    r = DenseCrdt.load("boot", path, wall_clock=FakeClock(start=BASE))
    h0 = ctr.value(outcome="hit", node="boot")
    m0 = ctr.value(outcome="miss", node="boot")
    t = r.digest_tree()
    assert ctr.value(outcome="hit", node="boot") == h0 + 1
    assert ctr.value(outcome="miss", node="boot") == m0   # no rebuild
    assert t.same_geometry(t_saved.n_slots, t_saved.leaf_width,
                           t_saved.depth)
    for saved_lvl, got_lvl in zip(t_saved.levels, t.levels):
        np.testing.assert_array_equal(np.asarray(saved_lvl),
                                      np.asarray(got_lvl))
    # and the seeded cache obeys the usual invalidation discipline
    r.put_batch([1], [999])
    assert r.digest_tree() is not t
    assert ctr.value(outcome="miss", node="boot") == m0 + 1


def test_pre_digest_snapshot_loads_and_rebuilds(tmp_path):
    """A snapshot saved WITHOUT a digest (store-level `save_dense`,
    i.e. every pre-persistence snapshot) still loads; the first walk
    simply rebuilds — a missing cache, never a failed restore."""
    from crdt_tpu.checkpoint import load_dense_digest, save_dense
    c = _make("old", 32)
    c.put_batch([1, 2], [10, 20])
    path = str(tmp_path / "old.npz")
    save_dense(c.store, path, node_ids=["old"])
    assert load_dense_digest(path) is None
    ctr = default_registry().counter("crdt_tpu_digest_cache_total", "")
    r = DenseCrdt.load("old", path, wall_clock=FakeClock(start=BASE))
    m0 = ctr.value(outcome="miss", node="old")
    r.digest_tree()
    assert ctr.value(outcome="miss", node="old") == m0 + 1


# ------------------------------------------------ socket path

def test_cold_empty_peer_converges_over_socket():
    server_crdt = _make("srv", 256)
    ids = list(range(0, 256, 3))
    server_crdt.put_batch(ids, [i + 7 for i in ids])
    server_crdt.delete_batch([3, 9])
    client = _make("cli", 256)
    stats = {}
    with SyncServer(server_crdt) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            sync_merkle_over_conn(client, conn, _stats=stats)
    _stores_equal(client, server_crdt)
    assert client.digest_tree().root == server_crdt.digest_tree().root
    # frontier prefetch batches PREFETCH_LEVELS tree levels per round
    # trip, so a cold join walks the whole tree in ceil(depth/P)
    # rounds — the pinned wire-round budget for high-RTT links
    depth = client.digest_tree().depth
    assert stats["rounds"] == -(-depth // PREFETCH_LEVELS)
    assert stats["pulled_rows"] == len(ids)


def test_clean_peers_exchange_zero_payload():
    a = _make("a", 128)
    b = _make("b", 128)
    a.put_batch([5, 6], [50, 60])
    packed, ids = a.pack_since(None)
    b.merge_packed(packed, ids)
    stats = {}
    with SyncServer(b) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            sync_merkle_over_conn(a, conn, _stats=stats)
    assert stats["rounds"] == 1                  # roots matched
    assert not stats["ranges"]
    assert stats["pushed_rows"] == 0 and stats["pulled_rows"] == 0


def test_divergence_proportional_bytes_vs_full_scan():
    """The acceptance ratio: a converged pair diverging in one small
    slot window re-syncs for <10% of the full-scan pack bytes. The
    walk's fixed cost is logarithmic meta traffic, so the ratio only
    tightens as the store grows (bench.py --mode sync measures the
    4096-slot headline)."""
    n = 2048
    a = _make("a", n)
    b = _make("b", n)
    ids = list(range(n))
    a.put_batch(ids, [i * 3 for i in ids])
    packed, pids = a.pack_since(None)
    b.merge_packed(packed, pids)
    # partition-era writes: 8 slots, clustered (interning order makes
    # divergence contiguous in slot space)
    b.put_batch(list(range(500, 508)), [0] * 8)
    full_scan = _packed_nbytes(b.pack_since(None)[0])
    tally = WireTally()
    stats = {}
    with SyncServer(b) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            sync_merkle_over_conn(a, conn, tally=tally, _stats=stats)
    _stores_equal(a, b)
    moved = tally.sent + tally.received
    assert moved < 0.10 * full_scan, \
        f"merkle moved {moved}B vs full-scan {full_scan}B"
    assert stats["pulled_rows"] <= 16            # leaf-rounded, not 1024


def test_prefetch_client_degrades_against_pre_prefetch_server(
        monkeypatch):
    """Mixed versions, new-client/old-server direction: a previous
    release advertises the same 'merkle' cap but ignores the 'more'
    prefetch groups and omits 'ks' from digest_resp. The walk must
    degrade to single-level rounds (sticky per session) and still
    converge — never abort with a framing error. Simulated by
    stripping exactly those fields at the module frame helpers the
    server resolves at call time."""
    import crdt_tpu.net as net_mod
    server_crdt = _make("srv", 256)
    ids = list(range(0, 256, 3))
    server_crdt.put_batch(ids, [i + 7 for i in ids])
    client = _make("cli", 256)

    real_recv, real_send = net_mod.recv_frame, net_mod.send_frame

    def legacy_recv(sock, *a, **kw):
        msg = real_recv(sock, *a, **kw)
        if isinstance(msg, dict) and msg.get("op") == "digest":
            msg.pop("more", None)        # server-side: never parsed
        return msg

    def legacy_send(sock, obj, tally=None, codec=None):
        if isinstance(obj, dict) and obj.get("op") == "digest_resp":
            obj = {k: v for k, v in obj.items() if k != "ks"}
        return real_send(sock, obj, tally, codec)

    monkeypatch.setattr(net_mod, "recv_frame", legacy_recv)
    monkeypatch.setattr(net_mod, "send_frame", legacy_send)
    stats = {}
    with SyncServer(server_crdt) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            sync_merkle_over_conn(client, conn, _stats=stats)
            # the degrade is sticky: the NEXT walk on this session
            # skips the futile multi-level probe entirely
            assert conn.digest_prefetch is False
            depth = client.digest_tree().depth
            # one aborted prefetch probe + one single-level round per
            # tree level
            assert stats["rounds"] == depth + 1
            stats2 = {}
            sync_merkle_over_conn(client, conn, _stats=stats2)
            assert stats2["rounds"] == 1         # converged roots
    _stores_equal(client, server_crdt)
    for s in ids:
        assert client.get(s) == s + 7


def test_legacy_server_rejects_merkle_before_payload():
    legacy = _LegacyDense("old", n_slots=32,
                          wall_clock=FakeClock(start=BASE))
    client = _make("new", 32)
    with SyncServer(legacy) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            conn.ensure()
            assert "merkle" not in conn.caps     # never advertised
            with pytest.raises(SyncProtocolError) as ei:
                sync_merkle_over_conn(client, conn)
            assert ei.value.code == "merkle_rejected"


def test_geometry_mismatch_is_rejected_in_process():
    a = _make("a", 64)
    b = _make("b", 128)
    with pytest.raises(ValueError, match="geometry"):
        sync_merkle(a, b)


def test_sync_merkle_report_accounts_traffic():
    a = _make("a", 256)
    b = _make("b", 256)
    ids = list(range(256))
    a.put_batch(ids, ids)
    p, i = a.pack_since(None)
    b.merge_packed(p, i)
    clean = sync_merkle(a, b)
    assert clean.ranges == () and clean.payload_bytes == 0
    assert clean.rounds == 1 and clean.total_bytes == 16
    b.put_batch([100], [-1])
    diverged = sync_merkle(a, b)
    assert len(diverged.ranges) == 1
    assert diverged.pulled_rows >= 1
    full = _packed_nbytes(b.pack_since(None)[0])
    assert diverged.total_bytes < 0.10 * full
    _stores_equal(a, b)


# ------------------------------------------------ gossip integration

def test_gossip_cold_join_walks_then_warm_rounds_pack():
    clk = FakeClock()
    b = _node(_make("b", 64))
    a = _node(_make("a", 64))
    with a, b:
        with b.lock:
            b.crdt.put_batch([1, 2], [10, 20])
        peer = a.add_peer("b", b.host, b.port)
        assert peer.mode == "merkle"             # fastest form by default
        assert a.sync_peer("b") == "ok"
        assert peer.last_attempt == "merkle"     # cold join = the walk
        with a.lock:
            a.crdt.put_batch([3], [30])
        assert a.sync_peer("b") == "ok"
        # warm session: watermark set, the incremental packed round is
        # strictly cheaper — mode still aims at merkle
        assert peer.last_attempt == "packed"
        assert peer.mode == "merkle"
        assert peer.stats.fallbacks == 0
    assert a.crdt.get(1) == 10 and b.crdt.get(3) == 30


def test_gossip_legacy_peer_capability_selected_without_fallback():
    clk = FakeClock(start=BASE)
    b = _node(_LegacyDense("b", n_slots=64, wall_clock=clk))
    a = _node(_make("a", 64))
    with a, b:
        with b.lock:
            b.crdt.put_batch([9], [90])
        peer = a.add_peer("b", b.host, b.port)
        with a.lock:
            a.crdt.put_batch([4], [40])
        assert a.sync_peer("b") == "ok"
        # no 'merkle' cap in the hello -> the walk is never offered;
        # that is selection, not failure
        assert peer.last_attempt == "packed"
        assert peer.stats.fallbacks == 0
        assert peer.mode == "merkle"
    assert a.crdt.get(9) == 90 and b.crdt.get(4) == 40


def test_gossip_digest_failure_downgrades_sticky_to_packed():
    clk = FakeClock(start=BASE)
    b = _node(_BrokenDigestDense("b", n_slots=64, wall_clock=clk))
    a = _node(_make("a", 64))
    with a, b:
        with b.lock:
            b.crdt.put_batch([7], [70])
        peer = a.add_peer("b", b.host, b.port)
        assert a.sync_peer("b") == "ok"          # fell back in-round
        assert peer.stats.fallbacks == 1
        assert peer.mode == "packed"             # sticky downgrade
        with a.lock:
            a.crdt.put_batch([8], [80])
        assert a.sync_peer("b") == "ok"
        assert peer.stats.fallbacks == 1         # no second fallback
    assert a.crdt.get(7) == 70 and b.crdt.get(8) == 80


def test_partitioned_peer_reconverges_by_walk_through_fault_proxy():
    clk = FakeClock()
    n = 1024
    b = _node(DenseCrdt("b", n_slots=n, wall_clock=clk))
    with b:
        with b.lock:
            ids = list(range(0, n, 2))
            b.crdt.put_batch(ids, [i + 1 for i in ids])
        sched = ScriptedSchedule([{"kind": "drop"}, None])
        with FaultProxy(b.host, b.port, sched) as proxy:
            a = _node(DenseCrdt("a", n_slots=n, wall_clock=clk),
                      retry=RetryPolicy(max_attempts=3,
                                        base_delay=0.001))
            with a:
                peer = a.add_peer("b", proxy.host, proxy.port)
                # cold join survives the dropped connection and walks
                assert a.sync_peer("b") == "ok"
                assert peer.stats.retries == 1
                assert peer.last_attempt == "merkle"
                assert proxy.counters.get("drop") == 1
                cold_recv = peer.stats.bytes_received
                # --- partition: both sides move, no rounds run; the
                # resumed replica also lost its watermark state
                with b.lock:
                    b.crdt.put_batch([101, 103], [5101, 5103])
                with a.lock:
                    a.crdt.put_batch([200], [5200])
                peer.watermark = None
                assert a.sync_peer("b") == "ok"
                assert peer.last_attempt == "merkle"
                heal_recv = peer.stats.bytes_received - cold_recv
                # the healing walk pulls the divergent leaves, not the
                # half-full store the cold join shipped (the tight
                # <10% ratio is asserted at socket level above; through
                # gossip the walk's per-round meta frames ride along)
                assert heal_recv < 0.5 * cold_recv, \
                    f"healed with {heal_recv}B vs cold {cold_recv}B"
        _stores_equal(a.crdt, b.crdt)
        assert a.crdt.get(101) == 5101 and b.crdt.get(200) == 5200


def test_three_replica_mixed_mode_soak():
    """One mesh, three wire forms: a->b walks (merkle), b->c stays on
    watermark packing, c->a is pinned to the legacy dense split. Every
    replica writes every round; everyone converges."""
    clk = FakeClock()
    nodes = {name: _node(DenseCrdt(name, n_slots=64, wall_clock=clk))
             for name in ("a", "b", "c")}
    a, b, c = nodes["a"], nodes["b"], nodes["c"]
    with a, b, c:
        a.add_peer("b", b.host, b.port)                  # merkle
        b.add_peer("c", c.host, c.port, mode="packed")
        c.add_peer("a", a.host, a.port, mode="dense")
        for r in range(4):
            for i, node in enumerate(nodes.values()):
                with node.lock:
                    node.crdt.put_batch([r * 8 + i], [100 * r + i])
            for node in nodes.values():
                outcomes = node.run_round()
                assert set(outcomes.values()) == {"ok"}
        # settle sweep so last-round writes reach every replica
        for node in nodes.values():
            assert set(node.run_round().values()) == {"ok"}
        for node in nodes.values():
            assert set(node.run_round().values()) == {"ok"}
        for node in nodes.values():
            assert all(p.stats.fallbacks == 0
                       for p in node.peers.values())
    _stores_equal(a.crdt, b.crdt)
    _stores_equal(b.crdt, c.crdt)


# ------------------------------------------------ Peer.dense back-compat

def test_dense_setter_preserves_faster_modes():
    """Regression: the old setter collapsed ANY binary mode to 'dense',
    silently downgrading merkle/packed peers that touched the legacy
    flag. `dense = True` now only upgrades json; False still forces
    json."""
    from crdt_tpu.gossip import BreakerPolicy, CircuitBreaker
    from crdt_tpu.utils.stats import PeerSyncStats
    p = Peer("p", "127.0.0.1", 1, mode="merkle",
             breaker=CircuitBreaker(BreakerPolicy()),
             stats=PeerSyncStats())
    for mode in ("merkle", "packed", "dense"):
        p.mode = mode
        p.dense = True
        assert p.mode == mode                    # preserved, not collapsed
        assert p.dense is True
    p.mode = "json"
    p.dense = True
    assert p.mode == "dense"                     # json upgrades to floor
    p.mode = "merkle"
    p.dense = False
    assert p.mode == "json"                      # escape hatch intact

"""Fault-injection TCP proxy for replication testing — EXPORTED API.

Sits between a sync client and a :class:`crdt_tpu.net.SyncServer` and
misbehaves on a SEEDED schedule: refuse connections, delay or trickle
bytes, truncate a frame mid-body, corrupt payload bytes, duplicate a
whole frame. The gossip runtime (`crdt_tpu.gossip`) must converge
through all of it — that is the robustness claim the fault-matrix
soak (tests/test_network_soak.py) makes, and this proxy is what makes
the claim falsifiable.

Faults are applied to the client→server byte stream (the direction
that carries pushes and requests); the reply stream is forwarded
verbatim. Every fault surfaces to a well-behaved client as either an
EOF/desync (retryable transport fault) or a server-side rejection —
never as silent corruption: a corrupted byte XORs to an invalid UTF-8
sequence, so a damaged JSON frame fails to decode instead of parsing
to different records.

>>> with SyncServer(crdt) as server:
...     proxy = FaultProxy(server.host, server.port,
...                        FaultSchedule(seed=7)).start()
...     sync_over_tcp(other, proxy.host, proxy.port)   # may fault!
...     proxy.counters                                 # what fired
...     proxy.stop()

`FaultSchedule` draws one fault (or none) per CONNECTION from a
seeded rng; `ScriptedSchedule` replays an explicit list — unit tests
use it to script "refuse once, then behave". Set
:attr:`FaultProxy.passthrough` True to disable faulting (the soak's
settle phase) without tearing down the proxy.

Two crash-shaped primitives ride along for the replication suite
(docs/REPLICATION.md): :func:`abrupt_kill` (die with no farewell —
RST/linger-0, the SIGKILL signature) and
:attr:`FaultProxy.blackhole` (asymmetric partition: swallow ONE
direction's bytes with no FIN and no RST, so the victim looks mute
rather than dead).
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Dict, Iterable, Optional

# Corruption XOR mask: flips the high bit of any ASCII byte, yielding
# an invalid UTF-8 sequence — corrupt JSON always FAILS to decode
# rather than decoding to different data.
_CORRUPT_MASK = 0xA5

# A frame larger than this is passed through un-duplicated rather than
# buffered (the duplicate fault is frame-aware and must not hold a
# 100 MB push in memory).
_DUP_FRAME_CAP = 1 << 20


def _slam(sock: socket.socket) -> None:
    """Close WITHOUT a FIN: SO_LINGER zero makes close() send a bare
    RST (or nothing the peer ever hears, if the segment is lost) — the
    kernel-level signature of a SIGKILLed process, as opposed to
    `_teardown`'s orderly shutdown. Replication tests use this to
    prove failover does not depend on the dying side saying goodbye
    (docs/REPLICATION.md)."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def abrupt_kill(target) -> None:
    """The abrupt-kill primitive: die NOW, with no farewell protocol.

    Dispatch by shape — a `ReplicaGroup` loses its primary
    (``kill_primary``), a `ServeTier` (anything with a no-arg
    ``kill()``) dies via its own SIGKILL-equivalent teardown, a
    `FaultProxy` slams every established relay (RST both ways), and a
    bare socket is linger-0 closed. This is the one primitive chaos
    tests should reach for, so "kill" means the same thing
    everywhere."""
    kill_primary = getattr(target, "kill_primary", None)
    if callable(kill_primary):
        kill_primary()
        return
    kill = getattr(target, "kill", None)
    if callable(kill) and not isinstance(target, socket.socket):
        kill()
        return
    if isinstance(target, FaultProxy):
        target.slam()
        return
    if isinstance(target, socket.socket):
        _slam(target)
        return
    raise TypeError(f"don't know how to abruptly kill {target!r}")


def _teardown(sock: socket.socket) -> None:
    """shutdown + close: the shutdown forces the FIN out (and wakes
    any thread blocked in recv on the socket) even while another
    in-flight syscall keeps the kernel file referenced — a bare
    close() in that state notifies nobody."""
    for call in (lambda: sock.shutdown(socket.SHUT_RDWR), sock.close):
        try:
            call()
        except OSError:
            pass


class FaultSchedule:
    """Seeded per-connection fault plan.

    ``rate`` is the probability a connection faults at all; ``kinds``
    weights the fault drawn when one does. Defaults exercise the whole
    matrix. Deterministic for a fixed seed and connection order."""

    DEFAULT_KINDS = {"drop": 2, "delay": 2, "trickle": 1,
                     "truncate": 2, "corrupt": 2, "duplicate": 1}

    def __init__(self, seed: int = 0, rate: float = 0.5,
                 kinds: Optional[Dict[str, float]] = None,
                 max_delay: float = 0.05):
        self._rng = random.Random(seed)
        self.rate = rate
        self.kinds = dict(kinds if kinds is not None
                          else self.DEFAULT_KINDS)
        self.max_delay = max_delay

    def next_fault(self) -> Optional[dict]:
        rng = self._rng
        if rng.random() >= self.rate:
            return None
        names = sorted(self.kinds)
        kind = rng.choices(names,
                           weights=[self.kinds[n] for n in names])[0]
        if kind == "delay":
            return {"kind": kind,
                    "seconds": rng.uniform(0.0, self.max_delay)}
        if kind == "truncate":
            # Inside the first frame's header-or-body for any real
            # payload, so the cut is mid-frame, not between frames.
            return {"kind": kind, "after": rng.randrange(1, 40)}
        if kind == "corrupt":
            # Past the 4-byte length prefix: framing stays intact and
            # the DAMAGE lands in the body, where it must be caught by
            # decode, not by a misread frame length.
            return {"kind": kind, "offset": rng.randrange(4, 160)}
        return {"kind": kind}


class ScriptedSchedule:
    """Replays an explicit fault sequence, one entry per connection
    (None = behave); after the script runs out, behaves forever."""

    def __init__(self, plan: Iterable[Optional[dict]]):
        self._plan = list(plan)
        self._i = 0

    def next_fault(self) -> Optional[dict]:
        if self._i >= len(self._plan):
            return None
        fault = self._plan[self._i]
        self._i += 1
        return fault


class FaultProxy:
    """TCP proxy with scheduled misbehavior (see module docstring).

    ``counters`` maps fault kind → times it actually FIRED (a
    truncate-at-1000 against a 40-byte stream never fires and is not
    counted), plus ``"connections"``. The soak asserts on these to
    prove its faults happened."""

    _passthrough = False
    _blackhole: Optional[str] = None

    def __init__(self, target_host: str, target_port: int,
                 schedule=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.target_host = target_host
        self.target_port = target_port
        self.schedule = schedule or FaultSchedule()
        self.counters: Dict[str, int] = {}
        self._counter_lock = threading.Lock()
        self._lsock = socket.create_server((host, port))
        self._lsock.settimeout(0.2)   # poll the stop flag
        self.host, self.port = self._lsock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._open: set = set()       # sockets to tear down on stop

    # --- lifecycle (SyncServer's shape) ---

    def start(self) -> "FaultProxy":
        self._thread = threading.Thread(
            target=self._serve, daemon=True,
            name=f"fault-proxy-{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for sock in list(self._open):
            _teardown(sock)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._lsock.close()

    def __enter__(self) -> "FaultProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def passthrough(self) -> bool:
        """Faulting disabled? Flipping True→False (a test starting
        its partition) also tears down ESTABLISHED relays: pooled
        clients hold sessions open across rounds, and a partition
        must cut those flows too — not just refuse new connects."""
        return self._passthrough

    @passthrough.setter
    def passthrough(self, value: bool) -> None:
        was = self._passthrough
        self._passthrough = value
        if was and not value:
            for sock in list(self._open):
                self._open.discard(sock)
                _teardown(sock)

    @property
    def blackhole(self) -> Optional[str]:
        """Asymmetric partition mode: ``"c2s"`` silently swallows the
        client→server byte stream (requests vanish, replies still
        flow), ``"s2c"`` the reverse (requests land, acks never come
        back — the direction that distinguishes "dead" from "mute",
        which is what lease fencing exists for), ``"both"`` swallows
        both, ``None`` restores normal relaying. Unlike a passthrough
        flip nothing is torn down: no FIN, no RST — bytes just stop
        arriving, exactly like a one-way network partition."""
        return self._blackhole

    @blackhole.setter
    def blackhole(self, value: Optional[str]) -> None:
        if value not in (None, "c2s", "s2c", "both"):
            raise ValueError(
                f"blackhole must be None/'c2s'/'s2c'/'both'; "
                f"got {value!r}")
        self._blackhole = value

    def slam(self) -> None:
        """RST every established relay, both directions, and refuse
        nothing afterward: the proxy itself stays up (unlike `stop`),
        but every flow that existed dies the SIGKILL way — no FIN."""
        self._count("slam")
        for sock in list(self._open):
            self._open.discard(sock)
            _slam(sock)

    def _count(self, key: str) -> None:
        with self._counter_lock:
            self.counters[key] = self.counters.get(key, 0) + 1

    # --- relay ---

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._relay, args=(conn,),
                             daemon=True,
                             name=f"fault-relay-{self.port}"
                                  f"-fd{conn.fileno()}").start()

    def _relay(self, conn: socket.socket) -> None:
        self._count("connections")
        fault = (None if self.passthrough
                 else self.schedule.next_fault())
        if fault is not None and fault["kind"] == "drop":
            # Accept-then-slam: the client sees a vanished peer.
            self._count("drop")
            conn.close()
            return
        try:
            up = socket.create_connection(
                (self.target_host, self.target_port), timeout=10)
        except OSError:
            conn.close()
            return
        self._open.update((conn, up))
        conn.settimeout(60)
        up.settimeout(60)
        if fault is not None and fault["kind"] == "delay":
            self._count("delay")
            time.sleep(fault["seconds"])
        reply_pump = threading.Thread(
            target=self._pump_verbatim, args=(up, conn), daemon=True,
            name=f"fault-reply-pump-{self.port}")
        reply_pump.start()
        try:
            self._pump_faulty(conn, up, fault)
        finally:
            # shutdown() BEFORE close(): close alone does not send the
            # FIN while the reply pump still holds a blocked recv on
            # the socket (the in-flight syscall keeps the kernel file
            # alive), and the un-notified server would park its
            # single-connection handler in a 30 s recv — starving the
            # client's own retry connection.
            for sock in (conn, up):
                self._open.discard(sock)
                _teardown(sock)
            reply_pump.join(timeout=10)

    def _pump_verbatim(self, src: socket.socket,
                       dst: socket.socket) -> None:
        """Server→client direction: faithful forwarding. A close from
        the server is PROPAGATED (shutdown of the client's read side):
        a client waiting for a reply the server will never send must
        see EOF now, not its whole round timeout later."""
        try:
            while True:
                data = src.recv(1 << 16)
                if not data:
                    return
                if self._blackhole in ("s2c", "both"):
                    self._count("blackhole_s2c")
                    continue
                dst.sendall(data)
        except OSError:
            return
        finally:
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def _pump_faulty(self, src: socket.socket, dst: socket.socket,
                     fault: Optional[dict]) -> None:
        """Client→server direction with the scheduled fault applied."""
        kind = fault["kind"] if fault is not None else None
        sent = 0
        try:
            if kind == "duplicate":
                sent = self._duplicate_first_frame(src, dst)
            while True:
                data = src.recv(1 << 16)
                if not data:
                    return
                if self._blackhole in ("c2s", "both"):
                    self._count("blackhole_c2s")
                    continue
                if kind == "truncate":
                    cut = fault["after"] - sent
                    if cut < len(data):
                        # Forward a prefix, then kill both ends: the
                        # server holds a partial frame, the client a
                        # dead socket.
                        self._count("truncate")
                        if cut > 0:
                            dst.sendall(data[:cut])
                        return
                elif kind == "corrupt":
                    off = fault["offset"] - sent
                    if 0 <= off < len(data):
                        self._count("corrupt")
                        damaged = bytearray(data)
                        damaged[off] ^= _CORRUPT_MASK
                        data = bytes(damaged)
                elif kind == "trickle" and sent < 64:
                    # Drip the first bytes through one at a time —
                    # exercises every whole-frame deadline bound.
                    if sent == 0:
                        self._count("trickle")
                    for i in range(len(data)):
                        dst.sendall(data[i:i + 1])
                        if sent + i < 64:
                            time.sleep(0.002)
                    sent += len(data)
                    continue
                dst.sendall(data)
                sent += len(data)
        except OSError:
            return

    def _duplicate_first_frame(self, src: socket.socket,
                               dst: socket.socket) -> int:
        """Read the first length-prefixed frame whole and send it
        TWICE — the server processes one request twice and the client's
        reply stream desynchronizes (a retryable fault, since rounds
        are idempotent). Returns bytes forwarded (the original's)."""
        head = self._read_exact(src, 4)
        if head is None:
            return 0
        (n,) = struct.unpack(">I", head)
        if n > _DUP_FRAME_CAP:
            dst.sendall(head)
            return 4
        body = self._read_exact(src, n)
        if body is None:
            dst.sendall(head)
            return 4
        self._count("duplicate")
        dst.sendall(head + body)
        dst.sendall(head + body)
        return 4 + n

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return bytes(buf)


class ProxyFarm:
    """One `FaultProxy` per real address, minted on demand — the
    ``addr_via`` seam that puts a misbehaving proxy on EVERY wire a
    replica group (or a whole `FederatedTier`) uses: pass
    ``addr_via=farm.via`` and each member's advertised address becomes
    its proxy's, so replication ships, heartbeats, split/merge streams
    and client traffic all cross scheduled faults. Partitions spawned
    LATER (a live split's recipient) get their own proxies the moment
    their addresses are first advertised. ``make_schedule(i)`` builds
    the i-th proxy's schedule (default: a mild drop/delay/duplicate
    mix seeded by i, so runs are reproducible)."""

    def __init__(self, make_schedule=None):
        self._make = make_schedule if make_schedule is not None else (
            lambda i: FaultSchedule(
                seed=i, rate=0.1,
                kinds={"drop": 1, "delay": 2, "duplicate": 1},
                max_delay=0.02))
        self.proxies: Dict[str, FaultProxy] = {}
        self._lock = threading.Lock()

    def via(self, real_addr: str) -> str:
        """The advertised (proxied) address for ``real_addr``,
        creating and starting the proxy on first sight."""
        with self._lock:
            proxy = self.proxies.get(real_addr)
            if proxy is None:
                host, _, port = str(real_addr).rpartition(":")
                proxy = FaultProxy(host, int(port),
                                   schedule=self._make(
                                       len(self.proxies))).start()
                self.proxies[real_addr] = proxy
            return f"{proxy.host}:{proxy.port}"

    def counters(self) -> Dict[str, int]:
        """Aggregate fault counters across every proxy — the soak's
        proof that chaos actually flowed through the wires."""
        agg: Dict[str, int] = {}
        with self._lock:
            proxies = list(self.proxies.values())
        for proxy in proxies:
            for k, v in proxy.counters.items():
                agg[k] = agg.get(k, 0) + v
        return agg

    def stop(self) -> None:
        with self._lock:
            proxies, self.proxies = list(self.proxies.values()), {}
        for proxy in proxies:
            proxy.stop()

    def __enter__(self) -> "ProxyFarm":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

"""crdtlint: static + dynamic correctness tooling (docs/ANALYSIS.md).

- `host_lint` — AST linter for host-layer race/discipline rules
- `concurrency` — whole-tree lock-order analyzer (declared
  `_CRDTLINT_LOCK_ORDER` contracts vs the observed acquisition graph)
  plus the runtime deadlock sanitizer (`make_lock`/`OrderedLock`)
- `lattice_laws` — seeded semilattice-law counterexample search
- `jaxpr_audit` — order-sensitivity hazards in merge kernel jaxprs
- `sanitizer` — opt-in runtime lattice assertions (CRDT_TPU_SANITIZE=1)
- CLI: ``python -m crdt_tpu.analysis`` (the CI gate)

This package is import-light on purpose: the sanitizer hook sits on
`crdt.Crdt.merge`'s path, so importing `crdt_tpu.analysis` (or
`.sanitizer`) must not pull in jax or the analyzers. Analyzer names
resolve lazily via ``__getattr__``.
"""

from __future__ import annotations

from . import sanitizer  # import-light: os + typing only
from .findings import Finding

_LAZY = {
    "lint_file": "host_lint", "lint_source": "host_lint",
    "lint_package": "host_lint",
    "LawTarget": "lattice_laws", "run_laws": "lattice_laws",
    "make_wire_join_target": "lattice_laws",
    "AuditTarget": "jaxpr_audit", "AuditReport": "jaxpr_audit",
    "audit_all": "jaxpr_audit",
    "LatticeViolation": "sanitizer",
    "analyze_source": "concurrency", "analyze_paths": "concurrency",
    "analyze_package": "concurrency",
    "make_lock": "concurrency", "OrderedLock": "concurrency",
}

__all__ = ["Finding", "sanitizer"] + sorted(_LAZY)


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        module = importlib.import_module("." + _LAZY[name], __name__)
        return getattr(module, name)
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")

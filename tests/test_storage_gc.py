"""Tombstone epoch GC + online compaction (docs/STORAGE.md).

Covers the whole storage plane in layers: the kernels (purge masks
only stable tombstones, compaction is a bit-identical remap), the
ledger invariants (one dispatch per pass, zero when the watermark
hasn't advanced), the merge-side resurrection fence (set-based: a
stale replay onto a PURGED slot drops, a first-time delivery to any
other slot lands — the migration case), the stability surfaces
(gossip mesh, serve tier, replica group) with their pinning
discipline, the shipped-bytes live/tombstone split, and a kill/
restart GC drill (-m soak) where a short durable set pins the
watermark until the member rejoins.
"""

import random
import threading
import time

import numpy as np
import pytest

import jax

from crdt_tpu import DenseCrdt, FederatedClient, GossipNode
from crdt_tpu.analysis import sanitizer
from crdt_tpu.federation import FederatedTier
from crdt_tpu.models.dense_crdt import ShardedDenseCrdt
from crdt_tpu.models.keyed_dense import KeyedDenseCrdt
from crdt_tpu.obs.device import default_ledger
from crdt_tpu.obs.registry import default_registry
from crdt_tpu.parallel import make_fanin_mesh
from crdt_tpu.replication import ReplicaGroup
from crdt_tpu.semantics import all_semantics
from crdt_tpu.semantics.types import MVREG_MAX, ORSET_UNIVERSE
from crdt_tpu.testing import FakeClock
from crdt_tpu.testing_faults import FaultProxy, FaultSchedule

BASE = 1_700_000_000_000
NO_SLEEP = lambda _s: None          # collapse backoff waits in tests

FAST = dict(flush_interval=0.002, heartbeat_interval=0.02,
            heartbeat_timeout=0.15, lease_misses=3)


def _make(node="n", n_slots=64, start=BASE, **kw):
    return DenseCrdt(node, n_slots=n_slots,
                     wall_clock=FakeClock(start=start), **kw)


def _delta(before, after):
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in set(before) | set(after)
            if after.get(k, 0) != before.get(k, 0)}


def _counter(name, **labels):
    return default_registry().counter(name).value(**labels)


def _wait(pred, timeout=10.0, interval=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# ------------------------------------------------- purge kernel + model

def test_gc_purge_drops_only_stable_tombstones():
    a = _make("a")
    a.put_batch([1, 2, 3], [10, 20, 30])
    a.delete_batch([1, 2])
    stability = a.canonical_time
    assert a.gc_purge(stability, drift_slack_ms=0) == 2
    occ = np.asarray(a._store.occupied)
    assert not occ[1] and not occ[2]
    assert a.get(3) == 30
    assert a.gc_floor > 0


def test_gc_purge_floor_is_inclusive():
    # The delete stamp IS the head: a durable watermark means
    # "delivered THROUGH the stamp", so floor == stamp must purge.
    a = _make("a")
    a.put_batch([7], [70])
    a.delete_batch([7])
    stability = a.canonical_time
    tomb_lt = int(np.asarray(a._store.lt)[7])
    assert int(stability.logical_time) == tomb_lt
    assert a.gc_purge(stability, drift_slack_ms=0) == 1


def test_gc_purge_respects_drift_slack():
    a = _make("a")
    a.put_batch([4], [40])
    a.delete_batch([4])
    stability = a.canonical_time
    # A generous slack puts the floor below the delete stamp: the
    # tombstone is NOT provably stable yet and must survive.
    assert a.gc_purge(stability, drift_slack_ms=1 << 20) == 0
    assert bool(np.asarray(a._store.tomb)[4])
    with pytest.raises(ValueError):
        a.gc_purge(stability, drift_slack_ms=-1)


def test_gc_pass_ledger_invariants():
    led = default_ledger()
    a = _make("a")
    a.put_batch(list(range(8)), list(range(8)))
    a.delete_batch([0, 1])
    stability = a.canonical_time

    before = led.as_dict()
    assert a.gc_purge(stability, drift_slack_ms=0) == 2
    moved = _delta(before, led.as_dict())
    assert moved.get("dense.gc_purge") == 1

    # Unadvanced watermark: zero purged, ZERO dispatches.
    before = led.as_dict()
    assert a.gc_purge(stability, drift_slack_ms=0) == 0
    assert _delta(before, led.as_dict()) == {}

    # One compaction pass is exactly one remap dispatch.
    before = led.as_dict()
    tr = a.compact()
    moved = _delta(before, led.as_dict())
    assert moved.get("dense.compact_remap") == 1
    assert int(tr[5]) >= 0


def test_purged_counter_and_passes_counter_move():
    purged0 = _counter("crdt_tpu_gc_purged_slots_total", node="ctr")
    passes0 = _counter("crdt_tpu_gc_passes_total", node="ctr")
    a = _make("ctr")
    a.put_batch([1, 2], [1, 2])
    a.delete_batch([1, 2])
    stability = a.canonical_time
    assert a.gc_purge(stability, drift_slack_ms=0) == 2
    assert _counter("crdt_tpu_gc_purged_slots_total",
                    node="ctr") == purged0 + 2
    assert _counter("crdt_tpu_gc_passes_total",
                    node="ctr") == passes0 + 1


# ------------------------------------------------- the resurrection fence

def _typed_payload(spec, slot):
    if spec.name == "lww":
        return slot % 1000
    if spec.name == "pncounter":
        return spec.encode(slot - 32)
    if spec.name == "orset":
        return spec.encode({slot % ORSET_UNIVERSE})
    if spec.name == "mvreg":
        return spec.encode(1 + slot % MVREG_MAX)
    return spec.encode(slot % 1000)


# Deterministic slot-residue per typed semantics (str hash is salted
# per process and two names can collide on the same residue).
_LANE_RESIDUE = {name: i for i, name in enumerate(
    spec.name for spec in all_semantics() if spec.name != "lww")}


@pytest.mark.parametrize("spec", all_semantics(),
                         ids=lambda s: s.name)
def test_stale_replay_cannot_resurrect_purged_slot(spec):
    """The adversarial shape for every registered semantics: a
    pre-delete delta held back (delayed merge) and replayed AFTER the
    tombstone was purged must be dropped by the fence."""
    w = _make("w")
    r = _make("r", start=BASE + 1_000_000)   # r's stamps dominate w's
    if spec.name != "lww":
        w.set_semantics([5], spec.name)
        r.set_semantics([5], spec.name)
    w.put_batch([5], [_typed_payload(spec, 5)])
    stale_pk, stale_ids = w.pack_since(None, sem_mode="include")

    r.merge_packed(stale_pk, stale_ids)
    assert bool(np.asarray(r._store.occupied)[5])
    r.delete_batch([5])
    stability = r.canonical_time
    assert r.gc_purge(stability, drift_slack_ms=0) == 1

    if spec.name != "lww":
        # Purged typed slots revert to the LWW default tag; without
        # re-asserting, a stale typed replay is REJECTED by the tag
        # validator before the fence even sees it — also safe.
        with pytest.raises(ValueError, match="semantics tag mismatch"):
            r.merge_packed(stale_pk, stale_ids)
        r.set_semantics([5], spec.name)
    fenced0 = _counter("crdt_tpu_gc_fenced_rows_total", node="r")
    r.merge_packed(stale_pk, stale_ids)     # the delayed replay
    assert not bool(np.asarray(r._store.occupied)[5]), \
        f"{spec.name}: purged slot resurrected by a stale replay"
    assert _counter("crdt_tpu_gc_fenced_rows_total", node="r") > fenced0


def test_fence_is_set_based_first_time_deliveries_land():
    """The migration regression: sub-floor rows to slots this replica
    NEVER purged are new information (merge_cold streams, initial
    syncs) and must land; only the purged set is fenced."""
    dst = _make("d", start=BASE + 1_000_000)
    dst.put_batch([1], [11])
    dst.delete_batch([1])
    stability = dst.canonical_time
    assert dst.gc_purge(stability, drift_slack_ms=0) == 1

    src = _make("s")                        # strictly older stamps
    src.put_batch([40], [77])
    src.put_batch([1], [99])
    pk, ids = src.pack_since(None)
    dst.merge_packed(pk, ids)
    assert dst.get(40) == 77                # first delivery survives
    assert dst.get(1) is None               # replay onto purged slot


def test_sanitizer_post_purge_resurrection_check(monkeypatch):
    monkeypatch.setenv("CRDT_TPU_SANITIZE", "1")
    a = _make("sanz")
    a.put_batch([3], [33])
    a.delete_batch([3])
    stability = a.canonical_time
    assert a.gc_purge(stability, drift_slack_ms=0) == 1
    purged_slots, floor = a._gc_purged
    assert list(purged_slots) == [3]
    # A clean store passes; a store where the purged slot re-occupied
    # below the floor is the violation the check exists for.
    sanitizer.check_dense_no_resurrection(a._store, purged_slots, floor)
    bad = a._store._replace(
        occupied=a._store.occupied.at[3].set(True),
        lt=a._store.lt.at[3].set(floor - 1))
    with pytest.raises(sanitizer.LatticeViolation):
        sanitizer.check_dense_no_resurrection(bad, purged_slots, floor)
    # Compaction remaps slot identity and retires the record.
    a.compact()
    assert a._gc_purged is None


# ------------------------------------------------- compaction bit-identity

def test_compaction_is_a_bit_identical_remap():
    """Oracle: compaction must be EXACTLY a permutation of the live
    rows — same lanes at remapped slots, same digest root, same pack
    bytes, same typed reads as a reference store permuted on host."""
    import copy

    a = _make("cmp")
    slots = list(range(0, 48))
    a.put_batch(slots, [1000 + s for s in slots])
    for spec in all_semantics():
        if spec.name == "lww":
            continue
        lane = [s for s in slots
                if s % 5 == _LANE_RESIDUE[spec.name]]
        if lane:
            a.set_semantics(lane, spec.name)
            a.put_batch(lane, [_typed_payload(spec, s) for s in lane])
    a.delete_batch([s for s in slots if s % 4 == 0])
    stability = a.canonical_time
    assert a.gc_purge(stability, drift_slack_ms=0) > 0

    pre = jax.device_get(a._store)
    pre_sem = None if a._sem is None else a._sem.copy()
    ref = copy.deepcopy(a)
    tr = np.asarray(a.compact())

    n = a.n_slots
    perm = {k: np.zeros(n, np.asarray(getattr(pre, k)).dtype)
            for k in pre._fields}
    sem = None if pre_sem is None else np.zeros(n, pre_sem.dtype)
    for s in range(n):
        if tr[s] >= 0:
            for k in pre._fields:
                perm[k][tr[s]] = np.asarray(getattr(pre, k))[s]
            if sem is not None:
                sem[tr[s]] = pre_sem[s]

    # Lane-level identity on the replicated lanes.
    for k in ("lt", "node", "val", "occupied", "tomb"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a._store, k)), perm[k], err_msg=k)

    # Digest root + pack bytes against the host-permuted reference.
    import jax.numpy as jnp
    ref._store = type(pre)(*(jnp.asarray(perm[k])
                             for k in pre._fields))
    ref._sem = sem if sem is not None and sem.any() else None
    ref._sem_dev = None
    ref._sem_version += 1
    assert int(a.digest_tree().root) == int(ref.digest_tree().root)
    pka, idsa = a.pack_since(None, sem_mode="include")
    pkr, idsr = ref.pack_since(None, sem_mode="include")
    assert idsa == idsr
    for lane_a, lane_r in zip(pka, pkr):
        if lane_a is None or lane_r is None:
            assert lane_a is lane_r
        else:
            np.testing.assert_array_equal(lane_a, lane_r)

    # Typed reads through the translation.
    for spec in all_semantics():
        if spec.name not in _LANE_RESIDUE:
            continue
        lane = [s for s in slots
                if s % 5 == _LANE_RESIDUE[spec.name] and s % 4 != 0]
        for s in lane:
            new = int(tr[s])
            assert new >= 0
            if spec.name == "pncounter":
                assert a.counter_value(new) == s - 32
            elif spec.name == "orset":
                assert a.orset_members(new) == \
                    frozenset({s % ORSET_UNIVERSE})
            elif spec.name == "mvreg":
                assert a.mvreg_get(new) == (1 + s % MVREG_MAX,)


def test_keyed_churn_stays_at_constant_capacity():
    """The bench's flatness claim as a unit test: a steady live set
    churned through unique keys holds capacity, store bytes and
    digest depth flat once GC + compaction run each cycle."""
    kc = KeyedDenseCrdt(_make("churn", n_slots=128))
    live = 64
    prev, shapes = [], []
    for cycle in range(4):
        keys = [f"c{cycle}:{i}" for i in range(live)]
        kc.put_all({k: i for i, k in enumerate(keys)})
        for k in prev:
            kc.delete(k)
        stability = kc.canonical_time
        purged = kc.gc_purge(stability, drift_slack_ms=0)
        assert purged == (live if cycle else 0)
        assert kc.compact() == live
        shapes.append((kc.dense.n_slots,
                       sum(ln.nbytes for ln in kc.dense._store),
                       kc.digest_tree().depth))
        prev = keys
    assert len(set(shapes)) == 1, shapes
    assert all(kc.get(k) == i for i, k in enumerate(prev))


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
def test_sharded_gc_and_compact_match_plain():
    mesh = make_fanin_mesh(2, 4)
    sh = ShardedDenseCrdt("ns", 64, mesh,
                          wall_clock=FakeClock(start=BASE))
    pl = _make("ns")
    for c in (sh, pl):
        c.put_batch([1, 9, 17, 33], [10, 90, 170, 330])
        c.delete_batch([9, 33])
        stability = c.canonical_time
        assert c.gc_purge(stability, drift_slack_ms=0) == 2
    np.testing.assert_array_equal(np.asarray(sh.store.occupied),
                                  np.asarray(pl.store.occupied))
    # Sharded compaction is range-preserving per key shard (each
    # shard's rows settle to ITS dense prefix inside one shard_map),
    # so translations differ from the plain full-store remap — but
    # every live row must survive with identical lanes, inside its
    # own shard's span.
    tr_sh, tr_pl = np.asarray(sh.compact()), np.asarray(pl.compact())
    span = 64 // mesh.shape["key"]
    for old, val in ((1, 10), (17, 170)):
        new_sh, new_pl = int(tr_sh[old]), int(tr_pl[old])
        assert new_sh >= 0 and new_sh // span == old // span
        assert sh.get(new_sh) == pl.get(new_pl) == val
    assert int(np.asarray(sh.store.occupied).sum()) == \
        int(np.asarray(pl.store.occupied).sum()) == 2


# ------------------------------------------------- stability surfaces

def _node(crdt, **kw):
    kw.setdefault("rng", random.Random(7))
    kw.setdefault("sleep", NO_SLEEP)
    return GossipNode(crdt, **kw)


def test_gossip_stability_pins_until_measured_then_purges():
    clk = FakeClock()
    a = _node(DenseCrdt("a", 64, wall_clock=clk))
    b = _node(DenseCrdt("b", 64, wall_clock=clk))
    with a, b:
        a.add_peer("b", b.host, b.port)
        b.add_peer("a", a.host, a.port)
        # Unmeasured peer: watermark None pins the fleet stability.
        assert b.stability_hlc() is None
        pinned0 = _counter("crdt_tpu_gc_pinned_total",
                           surface="gossip")
        assert b.gc_pass(drift_slack_ms=0) == 0
        assert _counter("crdt_tpu_gc_pinned_total",
                        surface="gossip") == pinned0 + 1

        a.crdt.put_batch([3], [30])
        assert a.run_round() == {"b": "ok"}
        assert b.run_round() == {"a": "ok"}
        b.crdt.delete_batch([3])
        assert a.run_round() == {"b": "ok"}   # a pulls the delete
        assert b.run_round() == {"a": "ok"}   # b's watermark advances
        stability = b.stability_hlc()
        assert stability is not None
        assert b.gc_pass(drift_slack_ms=0) == 1
        assert not bool(np.asarray(b.crdt._store.occupied)[3])
        # The metrics extra carries the stability section.
        extra = b._metrics_extra()
        assert extra["stability"]["pinned"] is False
        assert extra["stability"]["gc_floor"] > 0


def test_solo_gossip_node_stability_is_own_head():
    n = _node(_make("solo"))
    with n:
        n.crdt.put_batch([2], [20])
        n.crdt.delete_batch([2])
        stability = n.stability_hlc()
        assert stability == n.crdt.canonical_time
        assert n.gc_pass(drift_slack_ms=0) == 1


def test_replica_group_stability_and_rejoin_byte_split():
    with ReplicaGroup(128, replicas=3, ack_replicas=2,
                      **FAST) as group:
        cli = FederatedClient(group.member_addrs(), timeout=5.0)
        try:
            for s in range(0, 40, 2):
                cli.put(s, 100 + s)
            for s in range(0, 40, 4):
                cli.delete(s)
        finally:
            cli.close()
        tier = group.primary.tier
        _wait(lambda: tier.stability_hlc() is not None,
              what="all follower durable heads")
        _wait(lambda: tier.gc_pass(drift_slack_ms=0) > 0,
              what="stability watermark past the delete stamps")
        # Post-GC rejoin ships LIVE rows only: the byte split proves
        # the retired tombstones never hit the wire.
        live0 = _counter("crdt_tpu_shipped_live_bytes_total",
                         surface="rejoin")
        tomb0 = _counter("crdt_tpu_shipped_tombstone_bytes_total",
                         surface="rejoin")
        victim = 1 if group.primary.index != 1 else 2
        group.kill(victim)
        group.rejoin(victim)
        assert _counter("crdt_tpu_shipped_live_bytes_total",
                        surface="rejoin") > live0
        assert _counter("crdt_tpu_shipped_tombstone_bytes_total",
                        surface="rejoin") == tomb0


def test_merge_cold_after_recipient_gc_ships_and_survives():
    """Integration regression for the set-based fence: a recipient
    that ran GC (fence armed, floor > 0) must still absorb every
    migrated row from the donor — including rows stamped below its
    floor, which it sees for the first time."""
    with FederatedTier(256, partitions=2,
                       flush_interval=0.002) as fed:
        cli = FederatedClient(fed.addrs())
        try:
            for slot in range(0, 256, 5):
                cli.put(slot, slot + 7)
            # A deleted slot on each side arms fences everywhere.
            cli.delete(0)
            cli.delete(255)
        finally:
            cli.close()
        live0 = _counter("crdt_tpu_shipped_live_bytes_total",
                         surface="migrate")
        tomb0 = _counter("crdt_tpu_shipped_tombstone_bytes_total",
                         surface="migrate")
        for tier in fed.tiers:
            tier.gc_pass(drift_slack_ms=0)
        stats = fed.merge_cold()
        assert stats["gc_purged"] >= 0       # donor pass ran
        cli = FederatedClient(fed.addrs())
        try:
            for slot in range(5, 255, 5):
                assert cli.get(slot) == slot + 7
            assert cli.get(0) is None and cli.get(255) is None
        finally:
            cli.close()
        # Post-GC donor: live bytes moved, ~zero tombstone bytes.
        assert _counter("crdt_tpu_shipped_live_bytes_total",
                        surface="migrate") > live0
        assert _counter("crdt_tpu_shipped_tombstone_bytes_total",
                        surface="migrate") == tomb0


def test_purge_races_delayed_transport_without_resurrection():
    """FaultProxy-delayed rounds racing concurrent GC passes: every
    pull from the writer crosses a delaying proxy while the receiver
    purges on a timer — convergence must hold and nothing purged may
    resurrect (the fence drops the late frames' stale rows)."""
    clk = FakeClock()
    a = _node(DenseCrdt("a", 64, wall_clock=clk))
    b = _node(DenseCrdt("b", 64, wall_clock=clk))
    schedule = FaultSchedule(seed=11, rate=1.0,
                             kinds={"delay": 1}, max_delay=0.02)
    with a, b, FaultProxy(a.host, a.port, schedule) as proxy:
        b.add_peer("a", proxy.host, proxy.port)
        a.add_peer("b", b.host, b.port)
        stop = threading.Event()
        purged_total = [0]

        def reaper():
            while not stop.is_set():
                purged_total[0] += b.gc_pass(drift_slack_ms=0)
                time.sleep(0.002)

        t = threading.Thread(target=reaper, daemon=True)
        t.start()
        try:
            for i in range(12):
                a.crdt.put_batch([i], [100 + i])
                if i % 3 == 0:
                    b.crdt.delete_batch([max(0, i - 1)])
                a.run_round()
                b.run_round()
        finally:
            stop.set()
            t.join(timeout=5)
        # Settle: both directions clean.
        a.run_round()
        b.run_round()
        occ = np.asarray(b.crdt.store.occupied)
        tomb = np.asarray(b.crdt.store.tomb)
        if purged_total[0]:
            # Purged slots stayed dead or were re-written ABOVE the
            # floor — never silently resurrected below it.
            floor = b.crdt.gc_floor
            lt = np.asarray(b.crdt.store.lt)
            revived = occ & (lt <= floor) & tomb
            assert not bool(revived.any())
        assert proxy.counters.get("delay", 0) > 0


# ------------------------------------------------- the kill/restart drill

@pytest.mark.soak
def test_gc_drill_kill_pins_watermark_until_rejoin():
    """The -m soak GC drill. Two distinct pin regimes, both real:

    1. Kill a follower with the health monitor deliberately slow
       (lease_misses high): the dead member stays in the primary's
       write-concern set with its durable mark FROZEN below every
       post-kill stamp, so repeated passes purge nothing. (With a
       fast monitor the member is dropped from the set and GC
       legitimately proceeds with the live quorum — replication.py
       `_drop_follower` — which is why this drill pins detection.)
    2. `rejoin` re-adds the member with durable=None — unmeasured
       pins — until the first post-rejoin barrier records an ack;
       then the watermark frees and the purge fires, with zero
       acked rows lost."""
    slow = dict(FAST, heartbeat_interval=0.25,
                heartbeat_timeout=0.5, lease_misses=200)
    with ReplicaGroup(128, replicas=3, ack_replicas=1,
                      **slow) as group:
        cli = FederatedClient(group.member_addrs(), timeout=5.0)
        try:
            for s in range(0, 60, 2):
                cli.put(s, 500 + s)
            for s in range(0, 20, 2):
                cli.delete(s)
            tier = group.primary.tier
            # Drain ALL pre-kill tombstones before the kill so the
            # pinned-window assertion below starts from zero debt.
            drained = [0]

            def _drain():
                drained[0] += tier.gc_pass(drift_slack_ms=0)
                return drained[0] >= 10
            _wait(_drain, what="pre-kill purge", timeout=10.0)
            assert drained[0] == 10

            victim = 1 if group.primary.index != 1 else 2
            group.kill(victim)
            for s in range(20, 40, 2):      # post-kill tombstones
                cli.delete(s)
            # The dead member's durable head is frozen below the new
            # stamps: repeated passes purge NOTHING.
            deadline = time.monotonic() + 0.6
            while time.monotonic() < deadline:
                assert tier.gc_pass(drift_slack_ms=0) == 0
                time.sleep(0.03)

            group.rejoin(victim)
            # Resume traffic: the rejoined member re-enters with
            # durable=None (unmeasured pins), and barriers only run
            # when a flush tick has rows to ship — one write kicks
            # the full-pack barrier that records its first ack.
            cli.put(100, 777)
            freed = [0]

            def _freed():
                freed[0] += group.primary.tier.gc_pass(
                    drift_slack_ms=0)
                return freed[0] >= 10
            _wait(_freed, what="post-rejoin purge", timeout=15.0)
            assert freed[0] == 10
            for s in range(40, 60, 2):      # acked live rows survive
                assert cli.get(s) == 500 + s
            for s in range(20, 40, 2):
                assert cli.get(s) is None
        finally:
            cli.close()

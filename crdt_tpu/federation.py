"""Federated serving: N `ServeTier` partitions behind one keyspace.

`ServeTier` multiplexes 10k sessions onto ONE replica (SERVE_r01);
this module composes N of them into a single logical front door
(ROADMAP item 1). Each tier owns a consistent-hash share of the slot
space (`routing.RoutingTable`); cross-partition ops answer `moved`
(or are proxied for pre-federation sessions), and the table travels
on the hello/route/metrics surfaces so clients and tiers agree on
ownership by epoch.

The load-bearing piece is the **live split** (`split_hot`): when a
partition runs hot — ranked from its serve ack phases and dispatch-
ledger counts (PR 12) — half of its widest range is migrated to a new
tier while writes keep flowing:

1. pick the donor range ``[lo, hi)`` and midpoint ``mid``;
2. stream ``[mid, hi)`` to the recipient in watermark rounds:
   each round packs ``pack_since(mark, ranges=((mid, hi),))`` under
   the donor's lock and ships it over the recipient's ordinary
   ``push_packed`` op (PR 8 machinery, so a `FaultProxy` can sit on
   the wire and the rows are idempotent lattice joins — kill, retry,
   re-ship, nothing double-applies);
3. when a round ships few rows the backlog is small: flip the routing
   epoch (`RoutingTable.split`), publish the new table to every tier;
4. drain: writes accepted by the donor *before* the flip may still be
   sitting in its combiner — wait out the donor's flush tick, then
   ship one final ranged round so the recipient holds everything;
5. clients racing the flip are refused with `moved` (stale epoch),
   refetch the table, and replay at the recipient — the `moved` retry
   loop IS the consistency mechanism; no write is dropped because no
   write is ever acked by a tier that did not commit it.

The inverse, the **live merge** (`merge_cold`), retires a cold
partition the same way: stream every arc it owns to its ring
neighbor in idempotent watermark rounds, flip one epoch
(`RoutingTable.merge` — the recipient must already be an owner),
drain the donor's last flush tick, then retire the donor tier AND
its replica group, re-homing its watch sessions with a typed
``moved`` that carries the flip watermark so re-subscriptions resume
without a gap. Crash-safety is asymmetric around the flip: before
it, the table still names the donor, so any failure (including the
donor primary dying) aborts cleanly and the merge is simply
retryable; after it, the arcs already belong to the recipient, so a
donor crash hands off to the group's failover and the full arc is
re-shipped from the new primary. A recipient crash never flips at
all. `autoscale.Autoscaler` closes the loop: an SLO-driven daemon
that calls `split_hot`/`merge_cold` with hysteresis, cooldown and
epoch fencing, and freezes scaling entirely when its inputs are
unmeasured or a group is primaryless — unmeasured is never treated
as safe to shrink.

Geometry: every partition replica is built with the GLOBAL n_slots.
A partition's store is sparsely occupied outside its ranges, which is
exactly what makes range streaming, Merkle walks and `merge_packed`
work unchanged across partitions — a slot means the same thing
everywhere (docs/FEDERATION.md).

`FederatedClient` is the reference routed client: fetches the table,
sessions per owner, sends the epoch on every op, absorbs `moved` by
refetching and replaying, and can hold watch subscriptions
(`watch`/`next_event`) against any partition.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .analysis.concurrency import make_lock
from .net import (BINOP_DELETE, BINOP_GET, BINOP_PUT, BINOP_ST_BUSY,
                  BINOP_ST_MOVED, BINOP_ST_OK, BINOP_ST_OK_NULL,
                  FrameCodec, WireTally, _pack_for_peer, binop_round,
                  recv_frame, recv_bytes_frame, send_bytes_frame,
                  send_frame)
from .routing import PartitionRouter, RoutingTable
from .serve import ServeTier

__all__ = ["FederatedTier", "FederatedClient"]

# Streaming rounds stop chasing the write stream once a round ships
# this few rows — the leftover is the final post-flip drain's job.
_SETTLE_ROWS = 64
_MAX_ROUNDS = 64


def _metrics():
    from .obs.registry import default_registry
    reg = default_registry()
    return {
        "epoch": reg.gauge("crdt_tpu_federation_epoch",
                           "current routing-table epoch"),
        "partitions": reg.gauge(
            "crdt_tpu_federation_partitions",
            "live partitions behind the federated front door"),
        # The autoscaler-facing name the ISSUE/ROADMAP specify; kept
        # alongside the historical federation_partitions gauge so
        # existing dashboards and the fleet CLI keep reading.
        "partition_count": reg.gauge(
            "crdt_tpu_partition_count",
            "live partitions behind the federated front door"),
        "splits": reg.counter("crdt_tpu_federation_splits_total",
                              "completed live partition splits"),
        "merges": reg.counter("crdt_tpu_federation_merges_total",
                              "completed live partition merges"),
        "migrated": reg.counter(
            "crdt_tpu_federation_migrated_rows_total",
            "rows streamed to recipients during live splits and "
            "merges"),
        "split_seconds": reg.histogram(
            "crdt_tpu_federation_split_seconds",
            "live split wall time (first stream round to post-flip "
            "drain)"),
        "merge_seconds": reg.histogram(
            "crdt_tpu_federation_merge_seconds",
            "live merge wall time (first stream round to donor "
            "retire)"),
        # Wedge detection (obs/fleet.py `evaluate_slo`): wall-clock
        # millis when the in-flight topology change started / last
        # made progress, 0 when idle. A change whose progress stamp
        # stalls past the SLO budget is a hard failure — a wedged
        # split/merge holds `_control` and freezes the scale loop.
        "inflight_since_ms": reg.gauge(
            "crdt_tpu_topology_change_inflight_since_ms",
            "wall-clock ms when the in-flight topology change "
            "started (0 = idle)"),
        "progress_ms": reg.gauge(
            "crdt_tpu_topology_change_progress_ms",
            "wall-clock ms of the in-flight topology change's last "
            "progress (0 = idle)"),
        # Byte split for the GC payoff story (docs/STORAGE.md): how
        # much of every anti-entropy stream was live state vs
        # tombstones. Post-GC donors should ship tombstone_bytes ≈ 0.
        "live_bytes": reg.counter(
            "crdt_tpu_shipped_live_bytes_total",
            "packed bytes of live rows shipped by migration streams "
            "and rejoin walks (surface label: migrate|rejoin)"),
        "tomb_bytes": reg.counter(
            "crdt_tpu_shipped_tombstone_bytes_total",
            "packed bytes of tombstone rows shipped by migration "
            "streams and rejoin walks (surface label: "
            "migrate|rejoin)"),
    }


class _Upstream:
    """Blocking control-plane connection to one tier (federation
    caps negotiated) used by the split engine and the routed client:
    plain request/reply framing on the caller's thread — control
    traffic, never the serving hot path."""

    def __init__(self, addr: str, timeout: float = 30.0,
                 caps: Tuple[str, ...] = ("zlib", "packed",
                                          "semantics", "federation",
                                          "binop")):
        host, _, port = addr.rpartition(":")
        self.addr = addr
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=timeout)
        self.sock.settimeout(timeout)
        self.tally = WireTally()
        send_frame(self.sock, {"op": "hello", "proto": 1,
                               "caps": list(caps)}, self.tally)
        reply = recv_frame(self.sock, tally=self.tally)
        if not (isinstance(reply, dict) and reply.get("ok")):
            raise ConnectionError(
                f"hello to {addr} failed: {reply!r}")
        agreed = set(reply.get("caps") or ())
        self.caps = frozenset(agreed)
        self.codec = FrameCodec(compress="zlib" in agreed)
        self.routing_epoch = reply.get("routing_epoch")

    def request(self, msg: dict) -> Any:
        send_frame(self.sock, msg, self.tally, self.codec)
        return recv_frame(self.sock, tally=self.tally,
                          codec=self.codec)

    def request_with_blob(self, msg: dict, bufs) -> Any:
        send_frame(self.sock, msg, self.tally, self.codec)
        send_bytes_frame(self.sock, bufs, self.tally, self.codec)
        return recv_frame(self.sock, tally=self.tally,
                          codec=self.codec)

    def recv(self) -> Any:
        return recv_frame(self.sock, tally=self.tally,
                          codec=self.codec)

    def recv_blob(self) -> Optional[bytes]:
        return recv_bytes_frame(self.sock, tally=self.tally,
                                codec=self.codec)

    def close(self) -> None:
        try:
            send_frame(self.sock, {"op": "bye"}, self.tally,
                       self.codec)
        except (OSError, ValueError):
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class FederatedTier:
    """N consistent-hash partitions of one keyspace, each a
    `ServeTier` over its own replica, sharing one epoch-versioned
    `RoutingTable`.

    ``make_crdt(partition_id)`` builds each partition's replica
    (global ``n_slots`` geometry — see the module docstring); the
    default builds a CPU-backed `DenseCrdt`. The id is a monotone
    spawn sequence, NOT the partition's list position: elastic
    split/merge cycles retire and re-create partitions, and a reused
    node name would collide with the retired generation's rows still
    living in the survivors. ``layout="even"`` (the
    bench default) gives equal contiguous shares; ``layout="hash"``
    places consistent-hash tokens (`RoutingTable.build`).

    ``replicas > 1`` backs every partition with a
    `replication.ReplicaGroup` (docs/REPLICATION.md): ``tiers[i]``
    then tracks partition *i*'s current PRIMARY (so every existing
    consumer — splits, hot ranking, `tier_at` — keeps working), and
    a group promotion swaps the entry and republishes the table
    fleet-wide through `_on_promote`. With a custom ``make_crdt`` and
    ``replicas > 1`` the builder is called as
    ``make_crdt(partition, replica, generation)``.
    """

    # Checked by analysis/concurrency.py: `_control` may be held while
    # taking a donor tier's store lock (`_ship_ranges` migrates rows
    # under both), never the reverse — the promote path takes the
    # group lock alone and `publish` touches no tier lock, so the
    # PR 15 "cycle that doesn't happen" is now a machine-checked fact.
    _CRDTLINT_LOCK_ORDER = ("_control", ("donor.lock",
                                         "ServeTier.lock"))

    def __init__(self, n_slots: int, partitions: int = 4,
                 host: str = "127.0.0.1",
                 flush_interval: float = 0.002,
                 max_sessions: int = 12000,
                 make_crdt=None, layout: str = "even",
                 vnodes: int = 8, replicas: int = 1,
                 ack_replicas: int = 1,
                 heartbeat_interval: float = 0.05,
                 heartbeat_timeout: float = 0.25,
                 lease_misses: int = 4,
                 replicate_timeout: float = 0.25,
                 addr_via=None, **tier_kw):
        if partitions < 1:
            raise ValueError(
                f"partitions must be >= 1; got {partitions}")
        self.n_slots = int(n_slots)
        self.host = host
        self.flush_interval = flush_interval
        self.max_sessions = max_sessions
        self._layout = layout
        self._vnodes = vnodes
        self._tier_kw = dict(tier_kw)
        self._user_make_crdt = make_crdt
        self._make_crdt = make_crdt if make_crdt is not None \
            else self._default_crdt
        self._n_initial = partitions
        self.replicas = int(replicas)
        self.ack_replicas = int(ack_replicas)
        self.heartbeat_interval = float(heartbeat_interval)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.lease_misses = int(lease_misses)
        self.replicate_timeout = float(replicate_timeout)
        # Forwarded to every ReplicaGroup: maps a member's real listen
        # address to the address the fleet advertises — the chaos-test
        # seam that puts a FaultProxy on EVERY wire the federation
        # uses (replication, streaming, client traffic). Replicated
        # layouts only; bare tiers (replicas == 1) ignore it.
        self.addr_via = addr_via
        self.tiers: List[ServeTier] = []
        # Parallel to `tiers`: the ReplicaGroup backing partition i,
        # or None when replicas == 1 (the zero-overhead layout).
        self.groups: List[Optional[object]] = []
        self.table: Optional[RoutingTable] = None
        self.last_split: Optional[dict] = None
        self.last_merge: Optional[dict] = None
        # Serializes splits and table publication against each other;
        # the serving hot path never takes it.
        self._control = make_lock("FederatedTier._control", 10)
        # Monotone partition-identity counter. Spawn names must NEVER
        # be reused across elastic cycles: a merged-away partition's
        # rows live on in the survivor stamped with its node id, and
        # a later split recipient reusing that name would reject its
        # own ancestors' rows as a duplicate node mid-migration.
        self._spawn_seq = 0

    def _default_crdt(self, index: int):
        from .models.dense_crdt import DenseCrdt
        return DenseCrdt(f"fed-p{index}", self.n_slots)

    def _replica_crdt(self, pi: int, ri: int, gen: int):
        if self._user_make_crdt is not None:
            return self._user_make_crdt(pi, ri, gen)
        from .models.dense_crdt import DenseCrdt
        return DenseCrdt(f"fed-p{pi}-r{ri}.{gen}", self.n_slots)

    # --- lifecycle ---

    def _spawn_tier(self, index: int) -> ServeTier:
        tier = ServeTier(
            self._make_crdt(index), host=self.host, port=0,
            max_sessions=self.max_sessions,
            flush_interval=self.flush_interval,
            router=PartitionRouter(), **self._tier_kw)
        tier.start()
        tier.router.bind(f"{tier.host}:{tier.port}")
        return tier

    def _spawn_partition(self):
        """Spawn one partition under the next spawn-sequence identity:
        a bare tier when ``replicas == 1`` (the pre-replication
        layout, zero added moving parts), else a started
        `ReplicaGroup` whose primary tier is what the fleet routes
        to. Returns ``(primary_tier, group_or_None)``. The identity
        is the monotone ``_spawn_seq``, not the list position — list
        positions are reused as merges retire partitions, names are
        not (see ``_spawn_seq``)."""
        seq = self._spawn_seq
        self._spawn_seq += 1
        if self.replicas == 1:
            return self._spawn_tier(seq), None
        from .replication import ReplicaGroup
        grp = ReplicaGroup(
            self.n_slots, replicas=self.replicas,
            ack_replicas=self.ack_replicas, host=self.host,
            group=f"p{seq}",
            make_crdt=lambda ri, gen, pi=seq:
                self._replica_crdt(pi, ri, gen),
            flush_interval=self.flush_interval,
            heartbeat_interval=self.heartbeat_interval,
            heartbeat_timeout=self.heartbeat_timeout,
            lease_misses=self.lease_misses,
            replicate_timeout=self.replicate_timeout,
            on_promote=self._on_promote,
            addr_via=self.addr_via,
            tier_kwargs={"max_sessions": self.max_sessions,
                         **self._tier_kw})
        grp.start()
        return grp.primary.tier, grp

    def start(self) -> "FederatedTier":
        try:
            for _ in range(self._n_initial):
                tier, grp = self._spawn_partition()
                self.tiers.append(tier)
                self.groups.append(grp)
            owners = [t.router.addr for t in self.tiers]
            if self._layout == "hash":
                table = RoutingTable.build(self.n_slots, owners,
                                           vnodes=self._vnodes)
            else:
                table = RoutingTable.even(self.n_slots, owners)
            with self._control:
                self.publish(table)
        except BaseException:
            self.stop()
            raise
        return self

    def stop(self) -> None:
        tiers, self.tiers = self.tiers, []
        groups, self.groups = self.groups, []
        for i, tier in enumerate(tiers):
            grp = groups[i] if i < len(groups) else None
            try:
                if grp is not None:
                    grp.stop()
                else:
                    tier.stop()
            except Exception:
                pass

    def __enter__(self) -> "FederatedTier":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def publish(self, table: RoutingTable) -> None:
        """Install ``table`` on every tier — every group MEMBER for
        replicated partitions, so followers answer ``moved`` with the
        same epoch the primary serves — and refresh the fleet gauges
        (epoch-guarded installs, so an older table never rolls a tier
        back). Callers hold ``_control``; `install_table` is lock-free
        on the group side, which is what keeps the promote path
        (group lock → control lock) cycle-free."""
        for i, tier in enumerate(self.tiers):
            grp = self.groups[i] if i < len(self.groups) else None
            if grp is not None:
                grp.install_table(table)
            else:
                tier.router.install(table)
        self.table = table
        m = _metrics()
        m["epoch"].set(float(table.epoch))
        m["partitions"].set(float(len(self.tiers)))
        m["partition_count"].set(float(len(self.tiers)))

    # --- wedge instrumentation (obs/fleet.py `evaluate_slo`) ---

    def _change_started(self) -> None:
        from .hlc import wall_clock_millis
        ms = float(wall_clock_millis())
        m = _metrics()
        m["inflight_since_ms"].set(ms)
        m["progress_ms"].set(ms)

    def _change_progress(self) -> None:
        from .hlc import wall_clock_millis
        _metrics()["progress_ms"].set(float(wall_clock_millis()))

    def _change_done(self) -> None:
        m = _metrics()
        m["inflight_since_ms"].set(0.0)
        m["progress_ms"].set(0.0)

    def _on_promote(self, group, table) -> None:
        """Failover driver: a group monitor elected a new primary and
        hands us its proposed table flip. Swap the partition's `tiers`
        entry to the new primary and publish fleet-wide. Runs on the
        group's monitor thread AFTER it released the group lock (see
        `ReplicaGroup._promote`), so taking ``_control`` here cannot
        deadlock against a split holding ``_control`` while polling
        the group."""
        with self._control:
            idx = next((i for i, g in enumerate(self.groups)
                        if g is group), None)
            if idx is None:
                return        # group already detached (stop/abort)
            old_tier = self.tiers[idx]
            new_tier = group.primary.tier
            self.tiers[idx] = new_tier
            current = self.table
            if table is not None and (
                    current is None or table.epoch > current.epoch):
                fresh = table
            else:
                # The group's flip raced a concurrent epoch bump (a
                # split published while the election ran) and lost
                # the tie — re-derive the ownership move against the
                # CURRENT table so the dead primary's arcs still land
                # on the winner.
                fresh = current
                if current is not None:
                    old_addr = old_tier.router.addr
                    if old_addr in current.owners():
                        fresh = current.reassign(
                            old_addr, new_tier.router.addr)
            if fresh is not None:
                self.publish(fresh)

    def _await_failover(self, group, dead_tier: ServeTier,
                        timeout: float = 5.0) -> ServeTier:
        """Block until ``group`` promotes a replacement for
        ``dead_tier`` and return the new primary's tier. Used by the
        post-flip drain when the donor dies mid-split; safe to call
        while holding ``_control`` because `ReplicaGroup.primary`
        only takes the group lock, which `_promote` releases before
        it calls back into `_on_promote`."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            m = group.primary
            if m is not None and m.tier is not None \
                    and m.tier is not dead_tier \
                    and not m.tier.killed:
                return m.tier
            # crdtlint: disable=blocking-under-lock -- bounded failover wait; group.primary takes only the group lock, released before _on_promote re-enters _control
            time.sleep(group.heartbeat_interval)
        raise ConnectionError(
            f"group {group.group}: no replacement primary within "
            f"{timeout}s of donor death")

    def addrs(self) -> List[str]:
        return [t.router.addr for t in self.tiers]

    def tier_at(self, addr: str) -> ServeTier:
        for tier in self.tiers:
            if tier.router.addr == addr:
                return tier
        raise KeyError(f"no tier at {addr}")

    # --- hot-partition detection (serve ack phases + PR 12 ledger) ---

    def hot_partition(self) -> Tuple[int, dict]:
        """Rank partitions by committed write rows (the serve ack
        pipeline's volume signal) and return (index, evidence). The
        evidence dict records the per-partition rows plus the process
        dispatch-ledger ingest-scatter counts, so a split decision is
        auditable in the trace record."""
        from .obs.device import default_ledger
        rows = []
        for tier in self.tiers:
            wc = tier._wc
            rows.append(0 if wc is None else int(wc.rows_committed))
        hot = max(range(len(rows)), key=lambda i: rows[i])
        led = default_ledger()
        evidence = {
            "rows_committed": rows,
            "hot_index": hot,
            "ledger_ingest_dispatches": {
                k: v for k, v in led.as_dict().items()
                if "ingest" in k or "put_scatter" in k},
        }
        return hot, evidence

    def cold_partition(self) -> Tuple[Optional[int], dict]:
        """Rank partitions by committed write rows and return the
        COLDEST mergeable index plus the evidence dict (mirror of
        `hot_partition`, feeding `merge_cold`). A partition is
        mergeable when some OTHER partition owns a range adjacent to
        one of its arcs — with one partition left there is nothing to
        merge into and the index comes back None."""
        rows = []
        for tier in self.tiers:
            wc = tier._wc
            rows.append(0 if wc is None else int(wc.rows_committed))
        cold = None
        for i in sorted(range(len(rows)), key=lambda i: rows[i]):
            addr = self.tiers[i].router.addr
            if self.table is not None \
                    and self._merge_neighbor(addr) is not None:
                cold = i
                break
        evidence = {"rows_committed": rows, "cold_index": cold}
        return cold, evidence

    def _merge_neighbor(self, donor_addr: str) -> Optional[str]:
        """The ring neighbor that absorbs a retiring donor's arcs:
        the owner of the range following the donor's widest arc
        (wrapping), falling back to the one preceding it. None when
        no other owner borders the donor (single-owner table)."""
        table = self.table
        spans = table.ranges_of(donor_addr)
        if not spans:
            return None
        lo, hi = max(spans, key=lambda r: r[1] - r[0])
        n = table.n_slots
        for probe in (hi % n, (lo - 1) % n):
            owner = table.owner_of(probe)
            if owner != donor_addr:
                return owner
        return None

    # --- the live split state machine ---

    def split_hot(self, src: Optional[int] = None,
                  dst_addr_override: Optional[str] = None,
                  settle_rows: int = _SETTLE_ROWS) -> dict:
        """Split the hot partition live: spawn a recipient tier,
        stream the migrating half-range to it while writes keep
        flowing, flip the routing epoch, drain the donor's last tick.
        Returns the split stats dict (also kept as ``last_split``).

        ``dst_addr_override`` routes the *stream* through a different
        address than the recipient's own (tests interpose a
        `FaultProxy` there to kill mid-handoff); the routing table
        always names the recipient's real address.
        """
        with self._control:
            return self._split_locked(src, dst_addr_override,
                                      settle_rows)

    def _split_locked(self, src, dst_addr_override, settle_rows):
        if self.table is None:
            raise RuntimeError("federation not started")
        t0 = time.perf_counter()
        if src is None:
            src, evidence = self.hot_partition()
        else:
            evidence = {"hot_index": src, "forced": True}
        donor = self.tiers[src]
        donor_group = self.groups[src] if src < len(self.groups) \
            else None
        donor_addr = donor.router.addr
        spans = self.table.ranges_of(donor_addr)
        if not spans:
            raise ValueError(f"partition {src} owns no ranges")
        lo, hi = max(spans, key=lambda r: r[1] - r[0])
        if hi - lo < 2:
            raise ValueError(
                f"range [{lo}, {hi}) too narrow to split")
        mid = (lo + hi) // 2

        recipient, recipient_group = self._spawn_partition()
        self.tiers.append(recipient)
        self.groups.append(recipient_group)
        dst_addr = recipient.router.addr
        stream_addr = dst_addr_override or dst_addr

        # Pre-flip: recipient must already believe the CURRENT table
        # (it is not an owner yet, so forwarded/foreign ops answer
        # moved instead of enqueueing) before any client can find it.
        if recipient_group is not None:
            recipient_group.install_table(self.table)
        else:
            recipient.router.install(self.table)

        rounds = 0
        migrated = 0
        mark = None
        flipped = False
        self._change_started()
        # Dial INSIDE the try: a refused handshake must still run the
        # unwind (drop the just-spawned recipient) and `_change_done`
        # (a wedge gauge left in-flight reads as a stuck topology
        # change forever).
        up = None
        try:
            up = self._dial_upstream(stream_addr)
            while rounds < _MAX_ROUNDS:
                rounds += 1
                shipped, mark = self._ship_ranges(
                    donor, up, mark, ((mid, hi),))
                self._change_progress()
                migrated += shipped
                if shipped <= settle_rows:
                    break
            # Flip: one epoch bump, published everywhere. Writes the
            # donor acked before this instant are covered by the
            # post-flip drain; writes arriving after it answer moved.
            table = self.table.split(lo, mid, dst_addr)
            self.publish(table)
            flipped = True
            self._change_progress()
            flip_at = time.perf_counter()
            # Drain: anything the donor enqueued pre-flip commits
            # within one flush tick; wait it out, then ship the final
            # watermark round so the recipient holds every acked row.
            # crdtlint: disable=blocking-under-lock -- bounded drain (4 flush ticks); _control intentionally serializes the whole split against other topology changes
            time.sleep(max(donor.flush_interval * 4, 0.01))
            try:
                shipped, mark = self._ship_ranges(donor, up, mark,
                                                  ((mid, hi),))
            except ConnectionError:
                if donor_group is None or not donor.killed:
                    raise
                # Donor crashed AFTER the flip: the table already
                # names the recipient, so aborting would strand
                # [mid, hi). Hand off: wait for the donor's group to
                # promote (write concern means every acked row is on
                # the winner) and re-drain the full range from the
                # new primary — mark=None, because the watermark was
                # taken against the dead store's clock.
                donor = self._await_failover(donor_group, donor)
                shipped, mark = self._ship_ranges(donor, up, None,
                                                  ((mid, hi),))
            migrated += shipped
            rounds += 1
        except BaseException:
            if not flipped:
                # Pre-flip abort: no client ever saw the recipient
                # (the table never named it), so unwinding it IS the
                # clean abort — the donor's group fails over on its
                # own and the split can simply be retried.
                self.tiers.pop()
                grp = self.groups.pop()
                try:
                    if grp is not None:
                        grp.stop()
                    else:
                        recipient.stop()
                except Exception:
                    pass
            raise
        finally:
            if up is not None:
                up.close()
            self._change_done()

        m = _metrics()
        m["splits"].inc()
        m["migrated"].inc(migrated)
        dt = time.perf_counter() - t0
        m["split_seconds"].observe(dt)
        donor.last_scale = {"action": "split-donor",
                            "epoch": self.table.epoch,
                            "peer": dst_addr}
        recipient.last_scale = {"action": "split-recipient",
                                "epoch": self.table.epoch,
                                "peer": donor_addr}
        self.last_split = {
            "src": src, "src_addr": donor_addr, "dst_addr": dst_addr,
            "range": [lo, hi], "split_at": mid,
            "rounds": rounds, "migrated_rows": migrated,
            "epoch": self.table.epoch, "seconds": dt,
            "drain_rows": shipped,
            "flip_to_drain_seconds": time.perf_counter() - flip_at,
            "evidence": evidence,
        }
        return self.last_split

    def _ship_ranges(self, donor: ServeTier, up: _Upstream, mark,
                     spans: Tuple[Tuple[int, int], ...]):
        """One streaming round: pack the donor's rows in ``spans``
        modified at-or-after ``mark`` (under the donor's lock, with
        the watermark taken in the SAME hold so no commit can fall
        between pack and mark), ship via push_packed, return
        (rows, new_mark). A split streams one half-range; a merge
        streams every arc the donor owns. Transport faults retry on a
        fresh connection — the rows are idempotent lattice joins. A
        KILLED donor raises instead of packing: its in-process store
        object is still addressable, but a real crash would not be,
        and the abort/handoff paths key off this honesty."""
        from .ops.packing import pack_rows
        if donor.killed:
            raise ConnectionError(
                f"donor {donor.host}:{donor.port} killed mid-stream")
        with donor.lock:
            wm = donor.crdt.canonical_time
            # crdtlint: disable=blocking-under-lock -- migration pack must be atomic with the donor watermark; _control serializes topology changes so no other split waits on this dispatch
            packed, ids = _pack_for_peer(donor.crdt, mark, True,
                                         ranges=tuple(spans))
        if not packed.k:
            return 0, wm
        # Live/tombstone byte split (docs/STORAGE.md): every row costs
        # the same wire bytes, so the split is exact row accounting.
        # Donors that ran an epoch-GC pass first stream tomb_bytes ≈ 0
        # — the measurable payoff of purge-before-retire.
        per_row = packed.nbytes // packed.k
        tomb_rows = int(packed.tomb.sum())
        m = _metrics()
        m["tomb_bytes"].inc(tomb_rows * per_row, surface="migrate")
        m["live_bytes"].inc((packed.k - tomb_rows) * per_row,
                            surface="migrate")
        meta, bufs = pack_rows(packed)
        msg = {"op": "push_packed", "meta": meta,
               "node_ids": list(ids)}
        for attempt in range(8):
            try:
                reply = up.request_with_blob(msg, bufs)
                if isinstance(reply, dict) and reply.get("ok"):
                    return packed.k, wm
                raise ConnectionError(
                    f"push_packed refused: {reply!r}")
            except (ConnectionError, OSError, ValueError) as e:
                # Kill-and-restart mid-handoff (FaultProxy drops the
                # link, the recipient restarts): reconnect and replay
                # the SAME pack — merge_packed is idempotent.
                last = e
                try:
                    up.close()
                except Exception:
                    pass
                # crdtlint: disable=blocking-under-lock -- bounded redial backoff (8 attempts); abandoning mid-migration would strand shipped-but-unacked rows
                time.sleep(0.05 * (attempt + 1))
                try:
                    up.__init__(up.addr)
                except (ConnectionError, OSError) as e2:
                    last = e2
                    continue
        raise ConnectionError(
            f"range stream to {up.addr} failed after retries: {last!r}")

    @staticmethod
    def _dial_upstream(addr: str) -> _Upstream:
        """Handshake the control-plane stream, with retries. The dial
        is the one transport step `_ship_ranges` cannot re-run (its
        reconnects need a session object to exist first), and a single
        flaky accept should not abort a topology change that has not
        moved a row yet."""
        last: Exception = ConnectionError(f"no dial attempted: {addr}")
        for attempt in range(8):
            try:
                return _Upstream(addr)
            except (ConnectionError, OSError) as e:
                last = e
                # crdtlint: disable=blocking-under-lock -- bounded dial backoff (8 attempts, ≤1.8s total); the PR 16 fix moved the UNBOUNDED wait out, this residue is capped
                time.sleep(0.05 * (attempt + 1))
        raise ConnectionError(
            f"upstream dial to {addr} failed after retries: {last!r}")

    # --- the live merge state machine (inverse of split_hot) ---

    def merge_cold(self, src: Optional[int] = None,
                   dst_addr_override: Optional[str] = None,
                   settle_rows: int = _SETTLE_ROWS) -> dict:
        """Merge the cold partition away live: stream every arc it
        owns to its ring neighbor in the same idempotent watermark
        rounds the split uses, flip the routing epoch
        (`RoutingTable.merge`), drain the donor's last flush tick
        plus a final catch-up round, re-home its watch sessions, then
        retire the donor tier AND its `ReplicaGroup`. Returns the
        merge stats dict (also kept as ``last_merge``).

        Crash-safety mirrors the split. Donor primary killed PRE-flip:
        the stream raises, the table still names the donor, nothing
        was spawned — the merge is simply retryable once the group
        fails over, and the arc is served throughout. Donor killed
        POST-flip: hand off to `_await_failover` and re-ship the full
        arc from the new primary (write concern means every acked row
        is on the winner). Recipient crash: `push_packed` retries
        exhaust and the merge aborts WITHOUT flipping — the donor
        still owns its arc, and a later retry merges into whichever
        neighbor the recipient's own failover elected.

        ``dst_addr_override`` routes the *stream* through a different
        address than the recipient's own (tests interpose a
        `FaultProxy` there); the routing table always names the
        recipient's real address.
        """
        with self._control:
            stats, grp, donor = self._merge_locked(
                src, dst_addr_override, settle_rows)
        # Stop the retired group OUTSIDE the _control hold: after a
        # donor-kill handoff its monitor thread is parked in
        # `_on_promote` waiting for _control, and `stop()` joins that
        # thread — joining under the lock is a deadlock that only the
        # join timeout would break. Released first, the monitor wakes,
        # finds the group already detached, backs off, and the join
        # completes immediately.
        try:
            if grp is not None:
                grp.stop()
            else:
                donor.stop()
        except Exception:
            pass
        return stats

    def _merge_locked(self, src, dst_addr_override, settle_rows):
        if self.table is None:
            raise RuntimeError("federation not started")
        if len(self.tiers) <= 1:
            raise ValueError("cannot merge the last partition")
        t0 = time.perf_counter()
        if src is None:
            src, evidence = self.cold_partition()
            if src is None:
                raise ValueError("no mergeable partition "
                                 "(single-owner table)")
        else:
            evidence = {"cold_index": src, "forced": True}
        donor = self.tiers[src]
        donor_group = self.groups[src] if src < len(self.groups) \
            else None
        donor_addr = donor.router.addr
        spans = self.table.ranges_of(donor_addr)
        if not spans:
            raise ValueError(f"partition {src} owns no ranges")
        dst_addr = self._merge_neighbor(donor_addr)
        if dst_addr is None:
            raise ValueError(
                f"no ring neighbor to absorb {donor_addr}")
        recipient = self.tier_at(dst_addr)
        stream_addr = dst_addr_override or dst_addr

        # Spend the GC bytes (docs/STORAGE.md): purge the donor's
        # stable tombstones BEFORE streaming, so retiring a churned
        # partition ships live rows only — the recipient never pays
        # pack/merge/digest cost for deletes every replica already
        # observed. Zero-cost when the stability watermark is pinned
        # or has not advanced (gc_pass dispatches nothing).
        gc_purged = donor.gc_pass()

        rounds = 0
        migrated = 0
        mark = None
        flipped = False
        self._change_started()
        # Dial INSIDE the try: a refused handshake must still run
        # `_change_done`, or the wedge gauge reads as a stuck topology
        # change forever.
        up = None
        try:
            up = self._dial_upstream(stream_addr)
            while rounds < _MAX_ROUNDS:
                rounds += 1
                shipped, mark = self._ship_ranges(donor, up, mark,
                                                  spans)
                self._change_progress()
                migrated += shipped
                if shipped <= settle_rows:
                    break
            # Flip: the donor leaves the table in one epoch bump,
            # published everywhere — every write arriving after this
            # instant answers moved at the recipient; writes the
            # donor acked before it are the drain's job. The
            # recipient's watch watermark is rewound to the flip
            # watermark FIRST, so re-homed subscriptions cannot miss
            # rows whose origin stamps predate the recipient's head.
            table = self.table.merge(donor_addr, dst_addr)
            flip_mark = mark
            recipient.rearm_watch(flip_mark)
            self.publish(table)
            flipped = True
            self._change_progress()
            flip_at = time.perf_counter()
            # crdtlint: disable=blocking-under-lock -- bounded drain (4 flush ticks), same serialized-topology reasoning as _split_locked
            time.sleep(max(donor.flush_interval * 4, 0.01))
            try:
                shipped, mark = self._ship_ranges(donor, up, mark,
                                                  spans)
            except ConnectionError:
                if donor_group is None or not donor.killed:
                    raise
                # Donor crashed AFTER the flip: the table already
                # dropped it, so aborting would strand its arcs.
                # Hand off: wait for the group to promote (write
                # concern means every acked row is on the winner) and
                # re-ship the FULL arc from the new primary —
                # mark=None, the watermark was taken against the dead
                # store's clock.
                donor = self._await_failover(donor_group, donor)
                shipped, mark = self._ship_ranges(donor, up, None,
                                                  spans)
            migrated += shipped
            rounds += 1
            self._change_progress()
        except BaseException:
            # Pre-flip abort: the table still names the donor, so the
            # arc is served throughout and there is nothing to unwind
            # — the merge is simply retryable (after a donor-group
            # failover the retry streams from the new primary).
            # Post-flip, reaching here means the handoff above also
            # failed; the arcs belong to the recipient and acked rows
            # are on the donor group's survivors by write concern —
            # surface the error, the retire just did not happen.
            raise
        finally:
            if up is not None:
                up.close()
            self._change_done()

        # Retire the donor: re-home its watch sessions (typed moved +
        # flip-watermark resume at the recipient) and drop it from
        # the partition lists under _control (a late _on_promote for
        # this group then finds nothing and backs off). The caller
        # stops the group after releasing _control — heartbeats,
        # leases and replicator ships cease, the addresses are
        # released, and the fleet poller loses the member on its next
        # scrape.
        rehomed = donor.rehome_watchers(
            dst_addr, table.epoch,
            since=None if flip_mark is None else str(flip_mark))
        del self.tiers[src]
        grp = self.groups.pop(src) if src < len(self.groups) else None

        m = _metrics()
        m["merges"].inc()
        m["migrated"].inc(migrated)
        dt = time.perf_counter() - t0
        m["merge_seconds"].observe(dt)
        # publish() ran before the retire, so refresh the partition
        # gauges now that the donor is gone.
        m["partitions"].set(float(len(self.tiers)))
        m["partition_count"].set(float(len(self.tiers)))
        recipient.last_scale = {"action": "merge-absorb",
                                "epoch": table.epoch,
                                "peer": donor_addr}
        self.last_merge = {
            "src": src, "src_addr": donor_addr, "dst_addr": dst_addr,
            "spans": [list(s) for s in spans],
            "rounds": rounds, "migrated_rows": migrated,
            "gc_purged": gc_purged,
            "epoch": self.table.epoch, "seconds": dt,
            "drain_rows": shipped, "rehomed_watchers": rehomed,
            "flip_to_drain_seconds": time.perf_counter() - flip_at,
            "evidence": evidence,
        }
        return self.last_merge, grp, donor


class FederatedClient:
    """Routed synchronous client: one hello'd session per owner,
    table-aware, epoch-stamped ops, `moved`-driven retry.

    The retry loop is the protocol: on ``moved`` (or a routing-flux
    ``busy``) the client refetches the table from any live tier and
    replays the op at the new owner. An op is reported successful
    ONLY on a positive ack from the tier that committed it — which is
    what makes "zero dropped writes" measurable from the client side.

    Retries back off exponentially (10 ms doubling, capped at
    250 ms), and the default attempt budget is sized so the loop
    rides out a full replica-group failover (~2 s of cumulative
    sleep against a sub-second promote; docs/REPLICATION.md) —
    mid-failover, every path can fail at once: the old owner drops
    connections, a fenced primary answers ``busy``, and ``refresh``
    itself may find no reachable tier for a beat.
    """

    def __init__(self, seeds: List[str], timeout: float = 30.0,
                 max_redirects: int = 12):
        if not seeds:
            raise ValueError("need at least one seed address")
        self._seeds = list(seeds)
        self._timeout = timeout
        self._max_redirects = max_redirects
        self._sessions: Dict[str, _Upstream] = {}
        self.table: Optional[RoutingTable] = None
        self.moved_redirects = 0
        self.busy_retries = 0
        self.redirect_resets = 0
        # Binary op lane adoption accounting (docs/WIRE.md): rounds
        # sent on the negotiated `binop` lane, and sessions demoted to
        # framed JSON after a malformed binary reply (sticky for the
        # session's lifetime — one framing fault means the peer's
        # binary half cannot be trusted, but its JSON half still can).
        self.binop_rounds = 0
        self.binop_fallbacks = 0
        self.refresh()

    # --- plumbing ---

    def _session(self, addr: str) -> _Upstream:
        up = self._sessions.get(addr)
        if up is None:
            up = self._sessions[addr] = _Upstream(
                addr, timeout=self._timeout)
        return up

    def _drop_session(self, addr: str) -> None:
        up = self._sessions.pop(addr, None)
        if up is not None:
            up.close()

    def _backoff(self, attempt: int) -> None:
        time.sleep(min(0.25, 0.01 * (1 << attempt)))

    def _try_refresh(self, hint: Optional[str] = None) -> None:
        """Refresh, absorbing total unreachability: mid-failover the
        fleet can briefly answer nothing at all, and the op retry
        loop — not this probe — owns the failure budget."""
        try:
            self.refresh(hint)
        except ConnectionError:
            pass

    def refresh(self, hint: Optional[str] = None) -> RoutingTable:
        """Fetch the newest routing table from any reachable tier.
        ``hint`` (the owner address a ``moved`` reply named) is tried
        FIRST — it is the freshest routing signal available, and
        mid-topology-change it may be the only address that already
        serves the new epoch; then seeds, then every known owner."""
        candidates = list(dict.fromkeys(
            ([hint] if hint else [])
            + self._seeds + (list(self.table.owners())
                             if self.table is not None else [])))
        last: Optional[BaseException] = None
        for addr in candidates:
            try:
                reply = self._session(addr).request({"op": "route"})
            except (ConnectionError, OSError, ValueError) as e:
                self._drop_session(addr)
                last = e
                continue
            if isinstance(reply, dict) and reply.get("ok") \
                    and isinstance(reply.get("routing"), dict):
                table = RoutingTable.from_json(reply["routing"])
                self.table = RoutingTable.newest(self.table, table)
                return self.table
        raise ConnectionError(
            f"no tier answered a route request: {last!r}")

    # --- keyspace ops ---

    def _next_attempt(self, attempt: int, epoch_seen: int) -> int:
        """Redirect-budget accounting for one retry: a refresh that
        actually ADVANCED the table epoch means the fleet's topology
        moved under this op — the attempt bought progress, not a
        spin, so the budget resets. Back-to-back topology changes (a
        split chased by a merge chased by a failover) therefore can
        never burn the whole budget on one churn burst, while the
        budget still bounds consecutive attempts that learn nothing
        (resetting on ANY refresh would loop forever against a
        permanently stale table)."""
        if self.table is not None and self.table.epoch > epoch_seen:
            self.redirect_resets += 1
            return 0
        return attempt + 1

    def _keyspace(self, msg: dict, slot: int,
                  want_field: str = "ok") -> dict:
        if self.table is None:
            self.refresh()
        attempt = 0
        while attempt < self._max_redirects:
            epoch_seen = -1 if self.table is None \
                else self.table.epoch
            owner = self.table.owner_of(slot)
            msg["epoch"] = self.table.epoch
            try:
                reply = self._session(owner).request(msg)
                if reply is None:
                    # EOF without a reply frame: an abrupt kill (RST
                    # or half-close) reads as None, not an exception.
                    raise ConnectionError(f"{owner} closed mid-op")
            except (ConnectionError, OSError, ValueError):
                self._drop_session(owner)
                self._backoff(attempt)
                self._try_refresh()
                attempt = self._next_attempt(attempt, epoch_seen)
                continue
            if isinstance(reply, dict) and reply.get("ok"):
                return reply
            code = reply.get("code") if isinstance(reply, dict) \
                else None
            if code == "moved":
                # The typed redirect: adopt the owner's epoch view
                # and replay. (PeerConnection maps this same reply to
                # SyncRedirectError; here we stay dict-level.)
                self.moved_redirects += 1
                self._try_refresh()
                attempt = self._next_attempt(attempt, epoch_seen)
                continue
            if code == "busy":
                # Routing flux, a write-concern barrier miss, or a
                # FENCED ex-primary serving out its lease: back off,
                # then refetch the table — a fence usually means the
                # epoch has moved (or is about to) under us.
                self.busy_retries += 1
                self._backoff(attempt)
                self._try_refresh()
                attempt = self._next_attempt(attempt, epoch_seen)
                continue
            raise ValueError(f"op {msg.get('op')!r} rejected: "
                             f"{reply!r}")
        raise ConnectionError(
            f"op {msg.get('op')!r} on slot {slot} still redirecting "
            f"after {self._max_redirects} attempts")

    _JSON_OP = {BINOP_PUT: "put", BINOP_DELETE: "delete",
                BINOP_GET: "get"}

    def _json_msg(self, opcode: int, slot: int, value: int) -> dict:
        msg = {"op": self._JSON_OP[opcode], "slot": int(slot)}
        if opcode == BINOP_PUT:
            msg["value"] = int(value)
        return msg

    def _op(self, opcode: int, slot: int, value: int = 0) -> dict:
        """One keyspace op, preferring the binary op lane
        (docs/WIRE.md) when the owner's session negotiated the
        ``binop`` cap: fixed columnar frames instead of per-op JSON.
        Same retry protocol as `_keyspace` — MOVED replies carry the
        owner address + epoch in the detail tail, which feeds the
        refresh as a routing hint; BUSY backs off and refreshes. A
        malformed binary reply demotes that session to framed JSON
        permanently (sticky fallback) and replays the op there; a
        session that never negotiated the cap routes through
        `_keyspace` untouched."""
        if self.table is None:
            self.refresh()
        attempt = 0
        while attempt < self._max_redirects:
            epoch_seen = -1 if self.table is None \
                else self.table.epoch
            owner = self.table.owner_of(slot)
            try:
                up = self._session(owner)
                if "binop" not in up.caps \
                        or getattr(up, "json_ops", False):
                    return self._keyspace(
                        self._json_msg(opcode, slot, value), slot)
                self.binop_rounds += 1
                try:
                    status, values, details = binop_round(
                        up.sock, [opcode], [int(slot)], [int(value)],
                        epoch=self.table.epoch, tally=up.tally,
                        codec=up.codec)
                except ValueError:
                    # A well-framed but undecodable binary reply: the
                    # peer's binop half is broken, its JSON half is
                    # not — demote the session for good and replay.
                    up.json_ops = True
                    self.binop_fallbacks += 1
                    return self._keyspace(
                        self._json_msg(opcode, slot, value), slot)
            except (ConnectionError, OSError):
                self._drop_session(owner)
                self._backoff(attempt)
                self._try_refresh()
                attempt = self._next_attempt(attempt, epoch_seen)
                continue
            st = int(status[0])
            if st == BINOP_ST_OK:
                return {"ok": True,
                        "value": (int(values[0])
                                  if values is not None else None)}
            if st == BINOP_ST_OK_NULL:
                return {"ok": True, "value": None}
            det = next((d for d in details
                        if isinstance(d, dict) and d.get("i") == 0),
                       None)
            if det is None:
                det = next((d for d in details
                            if isinstance(d, dict) and "i" not in d),
                           {})
            if st == BINOP_ST_MOVED:
                self.moved_redirects += 1
                self._try_refresh(det.get("owner"))
                attempt = self._next_attempt(attempt, epoch_seen)
                continue
            if st == BINOP_ST_BUSY:
                self.busy_retries += 1
                self._backoff(attempt)
                self._try_refresh()
                attempt = self._next_attempt(attempt, epoch_seen)
                continue
            raise ValueError(
                f"op {self._JSON_OP[opcode]!r} rejected: {det!r}")
        raise ConnectionError(
            f"op {self._JSON_OP[opcode]!r} on slot {slot} still "
            f"redirecting after {self._max_redirects} attempts")

    def put(self, slot: int, value: int) -> None:
        self._op(BINOP_PUT, slot, int(value))

    def delete(self, slot: int) -> None:
        self._op(BINOP_DELETE, slot)

    def get(self, slot: int):
        return self._op(BINOP_GET, slot).get("value")

    # --- watch ---

    def watch(self, addr: str, slots=None) -> "_WatchSession":
        """Subscribe on one tier; returns a dedicated event session
        (`next_event` decodes one pushed pack into [(slot, value),
        ...] with typed lanes decoded — docs/FEDERATION.md)."""
        return _WatchSession(addr, slots, timeout=self._timeout)

    def close(self) -> None:
        for addr in list(self._sessions):
            self._drop_session(addr)


class _WatchSession:
    """One watch subscription riding its own connection (events are
    server-pushed; multiplexing them with request/reply frames on one
    socket would interleave streams)."""

    def __init__(self, addr: str, slots, timeout: float = 30.0):
        self._timeout = timeout
        # The server's WatchIndex routes by INTEREST but ships the
        # shared tick pack (zero-copy fan-out: one pack, N writers);
        # slot-scoped subscriptions filter here, client-side.
        self._slots = (None if slots is None
                       else [int(s) for s in slots])
        self._filter = (None if slots is None
                        else frozenset(self._slots))
        self._up: Optional[_Upstream] = None
        self.addr = addr
        self.moved_rehomes = 0
        self._subscribe(addr)

    def _subscribe(self, addr: str,
                   since: Optional[str] = None) -> None:
        """(Re)subscribe at ``addr`` with the original slot filter —
        the initial registration AND the typed-``moved`` re-home a
        partition merge pushes to live sessions. ``since`` is the
        resume mark a moved frame carries: the recipient rewinds its
        fan-out watermark to it at registration, so no commit event
        is dropped across the move."""
        up = _Upstream(addr, timeout=self._timeout)
        msg: dict = {"op": "watch"}
        if self._slots is not None:
            msg["slots"] = self._slots
        if since is not None:
            msg["since"] = str(since)
        reply = up.request(msg)
        if not (isinstance(reply, dict) and reply.get("ok")):
            up.close()
            raise ConnectionError(f"watch refused: {reply!r}")
        if self._up is not None:
            self._up.close()
        self._up = up
        self.addr = addr
        self.since = reply.get("since")

    def next_event(self, timeout: Optional[float] = None
                   ) -> List[Tuple[int, Any]]:
        """Block for one pushed event pack; returns decoded
        (slot, value) pairs (None value = tombstone; typed lanes
        decode through their registered semantics). A typed ``moved``
        frame — the partition this subscription lived on was merged
        away — transparently resubscribes at the named owner: the
        recipient's fan-out watermark was rewound to the flip
        watermark server-side, so no commit event is dropped across
        the move."""
        from .ops.packing import unpack_rows
        from .semantics import by_tag
        for _ in range(4):   # absorb back-to-back re-homes
            if timeout is not None:
                self._up.sock.settimeout(timeout)
            meta_msg = self._up.recv()
            if isinstance(meta_msg, dict) \
                    and meta_msg.get("code") == "moved":
                owner = meta_msg.get("owner")
                if not owner:
                    raise ConnectionError(
                        f"watch moved without owner: {meta_msg!r}")
                self.moved_rehomes += 1
                self._subscribe(str(owner), meta_msg.get("since"))
                continue
            if not (isinstance(meta_msg, dict)
                    and meta_msg.get("op") == "event"):
                raise ConnectionError(
                    f"watch stream broke: {meta_msg!r}")
            blob = self._up.recv_blob()
            if blob is None:
                raise ConnectionError("watch stream EOF mid-event")
            packed = unpack_rows(meta_msg["meta"], blob)
            out: List[Tuple[int, Any]] = []
            sem = packed.sem
            for i in range(packed.k):
                slot = int(packed.slots[i])
                if self._filter is not None \
                        and slot not in self._filter:
                    continue
                if packed.tomb[i]:
                    out.append((slot, None))
                    continue
                lane = int(packed.val[i])
                tag = int(sem[i]) if sem is not None else 0
                out.append((slot, lane if tag == 0
                            else by_tag(tag).decode(lane)))
            return out
        raise ConnectionError(
            "watch re-homed more than 4 times in one poll")

    def close(self) -> None:
        self._up.close()

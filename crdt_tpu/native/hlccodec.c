/* Native batch codec for the HLC wire string
 * "YYYY-MM-DDTHH:MM:SS.mmmZ-XXXX-<node>" (hlc.dart:102-104).
 *
 * The host-side wire boundary (crdt_json.dart:8-37) is a per-record
 * string codec; at 10k+ records per sync round the Python datetime
 * round trip dominates ingest. This module batch-converts the
 * CANONICAL shape only — exactly what `Hlc.__str__` emits — and
 * returns None for anything else so the Python parser keeps full
 * reference semantics (space separators, UTC offsets, odd precision).
 *
 * Pure CPython C API, no deps; built on first use by
 * crdt_tpu/native/__init__.py with the system C compiler and loaded
 * with a silent fallback to the Python path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* Howard Hinnant's civil-date algorithms (public domain), int64. */
static long long days_from_civil(long long y, int m, int d) {
    y -= m <= 2;
    long long era = (y >= 0 ? y : y - 399) / 400;
    long long yoe = y - era * 400;
    long long doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
    long long doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    return era * 146097 + doe - 719468;
}

static void civil_from_days(long long z, long long *y, int *m, int *d) {
    z += 719468;
    long long era = (z >= 0 ? z : z - 146096) / 146097;
    long long doe = z - era * 146097;
    long long yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
    long long yy = yoe + era * 400;
    long long doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
    long long mp = (5 * doy + 2) / 153;
    *d = (int)(doy - (153 * mp + 2) / 5 + 1);
    *m = (int)(mp + (mp < 10 ? 3 : -9));
    *y = yy + (*m <= 2);
}

static int digits(const char *s, int n, long long *out) {
    long long v = 0;
    for (int i = 0; i < n; i++) {
        if (s[i] < '0' || s[i] > '9') return 0;
        v = v * 10 + (s[i] - '0');
    }
    *out = v;
    return 1;
}

static int hex4(const char *s, long long *out) {
    long long v = 0;
    for (int i = 0; i < 4; i++) {
        char c = s[i];
        int d;
        if (c >= '0' && c <= '9') d = c - '0';
        else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
        else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
        else return 0;
        v = v * 16 + d;
    }
    *out = v;
    return 1;
}

static int days_in_month(long long y, int m) {
    static const int dim[] = {31, 28, 31, 30, 31, 30, 31, 31, 30, 31,
                              30, 31};
    if (m == 2 && (y % 4 == 0 && (y % 100 != 0 || y % 400 == 0)))
        return 29;
    return dim[m - 1];
}

/* "YYYY-MM-DDTHH:MM:SS.mmmZ" (24 chars) -> epoch millis. 1 on success.
 * Validates calendar ranges, not just shape — an invalid date must fall
 * through to the Python parser's ValueError, never silently normalize. */
static int parse_canonical_iso(const char *s, long long *out) {
    long long y, mo, d, h, mi, sec, ms;
    if (s[4] != '-' || s[7] != '-' || s[10] != 'T' || s[13] != ':' ||
        s[16] != ':' || s[19] != '.' || s[23] != 'Z')
        return 0;
    if (!digits(s, 4, &y) || !digits(s + 5, 2, &mo) ||
        !digits(s + 8, 2, &d) || !digits(s + 11, 2, &h) ||
        !digits(s + 14, 2, &mi) || !digits(s + 17, 2, &sec) ||
        !digits(s + 20, 3, &ms))
        return 0;
    if (mo < 1 || mo > 12 || d < 1 || d > days_in_month(y, (int)mo) ||
        h > 23 || mi > 59 || sec > 59)
        return 0;
    *out = (days_from_civil(y, (int)mo, (int)d) * 86400
            + h * 3600 + mi * 60 + sec) * 1000 + ms;
    return 1;
}

/* parse_hlc_batch(list[str]) -> (list, list, list):
 * per item (millis:int, counter:int, node:str), or (None, None, None)
 * when the item is not the canonical shape (caller falls back). */
static PyObject *parse_hlc_batch(PyObject *self, PyObject *arg) {
    if (!PyList_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a list of str");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(arg);
    PyObject *millis_l = PyList_New(n);
    PyObject *counter_l = PyList_New(n);
    PyObject *node_l = PyList_New(n);
    if (!millis_l || !counter_l || !node_l) goto fail;

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(arg, i);
        Py_ssize_t len;
        const char *s = PyUnicode_Check(item)
            ? PyUnicode_AsUTF8AndSize(item, &len) : NULL;
        long long ms, counter;
        /* 24 iso + '-' + 4 hex + '-' + at least 1 node char */
        if (!s || len < 31 || s[24] != '-' || s[29] != '-' ||
            !parse_canonical_iso(s, &ms) || !hex4(s + 25, &counter)) {
            if (s == NULL) PyErr_Clear();
            Py_INCREF(Py_None); PyList_SET_ITEM(millis_l, i, Py_None);
            Py_INCREF(Py_None); PyList_SET_ITEM(counter_l, i, Py_None);
            Py_INCREF(Py_None); PyList_SET_ITEM(node_l, i, Py_None);
            continue;
        }
        PyObject *node = PyUnicode_FromStringAndSize(s + 30, len - 30);
        PyObject *ms_o = PyLong_FromLongLong(ms);
        PyObject *c_o = PyLong_FromLongLong(counter);
        if (!node || !ms_o || !c_o) {
            Py_XDECREF(node); Py_XDECREF(ms_o); Py_XDECREF(c_o);
            goto fail;
        }
        PyList_SET_ITEM(millis_l, i, ms_o);
        PyList_SET_ITEM(counter_l, i, c_o);
        PyList_SET_ITEM(node_l, i, node);
    }
    {
        PyObject *out = PyTuple_Pack(3, millis_l, counter_l, node_l);
        Py_DECREF(millis_l); Py_DECREF(counter_l); Py_DECREF(node_l);
        return out;
    }
fail:
    Py_XDECREF(millis_l); Py_XDECREF(counter_l); Py_XDECREF(node_l);
    return NULL;
}

/* format_hlc_batch(list[int] millis, list[int] counter, list[str] node)
 * -> list[str] "<iso>-<HEX4>-<node>"; None entries where millis is out
 * of the 4-digit-year window (caller falls back). */
static PyObject *format_hlc_batch(PyObject *self, PyObject *args) {
    PyObject *millis_l, *counter_l, *node_l;
    if (!PyArg_ParseTuple(args, "O!O!O!", &PyList_Type, &millis_l,
                          &PyList_Type, &counter_l, &PyList_Type, &node_l))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(millis_l);
    if (PyList_GET_SIZE(counter_l) != n || PyList_GET_SIZE(node_l) != n) {
        PyErr_SetString(PyExc_ValueError, "length mismatch");
        return NULL;
    }
    PyObject *out = PyList_New(n);
    if (!out) return NULL;

    for (Py_ssize_t i = 0; i < n; i++) {
        long long ms = PyLong_AsLongLong(PyList_GET_ITEM(millis_l, i));
        long long counter = PyLong_AsLongLong(PyList_GET_ITEM(counter_l, i));
        if (PyErr_Occurred()) { Py_DECREF(out); return NULL; }
        PyObject *node_o = PyList_GET_ITEM(node_l, i);
        Py_ssize_t nlen;
        const char *node = PyUnicode_AsUTF8AndSize(node_o, &nlen);
        if (!node) {
            if (PyErr_ExceptionMatches(PyExc_UnicodeEncodeError)) {
                /* lone-surrogate node id: not UTF-8 encodable, but the
                 * pure-Python formatter handles it — defer the item. */
                PyErr_Clear();
                Py_INCREF(Py_None);
                PyList_SET_ITEM(out, i, Py_None);
                continue;
            }
            Py_DECREF(out);
            return NULL;
        }

        long long secs = ms >= 0 ? ms / 1000 : (ms - 999) / 1000;
        int frac = (int)(ms - secs * 1000);
        long long days = secs >= 0 ? secs / 86400 : (secs - 86399) / 86400;
        int sod = (int)(secs - days * 86400);
        long long y; int mo, d;
        civil_from_days(days, &y, &mo, &d);
        /* y < 1 (not < 0): the pure-Python _iso8601 raises for year 0,
         * so the native formatter must defer it to that fallback — the
         * two codecs stay behaviorally identical at the boundary. */
        if (y < 1 || y > 9999 || counter < 0 || counter > 0xFFFF) {
            Py_INCREF(Py_None);
            PyList_SET_ITEM(out, i, Py_None);
            continue;
        }
        char buf[64];
        int w = snprintf(buf, sizeof buf,
                         "%04lld-%02d-%02dT%02d:%02d:%02d.%03dZ-%04llX-",
                         y, mo, d, sod / 3600, (sod / 60) % 60, sod % 60,
                         frac, counter);
        PyObject *s;
        if (PyUnicode_IS_ASCII(node_o)) {
            /* ASCII node: one allocation, two memcpys (bytes == chars) */
            s = PyUnicode_New(w + nlen, 127);
            if (s) {
                memcpy(PyUnicode_DATA(s), buf, w);
                memcpy((char *)PyUnicode_DATA(s) + w, node, nlen);
            }
        } else {
            PyObject *prefix = PyUnicode_FromStringAndSize(buf, w);
            s = prefix ? PyUnicode_Concat(prefix, node_o) : NULL;
            Py_XDECREF(prefix);
        }
        if (!s) { Py_DECREF(out); return NULL; }
        PyList_SET_ITEM(out, i, s);
    }
    return out;
}

/* ================== wire JSON scanner ==================
 *
 * parse_wire(json_str) scans the full wire payload
 * `{"key":{"hlc":"...","value":V},...}` (crdt_json.dart:8-17) in one
 * pass, returning the columnar shape the vectorized backends consume
 * without materializing the intermediate dict-of-dicts `json.loads`
 * builds:
 *
 *   (keys: list[str],
 *    lt:   bytearray of native int64 — packed (millis<<16)|counter,
 *    nodes: list[str]   (for fallback items: the raw hlc string),
 *    values: list,
 *    bad:  list[int]    (indices whose hlc was not canonical-shaped —
 *                        the caller re-parses those via Hlc.parse))
 *
 * or None when the payload deviates from the expected structure in any
 * way this scanner does not model exactly (then the caller runs the
 * plain `json.loads` path, which either handles it or raises the
 * error the user would have seen anyway). Exactness rules:
 *  - duplicate keys keep the FIRST position with the LAST value, like
 *    a Python dict build;
 *  - inner members may come in any order; unknown members are parsed
 *    (validated) and discarded; a missing "value" member decodes as
 *    None (`v.get("value")`);
 *  - number grammar is validated strictly (leading zeros etc. fall
 *    back so json.loads raises); NaN/Infinity literals are accepted
 *    exactly as Python's json does;
 *  - strings with escapes are unescaped per RFC 8259; lone surrogates
 *    (which json.loads tolerates) trigger whole-payload fallback;
 *  - nested objects/arrays are span-matched and delegated to
 *    json.loads on the substring.
 */

typedef struct {
    const char *s;
    Py_ssize_t len, pos;
    int fallback;  /* set when the payload needs the Python path */
} Scan;

static PyObject *g_json_loads = NULL;

static int ensure_json_loads(void) {
    if (g_json_loads) return 1;
    PyObject *m = PyImport_ImportModule("json");
    if (!m) return 0;
    g_json_loads = PyObject_GetAttrString(m, "loads");
    Py_DECREF(m);
    return g_json_loads != NULL;
}

static void skip_ws(Scan *sc) {
    const char *s = sc->s;
    Py_ssize_t p = sc->pos, n = sc->len;
    while (p < n) {
        char c = s[p];
        if (c != ' ' && c != '\t' && c != '\n' && c != '\r') break;
        p++;
    }
    sc->pos = p;
}

/* Content span of the JSON string starting at sc->pos (which must be
 * '"'); advances past the closing quote. Returns 0 (with sc->fallback
 * set) on malformed input. */
static int string_span(Scan *sc, Py_ssize_t *start, Py_ssize_t *end,
                       int *has_escape) {
    const char *s = sc->s;
    Py_ssize_t p = sc->pos, n = sc->len;
    if (p >= n || s[p] != '"') { sc->fallback = 1; return 0; }
    p++;
    *start = p;
    *has_escape = 0;
    while (p < n) {
        unsigned char c = (unsigned char)s[p];
        if (c == '"') {
            *end = p;
            sc->pos = p + 1;
            return 1;
        }
        if (c == '\\') {
            *has_escape = 1;
            p += 2;
            continue;
        }
        if (c < 0x20) { sc->fallback = 1; return 0; }  /* json raises */
        p++;
    }
    sc->fallback = 1;
    return 0;
}

static int hexval(char c) {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return -1;
}

/* RFC 8259 unescape of a string span into a new str object. NULL with
 * fallback=1 for anything json.loads models differently (lone
 * surrogates), NULL with an exception set on allocation failure. */
static PyObject *unescape_span(const char *p, Py_ssize_t n,
                               int *fallback) {
    char *buf = (char *)PyMem_Malloc(n > 0 ? (size_t)n : 1);
    if (!buf) { PyErr_NoMemory(); return NULL; }
    Py_ssize_t o = 0, i = 0;
    while (i < n) {
        char c = p[i];
        if (c != '\\') { buf[o++] = c; i++; continue; }
        if (i + 1 >= n) goto bad;
        char e = p[i + 1];
        i += 2;
        switch (e) {
        case '"': buf[o++] = '"'; break;
        case '\\': buf[o++] = '\\'; break;
        case '/': buf[o++] = '/'; break;
        case 'b': buf[o++] = '\b'; break;
        case 'f': buf[o++] = '\f'; break;
        case 'n': buf[o++] = '\n'; break;
        case 'r': buf[o++] = '\r'; break;
        case 't': buf[o++] = '\t'; break;
        case 'u': {
            if (i + 4 > n) goto bad;
            int h0 = hexval(p[i]), h1 = hexval(p[i + 1]);
            int h2 = hexval(p[i + 2]), h3 = hexval(p[i + 3]);
            if ((h0 | h1 | h2 | h3) < 0) goto bad;
            unsigned int cp =
                (unsigned)(h0 << 12 | h1 << 8 | h2 << 4 | h3);
            i += 4;
            if (cp >= 0xD800 && cp <= 0xDBFF) {
                /* high surrogate: need a \uDC00-\uDFFF mate */
                if (i + 6 <= n && p[i] == '\\' && p[i + 1] == 'u') {
                    int g0 = hexval(p[i + 2]), g1 = hexval(p[i + 3]);
                    int g2 = hexval(p[i + 4]), g3 = hexval(p[i + 5]);
                    unsigned int lo = (g0 | g1 | g2 | g3) < 0 ? 0 :
                        (unsigned)(g0 << 12 | g1 << 8 | g2 << 4 | g3);
                    if (lo >= 0xDC00 && lo <= 0xDFFF) {
                        cp = 0x10000 + ((cp - 0xD800) << 10)
                             + (lo - 0xDC00);
                        i += 6;
                    } else goto bad;  /* lone surrogate: json tolerates */
                } else goto bad;
            } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
                goto bad;  /* unpaired low surrogate */
            }
            /* UTF-8 encode; output never exceeds input span length */
            if (cp < 0x80) buf[o++] = (char)cp;
            else if (cp < 0x800) {
                buf[o++] = (char)(0xC0 | (cp >> 6));
                buf[o++] = (char)(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
                buf[o++] = (char)(0xE0 | (cp >> 12));
                buf[o++] = (char)(0x80 | ((cp >> 6) & 0x3F));
                buf[o++] = (char)(0x80 | (cp & 0x3F));
            } else {
                buf[o++] = (char)(0xF0 | (cp >> 18));
                buf[o++] = (char)(0x80 | ((cp >> 12) & 0x3F));
                buf[o++] = (char)(0x80 | ((cp >> 6) & 0x3F));
                buf[o++] = (char)(0x80 | (cp & 0x3F));
            }
            break;
        }
        default: goto bad;  /* invalid escape: json raises */
        }
    }
    {
        PyObject *out = PyUnicode_DecodeUTF8(buf, o, NULL);
        PyMem_Free(buf);
        return out;
    }
bad:
    PyMem_Free(buf);
    *fallback = 1;
    return NULL;
}

/* Skip a complete JSON value span (used for bracket matching of nested
 * containers). String-aware; does NOT validate leaf grammar. */
static int value_span(Scan *sc, Py_ssize_t *start, Py_ssize_t *end) {
    const char *s = sc->s;
    Py_ssize_t p = sc->pos, n = sc->len;
    *start = p;
    int depth = 0;
    while (p < n) {
        char c = s[p];
        if (c == '"') {
            p++;
            while (p < n) {
                if (s[p] == '\\') { p += 2; continue; }
                if (s[p] == '"') break;
                p++;
            }
            if (p >= n) { sc->fallback = 1; return 0; }
            p++;
        } else if (c == '{' || c == '[') {
            depth++; p++;
        } else if (c == '}' || c == ']') {
            depth--; p++;
            if (depth == 0) { *end = p; sc->pos = p; return 1; }
            if (depth < 0) { sc->fallback = 1; return 0; }
        } else {
            p++;
        }
        if (depth == 0 && *start != p) {
            /* scalar value: ends at , } ] or ws */
            while (p < n) {
                char d = s[p];
                if (d == ',' || d == '}' || d == ']' || d == ' ' ||
                    d == '\t' || d == '\n' || d == '\r') break;
                p++;
            }
            *end = p; sc->pos = p; return 1;
        }
    }
    sc->fallback = 1;
    return 0;
}

/* Strict JSON number at sc->pos -> int or float object, matching
 * json.loads leaf semantics. NULL + fallback on grammar violations. */
static PyObject *parse_number(Scan *sc) {
    const char *s = sc->s;
    Py_ssize_t p = sc->pos, n = sc->len, b = p;
    int isfloat = 0;
    if (p < n && s[p] == '-') p++;
    if (p >= n) { sc->fallback = 1; return NULL; }
    if (s[p] == '0') p++;
    else if (s[p] >= '1' && s[p] <= '9') {
        while (p < n && s[p] >= '0' && s[p] <= '9') p++;
    } else { sc->fallback = 1; return NULL; }
    if (p < n && s[p] == '.') {
        isfloat = 1; p++;
        if (p >= n || s[p] < '0' || s[p] > '9') {
            sc->fallback = 1; return NULL;
        }
        while (p < n && s[p] >= '0' && s[p] <= '9') p++;
    }
    if (p < n && (s[p] == 'e' || s[p] == 'E')) {
        isfloat = 1; p++;
        if (p < n && (s[p] == '+' || s[p] == '-')) p++;
        if (p >= n || s[p] < '0' || s[p] > '9') {
            sc->fallback = 1; return NULL;
        }
        while (p < n && s[p] >= '0' && s[p] <= '9') p++;
    }
    sc->pos = p;
    if (isfloat) {
        PyObject *sub = PyUnicode_FromStringAndSize(s + b, p - b);
        if (!sub) return NULL;
        PyObject *f = PyFloat_FromString(sub);
        Py_DECREF(sub);
        return f;
    }
    if (p - b < 63) {
        char buf[64];
        memcpy(buf, s + b, p - b);
        buf[p - b] = 0;
        return PyLong_FromString(buf, NULL, 10);
    }
    {
        char *hbuf = (char *)PyMem_Malloc((size_t)(p - b) + 1);
        if (!hbuf) { PyErr_NoMemory(); return NULL; }
        memcpy(hbuf, s + b, p - b);
        hbuf[p - b] = 0;
        PyObject *v = PyLong_FromString(hbuf, NULL, 10);
        PyMem_Free(hbuf);
        return v;
    }
}

static int lit(Scan *sc, const char *word, Py_ssize_t wl) {
    if (sc->pos + wl <= sc->len &&
        memcmp(sc->s + sc->pos, word, wl) == 0) {
        sc->pos += wl;
        return 1;
    }
    return 0;
}

/* Tiny string dedup cache (shared shape with the node cache below;
 * also used for object MEMBER KEYS, which repeat across records the
 * way json.loads' memo exploits). Declared ahead of the recursive
 * value parser. */
#define NCACHE 64
typedef struct {
    const char *p;
    Py_ssize_t n;
    PyObject *obj;
} NodeEnt;

static PyObject *cached_str(NodeEnt *cache, const char *p,
                            Py_ssize_t n) {
    unsigned long long h = 1469598103934665603ULL;
    for (Py_ssize_t i = 0; i < n; i++)
        h = (h ^ (unsigned char)p[i]) * 1099511628211ULL;
    NodeEnt *e = NULL;
    for (int j = 0; j < 4; j++) {   /* 4-probe: no thrash on collisions */
        NodeEnt *c = &cache[(h + (unsigned)j) & (NCACHE - 1)];
        if (!c->obj) { if (!e) e = c; continue; }
        if (c->n == n && memcmp(c->p, p, (size_t)n) == 0) {
            Py_INCREF(c->obj);
            return c->obj;
        }
    }
    if (!e) e = &cache[h & (NCACHE - 1)];
    PyObject *s = PyUnicode_FromStringAndSize(p, n);
    if (!s) return NULL;
    Py_XDECREF(e->obj);
    e->p = p; e->n = n; e->obj = s;
    Py_INCREF(s);
    return s;
}

/* Containers nested deeper than this go to json.loads on the matched
 * span (bounded C recursion; json.loads enforces Python's own limits
 * beyond it). */
#define MAX_VALUE_DEPTH 48

/* Generic JSON value -> Python object (json.loads leaf semantics),
 * recursive for flat-ish containers. NULL + sc->fallback for anything
 * deferred; NULL + exception on real errors. */
static PyObject *parse_json_value(Scan *sc, NodeEnt *kcache,
                                  int depth) {
    const char *s = sc->s;
    Py_ssize_t n = sc->len;
    if (sc->pos >= n) { sc->fallback = 1; return NULL; }
    char c = s[sc->pos];
    if (c == '"') {
        Py_ssize_t b, e; int esc;
        if (!string_span(sc, &b, &e, &esc)) return NULL;
        if (!esc) return PyUnicode_FromStringAndSize(s + b, e - b);
        return unescape_span(s + b, e - b, &sc->fallback);
    }
    if (c == '{') {
        if (depth >= MAX_VALUE_DEPTH) {
            Py_ssize_t b, e;
            if (!value_span(sc, &b, &e)) return NULL;
            if (!ensure_json_loads()) return NULL;
            return PyObject_CallFunction(g_json_loads, "s#", s + b,
                                         (Py_ssize_t)(e - b));
        }
        sc->pos++;
        PyObject *d = PyDict_New();
        if (!d) return NULL;
        skip_ws(sc);
        if (sc->pos < n && s[sc->pos] == '}') { sc->pos++; return d; }
        for (;;) {
            skip_ws(sc);
            Py_ssize_t kb, ke; int kesc;
            if (!string_span(sc, &kb, &ke, &kesc)) goto obj_fail;
            PyObject *key = kesc
                ? unescape_span(s + kb, ke - kb, &sc->fallback)
                : (ke - kb <= 48
                   ? cached_str(kcache, s + kb, ke - kb)
                   : PyUnicode_FromStringAndSize(s + kb, ke - kb));
            if (!key) goto obj_fail;
            skip_ws(sc);
            if (sc->pos >= n || s[sc->pos] != ':') {
                Py_DECREF(key); sc->fallback = 1; goto obj_fail;
            }
            sc->pos++;
            skip_ws(sc);
            PyObject *v = parse_json_value(sc, kcache, depth + 1);
            if (!v) { Py_DECREF(key); goto obj_fail; }
            int rc = PyDict_SetItem(d, key, v);
            Py_DECREF(key); Py_DECREF(v);
            if (rc < 0) goto obj_fail;
            skip_ws(sc);
            if (sc->pos < n && s[sc->pos] == ',') { sc->pos++; continue; }
            if (sc->pos < n && s[sc->pos] == '}') { sc->pos++; return d; }
            sc->fallback = 1;
            goto obj_fail;
        }
    obj_fail:
        Py_DECREF(d);
        return NULL;
    }
    if (c == '[') {
        if (depth >= MAX_VALUE_DEPTH) {
            Py_ssize_t b, e;
            if (!value_span(sc, &b, &e)) return NULL;
            if (!ensure_json_loads()) return NULL;
            return PyObject_CallFunction(g_json_loads, "s#", s + b,
                                         (Py_ssize_t)(e - b));
        }
        sc->pos++;
        PyObject *l = PyList_New(0);
        if (!l) return NULL;
        skip_ws(sc);
        if (sc->pos < n && s[sc->pos] == ']') { sc->pos++; return l; }
        for (;;) {
            skip_ws(sc);
            PyObject *v = parse_json_value(sc, kcache, depth + 1);
            if (!v) { Py_DECREF(l); return NULL; }
            int rc = PyList_Append(l, v);
            Py_DECREF(v);
            if (rc < 0) { Py_DECREF(l); return NULL; }
            skip_ws(sc);
            if (sc->pos < n && s[sc->pos] == ',') { sc->pos++; continue; }
            if (sc->pos < n && s[sc->pos] == ']') { sc->pos++; return l; }
            sc->fallback = 1;
            Py_DECREF(l);
            return NULL;
        }
    }
    if (c == '-' || (c >= '0' && c <= '9')) {
        if (c == '-' && sc->pos + 1 < n && s[sc->pos + 1] == 'I') {
            if (lit(sc, "-Infinity", 9))
                return PyFloat_FromDouble(-Py_HUGE_VAL);
            sc->fallback = 1; return NULL;
        }
        return parse_number(sc);
    }
    if (c == 't') {
        if (lit(sc, "true", 4)) Py_RETURN_TRUE;
    } else if (c == 'f') {
        if (lit(sc, "false", 5)) Py_RETURN_FALSE;
    } else if (c == 'n') {
        if (lit(sc, "null", 4)) Py_RETURN_NONE;
    } else if (c == 'N') {
        if (lit(sc, "NaN", 3)) return PyFloat_FromDouble(Py_NAN);
    } else if (c == 'I') {
        if (lit(sc, "Infinity", 8))
            return PyFloat_FromDouble(Py_HUGE_VAL);
    }
    sc->fallback = 1;
    return NULL;
}

static PyObject *parse_wire(PyObject *self, PyObject *args) {
    PyObject *arg;
    int want_hlc = 0;
    if (!PyArg_ParseTuple(args, "O|p", &arg, &want_hlc)) return NULL;
    Py_ssize_t len;
    const char *s = PyUnicode_AsUTF8AndSize(arg, &len);
    if (!s) {
        /* e.g. raw lone surrogates in the payload str: not UTF-8
         * encodable. json.loads handles those — defer, like
         * parse_hlc_batch does. */
        PyErr_Clear();
        Py_RETURN_NONE;
    }

    Scan sc = {s, len, 0, 0};
    PyObject *keys = NULL, *nodes = NULL, *values = NULL;
    PyObject *pos_map = NULL, *result = NULL, *hlcs = NULL;
    long long *lt = NULL;
    unsigned char *badf = NULL;
    Py_ssize_t cap = 0, count = 0;
    NodeEnt cache[NCACHE];
    memset(cache, 0, sizeof cache);

    keys = PyList_New(0);
    nodes = PyList_New(0);
    values = PyList_New(0);
    pos_map = PyDict_New();
    /* want_hlc: also return each record's RAW wire hlc string (None
     * for deferred items) so string-storing backends skip the
     * re-format round trip. */
    if (want_hlc) hlcs = PyList_New(0);
    if (!keys || !nodes || !values || !pos_map ||
        (want_hlc && !hlcs)) goto done;

    skip_ws(&sc);
    if (sc.pos >= len || s[sc.pos] != '{') { sc.fallback = 1; goto done; }
    sc.pos++;
    skip_ws(&sc);
    if (sc.pos < len && s[sc.pos] == '}') {
        sc.pos++;
        goto finish;
    }

    for (;;) {
        /* ---- top-level key ---- */
        skip_ws(&sc);
        Py_ssize_t kb, ke; int kesc;
        if (!string_span(&sc, &kb, &ke, &kesc)) goto done;
        PyObject *key = kesc
            ? unescape_span(s + kb, ke - kb, &sc.fallback)
            : PyUnicode_FromStringAndSize(s + kb, ke - kb);
        if (!key) goto done;
        skip_ws(&sc);
        if (sc.pos >= len || s[sc.pos] != ':') {
            Py_DECREF(key); sc.fallback = 1; goto done;
        }
        sc.pos++;
        skip_ws(&sc);

        /* ---- inner record object ---- */
        if (sc.pos >= len || s[sc.pos] != '{') {
            Py_DECREF(key); sc.fallback = 1; goto done;
        }
        sc.pos++;
        long long item_lt = 0;
        PyObject *node_obj = NULL;   /* node id, or raw hlc when bad */
        PyObject *value_obj = NULL;
        PyObject *hlc_obj = NULL;    /* raw wire hlc str (want_hlc) */
        int bad = 0, have_hlc = 0;
        skip_ws(&sc);
        if (sc.pos < len && s[sc.pos] == '}') sc.pos++;
        else for (;;) {
            skip_ws(&sc);
            Py_ssize_t mb, me; int mesc;
            if (!string_span(&sc, &mb, &me, &mesc)) goto item_fail;
            if (mesc) { sc.fallback = 1; goto item_fail; }
            skip_ws(&sc);
            if (sc.pos >= len || s[sc.pos] != ':') {
                sc.fallback = 1; goto item_fail;
            }
            sc.pos++;
            skip_ws(&sc);
            if (me - mb == 3 && memcmp(s + mb, "hlc", 3) == 0) {
                Py_ssize_t hb, he; int hesc;
                if (sc.pos >= len || s[sc.pos] != '"') {
                    sc.fallback = 1; goto item_fail;
                }
                if (!string_span(&sc, &hb, &he, &hesc)) goto item_fail;
                Py_XDECREF(node_obj);
                node_obj = NULL;
                have_hlc = 1;
                long long ms, counter;
                Py_XDECREF(hlc_obj);
                hlc_obj = NULL;
                if (!hesc && he - hb >= 31 && s[hb + 24] == '-' &&
                    s[hb + 29] == '-' &&
                    parse_canonical_iso(s + hb, &ms) &&
                    /* (ms<<16) must fit int64: the lane packing's
                     * range. Beyond it (years > ~6429) defer to the
                     * Python path, which raises OverflowError on the
                     * int64 lane instead of silently wrapping. */
                    ms <= 0x7FFFFFFFFFFFLL && ms >= -0x800000000000LL &&
                    hex4(s + hb + 25, &counter)) {
                    bad = 0;
                    item_lt = (ms << 16) | counter;
                    node_obj = cached_str(cache, s + hb + 30,
                                           he - hb - 30);
                    if (want_hlc && node_obj) {
                        /* Certify byte-equality with str(hlc): the
                         * parser accepts lowercase counter hex, but
                         * the canonical re-derive emits %04X — only
                         * uppercase spans may skip the re-format. */
                        int canon = 1;
                        for (int ci = 25; ci < 29; ci++) {
                            char hc = s[hb + ci];
                            if (hc >= 'a' && hc <= 'f') { canon = 0;
                                                          break; }
                        }
                        if (canon) {
                            hlc_obj = PyUnicode_FromStringAndSize(
                                s + hb, he - hb);
                            if (!hlc_obj) {
                                Py_DECREF(node_obj);
                                node_obj = NULL;
                            }
                        }
                    }
                } else {
                    bad = 1;
                    item_lt = 0;
                    node_obj = hesc
                        ? unescape_span(s + hb, he - hb, &sc.fallback)
                        : PyUnicode_FromStringAndSize(s + hb, he - hb);
                }
                if (!node_obj) goto item_fail;
            } else if (me - mb == 5 &&
                       memcmp(s + mb, "value", 5) == 0) {
                PyObject *v = parse_json_value(&sc, cache, 0);
                if (!v) goto item_fail;
                Py_XDECREF(value_obj);
                value_obj = v;
            } else {
                PyObject *v = parse_json_value(&sc, cache, 0);
                if (!v) goto item_fail;
                Py_DECREF(v);
            }
            skip_ws(&sc);
            if (sc.pos < len && s[sc.pos] == ',') { sc.pos++; continue; }
            if (sc.pos < len && s[sc.pos] == '}') { sc.pos++; break; }
            sc.fallback = 1;
            goto item_fail;
        }
        if (!have_hlc) { sc.fallback = 1; goto item_fail; }
        if (!value_obj) { value_obj = Py_None; Py_INCREF(Py_None); }

        /* ---- store (duplicate keys: first position, last value) ---- */
        {
            /* SetDefault = one hash probe for both lookup and insert */
            PyObject *idx = PyLong_FromSsize_t(count);
            if (!idx) goto item_fail;
            PyObject *prev = PyDict_SetDefault(pos_map, key, idx);
            if (!prev) { Py_DECREF(idx); goto item_fail; }
            if (prev != idx) {
                Py_ssize_t i = PyLong_AsSsize_t(prev);
                Py_DECREF(idx);
                lt[i] = item_lt;
                badf[i] = (unsigned char)bad;
                if (want_hlc) {
                    PyObject *h = hlc_obj ? hlc_obj : Py_None;
                    if (!hlc_obj) Py_INCREF(Py_None);
                    if (PyList_SetItem(hlcs, i, h) < 0) {
                        Py_DECREF(key);
                        goto done;
                    }
                    hlc_obj = NULL;   /* ref stolen */
                }
                if (PyList_SetItem(nodes, i, node_obj) < 0 ||
                    PyList_SetItem(values, i, value_obj) < 0) {
                    /* refs stolen even on failure path bookkeeping */
                    Py_DECREF(key);
                    goto done;
                }
                Py_DECREF(key);
            } else {
                Py_DECREF(idx);
                if (count == cap) {
                    Py_ssize_t ncap = cap ? cap * 2 : 1024;
                    long long *nlt = (long long *)PyMem_Realloc(
                        lt, (size_t)ncap * sizeof(long long));
                    unsigned char *nb = NULL;
                    if (nlt) {
                        lt = nlt;
                        nb = (unsigned char *)PyMem_Realloc(
                            badf, (size_t)ncap);
                    }
                    if (!nlt || !nb) {
                        Py_DECREF(key); Py_DECREF(node_obj);
                        Py_DECREF(value_obj);
                        PyErr_NoMemory();
                        goto done;
                    }
                    badf = nb;
                    cap = ncap;
                }
                lt[count] = item_lt;
                badf[count] = (unsigned char)bad;
                int ok =
                    PyList_Append(keys, key) == 0 &&
                    PyList_Append(nodes, node_obj) == 0 &&
                    PyList_Append(values, value_obj) == 0 &&
                    (!want_hlc || PyList_Append(
                        hlcs, hlc_obj ? hlc_obj : Py_None) == 0);
                Py_DECREF(key);
                Py_DECREF(node_obj);
                Py_DECREF(value_obj);
                Py_XDECREF(hlc_obj);
                hlc_obj = NULL;
                if (!ok) goto done;
                count++;
            }
        }
        skip_ws(&sc);
        if (sc.pos < len && s[sc.pos] == ',') { sc.pos++; continue; }
        if (sc.pos < len && s[sc.pos] == '}') { sc.pos++; break; }
        sc.fallback = 1;
        goto done;

    item_fail:
        Py_DECREF(key);
        Py_XDECREF(node_obj);
        Py_XDECREF(value_obj);
        Py_XDECREF(hlc_obj);
        goto done;
    }

finish:
    skip_ws(&sc);
    if (sc.pos != len) { sc.fallback = 1; goto done; }
    {
        PyObject *lt_buf = PyByteArray_FromStringAndSize(
            (const char *)lt, count * (Py_ssize_t)sizeof(long long));
        PyObject *badl = PyList_New(0);
        if (!lt_buf || !badl) {
            Py_XDECREF(lt_buf); Py_XDECREF(badl);
            goto done;
        }
        for (Py_ssize_t i = 0; i < count; i++) {
            if (badf[i]) {
                PyObject *ix = PyLong_FromSsize_t(i);
                if (!ix || PyList_Append(badl, ix) < 0) {
                    Py_XDECREF(ix); Py_DECREF(lt_buf);
                    Py_DECREF(badl);
                    goto done;
                }
                Py_DECREF(ix);
            }
        }
        result = want_hlc
            ? PyTuple_Pack(6, keys, lt_buf, nodes, values, badl, hlcs)
            : PyTuple_Pack(5, keys, lt_buf, nodes, values, badl);
        Py_DECREF(lt_buf);
        Py_DECREF(badl);
    }

done:
    for (int i = 0; i < NCACHE; i++) Py_XDECREF(cache[i].obj);
    PyMem_Free(lt);
    PyMem_Free(badf);
    Py_XDECREF(keys); Py_XDECREF(nodes); Py_XDECREF(values);
    Py_XDECREF(pos_map); Py_XDECREF(hlcs);
    if (result) return result;
    if (sc.fallback && !PyErr_Occurred()) Py_RETURN_NONE;
    return NULL;
}

/* parse_wire_dense(json_str) — the DenseCrdt-targeted scan of the
 * canonical int-key wire payload: besides parse_wire's one-pass
 * structure it skips EVERY per-record Python object (no key strings,
 * no value ints, no node list), emitting raw columnar buffers:
 *
 *   (slots: bytearray int32   — strictly ascending int keys,
 *    lt:    bytearray int64   — packed (millis<<16)|counter,
 *    node_idx: bytearray int32 — index into uniq_nodes,
 *    uniq_nodes: list[str]    — first-seen order, deduped,
 *    values: bytearray int64  — 0 for tombstones,
 *    tomb:  bytearray uint8,
 *    vmin: int, vmax: int)    — value range (0, 0 when all tombs)
 *
 * or None to defer to the generic path. Beyond parse_wire's fallback
 * rules it defers when: a key is not a canonical non-negative int
 * literal fitting int32, or keys are not strictly ascending (so
 * duplicate-key collapse never arises — every producer in this
 * codebase exports slot-ordered); an hlc is non-canonical; a value is
 * not an int64-range integer literal or null (floats, bools, strings,
 * containers all defer — the generic path then raises the documented
 * TypeError or handles them); more than DENSE_MAX_NODES distinct node
 * ids appear. Deferring is always semantics-preserving: the generic
 * path computes the identical result, slower. */

#define DENSE_MAX_NODES 4096
#define DENSE_NTAB 8192   /* open-address table, 2x max uniques */

typedef struct {
    const char *p;
    Py_ssize_t n;
    int idx;              /* index into uniq list; -1 = empty */
} DenseNodeEnt;

static PyObject *parse_wire_dense(PyObject *self, PyObject *arg) {
    Py_ssize_t len;
    const char *s = PyUnicode_AsUTF8AndSize(arg, &len);
    if (!s) { PyErr_Clear(); Py_RETURN_NONE; }

    Scan sc = {s, len, 0, 0};
    PyObject *uniq = NULL, *result = NULL;
    int *slots = NULL;
    long long *lt = NULL, *vals = NULL;
    int *nidx = NULL;
    unsigned char *tomb = NULL;
    DenseNodeEnt *ntab = NULL;
    Py_ssize_t cap = 0, count = 0;
    long long vmin = 0, vmax = 0;
    int have_val_range = 0;
    long long last_slot = -1;
    NodeEnt kcache[NCACHE];       /* for discarded unknown members */
    memset(kcache, 0, sizeof kcache);

    uniq = PyList_New(0);
    ntab = (DenseNodeEnt *)PyMem_Malloc(
        DENSE_NTAB * sizeof(DenseNodeEnt));
    if (!uniq || !ntab) { PyErr_NoMemory(); goto done; }
    for (int i = 0; i < DENSE_NTAB; i++) ntab[i].idx = -1;

    skip_ws(&sc);
    if (sc.pos >= len || s[sc.pos] != '{') { sc.fallback = 1; goto done; }
    sc.pos++;
    skip_ws(&sc);
    if (sc.pos < len && s[sc.pos] == '}') {
        sc.pos++;
        goto finish;
    }

    for (;;) {
        /* ---- top-level key: canonical int literal, ascending ---- */
        skip_ws(&sc);
        Py_ssize_t kb, ke; int kesc;
        if (!string_span(&sc, &kb, &ke, &kesc)) goto done;
        if (kesc || ke == kb || ke - kb > 10) { sc.fallback = 1; goto done; }
        long long slot = 0;
        {
            /* digits only, no leading zeros (except "0" itself) —
             * anything else defers so int(key) semantics stay with
             * the generic path */
            if (s[kb] == '0' && ke - kb > 1) { sc.fallback = 1; goto done; }
            for (Py_ssize_t i = kb; i < ke; i++) {
                char c = s[i];
                if (c < '0' || c > '9') { sc.fallback = 1; goto done; }
                slot = slot * 10 + (c - '0');
            }
            if (slot > 0x7FFFFFFFLL || slot <= last_slot) {
                sc.fallback = 1; goto done;
            }
            last_slot = slot;
        }
        skip_ws(&sc);
        if (sc.pos >= len || s[sc.pos] != ':') { sc.fallback = 1; goto done; }
        sc.pos++;
        skip_ws(&sc);

        /* ---- inner record object ---- */
        if (sc.pos >= len || s[sc.pos] != '{') { sc.fallback = 1; goto done; }
        sc.pos++;
        long long item_lt = 0, item_val = 0;
        int item_node = -1, item_tomb = 1, have_hlc = 0, have_value = 0;
        skip_ws(&sc);
        if (sc.pos < len && s[sc.pos] == '}') sc.pos++;
        else for (;;) {
            skip_ws(&sc);
            Py_ssize_t mb, me; int mesc;
            if (!string_span(&sc, &mb, &me, &mesc)) goto done;
            if (mesc) { sc.fallback = 1; goto done; }
            skip_ws(&sc);
            if (sc.pos >= len || s[sc.pos] != ':') {
                sc.fallback = 1; goto done;
            }
            sc.pos++;
            skip_ws(&sc);
            if (me - mb == 3 && memcmp(s + mb, "hlc", 3) == 0) {
                Py_ssize_t hb, he; int hesc;
                if (sc.pos >= len || s[sc.pos] != '"') {
                    sc.fallback = 1; goto done;
                }
                if (!string_span(&sc, &hb, &he, &hesc)) goto done;
                long long ms, counter;
                if (hesc || he - hb < 31 || s[hb + 24] != '-' ||
                    s[hb + 29] != '-' ||
                    !parse_canonical_iso(s + hb, &ms) ||
                    ms > 0x7FFFFFFFFFFFLL || ms < -0x800000000000LL ||
                    !hex4(s + hb + 25, &counter)) {
                    sc.fallback = 1; goto done;  /* non-canonical hlc */
                }
                have_hlc = 1;
                item_lt = (ms << 16) | counter;
                /* node id -> uniq index (open-address, span-keyed) */
                {
                    const char *np_ = s + hb + 30;
                    Py_ssize_t nn = he - hb - 30;
                    unsigned long long h = 1469598103934665603ULL;
                    for (Py_ssize_t i = 0; i < nn; i++)
                        h = (h ^ (unsigned char)np_[i])
                            * 1099511628211ULL;
                    Py_ssize_t probe = (Py_ssize_t)(h & (DENSE_NTAB - 1));
                    item_node = -1;
                    for (;;) {
                        DenseNodeEnt *e = &ntab[probe];
                        if (e->idx < 0) {
                            Py_ssize_t u = PyList_GET_SIZE(uniq);
                            if (u >= DENSE_MAX_NODES) {
                                sc.fallback = 1; goto done;
                            }
                            PyObject *ns = PyUnicode_FromStringAndSize(
                                np_, nn);
                            if (!ns) goto done;
                            if (PyList_Append(uniq, ns) < 0) {
                                Py_DECREF(ns); goto done;
                            }
                            Py_DECREF(ns);
                            e->p = np_; e->n = nn; e->idx = (int)u;
                            item_node = (int)u;
                            break;
                        }
                        if (e->n == nn &&
                            memcmp(e->p, np_, (size_t)nn) == 0) {
                            item_node = e->idx;
                            break;
                        }
                        probe = (probe + 1) & (DENSE_NTAB - 1);
                    }
                }
            } else if (me - mb == 5 &&
                       memcmp(s + mb, "value", 5) == 0) {
                have_value = 1;
                if (sc.pos < len && s[sc.pos] == 'n') {
                    if (!lit(&sc, "null", 4)) { sc.fallback = 1; goto done; }
                    item_tomb = 1; item_val = 0;
                } else {
                    /* strict int64 literal; anything else defers */
                    Py_ssize_t p = sc.pos;
                    int neg = 0;
                    if (p < len && s[p] == '-') { neg = 1; p++; }
                    if (p >= len || s[p] < '0' || s[p] > '9') {
                        sc.fallback = 1; goto done;
                    }
                    if (s[p] == '0' && p + 1 < len &&
                        s[p + 1] >= '0' && s[p + 1] <= '9') {
                        sc.fallback = 1; goto done;
                    }
                    unsigned long long acc = 0;
                    while (p < len && s[p] >= '0' && s[p] <= '9') {
                        unsigned long long d =
                            (unsigned long long)(s[p] - '0');
                        if (acc > (0xFFFFFFFFFFFFFFFFULL - d) / 10) {
                            sc.fallback = 1; goto done;  /* overflow */
                        }
                        acc = acc * 10 + d;
                        p++;
                    }
                    if (p < len && (s[p] == '.' || s[p] == 'e' ||
                                    s[p] == 'E')) {
                        sc.fallback = 1; goto done;  /* float literal */
                    }
                    /* int64 range check (generic path raises past it) */
                    if (neg ? acc > 0x8000000000000000ULL
                            : acc > 0x7FFFFFFFFFFFFFFFULL) {
                        sc.fallback = 1; goto done;
                    }
                    item_val = neg ? (long long)(0ULL - acc)
                                   : (long long)acc;
                    item_tomb = 0;
                    sc.pos = p;
                }
            } else {
                /* unknown member: validate + discard */
                PyObject *v = parse_json_value(&sc, kcache, 0);
                if (!v) goto done;
                Py_DECREF(v);
            }
            skip_ws(&sc);
            if (sc.pos < len && s[sc.pos] == ',') { sc.pos++; continue; }
            if (sc.pos < len && s[sc.pos] == '}') { sc.pos++; break; }
            sc.fallback = 1;
            goto done;
        }
        if (!have_hlc) { sc.fallback = 1; goto done; }
        if (!have_value) { item_tomb = 1; item_val = 0; }

        if (count == cap) {
            Py_ssize_t ncap = cap ? cap * 2 : 1024;
            int *ns_ = (int *)PyMem_Realloc(
                slots, (size_t)ncap * sizeof(int));
            if (ns_) slots = ns_;
            long long *nl = ns_ ? (long long *)PyMem_Realloc(
                lt, (size_t)ncap * sizeof(long long)) : NULL;
            if (nl) lt = nl;
            long long *nv = nl ? (long long *)PyMem_Realloc(
                vals, (size_t)ncap * sizeof(long long)) : NULL;
            if (nv) vals = nv;
            int *ni = nv ? (int *)PyMem_Realloc(
                nidx, (size_t)ncap * sizeof(int)) : NULL;
            if (ni) nidx = ni;
            unsigned char *nt = ni ? (unsigned char *)PyMem_Realloc(
                tomb, (size_t)ncap) : NULL;
            if (nt) tomb = nt;
            if (!nt) { PyErr_NoMemory(); goto done; }
            cap = ncap;
        }
        slots[count] = (int)slot;
        lt[count] = item_lt;
        vals[count] = item_val;
        nidx[count] = item_node;
        tomb[count] = (unsigned char)item_tomb;
        if (!item_tomb) {
            if (!have_val_range) {
                vmin = vmax = item_val;
                have_val_range = 1;
            } else {
                if (item_val < vmin) vmin = item_val;
                if (item_val > vmax) vmax = item_val;
            }
        }
        count++;

        skip_ws(&sc);
        if (sc.pos < len && s[sc.pos] == ',') { sc.pos++; continue; }
        if (sc.pos < len && s[sc.pos] == '}') { sc.pos++; break; }
        sc.fallback = 1;
        goto done;
    }

finish:
    skip_ws(&sc);
    if (sc.pos != len) { sc.fallback = 1; goto done; }
    {
        PyObject *slot_buf = PyByteArray_FromStringAndSize(
            (const char *)slots, count * (Py_ssize_t)sizeof(int));
        PyObject *lt_buf = PyByteArray_FromStringAndSize(
            (const char *)lt, count * (Py_ssize_t)sizeof(long long));
        PyObject *nidx_buf = PyByteArray_FromStringAndSize(
            (const char *)nidx, count * (Py_ssize_t)sizeof(int));
        PyObject *val_buf = PyByteArray_FromStringAndSize(
            (const char *)vals, count * (Py_ssize_t)sizeof(long long));
        PyObject *tomb_buf = PyByteArray_FromStringAndSize(
            (const char *)tomb, count);
        PyObject *vmin_o = PyLong_FromLongLong(vmin);
        PyObject *vmax_o = PyLong_FromLongLong(vmax);
        if (slot_buf && lt_buf && nidx_buf && val_buf && tomb_buf &&
            vmin_o && vmax_o)
            result = PyTuple_Pack(8, slot_buf, lt_buf, nidx_buf, uniq,
                                  val_buf, tomb_buf, vmin_o, vmax_o);
        Py_XDECREF(slot_buf); Py_XDECREF(lt_buf); Py_XDECREF(nidx_buf);
        Py_XDECREF(val_buf); Py_XDECREF(tomb_buf);
        Py_XDECREF(vmin_o); Py_XDECREF(vmax_o);
    }

done:
    for (int i = 0; i < NCACHE; i++) Py_XDECREF(kcache[i].obj);
    PyMem_Free(slots); PyMem_Free(lt); PyMem_Free(vals);
    PyMem_Free(nidx); PyMem_Free(tomb); PyMem_Free(ntab);
    Py_XDECREF(uniq);
    if (result) return result;
    if (sc.fallback && !PyErr_Occurred()) Py_RETURN_NONE;
    return NULL;
}

/* ================== host-runtime batch helpers ==================
 *
 * The vectorized backends keep key->slot maps and payload tables as
 * Python dict/list (keys and values are arbitrary Python objects);
 * these helpers run their per-record bookkeeping loops in C. Same
 * semantics as the straightforward Python loops, minus the
 * interpreter dispatch — at 1M records the ensure-slots loop alone
 * is ~1.8 s of a 3.2 s wire merge. */

/* ordinals(node_ids: list, omap: dict) -> bytearray of int32
 * Batched ordinal lookup: out[i] = omap[node_ids[i]]. An identity
 * memo skips the dict probe for consecutive repeats (the wire
 * scanners dedup node strings, so runs share one object). KeyError
 * on a missing id, like the Python dict lookup it replaces. */
static PyObject *ordinals(PyObject *self, PyObject *args) {
    PyObject *ids, *omap;
    if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &ids,
                          &PyDict_Type, &omap)) return NULL;
    Py_ssize_t m = PyList_GET_SIZE(ids);
    PyObject *buf = PyByteArray_FromStringAndSize(
        NULL, m * (Py_ssize_t)sizeof(int32_t));
    if (!buf) return NULL;
    int32_t *out = (int32_t *)PyByteArray_AS_STRING(buf);
    PyObject *prev = NULL;
    int32_t prev_ord = 0;
    for (Py_ssize_t i = 0; i < m; i++) {
        PyObject *k = PyList_GET_ITEM(ids, i);
        if (k == prev) {
            out[i] = prev_ord;
            continue;
        }
        PyObject *v = PyDict_GetItemWithError(omap, k);
        if (!v) {
            if (!PyErr_Occurred())
                PyErr_SetObject(PyExc_KeyError, k);
            Py_DECREF(buf);
            return NULL;
        }
        long o = PyLong_AsLong(v);
        if (o == -1 && PyErr_Occurred()) {
            Py_DECREF(buf);
            return NULL;
        }
        out[i] = (int32_t)o;
        prev = k;
        prev_ord = (int32_t)o;
    }
    return buf;
}


/* ensure_slots(key_to_slot: dict, keys: list, start: int)
 * -> (bytearray of int64 slots, new_keys: list)
 * Get-or-insert each key; fresh keys take consecutive slots from
 * `start` in list order and are returned so the caller can extend its
 * slot->key / payload tables. */
static PyObject *ensure_slots(PyObject *self, PyObject *args) {
    PyObject *map, *keys;
    Py_ssize_t start;
    if (!PyArg_ParseTuple(args, "O!O!n", &PyDict_Type, &map,
                          &PyList_Type, &keys, &start))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(keys);
    PyObject *buf = PyByteArray_FromStringAndSize(
        NULL, n * (Py_ssize_t)sizeof(long long));
    PyObject *new_keys = PyList_New(0);
    if (!buf || !new_keys) goto fail;
    long long *slots = (long long *)PyByteArray_AS_STRING(buf);
    Py_ssize_t next = start;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *key = PyList_GET_ITEM(keys, i);
        PyObject *idx = PyLong_FromSsize_t(next);
        if (!idx) goto fail;
        PyObject *prev = PyDict_SetDefault(map, key, idx);
        if (!prev) { Py_DECREF(idx); goto fail; }
        if (prev == idx) {
            if (PyList_Append(new_keys, key) < 0) {
                /* the key IS in the dict but won't make new_keys, so
                 * the rollback loop below would miss it — undo the
                 * insert here (preserving the append's exception). */
                PyObject *et, *ev, *tb;
                PyErr_Fetch(&et, &ev, &tb);
                if (PyDict_DelItem(map, key) < 0) PyErr_Clear();
                PyErr_Restore(et, ev, tb);
                Py_DECREF(idx);
                goto fail;
            }
            slots[i] = (long long)next;
            next++;
        } else {
            slots[i] = PyLong_AsLongLong(prev);
            if (slots[i] == -1 && PyErr_Occurred()) {
                Py_DECREF(idx); goto fail;
            }
        }
        Py_DECREF(idx);
    }
    {
        PyObject *out = PyTuple_Pack(2, buf, new_keys);
        Py_DECREF(buf); Py_DECREF(new_keys);
        return out;
    }
fail:
    /* Exception safety: the caller extends its slot->key/payload
     * tables only on success, so any keys this batch already inserted
     * into the shared dict must be rolled back — otherwise the next
     * batch re-issues their slot numbers and two keys silently share
     * one lane slot. */
    if (new_keys) {
        PyObject *etype, *eval, *etb;
        PyErr_Fetch(&etype, &eval, &etb);
        for (Py_ssize_t i = 0; i < PyList_GET_SIZE(new_keys); i++) {
            if (PyDict_DelItem(map, PyList_GET_ITEM(new_keys, i)) < 0)
                PyErr_Clear();
        }
        PyErr_Restore(etype, eval, etb);
    }
    Py_XDECREF(buf); Py_XDECREF(new_keys);
    return NULL;
}

/* none_mask(values: list) -> bytearray of uint8 (1 where item is None)
 * — the tombstone lane build (value == null, record.dart:17). */
static PyObject *none_mask(PyObject *self, PyObject *arg) {
    if (!PyList_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a list");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(arg);
    PyObject *buf = PyByteArray_FromStringAndSize(NULL, n);
    if (!buf) return NULL;
    char *m = PyByteArray_AS_STRING(buf);
    for (Py_ssize_t i = 0; i < n; i++)
        m[i] = PyList_GET_ITEM(arg, i) == Py_None;
    return buf;
}

/* 8-byte signed-integer buffer check: format 'q'/'l' (64-bit
 * platforms), optionally '@'-prefixed (native order/size). */
static int wire_is_i64_buffer(const Py_buffer *b) {
    const char *f = b->format;
    if (b->itemsize != (Py_ssize_t)sizeof(long long) ||
        b->len % (Py_ssize_t)sizeof(long long))
        return 0;
    if (!f) return 0;
    if (*f == '@') f++;
    return (f[0] == 'q' || f[0] == 'l') && f[1] == '\0';
}

/* scatter_payload(payload: list, slots: int64 buffer,
 *                 winners: int64 buffer, values: list) -> None
 * payload[slots[w]] = values[w] for each winner index w. */
static PyObject *scatter_payload(PyObject *self, PyObject *args) {
    PyObject *payload, *slots_o, *win_o, *values;
    if (!PyArg_ParseTuple(args, "O!OOO!", &PyList_Type, &payload,
                          &slots_o, &win_o, &PyList_Type, &values))
        return NULL;
    Py_buffer slots_b, win_b;
    if (PyObject_GetBuffer(slots_o, &slots_b,
                           PyBUF_CONTIG_RO | PyBUF_FORMAT) < 0)
        return NULL;
    if (PyObject_GetBuffer(win_o, &win_b,
                           PyBUF_CONTIG_RO | PyBUF_FORMAT) < 0) {
        PyBuffer_Release(&slots_b);
        return NULL;
    }
    /* The casts below assume int64 elements; any other item type (an
     * int32 ndarray, a float64 ndarray — same width, different bits)
     * would silently misindex the payload list instead of erroring. */
    if (!wire_is_i64_buffer(&slots_b) || !wire_is_i64_buffer(&win_b)) {
        PyBuffer_Release(&slots_b); PyBuffer_Release(&win_b);
        PyErr_SetString(PyExc_TypeError,
                        "scatter_payload needs int64 slot/winner buffers");
        return NULL;
    }
    const long long *slots = (const long long *)slots_b.buf;
    const long long *win = (const long long *)win_b.buf;
    Py_ssize_t n_slots_arr = slots_b.len / (Py_ssize_t)sizeof(long long);
    Py_ssize_t n_win = win_b.len / (Py_ssize_t)sizeof(long long);
    Py_ssize_t n_pay = PyList_GET_SIZE(payload);
    Py_ssize_t n_val = PyList_GET_SIZE(values);
    for (Py_ssize_t i = 0; i < n_win; i++) {
        long long w = win[i];
        if (w < 0 || w >= n_slots_arr || w >= n_val ||
            slots[w] < 0 || slots[w] >= n_pay) {
            PyBuffer_Release(&slots_b); PyBuffer_Release(&win_b);
            PyErr_SetString(PyExc_IndexError,
                            "scatter_payload index out of range");
            return NULL;
        }
        PyObject *v = PyList_GET_ITEM(values, w);
        Py_INCREF(v);
        PyObject *old = PyList_GET_ITEM(payload, slots[w]);
        PyList_SET_ITEM(payload, slots[w], v);
        Py_XDECREF(old);
    }
    PyBuffer_Release(&slots_b); PyBuffer_Release(&win_b);
    Py_RETURN_NONE;
}

/* ================== wire JSON assembler ==================
 *
 * format_wire(keys, hlcs, values, dumps) -> str | None
 * Assembles `{"key":{"hlc":"...","value":V},...}` from parallel lists
 * in one pass, byte-identical to
 *   json.dumps(obj, separators=(",",":"), ensure_ascii=False, ...)
 * over the dict the Python paths would build. Keys are str (already
 * stringified by the caller) or int (dense slot exports); hlc strings
 * come from format_hlc_batch; scalar values (None/bool/int/float/str)
 * serialize natively, anything else goes through the `dumps` callable
 * (so custom to_json hooks keep working). Returns None only for
 * argument shapes it does not model (caller falls back). */

typedef struct {
    char *p;
    size_t len, cap;
} WBuf;

/* UTF-8 view of a str, or NULL. Lone surrogates are not UTF-8
 * encodable but json.dumps(ensure_ascii=False) still serializes
 * them — so on UnicodeEncodeError set *defer (caller returns None
 * for the whole payload and the Python path takes over), matching
 * parse_wire's precedent. Other errors propagate. */
static const char *wire_utf8(PyObject *o, Py_ssize_t *n, int *defer) {
    const char *u = PyUnicode_AsUTF8AndSize(o, n);
    if (!u && PyErr_ExceptionMatches(PyExc_UnicodeEncodeError)) {
        PyErr_Clear();
        *defer = 1;
    }
    return u;
}

static int wbuf_grow(WBuf *b, size_t need) {
    if (b->len + need <= b->cap) return 1;
    size_t ncap = b->cap ? b->cap : 4096;
    while (b->len + need > ncap) ncap *= 2;
    char *np = (char *)PyMem_Realloc(b->p, ncap);
    if (!np) { PyErr_NoMemory(); return 0; }
    b->p = np; b->cap = ncap;
    return 1;
}

static int wbuf_put(WBuf *b, const char *s, size_t n) {
    if (!wbuf_grow(b, n)) return 0;
    memcpy(b->p + b->len, s, n);
    b->len += n;
    return 1;
}

/* JSON string-escape (ensure_ascii=False rules: escape ", backslash,
 * and control chars — \b \t \n \f \r short forms, \u00XX otherwise;
 * non-ASCII passes through as raw UTF-8). */
static int wbuf_put_escaped(WBuf *b, const char *s, Py_ssize_t n) {
    if (!wbuf_grow(b, (size_t)n + 2)) return 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        unsigned char c = (unsigned char)s[i];
        if (c == '"' || c == '\\') {
            char e[2] = {'\\', (char)c};
            if (!wbuf_put(b, e, 2)) return 0;
        } else if (c >= 0x20) {
            if (!wbuf_grow(b, 1)) return 0;
            b->p[b->len++] = (char)c;
        } else {
            char e[8];
            int w;
            switch (c) {
            case '\b': memcpy(e, "\\b", 2); w = 2; break;
            case '\t': memcpy(e, "\\t", 2); w = 2; break;
            case '\n': memcpy(e, "\\n", 2); w = 2; break;
            case '\f': memcpy(e, "\\f", 2); w = 2; break;
            case '\r': memcpy(e, "\\r", 2); w = 2; break;
            default:
                w = snprintf(e, sizeof e, "\\u%04x", c);
            }
            if (!wbuf_put(b, e, (size_t)w)) return 0;
        }
    }
    return 1;
}

/* One JSON value; 1 on success, 0 on error, -1 when the caller must
 * use the dumps fallback for this value. */
static int wbuf_put_scalar(WBuf *b, PyObject *v) {
    if (v == Py_None) return wbuf_put(b, "null", 4);
    if (v == Py_True) return wbuf_put(b, "true", 4);
    if (v == Py_False) return wbuf_put(b, "false", 5);
    if (PyLong_CheckExact(v)) {
        int overflow = 0;
        long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (!overflow) {
            if (x == -1 && PyErr_Occurred()) return 0;
            char d[32];
            return wbuf_put(b, d, (size_t)snprintf(d, sizeof d,
                                                   "%lld", x));
        }
        PyObject *s = PyObject_Str(v);   /* big int */
        if (!s) return 0;
        Py_ssize_t n;
        const char *u = PyUnicode_AsUTF8AndSize(s, &n);
        int ok = u && wbuf_put(b, u, (size_t)n);
        Py_DECREF(s);
        return ok;
    }
    if (PyFloat_CheckExact(v)) {
        double x = PyFloat_AS_DOUBLE(v);
        /* json.dumps default: allow_nan=True emits these literals */
        if (x != x) return wbuf_put(b, "NaN", 3);
        if (x > 1.7976931348623157e308)
            return wbuf_put(b, "Infinity", 8);
        if (x < -1.7976931348623157e308)
            return wbuf_put(b, "-Infinity", 9);
        char *r = PyOS_double_to_string(x, 'r', 0, Py_DTSF_ADD_DOT_0,
                                        NULL);
        if (!r) return 0;
        int ok = wbuf_put(b, r, strlen(r));
        PyMem_Free(r);
        return ok;
    }
    if (PyUnicode_CheckExact(v)) {
        Py_ssize_t n;
        int defer = 0;
        const char *u = wire_utf8(v, &n, &defer);
        if (!u) return defer ? -2 : 0;
        return (wbuf_put(b, "\"", 1) && wbuf_put_escaped(b, u, n)
                && wbuf_put(b, "\"", 1));
    }
    return -1;   /* container / custom object: dumps fallback */
}

/* dumps() a subtree via the Python callable and splice the text in.
 * Returns 1 ok, 0 error, -2 defer (lone-surrogate output). */
static int wbuf_splice_dumps(WBuf *b, PyObject *v, PyObject *dumps) {
    PyObject *s = PyObject_CallFunctionObjArgs(dumps, v, NULL);
    if (!s) return 0;
    Py_ssize_t n;
    int defer = 0;
    const char *u = PyUnicode_CheckExact(s) ? wire_utf8(s, &n, &defer)
                                            : NULL;
    int ok = u && wbuf_put(b, u, (size_t)n);
    Py_DECREF(s);
    if (!ok) return defer ? -2 : 0;
    return 1;
}

/* Recursive compact JSON writer: scalars via wbuf_put_scalar, exact
 * dict/list/tuple walked natively with compact separators (the
 * `compact_dumps` wire style, ensure_ascii=False); anything else —
 * custom objects, str/int subclasses, dict keys that are not exact
 * str, nesting past the cap — is serialized by the `dumps` callable
 * and spliced in (partial native output is truncated first, so the
 * splice never duplicates bytes). Returns 1 ok, 0 error, -2 defer
 * (lone surrogate: the caller runs its whole-payload fallback). */
#define WIRE_MAX_DEPTH 64
static int wbuf_put_json(WBuf *b, PyObject *v, PyObject *dumps,
                         int depth) {
    int rc = wbuf_put_scalar(b, v);
    if (rc >= 0 || rc == -2) return rc == -2 ? -2 : rc;
    size_t start = b->len;
    if (depth < WIRE_MAX_DEPTH && PyDict_CheckExact(v)) {
        if (!wbuf_put(b, "{", 1)) return 0;
        Py_ssize_t pos = 0, i = 0;
        PyObject *k, *val;
        while (PyDict_Next(v, &pos, &k, &val)) {
            if (!PyUnicode_CheckExact(k)) {
                b->len = start;   /* non-str key: dumps whole dict */
                return wbuf_splice_dumps(b, v, dumps);
            }
            if (i++ && !wbuf_put(b, ",", 1)) return 0;
            Py_ssize_t kn;
            int kdefer = 0;
            const char *ku = wire_utf8(k, &kn, &kdefer);
            if (!ku) return kdefer ? -2 : 0;
            if (!wbuf_put(b, "\"", 1) ||
                !wbuf_put_escaped(b, ku, kn) ||
                !wbuf_put(b, "\":", 2)) return 0;
            int r = wbuf_put_json(b, val, dumps, depth + 1);
            if (r != 1) return r;
        }
        return wbuf_put(b, "}", 1);
    }
    if (depth < WIRE_MAX_DEPTH &&
        (PyList_CheckExact(v) || PyTuple_CheckExact(v))) {
        if (!wbuf_put(b, "[", 1)) return 0;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(v);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (i && !wbuf_put(b, ",", 1)) return 0;
            int r = wbuf_put_json(b, PySequence_Fast_GET_ITEM(v, i),
                                  dumps, depth + 1);
            if (r != 1) return r;
        }
        return wbuf_put(b, "]", 1);
    }
    return wbuf_splice_dumps(b, v, dumps);
}

static PyObject *format_wire(PyObject *self, PyObject *args) {
    PyObject *keys, *hlcs, *values, *dumps;
    if (!PyArg_ParseTuple(args, "O!O!O!O", &PyList_Type, &keys,
                          &PyList_Type, &hlcs, &PyList_Type, &values,
                          &dumps))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(keys);
    if (PyList_GET_SIZE(hlcs) != n || PyList_GET_SIZE(values) != n) {
        PyErr_SetString(PyExc_ValueError, "length mismatch");
        return NULL;
    }
    WBuf b = {NULL, 0, 0};
    if (!wbuf_put(&b, "{", 1)) goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (i && !wbuf_put(&b, ",", 1)) goto fail;
        PyObject *key = PyList_GET_ITEM(keys, i);
        if (PyUnicode_CheckExact(key)) {
            Py_ssize_t kn;
            int kdefer = 0;
            const char *ku = wire_utf8(key, &kn, &kdefer);
            if (!ku) {
                if (kdefer) { PyMem_Free(b.p); Py_RETURN_NONE; }
                goto fail;
            }
            if (!wbuf_put(&b, "\"", 1) ||
                !wbuf_put_escaped(&b, ku, kn) ||
                !wbuf_put(&b, "\"", 1)) goto fail;
        } else if (PyLong_CheckExact(key)) {
            int overflow = 0;
            long long x = PyLong_AsLongLongAndOverflow(key, &overflow);
            if (overflow || (x == -1 && PyErr_Occurred())) {
                PyErr_Clear();
                PyMem_Free(b.p);
                Py_RETURN_NONE;   /* exotic key: caller falls back */
            }
            char d[36];
            int w = snprintf(d, sizeof d, "\"%lld\"", x);
            if (!wbuf_put(&b, d, (size_t)w)) goto fail;
        } else {
            PyMem_Free(b.p);
            Py_RETURN_NONE;       /* caller stringifies, then retries */
        }
        if (!wbuf_put(&b, ":{\"hlc\":\"", 9)) goto fail;
        PyObject *h = PyList_GET_ITEM(hlcs, i);
        Py_ssize_t hn;
        int hdefer = 0;
        const char *hu = PyUnicode_CheckExact(h)
            ? wire_utf8(h, &hn, &hdefer) : NULL;
        if (!hu) {
            if (hdefer) { PyMem_Free(b.p); Py_RETURN_NONE; }
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_TypeError, "hlc must be str");
            goto fail;
        }
        if (!wbuf_put_escaped(&b, hu, hn)) goto fail;
        if (!wbuf_put(&b, "\",\"value\":", 10)) goto fail;
        PyObject *v = PyList_GET_ITEM(values, i);
        int rc = wbuf_put_json(&b, v, dumps, 0);
        if (rc == 0) goto fail;
        if (rc == -2) { PyMem_Free(b.p); Py_RETURN_NONE; }
        if (!wbuf_put(&b, "}", 1)) goto fail;
    }
    if (!wbuf_put(&b, "}", 1)) goto fail;
    {
        PyObject *out = PyUnicode_DecodeUTF8(b.p, (Py_ssize_t)b.len,
                                             NULL);
        PyMem_Free(b.p);
        return out;
    }
fail:
    PyMem_Free(b.p);
    return NULL;
}

/* dump_values(values: list, dumps) -> list[str]
 * Batch JSON text for a value column: each value serialized compact
 * (the wbuf_put_json writer); items the native writer can't emit
 * as UTF-8 (lone surrogates) fall back to the `dumps` callable per
 * item — pass a json.dumps that can represent them (ensure_ascii).
 * Scalar/container coverage matches format_wire's value field. */
static PyObject *dump_values(PyObject *self, PyObject *args) {
    PyObject *values, *dumps;
    if (!PyArg_ParseTuple(args, "O!O", &PyList_Type, &values, &dumps))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(values);
    PyObject *out = PyList_New(n);
    if (!out) return NULL;
    WBuf b = {NULL, 0, 0};
    for (Py_ssize_t i = 0; i < n; i++) {
        b.len = 0;
        PyObject *v = PyList_GET_ITEM(values, i);
        int rc = wbuf_put_json(&b, v, dumps, 0);
        PyObject *s;
        if (rc == 1) {
            s = PyUnicode_DecodeUTF8(b.p, (Py_ssize_t)b.len, NULL);
        } else if (rc == -2) {
            PyErr_Clear();
            s = PyObject_CallFunctionObjArgs(dumps, v, NULL);
        } else {
            s = NULL;
        }
        if (!s) { PyMem_Free(b.p); Py_DECREF(out); return NULL; }
        PyList_SET_ITEM(out, i, s);
    }
    PyMem_Free(b.p);
    return out;
}

/* records_to_columns(records: list[Record], with_modified: bool)
 * -> (lt: bytearray int64, nodes: list, values: list
 *     [, mod_lt: bytearray int64, mod_nodes: list])
 * Batch attribute extraction for the record-dict API surface: each
 * Record carries (hlc, value, modified) with hlc = (millis, counter,
 * node_id). lt packs (millis << 16) | counter; millis outside the
 * int64 lane range raises OverflowError (the columnar contract —
 * matching np.fromiter over .logical_time). */
static PyObject *s_hlc, *s_millis, *s_counter, *s_node_id,
                *s_value, *s_modified;

static int ensure_attr_names(void) {
    if (s_hlc) return 1;
    s_hlc = PyUnicode_InternFromString("hlc");
    s_millis = PyUnicode_InternFromString("millis");
    s_counter = PyUnicode_InternFromString("counter");
    s_node_id = PyUnicode_InternFromString("node_id");
    s_value = PyUnicode_InternFromString("value");
    s_modified = PyUnicode_InternFromString("modified");
    return (s_hlc && s_millis && s_counter && s_node_id && s_value
            && s_modified);
}

static PyObject *records_to_columns(PyObject *self, PyObject *args) {
    PyObject *records;
    if (!ensure_attr_names()) return NULL;
    int with_modified = 0;
    if (!PyArg_ParseTuple(args, "O!p", &PyList_Type, &records,
                          &with_modified))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(records);
    PyObject *lt_buf = PyByteArray_FromStringAndSize(
        NULL, n * (Py_ssize_t)sizeof(long long));
    PyObject *nodes = PyList_New(n);
    PyObject *values = PyList_New(n);
    PyObject *mlt_buf = NULL, *mnodes = NULL, *result = NULL;
    if (with_modified) {
        mlt_buf = PyByteArray_FromStringAndSize(
            NULL, n * (Py_ssize_t)sizeof(long long));
        mnodes = PyList_New(n);
        if (!mlt_buf || !mnodes) goto done;
    }
    if (!lt_buf || !nodes || !values) goto done;
    long long *lt = (long long *)PyByteArray_AS_STRING(lt_buf);
    long long *mlt = with_modified
        ? (long long *)PyByteArray_AS_STRING(mlt_buf) : NULL;

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *r = PyList_GET_ITEM(records, i);
        PyObject *hlc = PyObject_GetAttr(r, s_hlc);
        if (!hlc) goto done;
        PyObject *ms_o = PyObject_GetAttr(hlc, s_millis);
        PyObject *c_o = ms_o ? PyObject_GetAttr(hlc, s_counter)
                             : NULL;
        PyObject *node = c_o ? PyObject_GetAttr(hlc, s_node_id)
                             : NULL;
        Py_DECREF(hlc);
        if (!node) {
            Py_XDECREF(ms_o); Py_XDECREF(c_o);
            goto done;
        }
        long long ms = PyLong_AsLongLong(ms_o);
        Py_DECREF(ms_o);
        if (ms == -1 && PyErr_Occurred()) {   /* no API call with an
                                               * exception pending */
            Py_DECREF(c_o); Py_DECREF(node); goto done;
        }
        long long counter = PyLong_AsLongLong(c_o);
        Py_DECREF(c_o);
        if (counter == -1 && PyErr_Occurred()) {
            Py_DECREF(node); goto done;
        }
        if (ms > 0x7FFFFFFFFFFFLL || ms < -0x800000000000LL) {
            Py_DECREF(node);
            PyErr_SetString(PyExc_OverflowError,
                            "HLC millis outside the int64 lane range "
                            "(|millis| >= 2^47)");
            goto done;
        }
        /* + not |: matches .logical_time exactly even for
         * out-of-range counters on hand-built Hlcs */
        lt[i] = (ms << 16) + counter;
        PyList_SET_ITEM(nodes, i, node);
        PyObject *v = PyObject_GetAttr(r, s_value);
        if (!v) goto done;
        PyList_SET_ITEM(values, i, v);
        if (with_modified) {
            PyObject *mod = PyObject_GetAttr(r, s_modified);
            if (!mod) goto done;
            PyObject *mms_o = PyObject_GetAttr(mod, s_millis);
            PyObject *mc_o = mms_o
                ? PyObject_GetAttr(mod, s_counter) : NULL;
            PyObject *mnode = mc_o
                ? PyObject_GetAttr(mod, s_node_id) : NULL;
            Py_DECREF(mod);
            if (!mnode) {
                Py_XDECREF(mms_o); Py_XDECREF(mc_o);
                goto done;
            }
            long long mms = PyLong_AsLongLong(mms_o);
            Py_DECREF(mms_o);
            if (mms == -1 && PyErr_Occurred()) {
                Py_DECREF(mc_o); Py_DECREF(mnode); goto done;
            }
            long long mc = PyLong_AsLongLong(mc_o);
            Py_DECREF(mc_o);
            if (mc == -1 && PyErr_Occurred()) {
                Py_DECREF(mnode); goto done;
            }
            if (mms > 0x7FFFFFFFFFFFLL || mms < -0x800000000000LL) {
                Py_DECREF(mnode);
                PyErr_SetString(PyExc_OverflowError,
                                "HLC millis outside the int64 lane "
                                "range (|millis| >= 2^47)");
                goto done;
            }
            mlt[i] = (mms << 16) + mc;
            PyList_SET_ITEM(mnodes, i, mnode);
        }
    }
    result = with_modified
        ? PyTuple_Pack(5, lt_buf, nodes, values, mlt_buf, mnodes)
        : PyTuple_Pack(3, lt_buf, nodes, values);
done:
    Py_XDECREF(lt_buf); Py_XDECREF(nodes); Py_XDECREF(values);
    Py_XDECREF(mlt_buf); Py_XDECREF(mnodes);
    return result;
}

static PyMethodDef methods[] = {
    {"parse_hlc_batch", parse_hlc_batch, METH_O,
     "Batch-parse canonical HLC wire strings."},
    {"records_to_columns", records_to_columns, METH_VARARGS,
     "Batch attribute extraction from Record objects to lanes."},
    {"format_hlc_batch", format_hlc_batch, METH_VARARGS,
     "Batch-format HLC components to wire strings."},
    {"parse_wire", parse_wire, METH_VARARGS,
     "One-pass columnar scan of a wire JSON payload."},
    {"parse_wire_dense", parse_wire_dense, METH_O,
     "Dense-model scan: int keys + int values to raw buffers."},
    {"format_wire", format_wire, METH_VARARGS,
     "Assemble a wire JSON payload from parallel columns."},
    {"dump_values", dump_values, METH_VARARGS,
     "Batch compact-JSON text for a value column."},
    {"ensure_slots", ensure_slots, METH_VARARGS,
     "Batch get-or-insert of keys into a key->slot dict."},
    {"ordinals", ordinals, METH_VARARGS,
     "Batched int32 dict lookups for node ordinals."},
    {"none_mask", none_mask, METH_O,
     "uint8 mask of None entries in a list."},
    {"scatter_payload", scatter_payload, METH_VARARGS,
     "payload[slots[w]] = values[w] for winner indices."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_hlccodec",
    "Native batch codec for HLC wire strings.", -1, methods};

PyMODINIT_FUNC PyInit__hlccodec(void) { return PyModule_Create(&module); }

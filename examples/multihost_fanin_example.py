"""TRUE multi-process replica fan-in: the sharded lattice join running
across two OS processes with cross-process collectives.

Everything else in this repo demonstrates multi-chip sharding inside
one process (the virtual 8-device mesh). This example is the missing
hop: two separate processes — the multi-HOST shape, each owning half
the mesh's devices — running `ShardedDenseCrdt.merge_many` as ONE
SPMD program whose replica-axis reduction crosses the process
boundary (gloo over TCP here; on real TPU pods the identical code
rides ICI/DCN — nothing in `crdt_tpu.parallel` is host-count-aware,
the mesh just spans `jax.devices()` after `jax.distributed`
initializes).

Each process validates its ADDRESSABLE key shards against a
single-process reference replica merged from the same changesets —
lane-exact — and the replicated canonical clock must agree.

Run: ``python examples/multihost_fanin_example.py`` (it spawns and
coordinates both processes itself).
"""

import os
import socket
import subprocess
import sys

BASE = 1_700_000_000_000
N = 4096          # key slots, sharded 2-way across the processes
ROWS = 8          # replica rows, fanned in across the replica axis


def worker(process_id: int) -> None:
    # 2 local devices × 2 procs; the env flag must be set before jax
    # initializes its backends, and older jax lacks the config option
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        pass                      # older jax: the XLA flag covers it
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{os.environ['MH_EXAMPLE_PORT']}",
        num_processes=2, process_id=process_id)

    import numpy as np

    from crdt_tpu import DenseCrdt, ShardedDenseCrdt
    from crdt_tpu.parallel import make_fanin_mesh
    from crdt_tpu.testing import FakeClock

    assert jax.device_count() == 4 and jax.local_device_count() == 2
    # (replica=2, key=2): the replica axis CROSSES the process
    # boundary, so the fan-in's lexicographic-max reduction is a real
    # cross-process collective.
    mesh = make_fanin_mesh(2, 2)

    def batches():
        out = []
        for i in range(3):     # identical on both processes (seeded)
            p = DenseCrdt(f"peer{i}", N,
                          wall_clock=FakeClock(start=BASE + i * 7))
            rng = np.random.default_rng(i)
            slots = rng.choice(N, ROWS * 64, replace=False)
            p.put_batch(slots, rng.integers(0, 1 << 30, slots.size))
            p.delete_batch(slots[:5])
            out.append(p.export_delta())
        return out

    sharded = ShardedDenseCrdt("local", N, mesh,
                               wall_clock=FakeClock(start=BASE + 500))
    sharded.merge_many(batches())

    # Reference: the same merges on a plain single-process replica.
    ref = DenseCrdt("local", N, executor="xla",
                    wall_clock=FakeClock(start=BASE + 500))
    ref.merge_many(batches())

    assert sharded.canonical_time == ref.canonical_time
    checked = 0
    for lane_name in ("lt", "node", "val", "mod_lt", "mod_node",
                      "occupied", "tomb"):
        lane = getattr(sharded.store, lane_name)
        ref_lane = np.asarray(getattr(ref.store, lane_name))
        for shard in lane.addressable_shards:
            (sl,) = shard.index
            np.testing.assert_array_equal(
                np.asarray(shard.data), ref_lane[sl],
                err_msg=f"{lane_name} shard {shard.index}")
            checked += 1
    print(f"[process {process_id}] {checked} addressable shards "
          "lane-exact vs single-process reference; canonical clocks "
          "agree ✓", flush=True)


def main() -> None:
    if "MH_EXAMPLE_RANK" in os.environ:
        worker(int(os.environ["MH_EXAMPLE_RANK"]))
        return
    import jax
    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        # the CPU backend grew multi-process collectives in 0.5; on
        # older jax every cross-process device_put raises
        # "Multiprocess computations aren't implemented"
        print(f"skipped: jax {jax.__version__} cannot run "
              "multi-process CPU collectives (needs jax >= 0.5)")
        return
    # Fresh ephemeral coordinator port per run: concurrent suites on
    # one host must not collide. (The tiny bind/close race window is
    # acceptable for an example.)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {**os.environ, "MH_EXAMPLE_PORT": str(port)}
    p0 = subprocess.Popen([sys.executable, __file__],
                          env={**env, "MH_EXAMPLE_RANK": "0"})
    p1 = subprocess.Popen([sys.executable, __file__],
                          env={**env, "MH_EXAMPLE_RANK": "1"})
    try:
        # One shared deadline (not 300s each), and ALWAYS reap both:
        # an orphaned worker holding inherited pipes would hang the
        # example-CI harness past its own timeout.
        import time
        deadline = time.monotonic() + 240
        rc0 = p0.wait(timeout=max(1, deadline - time.monotonic()))
        rc1 = p1.wait(timeout=max(1, deadline - time.monotonic()))
    except Exception:
        p0.kill()
        p1.kill()
        raise
    if rc0 or rc1:
        p0.kill()
        p1.kill()
        raise SystemExit(f"worker exit codes: {rc0}, {rc1}")
    print("two processes, one SPMD fan-in, converged ✓")


if __name__ == "__main__":
    main()

"""Pod-local collective anti-entropy suite (docs/COLLECTIVE.md).

The load-bearing property: an N-member `CollectiveGroup.join` — ONE
device dispatch, zero wire bytes — lands every member on a state
bit-identical to pairwise `sync_packed` convergence of the same
writes, across mixed slot semantics and mid-window joiners. Plus the
group's contract surface (geometry/identity/semantics validation) and
the `GossipNode` fast lane (address-keyed detection, counted socket
fallback, `attach_group` re-scan).
"""

import random

import jax
import numpy as np
import pytest

from crdt_tpu import DenseCrdt, GossipNode, default_registry
from crdt_tpu.collective import CollectiveGroup
from crdt_tpu.obs.device import default_ledger
from crdt_tpu.sync import sync_collective, sync_packed
from crdt_tpu.testing import FakeClock

N = 64
BASE = 1_700_000_000_000
KERNEL = "parallel.collective_join"

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="collective join needs a multi-device (virtual) mesh")


def _pack_copy_bytes():
    c = default_registry().counter("crdt_tpu_pack_copy_bytes_total")
    return sum(s["value"] for s in c.samples())


def _lanes(c):
    s = c._store
    return jax.device_get((s.lt, s.node, s.val, s.tomb, s.occupied))


def _build_replicas(n_members=3, seed=0, mixed_sem=True):
    """One deterministic universe of writes: identical FakeClock bases
    and op sequences give bit-identical stamps, so a second call
    builds an exact twin set for the wire-path oracle."""
    rng = random.Random(seed)
    names = [chr(ord("a") + i) for i in range(n_members)]
    reps = [DenseCrdt(nm, N, wall_clock=FakeClock(start=BASE))
            for nm in names]
    if mixed_sem:
        for c in reps:
            c.set_semantics([0], "gcounter")
            c.set_semantics([1], "pncounter")
            c.set_semantics([2], "orset")
            c.set_semantics([3], "mvreg")
    for c in reps:
        slots = rng.sample(range(8, N), 6)
        c.put_batch(slots, [rng.randrange(1, 10_000) for _ in slots])
        c.delete_batch(slots[:1])
        if mixed_sem:
            c.counter_add(0, rng.randrange(1, 50))
            c.counter_add(1, rng.randrange(-20, 20))
            c.orset_add(2, rng.randrange(16))
            c.mvreg_put(3, rng.randrange(1, 100))
    return reps


def _wire_converge(reps):
    """Socket-path oracle: full (since=None) pairwise exchanges until
    every pair has seen every write. since=None sidesteps the
    same-round pull bound (a peer's writes stamped below the local
    pre-push watermark are invisible to a delta pull when every
    FakeClock shares one base)."""
    for _ in range(2):
        for i in range(len(reps)):
            for j in range(i + 1, len(reps)):
                sync_packed(reps[i], reps[j], since=None)
    return reps


def _assert_bit_identical(wire, coll):
    for wx, cx in zip(wire, coll):
        wl, cl = _lanes(wx), _lanes(cx)
        for lane, w, c in zip(("lt", "node", "val", "tomb", "occ"),
                              wl, cl):
            assert np.array_equal(w, c), (wx.node_id, lane)
        assert np.array_equal(wx._sem_host(), cx._sem_host())
    roots_w = {x.digest_tree().root for x in wire}
    roots_c = {x.digest_tree().root for x in coll}
    assert len(roots_c) == 1 and roots_c == roots_w


# --- the equivalence property ---

def test_collective_join_bit_identical_to_pairwise_packed():
    wire = _wire_converge(_build_replicas(seed=1))
    coll = _build_replicas(seed=1)
    group = CollectiveGroup(coll)
    report = group.join()
    assert report.members == 3 and report.adopted > 0
    assert report.bytes_to_wire == 0
    _assert_bit_identical(wire, coll)
    assert report.digest_root == wire[0].digest_tree().root


@pytest.mark.parametrize("seed", [2, 3, 4])
def test_collective_join_property_lww_only(seed):
    wire = _wire_converge(_build_replicas(seed=seed, mixed_sem=False))
    coll = _build_replicas(seed=seed, mixed_sem=False)
    CollectiveGroup(coll).join()
    _assert_bit_identical(wire, coll)


def test_collective_join_is_one_dispatch_and_zero_pack_bytes():
    coll = _build_replicas(seed=5)
    group = CollectiveGroup(coll)
    led = default_ledger()
    before_k = led.dispatches(kernel=KERNEL)
    before_bytes = _pack_copy_bytes()
    report = group.join()
    # The invariant the PR exists for: ONE collective dispatch per
    # round, and pack-path copy accounting does not move (cache
    # seeding is a host-side column select, not a wire stage).
    assert led.dispatches(kernel=KERNEL) - before_k == 1
    assert _pack_copy_bytes() == before_bytes
    assert report.bytes_to_wire == 0
    # Pre-seeded caches: digest_tree() and the watermark-aligned pack
    # must both come back without ANY further device dispatch.
    total = led.dispatches()
    for m in coll:
        m.digest_tree()
    assert led.dispatches() == total, "digest cache was cold"
    for m in coll:
        assert len(m._pack_cache) == 1


def test_second_join_is_idempotent():
    coll = _build_replicas(seed=6)
    group = CollectiveGroup(coll)
    first = group.join()
    again = group.join()
    assert again.adopted == 0
    assert again.digest_root == first.digest_root
    assert again.new_canonical == first.new_canonical


def test_mid_window_joiner_has_ingest_drained():
    wire = _wire_converge(_build_replicas(seed=7))
    coll = _build_replicas(seed=7)
    # Twin the staged writes on the wire oracle (window closed) and
    # the collective member (window still OPEN at join time): join()
    # must drain the overlay, so the staged rows participate.
    wire_w = DenseCrdt("w", N, wall_clock=FakeClock(start=BASE + 9))
    coll_w = DenseCrdt("w", N, wall_clock=FakeClock(start=BASE + 9))
    for c in (wire_w, coll_w):
        c.set_semantics([0], "gcounter")
        c.set_semantics([1], "pncounter")
        c.set_semantics([2], "orset")
        c.set_semantics([3], "mvreg")
    with wire_w.ingest():
        wire_w.put_batch([4, 5], [777, 888])
    for r in wire:
        sync_packed(r, wire_w, since=None)
    _wire_converge(wire)
    group = CollectiveGroup(coll + [coll_w])
    with coll_w.ingest():
        coll_w.put_batch([4, 5], [777, 888])
        group.join()
    _assert_bit_identical(wire + [wire_w], coll + [coll_w])


def test_sync_collective_wraps_group_join():
    coll = _build_replicas(seed=8)
    report = sync_collective(CollectiveGroup(coll))
    assert report.adopted > 0
    roots = {m.digest_tree().root for m in coll}
    assert len(roots) == 1


# --- contract surface ---

def test_group_rejects_fewer_than_two_members():
    (only,) = _build_replicas(seed=9)[:1]
    with pytest.raises(ValueError, match=">= 2 members"):
        CollectiveGroup([only])


def test_group_rejects_duplicate_node_ids():
    a = DenseCrdt("dup", N, wall_clock=FakeClock(start=BASE))
    b = DenseCrdt("dup", N, wall_clock=FakeClock(start=BASE))
    with pytest.raises(ValueError, match="distinct node ids"):
        CollectiveGroup([a, b])


def test_group_rejects_geometry_mismatch():
    a = DenseCrdt("a", N, wall_clock=FakeClock(start=BASE))
    b = DenseCrdt("b", N * 2, wall_clock=FakeClock(start=BASE))
    with pytest.raises(ValueError, match="n_slots"):
        CollectiveGroup([a, b])


def test_group_rejects_addresses_for_non_members():
    a, b, _ = _build_replicas(seed=10)
    with pytest.raises(ValueError, match="non-member"):
        CollectiveGroup([a, b], addresses={"ghost": "h:1"})


def test_join_rejects_semantics_mismatch():
    a = DenseCrdt("a", N, wall_clock=FakeClock(start=BASE))
    b = DenseCrdt("b", N, wall_clock=FakeClock(start=BASE))
    a.set_semantics([5], "orset")
    b.set_semantics([5], "gcounter")
    group = CollectiveGroup([a, b])
    with pytest.raises(ValueError, match="semantics tag mismatch"):
        group.join()


# --- GossipNode fast lane ---

def _gossip_pair():
    a = DenseCrdt("ga", N, wall_clock=FakeClock(start=BASE))
    b = DenseCrdt("gb", N, wall_clock=FakeClock(start=BASE))
    na = GossipNode(a, rng=random.Random(7))
    nb = GossipNode(b, rng=random.Random(7))
    return a, b, na, nb


def test_gossip_routes_co_located_peer_through_collective():
    a, b, na, nb = _gossip_pair()
    with na, nb:
        group = CollectiveGroup(
            [a, b], addresses={"ga": f"{na.host}:{na.port}",
                               "gb": f"{nb.host}:{nb.port}"})
        na.attach_group(group)
        peer = na.add_peer("gb", nb.host, nb.port)
        assert peer.collective
        a.put_batch([1], [11])
        b.put_batch([2], [22])
        led = default_ledger()
        before = led.dispatches(kernel=KERNEL)
        assert na.run_round() == {"gb": "ok"}
        assert led.dispatches(kernel=KERNEL) - before == 1
        assert peer.last_attempt == "collective"
        assert peer.stats.rounds_ok == 1
        assert peer.stats.bytes_sent == 0 and peer.stats.bytes_received == 0
        assert a.get(1) == b.get(1) == 11
        assert a.get(2) == b.get(2) == 22


def test_gossip_attach_group_rescans_existing_peers():
    a, b, na, nb = _gossip_pair()
    with na, nb:
        peer = na.add_peer("gb", nb.host, nb.port)
        assert not peer.collective
        group = CollectiveGroup(
            [a, b], addresses={"gb": f"{nb.host}:{nb.port}"})
        na.attach_group(group)
        assert peer.collective
        na.attach_group(None)
        assert not peer.collective


def test_gossip_node_rejects_group_without_its_replica():
    a, b, na, nb = _gossip_pair()
    stranger = DenseCrdt("ga", N, wall_clock=FakeClock(start=BASE))
    group = CollectiveGroup([stranger, b])
    with pytest.raises(ValueError, match="does not contain"):
        na.attach_group(group)


def test_gossip_collective_failure_falls_back_to_socket_counted():
    a, b, na, nb = _gossip_pair()
    with na, nb:
        group = CollectiveGroup(
            [a, b], addresses={"gb": f"{nb.host}:{nb.port}"})
        na.attach_group(group)
        peer = na.add_peer("gb", nb.host, nb.port)

        def boom(*args, **kwargs):
            raise RuntimeError("mesh went away")

        group.join = boom
        a.put_batch([1], [11])
        fb = default_registry().counter(
            "crdt_tpu_collective_fallback_total")
        before = sum(s["value"] for s in fb.samples())
        assert na.run_round() == {"gb": "ok"}
        # Downgrade is visible: counted per peer (reason label), peer
        # stats bumped, and the round still converged over the socket.
        assert sum(s["value"] for s in fb.samples()) > before
        assert peer.stats.fallbacks >= 1
        assert peer.last_attempt != "collective"
        assert b.get(1) == 11

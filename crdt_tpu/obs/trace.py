"""HLC-stamped structured trace events + profiler-annotated spans.

A :class:`TraceRing` is a bounded in-memory event ring (newest N
events) with an optional JSONL sink. Events are plain dicts:

    {"seq": 17, "kind": "gossip_round", "mono_s": 123.456,
     "hlc": "2026-08-05T..+0000-0000-n0", "peer": "b",
     "outcome": "ok", "dur_s": 0.0123}

- ``kind`` names the event class: ``merge`` (a merge dispatch span),
  ``gossip_round``, ``wire_frame``, ``checkpoint``, ``breaker``,
  ``bench_phase``, ``ingest`` (a write-combiner flush span,
  models/ingest.py — carries ``rows`` and ``trigger``).
- ``hlc`` is the emitting replica's canonical HLC at emission — the
  cluster-orderable stamp. ``mono_s`` (``time.monotonic()``) orders
  events within one process; wall-clock reads stay where they belong
  (``hlc.wall_clock_millis`` is the one sanctioned boundary).
- ``dur_s`` is present on span-shaped events.

**Cost model**: tracing is off by default and every emit site checks
``tracer().enabled`` (one attribute read) first. :func:`span` always
wraps its body in ``jax.profiler.TraceAnnotation`` — so TPU profiles
show named merge/pack/wire phases whether or not the ring is on — and
only times + emits when the ring is enabled. The stream-bench
per-phase row (bench.py) pins the enabled-overhead at ~0 on the hot
path.

HLC arguments may be zero-arg callables; they are invoked only when an
event is actually recorded, so disabled tracing never pays for a
``str(Hlc)``.
"""

from __future__ import annotations

import functools
import json
import os
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

import jax.profiler

from ..analysis.concurrency import make_lock


@functools.lru_cache(maxsize=512)
def _hlc_str_cached(millis: int, counter: int, node_id: Any) -> str:
    # Format straight from the fields (the Hlc.__str__ layout);
    # constructing a throwaway Hlc just to render it would double the
    # miss cost. millis comes from a live Hlc, already normalized.
    from ..hlc import _iso8601
    return f"{_iso8601(millis)}-{counter:04X}-{node_id}"


def _hlc_str(hlc: Any) -> str:
    """``str(hlc)`` with a small field-keyed cache: emit sites hand
    the SAME canonical stamp to every event between refreshes, so the
    ISO-8601 render (the single biggest per-event cost) is paid once
    per stamp, not once per event — what keeps the soak-measured
    tracing overhead inside the 5% budget (bench.py antientropy
    mode). Keyed on the raw fields, not the object: hashing must not
    re-render the stamp."""
    if isinstance(hlc, str):
        return hlc
    try:
        return _hlc_str_cached(hlc.millis, hlc.counter, hlc.node_id)
    except (AttributeError, TypeError):  # stamp-like — render directly
        return str(hlc)


class TraceRing:
    """Bounded in-memory trace event ring + optional JSONL sink.

    The sink is size-bounded: when ``max_sink_bytes`` is set on
    :meth:`enable`, the file rolls to ``<path>.1`` (one generation,
    overwritten on each roll) once it crosses the budget, so a
    multi-hour soak holds at most ~2x the budget on disk.
    """

    # crdtlint lock-discipline contract: ring storage and sink are
    # touched only under self._lock. ``enabled`` is a bare bool read
    # on hot paths by design (stale reads only delay on/off by one
    # event).
    _CRDTLINT_GUARDED = {"_lock": ("_events", "_sink", "_seq",
                                   "_sink_path", "_sink_bytes",
                                   "_sink_max_bytes")}
    # analysis/concurrency.py: leaf singleton — emit never takes
    # another lock inside the ring critical section.
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self, capacity: int = 4096):
        self.enabled = False
        self._lock = make_lock("TraceRing._lock", 82)
        self._events: deque = deque(maxlen=capacity)
        self._sink = None
        self._sink_path: Optional[str] = None
        self._sink_bytes = 0
        self._sink_max_bytes: Optional[int] = None
        self._seq = 0

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._events.maxlen

    def enable(self, capacity: Optional[int] = None,
               jsonl_path: Optional[str] = None,
               max_sink_bytes: Optional[int] = None) -> "TraceRing":
        """Turn event recording on; optionally resize the ring and/or
        append every event to a JSONL file. ``max_sink_bytes`` bounds
        the sink: once the file crosses it, it is rotated to
        ``<path>.1`` and a fresh file is started."""
        with self._lock:
            if capacity is not None:
                self._events = deque(self._events, maxlen=capacity)
            if jsonl_path is not None:
                if self._sink is not None:
                    self._sink.close()
                self._sink = open(jsonl_path, "a", encoding="utf-8")
                self._sink_path = jsonl_path
                try:
                    self._sink_bytes = os.path.getsize(jsonl_path)
                except OSError:
                    self._sink_bytes = 0
            if max_sink_bytes is not None:
                self._sink_max_bytes = max_sink_bytes
        self.enabled = True
        return self

    def disable(self) -> None:
        """Stop recording and close any JSONL sink."""
        self.enabled = False
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None
            self._sink_path = None
            self._sink_bytes = 0
            self._sink_max_bytes = None

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def emit(self, kind: str, hlc: Any = None, **fields: Any) -> None:
        """Record one event (no-op while disabled). ``hlc`` may be an
        `Hlc`, a string, or a zero-arg callable evaluated lazily."""
        if not self.enabled:
            return
        event: Dict[str, Any] = {"kind": kind,
                                 "mono_s": time.monotonic()}
        if hlc is not None:
            if callable(hlc):
                hlc = hlc()
            if hlc is not None:
                event["hlc"] = _hlc_str(hlc)
        event.update(fields)
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
            if self._sink is not None:
                # json.dumps defaults to ASCII output, so len() == bytes.
                line = json.dumps(event, default=str) + "\n"
                self._sink.write(line)
                self._sink.flush()
                self._sink_bytes += len(line)
                if (self._sink_max_bytes is not None
                        and self._sink_path is not None
                        and self._sink_bytes >= self._sink_max_bytes):
                    self._sink.close()
                    os.replace(self._sink_path, self._sink_path + ".1")
                    self._sink = open(self._sink_path, "a",
                                      encoding="utf-8")
                    self._sink_bytes = 0

    def events(self, kind: Optional[str] = None) -> List[dict]:
        """Snapshot the ring (oldest first), optionally one kind."""
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        return out


_DEFAULT = TraceRing()

# Cross-replica round ids come from a locked process counter, NOT the
# wall clock (crdtlint wall-clock-read): the node-id prefix makes them
# fleet-unique, the counter makes them process-unique, and no clock
# skew can make two rounds collide or reorder.
_RID_LOCK = make_lock("trace._RID_LOCK", 82)
_RID_N = 0


def round_id(node: Any = None) -> str:
    """Compact fleet-unique sync-round id, e.g. ``"a.r17"``: the
    initiator stamps one per round and piggybacks it on sync frames
    (the ``trace`` hello cap) so its ``sync_*`` span and the
    responder's merge span correlate in the JSONL sink."""
    global _RID_N
    with _RID_LOCK:
        _RID_N += 1
        n = _RID_N
    return f"{node}.r{n}" if node not in (None, "") else f"r{n}"

# Span durations double into a fixed log2 histogram so the metrics op
# exposes per-phase latency distributions, not just the event tail the
# ring happens to hold. Created lazily to keep import order trivial.
_SPAN_HIST = None
_SPAN_HIST_LOCK = make_lock("trace._SPAN_HIST_LOCK", 82)


def tracer() -> TraceRing:
    """The process-wide trace ring every in-tree emit site uses."""
    return _DEFAULT


def _span_histogram():
    global _SPAN_HIST
    with _SPAN_HIST_LOCK:
        if _SPAN_HIST is None:
            from .registry import default_registry
            _SPAN_HIST = default_registry().histogram(
                "crdt_tpu_span_seconds",
                "traced span durations by span name (log2 buckets)",
                low_exp=-20, high_exp=5)
        return _SPAN_HIST


@contextmanager
def span(name: str, kind: str = "span", hlc: Any = None,
         **fields: Any):
    """Profiler-annotated span: the body always runs inside
    ``jax.profiler.TraceAnnotation(name)`` (named kernels in TPU
    profiles); when the process tracer is enabled the span is also
    timed, emitted as an HLC-stamped ring event, and observed into the
    ``crdt_tpu_span_seconds`` histogram."""
    ring = _DEFAULT
    if not ring.enabled:
        with jax.profiler.TraceAnnotation(name):
            yield
        return
    start = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dur = time.perf_counter() - start
        ring.emit(kind, hlc=hlc, span=name, dur_s=dur, **fields)
        _span_histogram().observe(dur, span=name)

"""Sharded fan-in at scale on the virtual 8-device mesh.

Correctness at scale (round 2) plus a COMPUTE-DOMINATED weak-scaling
characterization (round 5): 1/2/4/8 devices with a CONSTANT
per-device block (rows × keys), per-device work sized thousands of
times above the dispatch floor (each curve row reports the ratio), so
the round-4 flaw — a curve that measured the ~2 ms one-host dispatch
floor — cannot recur.

CAVEAT the artifact also records: these are 8 VIRTUAL CPU devices on
ONE host with ONE core (``host_cpu_cores`` in the output) —
"collectives" are memcpy and all device computations serialize, so
wall-clock per-device throughput falls ~1/D for ANY program. The
meaningful flatness signal is ``serial_efficiency = D·t_1/t_D``:
≈ 1.0 (measured ≥ 1.0 at every width) means the sharded machinery
adds no cost beyond that serialization — which real parallel chips
do not pay. Real ICI scaling needs real chips.

Run:
    python benchmarks/sharded_scale.py [--keys 524288] [--rows 64]
(The script pins jax to the virtual CPU mesh itself — no env needed.)
"""

from __future__ import annotations

import argparse
import json
import time

import jax

# Must run before any backend init: this environment pins an 'axon' TPU
# plugin via sitecustomize, so the env var alone cannot switch to the
# virtual CPU mesh (tests/conftest.py does the same).
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax spells the same pre-init knob as an XLA flag; we are
    # still before backend init, so the env route works here too.
    import os
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from crdt_tpu.hlc import SHIFT  # noqa: E402
from crdt_tpu.models.dense_crdt import DenseCrdt, ShardedDenseCrdt  # noqa: E402
from crdt_tpu.ops.dense import DenseChangeset  # noqa: E402
from crdt_tpu.parallel import make_fanin_mesh  # noqa: E402
from crdt_tpu.testing import FakeClock, assert_dense_stores_equal  # noqa: E402

BASE = 1_700_000_000_000


def random_changesets(rows: int, n: int, seed: int, n_groups: int):
    """``n_groups`` peer changesets of rows//n_groups replica rows each,
    all-distinct random records, as (DenseChangeset, node_ids) pairs."""
    rng = np.random.default_rng(seed)
    per = rows // n_groups
    out = []
    for g in range(n_groups):
        lt = ((BASE + rng.integers(0, 1000, (per, n))) << SHIFT) \
            + rng.integers(0, 4, (per, n))
        cs = DenseChangeset(
            lt=jnp.asarray(lt, jnp.int64),
            node=jnp.asarray(rng.integers(0, 4, (per, n)), jnp.int32),
            val=jnp.asarray(lt, jnp.int64),
            tomb=jnp.asarray(rng.random((per, n)) < 0.3),
            valid=jnp.asarray(rng.random((per, n)) < 0.8),
        )
        out.append((cs, [f"peer{g}-{i}" for i in range(4)]))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=1 << 18)
    ap.add_argument("--rows", type=int, default=64)
    ap.add_argument("--out", default="MULTICHIP_SCALE_r05.json")
    ap.add_argument("--trajectory", metavar="JSONL", default=None,
                    help="trajectory file to append a normalized "
                         "record to (default: the shared "
                         "benchmarks/history series)")
    ap.add_argument("--no-trajectory", action="store_true",
                    help="skip the trajectory append")
    args = ap.parse_args()
    n, rows = args.keys, args.rows

    result = {"ok": False, "n_devices": jax.device_count(),
              "n_keys": n, "replica_rows": rows,
              "mesh": "(replica=2, key=4)"}
    mesh = make_fanin_mesh(2, 4)
    changesets = random_changesets(rows, n, seed=7, n_groups=8)
    merges = int(sum(int(jnp.sum(cs.valid)) for cs, _ in changesets))

    # --- sharded fan-in: 64 replica rows into 256k+ sharded slots ---
    sharded = ShardedDenseCrdt("local", n, mesh,
                               wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    sharded.merge_many(changesets)
    jax.block_until_ready(sharded.store.lt)
    warm_compile = time.perf_counter() - t0

    sharded2 = ShardedDenseCrdt("local", n, mesh,
                                wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    sharded2.merge_many(changesets)
    jax.block_until_ready(sharded2.store.lt)
    sharded_s = time.perf_counter() - t0

    # --- single-device cross-check (lane-exact) ---
    single = DenseCrdt("local", n, executor="xla",
                       wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    single.merge_many(changesets)
    jax.block_until_ready(single.store.lt)
    single_s = time.perf_counter() - t0

    assert_dense_stores_equal(single.store, sharded2.store,
                              "single vs sharded @ scale")
    assert single.canonical_time == sharded2.canonical_time
    result["lane_exact_vs_single_device"] = True
    result["merges"] = merges
    result["timings_s"] = {
        "sharded_fanin_first_call_incl_compile": round(warm_compile, 3),
        "sharded_fanin_warm": round(sharded_s, 3),
        "single_device_fanin_warm": round(single_s, 3),
    }
    result["sharded_merges_per_sec_warm"] = round(merges / sharded_s, 1)

    # --- put_batch cost on the sharded store (the round-2 concern:
    # a full-store re-shard per local write batch?) ---
    k = 1024
    slots = np.arange(0, k * 16, 16)
    vals = np.arange(k, dtype=np.int64)
    sharded2.put_batch(slots, vals)  # compile
    single.put_batch(slots, vals)
    jax.block_until_ready(sharded2.store.lt)
    jax.block_until_ready(single.store.lt)
    # Interleaved best-of reps: host-contention noise on the virtual
    # mesh hits both sides alike, so the RATIO stays meaningful.
    reps = 12
    put_sharded = put_single = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        sharded2.put_batch(slots, vals)
        jax.block_until_ready(sharded2.store.lt)
        put_sharded = min(put_sharded, time.perf_counter() - t0)
        t0 = time.perf_counter()
        single.put_batch(slots, vals)
        jax.block_until_ready(single.store.lt)
        put_single = min(put_single, time.perf_counter() - t0)

    # Dispatch floor: one trivial elementwise program over the same
    # store — what merely RUNNING an 8-partition program on this ONE
    # host costs, independent of any scatter work. The sharded write's
    # "overhead" over single-device is ~this floor (plus each
    # partition scanning the replicated index list serially on one
    # host); on real chips partitions dispatch in parallel and the
    # floor collapses. No re-shard exists: see
    # sharded_put_collective_free below.
    @jax.jit
    def _touch(store):
        return type(store)(*(
            (lane if lane.dtype == bool else lane + 0)
            for lane in store))

    floors = {}
    for label, cc in (("sharded", sharded2), ("single_device", single)):
        st = cc.store
        jax.block_until_ready(_touch(st))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(_touch(st))
            best = min(best, time.perf_counter() - t0)
        floors[label] = round(best * 1e3, 3)
    result["dispatch_floor_ms"] = floors

    shardings = {str(getattr(sharded2.store, f).sharding)
                 for f in sharded2.store._fields}
    result["put_batch_1024_slots_ms"] = {
        "sharded": round(put_sharded * 1e3, 2),
        "single_device": round(put_single * 1e3, 2),
    }
    result["store_sharding_consistent"] = len(shardings) == 1
    result["store_sharding"] = shardings.pop()

    # --- weak scaling (round 5: COMPUTE-DOMINATED) ---
    # Per-device block work held CONSTANT (rows_per_dev × keys_per_dev)
    # while the mesh grows 1/2/4/8; per-device work is sized so the
    # warm step dwarfs the dispatch floor (reported as a ratio).
    #
    # The honest frame on THIS host: os.cpu_count() == 1 here — all
    # virtual devices execute on ONE core, so wall-clock per-device
    # throughput falls as 1/D for ANY program, no matter how perfect
    # the sharding (there is zero parallel hardware to win back). The
    # verdict-grade signal this curve CAN carry is therefore
    # ``serial_efficiency = D × t_1 / t_D``: if ≈ 1, the collective
    # fan-in machinery adds NO cost beyond the unavoidable one-core
    # serialization of D devices' constant work — i.e. on hardware
    # where devices are real, per-device throughput stays flat.
    import os as _os
    host_cores = _os.cpu_count()
    rows_per_dev = max(rows, 64)
    keys_per_dev = max(n // 4, 1 << 17)
    curve = []
    for n_dev, (r_sh, k_sh) in [(1, (1, 1)), (2, (2, 1)),
                                (4, (2, 2)), (8, (2, 4))]:
        keys_d = keys_per_dev * k_sh
        rows_d = rows_per_dev * r_sh
        mesh_d = make_fanin_mesh(r_sh, k_sh,
                                 devices=jax.devices()[:n_dev])
        batches = random_changesets(rows_d, keys_d, seed=11,
                                    n_groups=4)
        m_count = int(sum(int(jnp.sum(cs.valid)) for cs, _ in batches))
        c = ShardedDenseCrdt("local", keys_d, mesh_d,
                             wall_clock=FakeClock(start=BASE + 2000))
        c.merge_many(batches)                      # compile
        jax.block_until_ready(c.store.lt)
        # Best-of protocol (on a one-host virtual mesh only minima are
        # noise-robust; the curve SHAPE is the deliverable).
        fanin_s = float("inf")
        for _ in range(2):
            c2 = ShardedDenseCrdt(
                "local", keys_d, mesh_d,
                wall_clock=FakeClock(start=BASE + 2000))
            t0 = time.perf_counter()
            c2.merge_many(batches)
            jax.block_until_ready(c2.store.lt)
            fanin_s = min(fanin_s, time.perf_counter() - t0)

        # Per-width dispatch floor: a trivial elementwise program over
        # THIS store — step_over_floor shows the step is compute-
        # dominated, not dispatch-bound (the round-4 curve's flaw).
        st = c2.store
        jax.block_until_ready(_touch(st))
        floor = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(_touch(st))
            floor = min(floor, time.perf_counter() - t0)
        curve.append({
            "devices": n_dev, "mesh": f"(replica={r_sh}, key={k_sh})",
            "n_keys": keys_d, "replica_rows": rows_d,
            "per_device_block": f"{rows_per_dev}x{keys_per_dev}",
            "fanin_warm_s": round(fanin_s, 4),
            "dispatch_floor_ms": round(floor * 1e3, 2),
            "step_over_floor": round(fanin_s / floor, 1),
            "fanin_merges_per_sec": round(m_count / fanin_s, 1),
            "fanin_merges_per_sec_per_device":
                round(m_count / fanin_s / n_dev, 1),
        })
    t_1 = curve[0]["fanin_warm_s"]
    for row in curve:
        # ≈1.0 ⇒ the sharded machinery costs nothing beyond one-core
        # serialization of D× the constant per-device work.
        row["serial_efficiency"] = round(
            row["devices"] * t_1 / row["fanin_warm_s"], 3)
    result["host_cpu_cores"] = host_cores
    result["weak_scaling_note"] = (
        f"constant per-device block ({rows_per_dev}x{keys_per_dev}), "
        f"compute-dominated (see step_over_floor); host has "
        f"{host_cores} CPU core(s), so all virtual devices SERIALIZE "
        "and wall-clock per-device throughput must fall ~1/D for any "
        "program — serial_efficiency (D*t_1/t_D ~ 1.0) is the "
        "meaningful flatness signal: the collective machinery adds no "
        "overhead beyond that serialization, which real parallel "
        "chips do not pay")
    result["weak_scaling"] = curve
    result["sharded_put_vs_single_ratio"] = round(
        put_sharded / put_single, 2)

    # --- structural check: the sharded write must compile with ZERO
    # collectives (each shard scatters its own rows; no re-shard, no
    # gather). Robust where virtual-CPU timings wobble. ---
    import re
    from collections import Counter

    from crdt_tpu.ops.dense import _put_scatter
    from crdt_tpu.parallel import store_sharding
    fn = _put_scatter(False, store_sharding(mesh))
    hlo = fn.lower(
        sharded2.store, jnp.asarray(slots, jnp.int32),
        jnp.asarray(vals), jnp.zeros(len(slots), bool),
        jnp.int64(1), jnp.int32(0)).compile().as_text()
    colls = Counter(re.findall(
        r"(all-gather|all-reduce|collective-permute|all-to-all)", hlo))
    result["sharded_put_collectives"] = dict(colls)
    result["sharded_put_collective_free"] = not colls
    result["ok"] = True

    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))

    if not args.no_trajectory:
        # One normalized trajectory record, under an honest host
        # class: the "-virtualmesh" suffix marks every number as
        # measured on xla_force_host_platform virtual devices
        # time-slicing this host's core(s), so the series never
        # compares it against (or gates) real-hardware runs.
        from crdt_tpu.obs import trajectory as _traj
        flat = dict(result)
        flat["weak_scaling"] = {  # list -> flattenable per-width dict
            f"d{row['devices']}": row for row in curve}
        _traj.append_record(
            _traj.normalize_record(
                "multichip-scale", flat,
                host=_traj.host_class() + "-virtualmesh"),
            args.trajectory or _traj.TRAJECTORY_PATH)


if __name__ == "__main__":
    main()

"""Native HLC wire codec: differential vs the pure-Python path.

The C batch codec (`crdt_tpu/native/hlccodec.c`) must be bit-identical
to the Python codec on canonical-shape strings and must defer (None)
on everything else. The build environment ships a C compiler, so the
module is REQUIRED to load here — a silent fallback hiding a build
break would otherwise go unnoticed.
"""

import random

import pytest

import crdt_tpu.crdt_json as crdt_json
from crdt_tpu import Hlc, MapCrdt
from crdt_tpu.native import load
from crdt_tpu.testing import FakeClock


@pytest.fixture(scope="module")
def codec():
    mod = load()
    assert mod is not None, "native codec failed to build/load"
    return mod


def random_hlcs(n, seed=0):
    rng = random.Random(seed)
    nodes = ["abc", "node-x", "a-b-c", "x" * 10, "n0", "ünïcode"]
    return [Hlc(rng.randrange(0, 1 << 44), rng.randrange(0, 1 << 16),
                rng.choice(nodes)) for _ in range(n)]


def test_parse_batch_matches_python(codec):
    hlcs = random_hlcs(500)
    strings = [str(h) for h in hlcs]
    millis_l, counter_l, node_l = codec.parse_hlc_batch(strings)
    for h, s, ms, c, node in zip(hlcs, strings, millis_l, counter_l,
                                 node_l):
        assert ms is not None, s
        assert Hlc(ms, c, node) == h
        assert Hlc.parse(s) == Hlc(ms, c, node)


def test_format_batch_matches_python(codec):
    hlcs = random_hlcs(500, seed=1)
    out = codec.format_hlc_batch([h.millis for h in hlcs],
                                 [h.counter for h in hlcs],
                                 [str(h.node_id) for h in hlcs])
    for h, s in zip(hlcs, out):
        assert s == str(h)


def test_non_canonical_defers(codec):
    bad = ["", "garbage", "2026-07-29 12:00:00.000Z-0000-n",  # space sep
           "2026-07-29T12:00:00Z-0000-n",                     # no millis
           "2026-07-29T12:00:00.000+00:00-0000-n",            # offset
           "2026-07-29T12:00:00.000Z-00-n"]                   # short hex
    millis_l, _, _ = codec.parse_hlc_batch(bad)
    assert millis_l == [None] * len(bad)


def test_format_out_of_range_defers(codec):
    out = codec.format_hlc_batch([-1, 400_000_000_000_000],
                                 [0, 0], ["n", "n"])
    # Negative millis -> year < 1970 but >= 0: formatted fine; the
    # far-future value exceeds year 9999 -> deferred.
    assert out[0] == str(Hlc(-1, 0, "n"))
    assert out[1] is None


def test_invalid_calendar_dates_rejected(codec):
    # Shape-valid but calendar-invalid strings must NOT silently
    # normalize — the C path defers, the Python path raises.
    bad = ["2026-02-30T00:00:00.000Z-0000-n",   # Feb 30
           "2026-13-01T00:00:00.000Z-0000-n",   # month 13
           "2026-01-01T25:00:00.000Z-0000-n",   # hour 25
           "2026-01-01T00:61:00.000Z-0000-n"]   # minute 61
    millis_l, _, _ = codec.parse_hlc_batch(bad)
    assert millis_l == [None] * len(bad)
    for s in bad:
        with pytest.raises(ValueError):
            Hlc.parse(s)
    # Leap day valid in leap years only.
    assert codec.parse_hlc_batch(
        ["2024-02-29T00:00:00.000Z-0000-n"])[0][0] is not None
    assert codec.parse_hlc_batch(
        ["2023-02-29T00:00:00.000Z-0000-n"])[0][0] is None


def test_out_of_range_year_fails_fast():
    # Encoding a year beyond 9999 must raise, not emit unparseable wire.
    from crdt_tpu.hlc import _iso8601
    with pytest.raises(ValueError):
        _iso8601(400_000_000_000_000)
    with pytest.raises(ValueError):
        _iso8601(-63_000_000_000_000)  # before year 1


class TestWireScanner:
    """`parse_wire` one-pass columnar scan: exactness vs `json.loads`.

    The scanner must either produce EXACTLY what the json.loads-based
    column build produces, or return None (whole-payload fallback) so
    the Python path decides — including deciding to raise.
    """

    def _differential(self, monkeypatch, payload, **kw):
        import numpy as np
        fast = crdt_json.decode_columns(payload, **kw)
        monkeypatch.setattr(crdt_json.native, "load", lambda: None)
        slow = crdt_json.decode_columns(payload, **kw)
        monkeypatch.undo()
        def veq(a, b):   # NaN-tolerant value equality
            if isinstance(a, float) and isinstance(b, float):
                return a == b or (a != a and b != b)
            return a == b

        assert fast[0] == slow[0]                    # keys
        assert np.array_equal(fast[1], slow[1])      # lt lanes
        assert list(fast[2]) == list(slow[2])        # node ids
        assert len(fast[3]) == len(slow[3])          # values
        assert all(veq(a, b) for a, b in zip(fast[3], slow[3]))
        return fast

    def test_value_shapes(self, codec, monkeypatch):
        h = "2026-01-01T00:00:01.123Z-004D-nodeid"
        payload = ('{"int":{"hlc":"%s","value":42},'
                   '"neg":{"hlc":"%s","value":-7},'
                   '"float":{"hlc":"%s","value":3.14e2},'
                   '"str":{"hlc":"%s","value":"plain"},'
                   '"esc":{"hlc":"%s","value":"a\\"b\\\\c\\n\\u00e9"},'
                   '"emoji":{"hlc":"%s","value":"\\ud83d\\ude00"},'
                   '"true":{"hlc":"%s","value":true},'
                   '"false":{"hlc":"%s","value":false},'
                   '"null":{"hlc":"%s","value":null},'
                   '"miss":{"hlc":"%s"},'
                   '"obj":{"hlc":"%s","value":{"a":[1,{"b":null}]}},'
                   '"arr":{"hlc":"%s","value":[1,"two",3.0]}}'
                   % ((h,) * 12))
        keys, lt, nodes, values = self._differential(monkeypatch, payload)
        assert values[0] == 42 and values[3] == "plain"
        assert values[4] == 'a"b\\c\né'
        assert values[5] == "\U0001F600"
        assert values[9] is None
        assert values[10] == {"a": [1, {"b": None}]}

    def test_member_order_extras_and_duplicates(self, codec,
                                                monkeypatch):
        h1 = "2026-01-01T00:00:01.123Z-004D-na"
        h2 = "2026-01-01T00:00:02.000Z-0000-nb"
        payload = ('{"swap":{"value":1,"hlc":"%s"},'
                   '"extra":{"hlc":"%s","value":2,"x":[1,2],"y":"z"},'
                   '"dup":{"hlc":"%s","value":3},'
                   '"dup":{"hlc":"%s","value":4}}' % (h1, h1, h1, h2))
        keys, lt, nodes, values = self._differential(monkeypatch, payload)
        # duplicate key: first position, LAST value — dict semantics
        assert keys == ["swap", "extra", "dup"]
        assert values == [1, 2, 4]
        assert nodes[2] == "nb"

    def test_escaped_keys_and_nodes(self, codec, monkeypatch):
        h_esc = "2026-01-01T00:00:01.123Z-004D-n\\u00e9\\\\x"
        payload = ('{"k\\u00e9y\\t1":{"hlc":"%s","value":1}}' % h_esc)
        keys, lt, nodes, values = self._differential(monkeypatch, payload)
        assert keys == ["kéy\t1"]
        assert nodes[0] == "né\\x"   # escaped hlc -> Hlc.parse path

    def test_non_canonical_hlc_per_item(self, codec, monkeypatch):
        # Space separator parses via the Python Hlc.parse fallback.
        payload = ('{"a":{"hlc":"2026-01-01 00:00:01.123Z-004D-n",'
                   '"value":1}}')
        keys, lt, nodes, values = self._differential(monkeypatch, payload)
        assert nodes == ["n"]

    def test_whitespace_and_nan_infinity(self, codec, monkeypatch):
        h = "2026-01-01T00:00:01.123Z-004D-n"
        payload = (' {\n "a" :\t{ "hlc" : "%s" , "value" : Infinity },'
                   '"b":{"hlc":"%s","value":-Infinity},'
                   '"c":{"hlc":"%s","value":NaN} } ' % (h, h, h))
        keys, lt, nodes, values = self._differential(monkeypatch, payload)
        assert values[0] == float("inf") and values[1] == float("-inf")
        assert values[2] != values[2]  # NaN

    def test_malformed_payloads_raise_identically(self, codec,
                                                  monkeypatch):
        h = "2026-01-01T00:00:01.123Z-004D-n"
        bad = ['{"a":{"hlc":"%s","value":01}}' % h,    # leading zero
               '{"a":{"hlc":"%s","value":1.}}' % h,    # bare frac
               '{"a":{"hlc":"%s","value":+1}}' % h,    # plus sign
               '{"a":{"hlc":"%s","value":1}} x' % h,   # trailing junk
               '{"a":{"hlc":"%s","value":1}',          # truncated
               '{"a":{"hlc":"%s","value":tru}}' % h,   # bad literal
               '[1,2]', '42', '']                      # not an object
        for payload in bad:
            with pytest.raises(Exception) as fast_err:
                crdt_json.decode_columns(payload)
            monkeypatch.setattr(crdt_json.native, "load", lambda: None)
            with pytest.raises(Exception) as slow_err:
                crdt_json.decode_columns(payload)
            monkeypatch.undo()
            assert type(fast_err.value) is type(slow_err.value), payload

    def test_missing_hlc_member_raises_identically(self, codec,
                                                   monkeypatch):
        payload = '{"a":{"value":1}}'
        with pytest.raises(KeyError):
            crdt_json.decode_columns(payload)
        monkeypatch.setattr(crdt_json.native, "load", lambda: None)
        with pytest.raises(KeyError):
            crdt_json.decode_columns(payload)

    def test_lone_surrogate_falls_back(self, codec, monkeypatch):
        # json.loads tolerates lone surrogates; the scanner defers.
        h = "2026-01-01T00:00:01.123Z-004D-n"
        payload = '{"a":{"hlc":"%s","value":"\\ud800"}}' % h
        assert codec.parse_wire(payload) is None
        keys, lt, nodes, values = self._differential(monkeypatch, payload)
        assert values == ["\ud800"]

    def test_year_zero_hlc_parses_identically(self, codec, monkeypatch):
        # The wire FORMATTER refuses years < 1 but the parser accepts
        # them (proleptic civil-date math, no datetime) — both paths
        # must produce the same pre-epoch lt lane.
        payload = ('{"a":{"hlc":"0000-01-01T00:00:01.123Z-004D-n",'
                   '"value":1}}')
        keys, lt, nodes, values = self._differential(monkeypatch, payload)
        assert int(lt[0]) < 0 and nodes == ["n"]

    def test_decoders_applied_like_generic_path(self, codec,
                                                monkeypatch):
        h = "2026-01-01T00:00:01.123Z-004D-n"
        payload = ('{"1":{"hlc":"%s","value":10},'
                   '"2":{"hlc":"%s","value":null}}' % (h, h))
        kw = dict(key_decoder=int,
                  value_decoder=lambda k, v: (k, v * 2))
        keys, lt, nodes, values = self._differential(monkeypatch,
                                                     payload, **kw)
        assert keys == [1, 2]
        # decoder sees the RAW wire key; None skips the decoder
        assert values == [("1", 20), None]

    def test_decode_fast_path_matches_generic(self, codec, monkeypatch):
        src = MapCrdt("remote", wall_clock=FakeClock())
        src.put_all({f"k{i}": i for i in range(50)})
        src.delete("k7")
        payload = src.to_json()
        canonical = Hlc(1, 0, "local")
        fast = crdt_json.decode(payload, canonical, now_millis=5)
        monkeypatch.setattr(crdt_json.native, "load", lambda: None)
        slow = crdt_json.decode(payload, canonical, now_millis=5)
        monkeypatch.undo()
        assert fast == slow

    def test_node_string_dedup(self, codec):
        h = "2026-01-01T00:00:01.123Z-004D-samenode"
        payload = "{%s}" % ",".join(
            '"k%d":{"hlc":"%s","value":%d}' % (i, h, i)
            for i in range(100))
        keys, lt_buf, nodes, values, bad = codec.parse_wire(payload)
        assert len({id(n) for n in nodes}) == 1


def test_wire_roundtrip_native_vs_python(monkeypatch):
    src = MapCrdt("remote", wall_clock=FakeClock())
    src.put_all({f"k{i}": {"v": i, "s": "x" * (i % 23)}
                 for i in range(200)})
    src.delete("k3")
    native_json = src.to_json()

    monkeypatch.setattr(crdt_json.native, "load", lambda: None)
    python_json = src.to_json()
    assert native_json == python_json

    dst_py = MapCrdt("local", wall_clock=FakeClock())
    dst_py.merge_json(python_json)
    monkeypatch.undo()
    dst_nat = MapCrdt("local", wall_clock=FakeClock())
    dst_nat.merge_json(native_json)
    assert dst_py.record_map() == dst_nat.record_map()
    assert dst_py.to_json() == dst_nat.to_json()


def test_raw_lone_surrogate_payload_falls_back(codec, monkeypatch):
    """A payload str holding a RAW unpaired surrogate (not the \\ud800
    escape — e.g. os.fsdecode data round-tripped through the codec's
    own ensure_ascii=False encoder) is not UTF-8 encodable, so the C
    scanner must defer the whole payload instead of raising
    UnicodeEncodeError; json.loads tolerates it."""
    h = "2026-01-01T00:00:01.123Z-004D-n"
    payload = '{"a":{"hlc":"%s","value":"x\ud800y"}}' % h
    assert codec.parse_wire(payload) is None
    keys, lt, nodes, values = crdt_json.decode_columns(payload)
    assert values == ["x\ud800y"]
    monkeypatch.setattr(crdt_json.native, "load", lambda: None)
    slow = crdt_json.decode_columns(payload)
    monkeypatch.undo()
    assert slow[3] == values


def test_stale_so_cannot_load():
    """The build cache is keyed by SOURCE CONTENT (hash in the .so
    filename), so a .so compiled from an older hlccodec.c — e.g. after
    an sdist upgrade where archive mtimes defeat an mtime check — can
    never be picked up and miss newer symbols."""
    import hashlib
    import os
    import sysconfig

    import crdt_tpu.native as native_pkg
    here = os.path.dirname(os.path.abspath(native_pkg.__file__))
    src = os.path.join(here, "hlccodec.c")
    with open(src, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:12]
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    mod = load()
    assert mod is not None
    assert mod.__spec__.origin.endswith(f"_hlccodec_{tag}{suffix}")
    # every symbol the Python side calls exists on the loaded module
    for sym in ("parse_hlc_batch", "format_hlc_batch", "parse_wire"):
        assert hasattr(mod, sym)


def test_deeply_nested_value_falls_back_to_json_loads(codec,
                                                      monkeypatch):
    """Containers nested past the C recursion bound parse via
    json.loads on the matched span — same object out."""
    h = "2026-01-01T00:00:01.123Z-004D-n"
    depth = 80   # beyond MAX_VALUE_DEPTH=48
    v = "[" * depth + "1" + "]" * depth
    payload = '{"a":{"hlc":"%s","value":%s}}' % (h, v)
    keys, lt_buf, nodes, values, bad = codec.parse_wire(payload)
    import json as json_mod
    expect = json_mod.loads(v)
    assert values[0] == expect
    fast = crdt_json.decode_columns(payload)
    monkeypatch.setattr(crdt_json.native, "load", lambda: None)
    slow = crdt_json.decode_columns(payload)
    monkeypatch.undo()
    assert fast[3] == slow[3]


def test_member_key_dedup_in_nested_values(codec):
    h = "2026-01-01T00:00:01.123Z-004D-n"
    # multi-char key: 1-char strings are interned by CPython anyway,
    # which would make this assertion vacuous
    payload = "{%s}" % ",".join(
        '"k%d":{"hlc":"%s","value":{"shared_key":"x","i":%d}}' % (i, h, i)
        for i in range(50))
    keys, lt_buf, nodes, values, bad = codec.parse_wire(payload)
    s_ids = {id(k) for v in values for k in v.keys() if k == "shared_key"}
    assert len(s_ids) == 1   # member keys shared, json.loads-memo style


class TestWireAssembler:
    """`format_wire` one-pass JSON assembly: byte-identity with the
    json.dumps dict build across the value/key space."""

    def _dumps(self):
        import functools
        import json as json_mod
        return functools.partial(json_mod.dumps, separators=(",", ":"),
                                 ensure_ascii=False)

    def test_scalar_space_byte_identity(self, codec):
        import json as json_mod
        h = "2026-01-01T00:00:01.123Z-004D-n"
        values = [None, True, False, 0, -7, 10 ** 30, -(10 ** 30),
                  1.5, -0.0, 2.5e-10, float("nan"), float("inf"),
                  float("-inf"), "plain", 'q"uo\\te', "tab\there",
                  "ctrl\x01\x1f", "émoji😀", "", {"n": [1, None]},
                  [1, "two", 3.5]]
        keys = [f"key-{i}é" for i in range(len(values))]
        out = codec.format_wire(keys, [h] * len(values), values,
                                self._dumps())
        expect = json_mod.dumps(
            {k: {"hlc": h, "value": v} for k, v in zip(keys, values)},
            separators=(",", ":"), ensure_ascii=False)
        assert out == expect

    def test_int_keys_and_escaped_hlc(self, codec):
        import json as json_mod
        h = '2026-01-01T00:00:01.123Z-004D-n"quote\\x'
        out = codec.format_wire([0, 42, -3], [h] * 3, [1, None, 2],
                                self._dumps())
        expect = json_mod.dumps(
            {"0": {"hlc": h, "value": 1}, "42": {"hlc": h, "value": None},
             "-3": {"hlc": h, "value": 2}},
            separators=(",", ":"), ensure_ascii=False)
        assert out == expect

    def test_exotic_key_defers(self, codec):
        h = "2026-01-01T00:00:01.123Z-004D-n"
        assert codec.format_wire([("tuple",)], [h], [1],
                                 self._dumps()) is None
        assert codec.format_wire([1 << 80], [h], [1],
                                 self._dumps()) is None

    def test_encode_collision_falls_back_to_dict_semantics(self):
        # dart_str(3) == dart_str("3"): colliding stringified keys
        # must collapse dict-style, exactly like the generic path.
        from crdt_tpu import Hlc, Record
        h = Hlc(1_700_000_000_000, 0, "n")
        rm = {3: Record(h, "int3", h), "3": Record(h, "str3", h)}
        out = crdt_json.encode(rm)
        import json as json_mod
        parsed = json_mod.loads(out)
        assert parsed == {"3": {"hlc": str(h), "value": "str3"}}

    def test_empty(self, codec):
        assert codec.format_wire([], [], [], self._dumps()) == "{}"


def test_surrogate_values_defer_to_python_encode(codec):
    """Lone surrogates (not UTF-8 encodable) anywhere in the payload —
    value, key, node id — must defer the C paths, never raise; the
    Python encoder serializes them like json.dumps does."""
    import json as json_mod
    from crdt_tpu import Record
    h = Hlc(1_700_000_000_000, 0, "n")
    cases = [
        {"k": Record(h, "x\ud800y", h)},                     # value
        {"k\ud800": Record(h, 1, h)},                        # key
        {"k": Record(Hlc(1_700_000_000_000, 0, "n\ud800"),   # node id
                     1, Hlc(1_700_000_000_000, 0, "n\ud800"))},
    ]
    for rm in cases:
        out = crdt_json.encode(rm)
        assert json_mod.loads(out)  # round-trips through json.loads


def test_scatter_payload_rejects_non_int64_buffers(codec):
    """A non-int64 buffer would silently misindex (buffer_len/8 with
    4-byte elements reads garbage); the codec must refuse it."""
    import numpy as np
    payload = [None, None]
    ok_slots = np.array([0, 1], np.int64)
    ok_win = np.array([0], np.int64)
    codec.scatter_payload(payload, ok_slots, ok_win, ["a", "b"])
    assert payload[0] == "a"
    for bad in (np.array([0, 1], np.int32), np.array([0.0, 1.0])):
        with pytest.raises(TypeError):
            codec.scatter_payload(payload, bad, ok_win, ["a", "b"])
        with pytest.raises(TypeError):
            codec.scatter_payload(payload, ok_slots,
                                  bad[:1], ["a", "b"])


def test_stale_so_siblings_reaped():
    """Content-hash .so naming must not accumulate one stale binary per
    source update: a successful build unlinks AGED siblings with a
    different tag (fresh ones are spared — two live processes on
    different source versions must not delete each other's binaries
    and recompile forever; ADVICE r4). The current tag survives."""
    import os
    import sysconfig

    import crdt_tpu.native as native_pkg
    here = os.path.dirname(os.path.abspath(native_pkg.__file__))
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    stale = os.path.join(here, f"_hlccodec_{'0' * 12}{suffix}")
    with open(stale, "wb") as f:
        f.write(b"not a real so")
    two_days = 2 * 24 * 3600
    import time as _time
    old = _time.time() - two_days
    os.utime(stale, (old, old))
    fresh = os.path.join(here, f"_hlccodec_{'f' * 12}{suffix}")
    with open(fresh, "wb") as f:
        f.write(b"not a real so either")
    try:
        import importlib

        import crdt_tpu.native as n2
        # force a fresh load pass that takes the build branch: remove
        # the cached current .so so the builder runs and then reaps
        mod = load()
        cur = mod.__spec__.origin
        os.unlink(cur)
        n2._mod = None
        n2._tried = False
        try:
            mod2 = n2.load()
            assert mod2 is not None
            assert not os.path.exists(stale)     # aged: reaped
            assert os.path.exists(fresh)         # fresh: spared
        finally:
            importlib.reload(n2)
    finally:
        for leftover in (stale, fresh):
            if os.path.exists(leftover):
                os.unlink(leftover)


def test_decode_columns_deferred_item_curated_overflow(codec, monkeypatch):
    """A deferred (non-C-window) item whose millis exceed the int64
    lane packing must raise the same curated OverflowError as the
    batch path, not numpy's generic assignment error — in both native
    and pure-Python modes."""
    # Year 9000 parses fine (within ISO range) but (millis << 16)
    # exceeds int64: millis ~ 2.2e14 > 2^47.
    payload = ('{"k":{"hlc":"9000-01-01T00:00:00.000Z-0000-n1",'
               '"value":1}}')
    with pytest.raises(OverflowError, match="scalar MapCrdt"):
        crdt_json.decode_columns(payload)
    monkeypatch.setattr(crdt_json.native, "load", lambda: None)
    with pytest.raises(OverflowError, match="scalar MapCrdt"):
        crdt_json.decode_columns(payload)


def test_dump_values_differential_vs_json_dumps(codec):
    """The C value writer must parse-match json.dumps on everything
    format_wire's value field models — scalars, containers, weird
    floats, unicode, nesting."""
    import json as json_mod
    cases = [None, True, False, 0, -1, 2**70, 1.5, float("nan"),
             float("inf"), "", "a\"b\\c", "ünïcode\n\t", {"k": [1, {"n":
             None}]}, [1, [2, [3, {"d": "x"}]]], {"": ""},
             {"num": 1e-7}, (1, 2), {"mixed": [True, None, "s", 3.25]}]
    texts = codec.dump_values(cases, json_mod.dumps)
    for v, t in zip(cases, texts):
        expect = json_mod.loads(json_mod.dumps(v))
        got = json_mod.loads(t)
        if isinstance(v, float) and v != v:
            assert got != got
        else:
            assert got == expect, (v, t)


def test_dump_values_surrogate_falls_back_per_item(codec):
    import json as json_mod
    texts = codec.dump_values(["ok", "bad\ud800"], json_mod.dumps)
    assert texts[0] == '"ok"'
    assert json_mod.loads(texts[1]) == "bad\ud800"


def test_parse_wire_raw_hlc_strings(codec):
    """want_hlc returns the raw wire hlc strings byte-equal to what
    str(hlc) would re-derive for canonical shapes, None for deferred
    shapes; duplicate keys keep last-value semantics."""
    h = "2023-05-06T07:08:09.123Z-00AB-nodeZ"
    weird = "2023-05-06 07:08:09.123+00:00-0001-n2"   # non-canonical
    payload = (f'{{"a":{{"hlc":"{h}","value":1}},'
               f'"b":{{"hlc":"{weird}","value":2}},'
               f'"a":{{"hlc":"{h}","value":9}}}}')
    keys, lt_buf, nodes, values, bad, hlcs = codec.parse_wire(
        payload, True)
    assert keys == ["a", "b"]
    assert hlcs[0] == h and hlcs[1] is None
    assert values == [9, 2]           # last value, first position
    # and the 5-tuple form is unchanged
    assert len(codec.parse_wire(payload)) == 5

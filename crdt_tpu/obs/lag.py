"""Convergence-lag derivation: "how far behind is replica B?".

Merkle-CRDTs answer divergence questions by comparing DAG roots; the
operational analogue for this LWW/HLC lattice is **HLC-delta lag**
computed from state the gossip runtime already keeps:

- ``peer.watermark`` is the local canonical time captured at the start
  of the last COMPLETED anti-entropy round with that peer (the delta
  ``since`` bound, persisted across restarts).
- Everything this replica wrote after the watermark has therefore not
  been confirmed through a round with that peer.

So per peer:

- ``lag_ms``  = local HLC head millis − watermark millis (clamped at
  0; both are HLC fields, no wall-clock read involved). ``None`` when
  the peer has never completed a round — unbounded, not zero.
- ``pending_records`` = ``crdt.count_modified_since(watermark)`` —
  the records a next delta round would carry (an upper-bound estimate:
  records the peer obtained out-of-band are still counted).

`GossipNode.lag_snapshot()` / `GossipNode.health()` assemble these
under the right locks; the helpers here are pure so they test without
sockets and render identically everywhere (CLI, metrics op, docs).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..hlc import Hlc

# Breaker states that mean the runtime is actively avoiding the peer.
_UNHEALTHY_BREAKER = ("open", "half_open")


def lag_millis(local_head: Hlc, watermark: Optional[Hlc]
               ) -> Optional[int]:
    """HLC-delta staleness in milliseconds, ``None`` when the peer has
    never completed a round (unbounded lag, not zero)."""
    if watermark is None:
        return None
    return max(0, local_head.millis - watermark.millis)


def lag_entry(local_head: Hlc, watermark: Optional[Hlc], *,
              pending: Optional[int] = None,
              breaker: Optional[str] = None,
              dense: Optional[bool] = None,
              last_error: Optional[BaseException] = None
              ) -> Dict[str, Any]:
    """One peer's staleness row — the shape `health()`, the ``metrics``
    wire op, the fleet lag matrix, and the CLI all share.
    ``seconds_behind`` is the same HLC-millis delta in seconds (the
    unit the fleet poller's convergence-SLO budget is expressed in);
    ``None`` when unsynced, like ``lag_ms``."""
    ms = lag_millis(local_head, watermark)
    return {
        "watermark": None if watermark is None else str(watermark),
        "synced": watermark is not None,
        "lag_ms": ms,
        "seconds_behind": None if ms is None else ms / 1000.0,
        "pending_records": pending,
        "breaker": breaker,
        "dense": dense,
        "last_error": (None if last_error is None
                       else f"{type(last_error).__name__}: "
                            f"{last_error}"),
    }


def health_status(peers: Dict[str, Dict[str, Any]],
                  stale_after_ms: int = 60_000) -> str:
    """``"ok"`` unless some peer is unreachable-by-policy (breaker
    open/half-open), never synced, or staler than ``stale_after_ms``."""
    for entry in peers.values():
        if entry.get("breaker") in _UNHEALTHY_BREAKER:
            return "degraded"
        if not entry.get("synced"):
            return "degraded"
        lag = entry.get("lag_ms")
        if lag is not None and lag > stale_after_ms:
            return "degraded"
    return "ok"

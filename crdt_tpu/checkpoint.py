"""Checkpoint / resume (SURVEY.md §5).

The reference's checkpoint mechanism IS its wire format: ``toJson`` is
the snapshot, ``mergeJson`` the restore, and construction-time
``refreshCanonicalTime`` the resume path (crdt.dart:31-33,100-135) —
persistent backends subclass `Crdt` (README.md:39). That path is kept
verbatim here (:func:`save_json` / :func:`load_json`), plus what the
reference can't have: a **columnar native snapshot** of the packed
device lanes (:func:`save_dense` / :func:`load_dense`) that round-trips
a `DenseStore` through one ``npz`` file without per-record encoding.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Optional, Type

import numpy as np

import jax.numpy as jnp

from .crdt import Crdt
from .obs.trace import tracer as _tracer
from .ops.dense import DenseStore
from .record import (KeyDecoder, KeyEncoder, ValueDecoder, ValueEncoder)


def _note(action: str, path: str, start: float, hlc=None) -> None:
    """Account one completed checkpoint op: bump the process counter,
    and — when the tracer is on — emit an HLC-stamped ``checkpoint``
    event with duration and on-disk size. Checkpoints are rare and
    already did file I/O, so this is never on a hot path."""
    from .obs.registry import default_registry
    default_registry().counter(
        "crdt_tpu_checkpoints_total",
        "checkpoint save/load operations by action").inc(action=action)
    ring = _tracer()
    if ring.enabled:
        fields = {"action": action, "path": path,
                  "dur_s": time.perf_counter() - start}
        try:
            fields["bytes"] = os.path.getsize(path)
        except OSError:
            pass
        ring.emit("checkpoint", hlc=hlc, **fields)


def save_json(crdt: Crdt, path: str,
              key_encoder: Optional[KeyEncoder] = None,
              value_encoder: Optional[ValueEncoder] = None) -> None:
    """Snapshot via the wire format — full state including tombstones
    (crdt.dart:124-135). Any conformant backend can restore it."""
    start = time.perf_counter()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(crdt.to_json(key_encoder=key_encoder,
                             value_encoder=value_encoder))
    os.replace(tmp, path)
    _note("save_json", path, start, hlc=crdt.canonical_time)


def load_json(cls: Type[Crdt], node_id: Any, path: str,
              key_decoder: Optional[KeyDecoder] = None,
              value_decoder: Optional[ValueDecoder] = None,
              wall_clock: Optional[Callable[[], int]] = None,
              **kwargs) -> Crdt:
    """Restore a replica from its own snapshot.

    This is the reference's resume-from-storage path — records are
    seeded into the backend and the canonical clock is rebuilt from
    their max logical time (`refreshCanonicalTime`, crdt.dart:31-33,
    114-121). NOT a merge: merging records you authored back into a
    fresh replica with the same node id trips the duplicate-node guard
    by design (hlc.dart:88-90). To ingest ANOTHER replica's snapshot,
    use ``crdt.merge_json`` directly."""
    from . import crdt_json
    from .hlc import Hlc

    start = time.perf_counter()
    with open(path) as f:
        records = crdt_json.decode(
            f.read(), Hlc.zero(node_id),
            key_decoder=key_decoder, value_decoder=value_decoder,
            now_millis=wall_clock() if wall_clock else None)
    crdt = cls(node_id, seed=records, wall_clock=wall_clock, **kwargs)
    _note("load_json", path, start, hlc=crdt.canonical_time)
    return crdt


_GOSSIP_STATE_MAGIC = "crdt_tpu/gossip-state@1"


def save_gossip_state(path: str, node_id: Any,
                      watermarks: dict) -> None:
    """Durable per-peer watermark table for the gossip runtime
    (`crdt_tpu.gossip.GossipNode`): ``{peer name: Hlc}``, written
    atomically (tmp + rename, same discipline as the snapshots above)
    so a crash mid-write leaves the previous state intact.

    The watermark is the only state a restarted node needs to resume
    DELTA sync instead of re-pulling full peer state — the replica
    contents themselves persist through :func:`save_json` /
    :func:`load_json` (or a durable backend like `SqliteCrdt`).
    ``node_id`` is recorded so a state file restored onto the wrong
    node is rejected instead of silently skipping records."""
    start = time.perf_counter()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"magic": _GOSSIP_STATE_MAGIC,
                   "node_id": str(node_id),
                   "watermarks": {str(name): str(hlc)
                                  for name, hlc in watermarks.items()
                                  if hlc is not None}}, f)
    os.replace(tmp, path)
    _note("save_gossip_state", path, start)


def load_gossip_state(path: str, node_id: Any) -> dict:
    """Load a watermark table saved by :func:`save_gossip_state`;
    ``{}`` when the file does not exist (cold start). Raises
    ``ValueError`` on a foreign file or another node's state —
    resuming from someone else's watermarks would skip records."""
    from .hlc import Hlc

    if not os.path.exists(path):
        return {}
    with open(path) as f:
        state = json.load(f)
    if not isinstance(state, dict) \
            or state.get("magic") != _GOSSIP_STATE_MAGIC:
        raise ValueError(f"not a gossip state file: {path}")
    if state.get("node_id") != str(node_id):
        raise ValueError(
            f"{path} holds watermarks for node "
            f"{state.get('node_id')!r}, not {node_id!r}")
    return {name: Hlc.parse(mark)
            for name, mark in state.get("watermarks", {}).items()}


_DENSE_MAGIC_V1 = "crdt_tpu/dense-store@1"
_DENSE_MAGIC = "crdt_tpu/dense-store@2"


def save_dense(store: DenseStore, path: str,
               node_ids: Optional[list] = None,
               digest: Optional[tuple] = None) -> None:
    """Columnar snapshot: one compressed npz of the seven lanes, plus
    the node-id interning table when given — the ``node``/``mod_node``
    ordinal lanes are meaningless without it, so model-level snapshots
    (`DenseCrdt.save`) always include it.

    ``digest`` optionally persists the Merkle digest tree alongside
    the lanes as ``(DigestTree, logical_time, sem_version)`` — the
    tree plus the exact cache key it was computed under
    (docs/ANTIENTROPY.md). A restart can then seed its digest cache
    and answer the first anti-entropy walk with ZERO device
    dispatches. Extra npz entries are invisible to older readers
    (loads only touch known keys), so digest-bearing snapshots stay
    backward readable."""
    start = time.perf_counter()
    tmp = path + ".tmp"
    extra = ({} if node_ids is None
             else {"node_ids": np.array(json.dumps(list(node_ids)))})
    if digest is not None:
        tree, logical_time, sem_version = digest
        # Root-first levels have widths 1, 2, 4, ..., n_leaves — fully
        # determined by depth — so one flat concatenation round-trips.
        extra["digest_tree"] = np.concatenate(
            [np.asarray(lvl, np.uint64) for lvl in tree.levels])
        extra["digest_meta"] = np.array(json.dumps({
            "n_slots": int(tree.n_slots),
            "leaf_width": int(tree.leaf_width),
            "depth": int(tree.depth),
            "logical_time": int(logical_time),
            "sem_version": int(sem_version)}))
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f, magic=np.array(_DENSE_MAGIC), **extra,
            **{lane: np.asarray(getattr(store, lane))
               for lane in DenseStore._fields})
    os.replace(tmp, path)
    _note("save_dense", path, start)


def _validated_npz(z, path: str):
    if str(z["magic"]) not in (_DENSE_MAGIC, _DENSE_MAGIC_V1):
        raise ValueError(f"not a dense-store snapshot: {path}")
    return z


def load_dense_with_node_ids(path: str):
    """One-open load of ``(DenseStore, node_ids-or-None)``. ``None``
    marks a lane-only (v1 / store-level) snapshot whose ordinal lanes
    only a caller holding the original table can interpret."""
    start = time.perf_counter()
    with np.load(path) as z:
        _validated_npz(z, path)
        store = DenseStore(**{lane: jnp.asarray(z[lane])
                              for lane in DenseStore._fields})
        ids = (json.loads(str(z["node_ids"]))
               if "node_ids" in z else None)
    _note("load_dense", path, start)
    return store, ids


def load_dense(path: str) -> DenseStore:
    return load_dense_with_node_ids(path)[0]


def load_dense_digest(path: str) -> Optional[tuple]:
    """The persisted Merkle digest tree and its cache key:
    ``(DigestTree, logical_time, sem_version)``, or None for
    snapshots saved without one (including every pre-digest
    snapshot). Malformed digest entries also answer None — the tree
    is a pure cache, so the correct degradation is 'rebuild on first
    walk', never a failed restore."""
    from .ops.digest import DigestTree

    with np.load(path) as z:
        _validated_npz(z, path)
        if "digest_tree" not in z or "digest_meta" not in z:
            return None
        try:
            meta = json.loads(str(z["digest_meta"]))
            depth = int(meta["depth"])
            flat = np.asarray(z["digest_tree"], np.uint64)
            widths = [1 << lvl for lvl in range(depth)]
            if int(flat.shape[0]) != sum(widths):
                return None
            levels, off = [], 0
            for w in widths:
                levels.append(flat[off:off + w].copy())
                off += w
            tree = DigestTree(n_slots=int(meta["n_slots"]),
                              leaf_width=int(meta["leaf_width"]),
                              levels=tuple(levels))
            return (tree, int(meta["logical_time"]),
                    int(meta["sem_version"]))
        except (KeyError, TypeError, ValueError):
            return None


def load_dense_node_ids(path: str) -> Optional[list]:
    """The node-id table a snapshot's ordinal lanes index into, or None
    for lane-only (v1 / store-level) snapshots."""
    with np.load(path) as z:
        _validated_npz(z, path)
        if "node_ids" not in z:
            return None
        return json.loads(str(z["node_ids"]))

"""crdt_tpu — a TPU-native CRDT framework.

A brand-new JAX/XLA/Pallas implementation of a hybrid-logical-clock,
last-writer-wins map CRDT with delta sync, matching the capabilities of
the reference Dart package (siliconsorcery/crdt v4.0.2) with a TPU-first
architecture:

- Scalar host path (`Hlc`, `MapCrdt`) — the semantic oracle, matching
  the reference's behavior including golden wire strings.
- TPU path (`TpuMapCrdt`, `crdt_tpu.ops`) — HLCs packed into sortable
  (int64 logical_time, int32 node-ordinal) lanes; merge is a batched
  vectorized lattice join; multi-replica fan-in is a segmented
  lexicographic max reduction.
- Parallel path (`crdt_tpu.parallel`, in progress) — key-space sharding
  over a `jax.sharding.Mesh` with replica fan-in collectives over
  ICI/DCN.

Barrel export mirrors the reference's `lib/crdt.dart`.
"""

from .hlc import (Hlc, ClockDriftException, DuplicateNodeException,
                  OverflowException, MAX_COUNTER, MAX_DRIFT,
                  wall_clock_millis)
from .record import (Record, KeyDecoder, KeyEncoder, NodeIdDecoder,
                     ValueDecoder, ValueEncoder)
from .crdt import Crdt
from .crdt_json import CrdtJson, dart_str
from .watch import ChangeEvent, ChangeStream
from .models.map_crdt import MapCrdt
from .models.tpu_map_crdt import TpuMapCrdt
from .models.dense_crdt import (DenseCrdt, PipelinedGuardError,
                                ShardedDenseCrdt, sync_dense)
from .models.keyed_dense import KeyedDenseCrdt
from .models.sqlite_crdt import SqliteCrdt
from .sync import (sync, sync_collective, sync_json, sync_merkle,
                   sync_packed)
from .collective import CollectiveGroup, CollectiveJoinReport
from .net import (FrameCodec, PeerConnection, SyncError,
                  SyncProtocolError, SyncRedirectError, SyncServer,
                  SyncTransportError, WireTally, fetch_metrics,
                  sync_dense_over_conn, sync_dense_over_tcp,
                  sync_merkle_over_conn, sync_over_conn, sync_over_tcp,
                  sync_packed_over_conn)
from .serve import ServeTier
from .routing import PartitionRouter, RoutingTable
from .federation import FederatedClient, FederatedTier
from .autoscale import Autoscaler
from .replication import ReplicaGroup, Replicator
from .ops.packing import PackedDelta
from .obs import (MetricsRegistry, TraceRing, default_registry,
                  metrics_snapshot, tracer)
from .checkpoint import (load_dense, load_gossip_state, load_json,
                         save_dense, save_gossip_state, save_json)
from .gossip import (BreakerPolicy, CircuitBreaker, GossipNode, Peer,
                     RetryPolicy)

__version__ = "0.5.0"

__all__ = [
    "Hlc", "ClockDriftException", "DuplicateNodeException",
    "OverflowException", "MAX_COUNTER", "MAX_DRIFT", "wall_clock_millis",
    "Record", "KeyDecoder", "KeyEncoder", "NodeIdDecoder", "ValueDecoder",
    "ValueEncoder", "Crdt", "CrdtJson", "dart_str", "ChangeEvent",
    "ChangeStream", "MapCrdt", "TpuMapCrdt", "DenseCrdt",
    "ShardedDenseCrdt", "KeyedDenseCrdt", "PipelinedGuardError",
    "sync_dense", "SqliteCrdt",
    "sync", "sync_json", "sync_packed", "sync_merkle",
    "sync_collective", "CollectiveGroup", "CollectiveJoinReport",
    "SyncServer",
    "sync_dense_over_tcp", "sync_over_tcp",
    "PeerConnection", "FrameCodec", "PackedDelta",
    "sync_over_conn", "sync_dense_over_conn", "sync_packed_over_conn",
    "sync_merkle_over_conn",
    "SyncError", "SyncTransportError", "SyncProtocolError",
    "SyncRedirectError", "WireTally",
    "fetch_metrics", "ServeTier",
    "RoutingTable", "PartitionRouter", "FederatedTier",
    "FederatedClient", "Autoscaler", "ReplicaGroup", "Replicator",
    "GossipNode", "Peer", "RetryPolicy", "BreakerPolicy", "CircuitBreaker",
    "load_dense", "load_json", "save_dense", "save_json",
    "load_gossip_state", "save_gossip_state",
    "MetricsRegistry", "TraceRing", "default_registry",
    "metrics_snapshot", "tracer",
]

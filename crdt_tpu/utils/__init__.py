"""Utilities: merge observability (stats counters, profiler spans)."""

from .stats import MergeStats, merge_annotation

__all__ = ["MergeStats", "merge_annotation"]

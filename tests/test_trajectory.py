"""Bench trajectory (benchmarks/README.md): the normalized record
schema, fastest-of-N floors with `evaluate_slo` semantics (unmeasured
!= passed), and the ``python -m crdt_tpu.obs bench --compare`` exit
codes — including the planted-regression fixture the CI smoke gate is
proven against: exit 1 on the regressed candidate, exit 0 on the clean
one, exit 2 when nothing was comparable."""

import io
import json
import os

import pytest

from crdt_tpu.obs.trajectory import (append_record, bench_main, compare,
                                     flatten_metrics, load_trajectory,
                                     metric_direction, normalize_record)

pytestmark = pytest.mark.trajectory

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
BASELINE = os.path.join(FIXTURES, "trajectory_baseline.jsonl")
REGRESSED = os.path.join(FIXTURES, "trajectory_regressed.jsonl")
CLEAN = os.path.join(FIXTURES, "trajectory_clean.jsonl")


def _rec(run_id, metrics, mode="sync", host="ci-fixture", smoke=True):
    return {"run_id": run_id, "mode": mode, "git_sha": "f00",
            "host_class": host, "smoke": smoke, "metrics": metrics,
            "slo": None}


# --- schema ----------------------------------------------------------

def test_flatten_metrics_dotted_numeric_leaves():
    flat = flatten_metrics({
        "merge_ms": 3, "ok": True, "name": "x", "none": None,
        "cold_peer": {"bytes_per_s": 9.5, "nested": {"depth_ms": 1}},
        "list": [1, 2]})
    assert flat == {"merge_ms": 3.0, "cold_peer.bytes_per_s": 9.5,
                    "cold_peer.nested.depth_ms": 1.0}


def test_metric_direction_heuristic():
    assert metric_direction("merge_ms") == "lower"
    assert metric_direction("cold_peer.fetch_latency") == "lower"
    assert metric_direction("merges_per_sec") == "higher"
    assert metric_direction("pooled_speedup") == "higher"
    # config echoes, counts and self-gated metrics never auto-compare
    assert metric_direction("rounds") is None
    assert metric_direction("n_slots") is None
    assert metric_direction("merkle_bytes") is None
    assert metric_direction("ledger_overhead_budget_frac") is None
    assert metric_direction("ledger_overhead_frac") is None


def test_normalize_record_schema_and_slo():
    rec = normalize_record(
        "sync", {"merge_ms": 2.5, "slo": {"checks": {}, "ok": True}},
        run_id="r1", sha="abc", host="h", smoke=True, source="SRC")
    assert rec["run_id"] == "r1"
    assert rec["mode"] == "sync"
    assert rec["git_sha"] == "abc"
    assert rec["host_class"] == "h"
    assert rec["smoke"] is True
    assert rec["metrics"] == {"merge_ms": 2.5}
    assert rec["slo"] == {"checks": {}, "ok": True}
    assert rec["source"] == "SRC"


def test_append_and_load_roundtrip_skips_torn_lines(tmp_path):
    path = str(tmp_path / "t.jsonl")
    append_record(_rec("a", {"merge_ms": 1.0}), path)
    with open(path, "a") as f:
        f.write('{"torn": \n')          # torn append
        f.write("not json either\n")
    append_record(_rec("b", {"merge_ms": 2.0}), path)
    recs = load_trajectory(path)
    assert [r["run_id"] for r in recs] == ["a", "b"]


# --- compare semantics ----------------------------------------------

def _baseline():
    return [_rec("b1", {"merge_ms": 10.2, "merges_per_sec": 980.0}),
            _rec("b2", {"merge_ms": 10.0, "merges_per_sec": 1000.0}),
            _rec("b3", {"merge_ms": 10.5, "merges_per_sec": 950.0})]


def test_compare_fastest_of_n_floors():
    v = compare(_baseline(), _rec("c", {"merge_ms": 10.4,
                                        "merges_per_sec": 990.0}))
    assert v["ok"] is True
    assert v["checks"]["merge_ms"]["baseline"] == 10.0       # min
    assert v["checks"]["merges_per_sec"]["baseline"] == 1000.0  # max
    assert v["compared"] == 2


def test_compare_flags_regression_outside_budget():
    v = compare(_baseline(), _rec("c", {"merge_ms": 20.0,
                                        "merges_per_sec": 990.0}))
    assert v["ok"] is False
    assert v["checks"]["merge_ms"]["ok"] is False
    assert v["checks"]["merges_per_sec"]["ok"] is True


def test_compare_unmeasured_is_not_passed():
    # candidate metric absent from every baseline run -> unmeasured
    v = compare(_baseline(), _rec("c", {"fresh_ms": 1.0}))
    assert v["ok"] is None          # zero measured checks: NOT ok
    assert v["compared"] == 0
    assert v["unmeasured"] == 1


def test_compare_groups_never_cross_hosts():
    v = compare(_baseline(), _rec("c", {"merge_ms": 99.0},
                                  host="other-host"))
    assert v["baseline_runs"] == []
    assert v["ok"] is None


def test_compare_zero_floor_is_unmeasured_not_regressed():
    base = [_rec("b", {"warm_ms": 0.0})]
    v = compare(base, _rec("c", {"warm_ms": 0.031}))
    assert v["checks"]["warm_ms"]["ok"] is None
    assert v["ok"] is None


def test_compare_explicit_metric_list_surfaces_unclassifiable():
    v = compare(_baseline(), _rec("c", {"rounds": 64.0}),
                metrics=["rounds"])
    assert v["checks"]["rounds"]["ok"] is None
    assert v["unmeasured"] == 1


# --- the CI gate (exit codes over the planted fixtures) -------------

def test_gate_exits_nonzero_on_planted_regression():
    out = io.StringIO()
    rc = bench_main(["--compare", BASELINE, "--candidate", REGRESSED],
                    out)
    assert rc == 1
    assert "REGRESSED" in out.getvalue()
    assert "merge_ms" in out.getvalue()


def test_gate_exits_zero_on_clean_rerun():
    out = io.StringIO()
    rc = bench_main(["--compare", BASELINE, "--candidate", CLEAN], out)
    assert rc == 0
    assert "REGRESSED" not in out.getvalue()


def test_gate_self_trajectory_mode(tmp_path):
    # append-then-gate: the series' own last record is the candidate
    path = str(tmp_path / "t.jsonl")
    for rec in load_trajectory(BASELINE):
        append_record(rec, path)
    append_record(json.load(open(REGRESSED)), path)
    assert bench_main(["--compare", path], io.StringIO()) == 1
    path2 = str(tmp_path / "t2.jsonl")
    for rec in load_trajectory(BASELINE):
        append_record(rec, path2)
    append_record(json.load(open(CLEAN)), path2)
    assert bench_main(["--compare", path2], io.StringIO()) == 0


def test_gate_exit_2_when_nothing_comparable(tmp_path):
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert bench_main(["--compare", empty], io.StringIO()) == 2
    # a lone record has no baseline pool: unmeasured != passed
    lone = str(tmp_path / "lone.jsonl")
    append_record(_rec("only", {"merge_ms": 1.0}), lone)
    assert bench_main(["--compare", lone], io.StringIO()) == 2


def test_gate_json_output():
    out = io.StringIO()
    rc = bench_main(["--compare", BASELINE, "--candidate", REGRESSED,
                     "--json"], out)
    payload = json.loads(out.getvalue())
    assert rc == 1
    assert payload["candidate"] == "fix-cand-regressed"
    assert payload["verdict"]["ok"] is False


def test_gate_budget_override_loosens_the_floor():
    rc = bench_main(["--compare", BASELINE, "--candidate", REGRESSED,
                     "--budget", "2.0"], io.StringIO())
    assert rc == 0

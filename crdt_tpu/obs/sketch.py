"""Mergeable relative-error quantile sketch (DDSketch-style).

The registry's log2 histograms answer quantile queries with *bucket
ceilings*: ``histogram_quantile`` on a sample whose true p99 is 16 ms
reports 31.25 ms, because 16 ms lands in the (15.625, 31.25] bucket
and the upper bound is all a fixed-bucket histogram can promise. Any
SLO envelope that does not sit exactly on a power of two — the
measured 14.6 ms SERVE_r01 ack envelope, say — is therefore
unexpressible as a histogram gate (docs/OBSERVABILITY.md).

:class:`QuantileSketch` fixes that with γ-indexed logarithmic buckets:
for relative accuracy ``α`` it uses ``γ = (1+α)/(1−α)`` and maps a
positive value ``v`` to bucket ``ceil(log_γ(v))``, so the bucket
midpoint estimate ``2·γ^k/(γ+1)`` is within ``α·v`` of every value in
the bucket. With the default α = 1% that is ~230 buckets per decade —
sparse dict storage keeps only the touched ones, and a collapsing
bound folds the *lowest* buckets together when the sketch grows past
``max_bins``, preserving upper-quantile (p99) accuracy exactly where
SLO gates look.

The sketch is deliberately CRDT-shaped:

- :meth:`merge` adds per-bucket counts — **commutative** and
  **associative** (collapse is canonical: lowest keys fold upward
  deterministically given the final bucket multiset), with the
  relative-error bound **preserved** across any merge order. The
  property obligations are executable: ``tests/test_sketch.py``
  checks the laws under 64-way merge permutations.
- Serialization is self-describing (:meth:`to_dict` for the JSON
  wire, :meth:`to_bytes` for compact binary) so per-replica sketches
  ship on the ``metrics`` op and fold into fleet-true quantiles in
  ``obs/fleet.py`` — the same delta/state composition discipline the
  store CRDTs follow.

Zero dependencies beyond the standard library; nothing here imports
JAX or the registry (the labelled ``Sketch`` instrument lives in
``obs/registry.py`` beside Counter/Gauge/Histogram).
"""

from __future__ import annotations

import math
import struct
from typing import Any, Dict, Iterable, List, Optional

DEFAULT_RELATIVE_ACCURACY = 0.01
DEFAULT_MAX_BINS = 512

# Compact binary frame: magic, relative accuracy, running sum, zero
# count, total count, number of sparse bins; then (key, count) pairs.
_HEADER = struct.Struct("<4sddQQI")
_BIN = struct.Struct("<qQ")
_MAGIC = b"QSK1"


class QuantileSketch:
    """Sparse γ-indexed log-bucket quantile sketch.

    ``relative_accuracy`` is the guaranteed bound: for any quantile
    that falls above the collapse region, the estimate ``m`` satisfies
    ``|m − v| ≤ relative_accuracy · v`` for the true order statistic
    ``v``. Values ``<= 0`` land in a dedicated zero bucket (latencies
    are non-negative; a clock that runs backwards should not crash the
    scrape path).
    """

    def __init__(self, relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY,
                 max_bins: int = DEFAULT_MAX_BINS):
        if not 0.0 < relative_accuracy < 1.0:
            raise ValueError("relative_accuracy must be in (0, 1)")
        if max_bins < 2:
            raise ValueError("need max_bins >= 2")
        self.relative_accuracy = float(relative_accuracy)
        self.max_bins = int(max_bins)
        self.gamma = (1.0 + self.relative_accuracy) / \
                     (1.0 - self.relative_accuracy)
        self._log_gamma = math.log(self.gamma)
        self.bins: Dict[int, int] = {}
        self.zeros = 0
        self.count = 0
        self.sum = 0.0

    # --- recording ---

    def key_for(self, value: float) -> int:
        """Bucket key for a positive value: ``ceil(log_γ(v))`` — v in
        ``(γ^(k−1), γ^k]`` maps to k."""
        return int(math.ceil(math.log(value) / self._log_gamma
                             - 1e-12))

    def value_for(self, key: int) -> float:
        """Midpoint estimate for bucket ``key`` — within the relative
        accuracy of every value the bucket covers."""
        return 2.0 * self.gamma ** key / (self.gamma + 1.0)

    def record(self, value: float, count: int = 1) -> None:
        if count <= 0:
            return
        if value <= 0.0:
            self.zeros += count
        else:
            k = self.key_for(value)
            self.bins[k] = self.bins.get(k, 0) + count
        self.count += count
        self.sum += value * count
        if len(self.bins) > self.max_bins:
            self._collapse()

    def _collapse(self) -> None:
        # Fold the lowest buckets upward until the bound holds. The
        # fold direction is the whole trick: p50/p99 gates read the
        # top of the distribution, so accuracy is sacrificed only at
        # the bottom. Deterministic given the final bucket multiset,
        # which is what keeps merge order-independent.
        keys = sorted(self.bins)
        i = 0
        while len(keys) - i > self.max_bins:
            k0, k1 = keys[i], keys[i + 1]
            self.bins[k1] += self.bins.pop(k0)
            i += 1

    # --- queries ---

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the ``q``-quantile (0 ≤ q ≤ 1); ``None`` when the
        sketch is empty (unmeasured ≠ zero)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be in [0, 1]")
        if self.count == 0:
            return None
        rank = q * (self.count - 1)
        cum = self.zeros
        if cum > rank:
            return 0.0
        last = 0.0
        for k in sorted(self.bins):
            cum += self.bins[k]
            last = self.value_for(k)
            if cum > rank:
                return last
        return last  # floating-point slack on rank; top bucket

    # --- merge (commutative, associative, error-preserving) ---

    def copy(self) -> "QuantileSketch":
        out = QuantileSketch(self.relative_accuracy, self.max_bins)
        out.bins = dict(self.bins)
        out.zeros = self.zeros
        out.count = self.count
        out.sum = self.sum
        return out

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into this sketch in place; returns ``self``.

        Requires matching γ (same ``relative_accuracy``) — merging
        differently-indexed sketches would silently discard the error
        bound, so it raises instead.
        """
        if abs(other.gamma - self.gamma) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different relative "
                f"accuracy ({self.relative_accuracy} vs "
                f"{other.relative_accuracy})")
        for k, c in other.bins.items():
            self.bins[k] = self.bins.get(k, 0) + c
        self.zeros += other.zeros
        self.count += other.count
        self.sum += other.sum
        if len(self.bins) > self.max_bins:
            self._collapse()
        return self

    # --- serialization ---

    def to_dict(self) -> dict:
        """JSON-safe snapshot (rides the ``metrics`` wire op behind
        the negotiated ``sketch`` cap)."""
        return {"relative_accuracy": self.relative_accuracy,
                "max_bins": self.max_bins,
                "zeros": self.zeros,
                "count": self.count,
                "sum": self.sum,
                "bins": {str(k): c for k, c in self.bins.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileSketch":
        out = cls(float(d.get("relative_accuracy",
                              DEFAULT_RELATIVE_ACCURACY)),
                  int(d.get("max_bins", DEFAULT_MAX_BINS)))
        out.bins = {int(k): int(c)
                    for k, c in dict(d.get("bins", {})).items()}
        out.zeros = int(d.get("zeros", 0))
        out.count = int(d.get("count", 0))
        out.sum = float(d.get("sum", 0.0))
        return out

    def to_bytes(self) -> bytes:
        """Compact binary form (checkpoint / debug-bundle payloads)."""
        parts = [_HEADER.pack(_MAGIC, self.relative_accuracy,
                              self.sum, self.zeros, self.count,
                              len(self.bins))]
        for k in sorted(self.bins):
            parts.append(_BIN.pack(k, self.bins[k]))
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes) -> "QuantileSketch":
        if len(data) < _HEADER.size:
            raise ValueError("truncated sketch frame")
        magic, acc, total, zeros, count, n = _HEADER.unpack_from(data)
        if magic != _MAGIC:
            raise ValueError(f"bad sketch magic {magic!r}")
        need = _HEADER.size + n * _BIN.size
        if len(data) < need:
            raise ValueError("truncated sketch frame")
        out = cls(acc)
        off = _HEADER.size
        for _ in range(n):
            k, c = _BIN.unpack_from(data, off)
            out.bins[k] = out.bins.get(k, 0) + c
            off += _BIN.size
        out.zeros = zeros
        out.count = count
        out.sum = total
        return out

    def __repr__(self) -> str:  # debugging aid only
        return (f"QuantileSketch(acc={self.relative_accuracy}, "
                f"count={self.count}, bins={len(self.bins)})")


def merge_sketches(
        sketches: Iterable[QuantileSketch]) -> Optional[QuantileSketch]:
    """Merge an iterable of sketches into a fresh one (inputs are not
    mutated); ``None`` when the iterable is empty. The fleet-true
    roll-up: per-replica ack sketches fold into one sketch whose
    quantiles hold fleet-wide with the same relative-error bound."""
    out: Optional[QuantileSketch] = None
    for sk in sketches:
        if out is None:
            out = sk.copy()
        else:
            out.merge(sk)
    return out


def sketch_from_sample(sample: Any) -> Optional[QuantileSketch]:
    """Rebuild a sketch from one wire ``samples()`` entry (a dict with
    a ``"sketch"`` payload) or a raw ``to_dict`` payload. Returns
    ``None`` on anything malformed — a half-upgraded peer's snapshot
    must degrade to unmeasured, not break the poller."""
    if not isinstance(sample, dict):
        return None
    payload = sample.get("sketch", sample)
    if not isinstance(payload, dict) or "bins" not in payload:
        return None
    try:
        return QuantileSketch.from_dict(payload)
    except (TypeError, ValueError):
        return None


def sketch_quantile(samples: List[Any], q: float) -> Optional[float]:
    """Merged ``q``-quantile across wire sample entries (all label
    sets of one instrument, or one entry per replica). ``None`` when
    nothing parseable carries data — unmeasured ≠ zero."""
    merged = merge_sketches(
        sk for sk in (sketch_from_sample(s) for s in samples)
        if sk is not None and sk.count > 0)
    if merged is None:
        return None
    return merged.quantile(q)

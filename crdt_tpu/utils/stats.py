"""Merge observability (SURVEY.md §5: tracing/metrics are absent in the
reference — the TPU build adds lightweight counters and profiler
annotations around the merge kernel).

`MergeStats` counts merges and record flow on a backend;
`merge_annotation` wraps the device dispatch in a
`jax.profiler.TraceAnnotation` so kernel time shows up named in TPU
profiles (`jax.profiler.trace` / tensorboard).
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass, field

import jax.profiler


@dataclass
class MergeStats:
    """Counters for one CRDT backend instance."""
    merges: int = 0            # merge() calls
    records_seen: int = 0      # remote records examined (winners+losers)
    records_adopted: int = 0   # LWW winners written
    puts: int = 0              # local write batches (put/put_all)
    records_put: int = 0       # local records written

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in
                ("merges", "records_seen", "records_adopted", "puts",
                 "records_put")}

    def reset(self) -> None:
        for k in self.as_dict():
            setattr(self, k, 0)


@contextmanager
def merge_annotation(name: str = "crdt_tpu.merge"):
    """Named span around a merge dispatch for TPU profile traces."""
    with jax.profiler.TraceAnnotation(name):
        yield

"""ShardedDenseCrdt on the virtual 8-device mesh: behaviorally
identical to the single-device DenseCrdt."""

import numpy as np
import pytest

import jax

from crdt_tpu import DuplicateNodeException
from crdt_tpu.models.dense_crdt import (DenseCrdt, ShardedDenseCrdt,
                                        sync_dense)
from crdt_tpu.parallel import make_fanin_mesh
from crdt_tpu.testing import FakeClock

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

N = 64
BASE = 1_700_000_000_000


def make_pair(mesh_shape=(2, 4)):
    mesh = make_fanin_mesh(*mesh_shape)
    sharded = ShardedDenseCrdt("ns", N, mesh,
                               wall_clock=FakeClock(start=BASE))
    plain = DenseCrdt("ns", N, wall_clock=FakeClock(start=BASE))
    return sharded, plain


def test_local_ops_match_plain():
    sharded, plain = make_pair()
    for c in (sharded, plain):
        c.put_batch([1, 5, 9], [10, 50, 90])
        c.delete_batch([5])
    assert sharded.get(1) == plain.get(1) == 10
    assert sharded.get(5) is plain.get(5) is None
    np.testing.assert_array_equal(np.asarray(sharded.store.val),
                                  np.asarray(plain.store.val))


@pytest.mark.parametrize("mesh_shape", [(2, 4), (1, 8), (4, 2)])
def test_sync_with_plain_replica(mesh_shape):
    mesh = make_fanin_mesh(*mesh_shape)
    a = ShardedDenseCrdt("na", N, mesh, wall_clock=FakeClock(start=BASE))
    b = DenseCrdt("nb", N, wall_clock=FakeClock(start=BASE + 7))
    a.put_batch([0, 1], [10, 11])
    b.put_batch([2], [22])
    sync_dense(a, b)
    for c in (a, b):
        assert c.get(0) == 10 and c.get(1) == 11 and c.get(2) == 22
    assert_occupied_lanes_equal(a, b)


def assert_occupied_lanes_equal(a, b):
    """Observable state only: unoccupied slots may hold divergent
    garbage (node-ordinal remaps rewrite them differently depending on
    each replica's interning history) and are filtered from every view
    (record_map semantics)."""
    occ = np.asarray(a.store.occupied)
    np.testing.assert_array_equal(occ, np.asarray(b.store.occupied))
    # node ordinals compare via the ids they name, not raw ints
    ids_a = [a._table.id_of(int(o)) for o in np.asarray(a.store.node)[occ]]
    ids_b = [b._table.id_of(int(o)) for o in np.asarray(b.store.node)[occ]]
    assert ids_a == ids_b
    for lane in ("lt", "val", "tomb"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.store, lane))[occ],
            np.asarray(getattr(b.store, lane))[occ], err_msg=lane)


def test_merge_many_fanin():
    mesh = make_fanin_mesh(2, 4)
    hub = ShardedDenseCrdt("hub", N, mesh, wall_clock=FakeClock(start=BASE))
    spokes = [DenseCrdt(f"n{i}", N,
                        wall_clock=FakeClock(start=BASE + 1 + i))
              for i in range(5)]
    for i, s in enumerate(spokes):
        s.put_batch([i, 10 + i], [100 + i, 200 + i])
    hub.merge_many([s.export_delta() for s in spokes])
    for i in range(5):
        assert hub.get(i) == 100 + i
        assert hub.get(10 + i) == 200 + i
    assert hub.stats.records_adopted == 10


def test_conflict_resolution_matches_plain():
    mesh = make_fanin_mesh(2, 4)
    writers = [DenseCrdt(f"w{i}", N, wall_clock=FakeClock(start=BASE + i))
               for i in range(4)]
    for i, w in enumerate(writers):
        w.put_batch([0, 1, 2], [i * 10, i * 10 + 1, i * 10 + 2])
    deltas = [w.export_delta() for w in writers]

    sharded = ShardedDenseCrdt("hub", N, mesh,
                               wall_clock=FakeClock(start=BASE + 99))
    plain = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 99))
    sharded.merge_many(list(deltas))
    plain.merge_many(list(deltas))
    assert_occupied_lanes_equal(sharded, plain)
    assert (sharded.canonical_time.logical_time
            == plain.canonical_time.logical_time)


def test_duplicate_node_guard():
    mesh = make_fanin_mesh(2, 4)
    a = ShardedDenseCrdt("na", N, mesh, wall_clock=FakeClock(start=BASE))
    other = DenseCrdt("na", N, wall_clock=FakeClock(start=BASE + 50))
    other.put_batch([0], [1])
    with pytest.raises(DuplicateNodeException):
        a.merge(*other.export_delta())

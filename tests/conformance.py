"""Shim: the conformance kit is exported as crdt_tpu.testing."""

from crdt_tpu.testing import (CrdtConformance, FakeClock,
                              SemanticsConformance)

__all__ = ["CrdtConformance", "FakeClock", "SemanticsConformance"]

"""Typed per-lane merge kernels: one fused join for mixed semantics.

`ops.dense` joins every slot by the LWW rule — strict ``(lt, node)``
lexicographic compare, winner takes all lanes. The semantics registry
(`crdt_tpu.semantics`) generalizes that to a per-slot *type tag* lane
(``sem``: int8, 0 = LWW) while keeping the columnar store layout and
the HLC machinery untouched. The composition is the semidirect-product
construction (PAPERS.md, "Composing and Decomposing Op-Based CRDTs
with Semidirect Products"):

- The **clock lanes** (lt, node) always join by the strict lex max —
  identical for every semantics, so watermarks, ``pack_since`` deltas,
  recv guards and the canonical-clock absorption all keep working
  unchanged on typed stores.
- The **value lane** joins by the tag's own sub-semilattice when both
  sides are present (counter max, per-half max, per-nibble max, top-k
  union), and by presence otherwise. For ``sem == 0`` the value
  follows the clock winner bit-for-bit — the LWW branch reproduces
  `ops.dense._wire_join_body` exactly.
- The **tomb flag** is the clock winner's: deletion stays an
  LWW-resettable action *on top of* the typed state (the semidirect
  action) — a tombstoned counter keeps its monotone lane and joins
  normally, so un-deleting reveals the converged count.

Each composed per-slot join is a lexicographic/product lattice, so
idempotence/commutativity/associativity hold by construction — and are
*checked*, not trusted: every registered tag generates a seeded
`LawTarget` and a jaxpr `AuditTarget` (see `crdt_tpu.semantics.types`).

Value-lane encodings (all within one int64; value_width must be 64):

====== === ===========================================================
name   tag encoding
====== === ===========================================================
lww      0 opaque payload; clock winner takes the lane
gcount   1 non-negative count; join = max
pncount  2 pos in bits 32..62, neg in bits 0..30; join = per-half max;
           user value = pos - neg
orset    3 causal-length set over 16 elements: 4-bit causal length per
           element (PAPERS.md: low-cost set CRDT based on causal
           lengths); join = per-nibble max; element present iff its
           length is ODD; lengths saturate at 15 (7 add/remove cycles)
mvreg    4 top-4 concurrent 16-bit values (1..65535, 0 = empty) packed
           descending (bits 63:48 hold the largest); strictly newer lt
           wins outright, equal lt joins by dedup-union-top-4
====== === ===========================================================

Kernel surface mirrors `ops.dense`: jit-cached factories keyed on
``(donate, sharding)``, store donation for O(k) in-place lane updates,
``with_sharding_constraint`` pinning sharded outputs. Everything is
elementwise (plus one small last-axis sort for mvreg), so the typed
kernels shard under jit without new collectives.
"""

from __future__ import annotations

import functools as _ft
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..obs import device as _obs_device
from ..ops.dense import (DenseStore, DenseChangeset, FaninResult,
                         _NEG)
from ..ops.merge import recv_guards

_obs_device.register(
    "semantics.typed_wire_join_step", "semantics.typed_sparse_join_step",
    "semantics.typed_fanin_step")

# Wire tags. LWW MUST be 0: a store with no semantics column is
# all-zeros by construction, and the packed wire form omits the sem
# lane entirely for all-LWW stores.
SEM_LWW = 0
SEM_GCOUNTER = 1
SEM_PNCOUNTER = 2
SEM_ORSET = 3
SEM_MVREG = 4

_PN_HALF = (1 << 31) - 1     # 31-bit pos/neg halves; bit 63 stays 0
ORSET_UNIVERSE = 16          # elements per orset lane (4-bit lengths)
ORSET_MAX_LEN = 15           # causal-length saturation point
MVREG_K = 4                  # concurrent values kept per mvreg lane
MVREG_MAX = 0xFFFF           # 16-bit values, 0 reserved for "empty"


def _pn_join(l_val: jax.Array, r_val: jax.Array) -> jax.Array:
    pos = jnp.maximum((l_val >> 32) & _PN_HALF, (r_val >> 32) & _PN_HALF)
    neg = jnp.maximum(l_val & _PN_HALF, r_val & _PN_HALF)
    return (pos << 32) | neg


def _orset_join(l_val: jax.Array, r_val: jax.Array) -> jax.Array:
    """Per-nibble max of 16 packed causal lengths — Python-unrolled
    shift/mask, the elementwise shape TPU tiles well (no gather)."""
    out = jnp.zeros_like(l_val)
    for i in range(ORSET_UNIVERSE):
        sh = 4 * i
        out = out | (jnp.maximum((l_val >> sh) & 0xF,
                                 (r_val >> sh) & 0xF) << sh)
    return out


def _mvreg_union(l_val: jax.Array, r_val: jax.Array) -> jax.Array:
    """Dedup-union of two top-4 packs, keeping the 4 largest. Taking
    top-k after a union is a closure (top4(top4(a∪b)∪c) ==
    top4(a∪b∪c)), so the equal-lt branch stays associative."""
    shifts = (48, 32, 16, 0)
    cand = jnp.stack([(l_val >> s) & MVREG_MAX for s in shifts]
                     + [(r_val >> s) & MVREG_MAX for s in shifts],
                     axis=-1)
    cand = -jnp.sort(-cand, axis=-1)          # descending
    prev = jnp.concatenate(
        [jnp.full(cand.shape[:-1] + (1,), -1, cand.dtype),
         cand[..., :-1]], axis=-1)
    keep = (cand != prev) & (cand > 0)        # first occurrence, nonzero
    rank = jnp.cumsum(keep.astype(jnp.int64), axis=-1) - 1
    sel = keep & (rank < MVREG_K)
    shift = jnp.clip(48 - 16 * rank, 0, 48)
    return jnp.sum(jnp.where(sel, cand << shift, 0), axis=-1)


def _typed_val(sem: jax.Array, l_lt: jax.Array, r_lt: jax.Array,
               l_val: jax.Array, r_val: jax.Array,
               winner_val: jax.Array) -> jax.Array:
    """Value join for BOTH-PRESENT lanes by tag; unknown tags fall
    back to the clock winner's value (safe: still a semilattice)."""
    mv = jnp.where(l_lt == r_lt, _mvreg_union(l_val, r_val),
                   jnp.where(r_lt > l_lt, r_val, l_val))
    out = winner_val
    out = jnp.where(sem == SEM_GCOUNTER, jnp.maximum(l_val, r_val), out)
    out = jnp.where(sem == SEM_PNCOUNTER, _pn_join(l_val, r_val), out)
    out = jnp.where(sem == SEM_ORSET, _orset_join(l_val, r_val), out)
    out = jnp.where(sem == SEM_MVREG, mv, out)
    return out


def typed_join_lanes(sem, l_lt, l_node, l_val, l_occ, l_tomb,
                     r_lt, r_node, r_val, r_tomb, r_valid
                     ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                jax.Array, jax.Array, jax.Array]:
    """One elementwise typed join of remote lanes into local lanes.

    Returns ``(lt, node, val, tomb, occupied, win)``. ``win`` is the
    adoption mask LWW lanes use (strictly-newer remote, exactly
    `_wire_join_body`) and the CHANGED mask for typed lanes (a
    re-delivered or dominated typed row is a no-op, so its ``mod``
    stamp — and its watch event — must not fire)."""
    lt_m = jnp.where(r_valid, r_lt, _NEG)
    node32 = r_node.astype(jnp.int32)
    val64 = r_val.astype(jnp.int64)
    # Strict (lt, node) compare: local wins exact ties (crdt.dart:84).
    remote_newer = ((lt_m > l_lt) | ((lt_m == l_lt) & (node32 > l_node)))
    take = r_valid & (~l_occ | remote_newer)

    lt_out = jnp.where(take, lt_m, l_lt)
    node_out = jnp.where(take, node32, l_node)
    tomb_out = jnp.where(take, r_tomb, l_tomb)
    occ_out = l_occ | r_valid

    winner_val = jnp.where(take, val64, l_val)
    both = l_occ & r_valid
    tval = jnp.where(
        both, _typed_val(sem, l_lt, lt_m, l_val, val64, winner_val),
        jnp.where(r_valid & ~l_occ, val64, l_val))
    val_out = jnp.where(sem == SEM_LWW, winner_val, tval)

    changed = r_valid & ((lt_out != l_lt) | (node_out != l_node)
                         | (val_out != l_val) | (tomb_out != l_tomb)
                         | ~l_occ)
    win = jnp.where(sem == SEM_LWW, take, changed)
    return lt_out, node_out, val_out, tomb_out, occ_out, win


# --- jit-cached entry points, keyed (donate, sharding) like ops.dense


@_ft.lru_cache(maxsize=None)
def _typed_wire_join_jit(donate: bool, sharding=None):
    def step(store, sem, lt, node, val, tomb, valid, stamp_lt,
             local_node):
        lt_o, node_o, val_o, tomb_o, occ_o, win = typed_join_lanes(
            sem, store.lt, store.node, store.val, store.occupied,
            store.tomb, lt, node, val, tomb, valid)
        new_store = DenseStore(
            lt=lt_o, node=node_o, val=val_o,
            mod_lt=jnp.where(win, stamp_lt, store.mod_lt),
            mod_node=jnp.where(win, local_node, store.mod_node),
            occupied=occ_o, tomb=tomb_o)
        if sharding is not None:
            new_store = jax.lax.with_sharding_constraint(new_store,
                                                         sharding)
        return new_store, win
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def typed_wire_join_step(store: DenseStore, sem: jax.Array,
                         lt: jax.Array, node: jax.Array,
                         val: jax.Array, tomb: jax.Array,
                         valid: jax.Array, stamp_lt: jax.Array,
                         local_node: jax.Array, *,
                         donate: bool = False, sharding=None
                         ) -> Tuple[DenseStore, jax.Array]:
    """Elementwise N-wide typed join of a slot-aligned wire delta —
    the `ops.dense.wire_join_step` shape plus a per-slot ``sem`` tag
    lane. Clock absorption and recv guards stay the CALLER's job;
    ``stamp_lt`` stamps winners' ``modified`` lanes. For an all-zero
    ``sem`` lane the result is bit-identical to `wire_join_step`."""
    with _obs_device.record("semantics.typed_wire_join_step",
                            dim=lt.shape[0],
                            donated=store.lt if donate else None):
        return _typed_wire_join_jit(donate, sharding)(
            store, sem, lt, node, val, tomb, valid, stamp_lt,
            local_node)


@_ft.lru_cache(maxsize=None)
def _typed_sparse_join_jit(donate: bool, sharding=None):
    def step(store, sem_rows, slot, lt, node, val, tomb, valid,
             stamp_lt, local_node):
        l_lt = store.lt.at[slot].get(mode="fill", fill_value=0)
        l_node = store.node.at[slot].get(mode="fill", fill_value=0)
        l_val = store.val.at[slot].get(mode="fill", fill_value=0)
        l_occ = store.occupied.at[slot].get(mode="fill",
                                            fill_value=False)
        l_tomb = store.tomb.at[slot].get(mode="fill", fill_value=False)
        lt_o, node_o, val_o, tomb_o, _occ_o, win = typed_join_lanes(
            sem_rows, l_lt, l_node, l_val, l_occ, l_tomb,
            lt, node, val, tomb, valid)
        target = jnp.where(win, slot, store.n_slots).astype(jnp.int32)
        k = slot.shape[0]
        new_store = DenseStore(
            lt=store.lt.at[target].set(lt_o, mode="drop"),
            node=store.node.at[target].set(node_o, mode="drop"),
            val=store.val.at[target].set(val_o, mode="drop"),
            mod_lt=store.mod_lt.at[target].set(
                jnp.zeros((k,), jnp.int64) + stamp_lt, mode="drop"),
            mod_node=store.mod_node.at[target].set(
                jnp.zeros((k,), jnp.int32) + local_node, mode="drop"),
            occupied=store.occupied.at[target].set(True, mode="drop"),
            tomb=store.tomb.at[target].set(tomb_o, mode="drop"),
        )
        if sharding is not None:
            new_store = jax.lax.with_sharding_constraint(new_store,
                                                         sharding)
        return new_store, win
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def typed_sparse_join_step(store: DenseStore, sem_rows: jax.Array,
                           slot: jax.Array, lt: jax.Array,
                           node: jax.Array, val: jax.Array,
                           tomb: jax.Array, valid: jax.Array,
                           stamp_lt: jax.Array, local_node: jax.Array,
                           *, donate: bool = False, sharding=None
                           ) -> Tuple[DenseStore, jax.Array]:
    """O(k) typed scatter join — `ops.dense.sparse_fanin_step` with a
    per-ROW ``sem_rows`` tag lane (the host gathers the store's tags
    at the delta's slots). Gathers the local rows (mode="fill"),
    joins row-wise, scatters the MERGED rows back at winning slots
    (``slot == n_slots`` sentinel padding drops, mode="drop"). Slots
    must be unique within one delta — the same contract as
    `sparse_fanin_step`, and why duplicate-index scatter order can
    never matter here."""
    with _obs_device.record("semantics.typed_sparse_join_step",
                            dim=slot.shape[0],
                            donated=store.lt if donate else None):
        return _typed_sparse_join_jit(donate, sharding)(
            store, sem_rows, slot, lt, node, val, tomb, valid,
            stamp_lt, local_node)


@_ft.lru_cache(maxsize=None)
def _typed_fanin_jit(donate: bool, sharding=None):
    def step(store, sem, cs, canonical_lt, local_node, wall_millis,
             stamp_lt):
        any_bad, first_bad, first_is_dup, canonical_at_fail = \
            recv_guards(cs.lt, cs.node, cs.valid, canonical_lt,
                        local_node, wall_millis)
        new_canonical = jnp.maximum(
            canonical_lt, jnp.max(jnp.where(cs.valid, cs.lt, _NEG)))
        stamp = new_canonical if stamp_lt is None else stamp_lt
        # Python-unrolled fold of the typed join over the R rows —
        # join associativity makes this the union join; the typed
        # kernels never stream (merge sizes that need lax.scan are an
        # LWW fast-path concern, and typed stores disable Pallas too).
        lt, node, val = store.lt, store.node, store.val
        occ, tomb = store.occupied, store.tomb
        for r in range(cs.lt.shape[0]):
            lt, node, val, tomb, occ, _w = typed_join_lanes(
                sem, lt, node, val, occ, tomb,
                cs.lt[r], cs.node[r], cs.val[r], cs.tomb[r],
                cs.valid[r])
        win = ((lt != store.lt) | (node != store.node)
               | (val != store.val) | (tomb != store.tomb)
               | (occ & ~store.occupied))
        new_store = DenseStore(
            lt=lt, node=node, val=val,
            mod_lt=jnp.where(win, stamp, store.mod_lt),
            mod_node=jnp.where(win, local_node, store.mod_node),
            occupied=occ, tomb=tomb)
        if sharding is not None:
            new_store = jax.lax.with_sharding_constraint(new_store,
                                                         sharding)
        return new_store, FaninResult(
            new_canonical=new_canonical,
            win_count=jnp.sum(win).astype(jnp.int32),
            win=win, any_bad=any_bad, first_bad=first_bad,
            first_is_dup=first_is_dup,
            canonical_at_fail=canonical_at_fail)
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def typed_fanin_step(store: DenseStore, sem: jax.Array,
                     cs: DenseChangeset, canonical_lt: jax.Array,
                     local_node: jax.Array, wall_millis: jax.Array,
                     stamp_lt: Optional[jax.Array] = None, *,
                     donate: bool = False, sharding=None
                     ) -> Tuple[DenseStore, FaninResult]:
    """R-replica typed fan-in — `ops.dense.fanin_step` plus the
    per-slot ``sem`` lane: recv guards and canonical absorption are
    identical (the clock lanes ARE identical across semantics), the
    fold applies the typed join per row, and ``win`` is the
    changed-vs-original mask. Purely elementwise, so a sharded model
    runs it under jit with its store sharding pinned — no collective
    dispatch needed."""
    with _obs_device.record("semantics.typed_fanin_step",
                            dim=cs.lt.shape[0],
                            donated=store.lt if donate else None):
        return _typed_fanin_jit(donate, sharding)(
            store, sem, cs, canonical_lt, local_node, wall_millis,
            stamp_lt)


def combine_wire_deltas(sem, a: dict, b: dict) -> dict:
    """Join two slot-aligned wire deltas into one, per the SAME typed
    join the kernels apply — the associativity ``combine`` for
    registry law targets (a combine that disagrees with the kernel is
    exactly what the law search must catch). Runs eagerly on host
    arrays; returns plain numpy lanes."""
    import numpy as np
    lt, node, val, tomb, occ, _w = typed_join_lanes(
        sem, jnp.asarray(a["lt"]), jnp.asarray(a["node"], jnp.int32),
        jnp.asarray(a["val"], jnp.int64), jnp.asarray(a["valid"]),
        jnp.asarray(a["tomb"]), jnp.asarray(b["lt"]),
        jnp.asarray(b["node"]), jnp.asarray(b["val"]),
        jnp.asarray(b["tomb"]), jnp.asarray(b["valid"]))
    return {"lt": np.asarray(lt), "node": np.asarray(node, np.int32),
            "val": np.asarray(val), "tomb": np.asarray(tomb),
            "valid": np.asarray(occ)}

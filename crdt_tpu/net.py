"""TCP replication endpoints (stdlib sockets, no dependencies).

The reference deliberately leaves transport to the application — its
example mocks the remote with a function returning a JSON string
(example/crdt_example.dart:21-25). This module is that boundary made
concrete: a :class:`SyncServer` exposes any `Crdt` over one TCP
listener, and :func:`sync_over_tcp` runs the reference's anti-entropy
round against it (full push + inclusive delta pull,
test/map_crdt_test.dart:273-279). Nothing crosses the wire but the
JSON format (crdt_json.dart:8-37), length-prefixed.

Frames (4-byte big-endian length + UTF-8 JSON):

    client -> server  {"op": "push",  "payload": <wire json>}
    server -> client  {"ok": true}
    client -> server  {"op": "delta", "since": <hlc str> | null}
    server -> client  {"payload": <wire json>}
    client -> server  {"op": "bye"}

Dense replicas can additionally sync in the KERNEL WIRE FORM
(`DenseCrdt.export_split_delta` / `merge_split`): the split 32-bit
lanes cross the wire as ONE raw binary frame (~19 B/slot vs ~90 B of
JSON text, no text codec on either side), described by a JSON meta
frame. Both peers must be dense models at the same capacity; the JSON
ops above remain the universal interop path.

    client -> server  {"op": "push_dense", "meta": {...lanes...}}
    client -> server  <raw binary frame: concatenated lanes>
    server -> client  {"ok": true}
    client -> server  {"op": "delta_dense", "since": <hlc str> | null}
    server -> client  {"meta": {...lanes...}}
    server -> client  <raw binary frame>

The sync fast path adds three NEGOTIATED extensions on top — all
opt-in via a ``hello`` handshake, so a pre-hello peer keeps speaking
the exact legacy bytes above (see docs/WIRE.md for the full matrix):

    client -> server  {"op": "hello", "proto": 1, "caps": [...]}
    server -> client  {"ok": true, "proto": 1, "caps": <intersection>}

After a successful hello, every later frame body on the connection
carries ONE leading tag byte (`FrameCodec`): 0x00 raw, 0x01
zlib-compressed (sent only when the "zlib" cap was agreed AND the
body clears a size threshold — tiny control frames never pay the
codec). The "packed" cap unlocks the O(k) incremental columnar ops
(`DenseCrdt.pack_since` / `merge_packed` — ~25 B per MODIFIED row,
vs the dense form's O(capacity) lanes):

    client -> server  {"op": "push_packed", "meta": ..., "node_ids": [...]}
    client -> server  <raw binary frame: packed lanes>
    server -> client  {"ok": true}
    client -> server  {"op": "delta_packed", "since": <hlc str> | null}
    server -> client  {"meta": ..., "node_ids": [...], "k": <rows>}
    server -> client  <raw binary frame>

:class:`PeerConnection` keeps one negotiated session alive across
rounds (connect + hello once, not per round), detecting pre-hello
servers (they answer ``unknown_op`` and hang up) and sticking to the
legacy framing for them.

Error replies carry a structured ``code`` ("merge_rejected",
"delta_failed", "dense_rejected", "packed_rejected", "unknown_op")
plus the server-side exception name/detail. Client-side, the sync
functions raise a split taxonomy: :class:`SyncTransportError` for
link faults (retryable — rounds are idempotent) and
:class:`SyncProtocolError` for peer rejections (fatal; for dense or
packed ops, fall back to the JSON path). The gossip runtime
(`crdt_tpu.gossip`) keys its retry/backoff/breaker and
packed→dense→JSON fallback decisions off exactly this split.

Threading model: replicas are single-threaded state machines (same
contract as the reference's isolate model — see SqliteCrdt's notes).
The server serializes ALL replica access through :attr:`SyncServer.lock`
— it accepts up to ``max_conns`` concurrent connections (pooled
gossip peers park sessions between rounds), each on its own handler
thread, but requests still execute one at a time under the lock. An
application that also writes locally from another thread must take
the same lock around its own operations. To serve a `SqliteCrdt`,
construct it with ``check_same_thread=False`` (sqlite3's own thread
guard; the server's lock provides the actual serialization).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import zlib
from typing import Any, Iterable, Optional, Sequence, Tuple

from .analysis.concurrency import make_lock
from .crdt import Crdt
from .hlc import Hlc


# A 1M-record full-state payload is ~100 MB; anything near this cap
# is a corrupt stream or a peer speaking another protocol — reject
# before allocating, never trust a 4-byte prefix with 4 GiB.
MAX_FRAME_BYTES = 1 << 30


class SyncError(ConnectionError):
    """A sync round failed. Subclasses split the taxonomy the gossip
    runtime retries on: transport faults (retryable — the lattice join
    is idempotent, replaying a round is always safe) vs. protocol
    rejections (fatal — the peer understood the round and refused it,
    so replaying the same bytes cannot succeed). Kept a
    `ConnectionError` so pre-taxonomy callers' handlers still fire."""


class SyncTransportError(SyncError):
    """The LINK failed: refused/reset connection, timeout, EOF
    mid-frame, framing violation, or a reply desynchronized from the
    request stream. Nothing says the peer rejected the round — retry
    with backoff."""


class SyncRedirectError(SyncTransportError):
    """The PEER is not the slot's owner: a federated tier answered a
    keyspace op with ``moved``, naming the owning tier's address and
    the routing epoch it routed by (docs/FEDERATION.md). A transport
    subclass on purpose — like ``busy`` (PR 9), a redirect is
    retryable-by-construction (refetch the routing table, replay at
    the owner; the lattice join is idempotent) and must NEVER
    downgrade the session to the legacy protocol or mark the peer
    rejected."""

    def __init__(self, message: str, owner: Optional[str] = None,
                 epoch: Optional[int] = None):
        super().__init__(message)
        self.owner = owner
        self.epoch = epoch


class SyncProtocolError(SyncError):
    """The PEER rejected the round: a clock guard tripped, the op is
    unknown, or the dense wire form is unsupported/incompatible.
    ``code`` is the server's structured reason (see
    :class:`SyncServer`), ``error``/``detail`` the exception it maps
    from. Do not retry; for dense ops, fall back to the universal
    JSON path."""

    def __init__(self, message: str, code: str = "rejected",
                 error: Optional[str] = None,
                 detail: Optional[str] = None):
        super().__init__(message)
        self.code = code
        self.error = error
        self.detail = detail

    @classmethod
    def from_reply(cls, what: str, reply: Any) -> "SyncProtocolError":
        """Build from a server error reply, preserving the legacy
        message shape (tests match on 'rejected: ...ExceptionName')."""
        code, error, detail = "rejected", None, None
        if isinstance(reply, dict):
            code = reply.get("code", code)
            error = reply.get("error")
            detail = reply.get("detail")
        return cls(f"{what}: {reply!r}", code=code, error=error,
                   detail=detail)


class WireTally:
    """Mutable wire byte counters (frame headers included) the sync
    functions fill when given one — per-round for the gossip runtime's
    per-peer ``bytes_sent``/``bytes_received`` accounting, cumulative
    for the endpoint-lifetime tallies `SyncServer` and `GossipNode`
    attach to the metrics registry (the ``__weakref__`` slot exists so
    the registry can hold them weakly). ``z_raw``/``z_wire`` count the
    before/after bytes of every body `FrameCodec` actually compressed,
    so ``z_ratio`` is the achieved compression ratio (1.0 when nothing
    was compressed)."""

    __slots__ = ("sent", "received", "z_raw", "z_wire", "__weakref__")

    def __init__(self) -> None:
        self.sent = 0
        self.received = 0
        self.z_raw = 0
        self.z_wire = 0

    @property
    def z_ratio(self) -> float:
        return self.z_raw / self.z_wire if self.z_wire else 1.0

    def as_dict(self) -> dict:
        return {"sent": self.sent, "received": self.received,
                "z_raw": self.z_raw, "z_wire": self.z_wire,
                "z_ratio": round(self.z_ratio, 4)}


class FrameCodec:
    """Per-connection frame body transform, active only AFTER a
    successful ``hello``: every body gets one leading tag byte —
    ``0x00`` raw, ``0x01`` zlib. Compression is sent only when enabled
    (both sides advertised "zlib") and the body clears
    ``min_compress_bytes`` — a 20-byte control frame costs more as a
    zlib stream than as itself. Decoding always accepts BOTH tags
    (negotiating the cap governs what a peer may *send*, not what it
    must *understand*), with the inflated size capped at
    ``MAX_FRAME_BYTES`` so a zlib bomb rejects before allocating."""

    TAG_RAW = b"\x00"
    TAG_ZLIB = b"\x01"

    def __init__(self, compress: bool = False, level: int = 1,
                 min_compress_bytes: int = 512):
        self.compress = compress
        self.level = level
        self.min_compress_bytes = min_compress_bytes

    def encode(self, bufs: Sequence, tally: Optional[WireTally] = None
               ) -> list:
        """Tag (and maybe compress) a body given as buffer pieces;
        returns the pieces to ship. Incompressible bodies ship raw —
        the tag byte means the receiver never guesses. Raw pieces pass
        through untouched (zero-copy); zlib consumes each piece via
        the buffer protocol (no ``bytes()`` staging copy) and the
        compressed bytes it does materialize are counted in
        ``crdt_tpu_pack_copy_bytes_total{stage="encode_zlib"}``."""
        total = sum(_buf_nbytes(b) for b in bufs)
        if self.compress and total >= self.min_compress_bytes:
            co = zlib.compressobj(self.level)
            pieces = [co.compress(b) for b in bufs]
            pieces.append(co.flush())
            z_total = sum(len(p) for p in pieces)
            if z_total < total:
                if tally is not None:
                    tally.z_raw += total
                    tally.z_wire += z_total
                from .obs.registry import default_registry
                default_registry().counter(
                    "crdt_tpu_pack_copy_bytes_total",
                    "bytes copied between pack and frame (zero on the "
                    "arena fast path)").inc(z_total,
                                            stage="encode_zlib")
                return [self.TAG_ZLIB] + pieces
        return [self.TAG_RAW] + list(bufs)

    def decode(self, body: bytes) -> bytes:
        if not body:
            raise ValueError("tagged frame with empty body")
        tag, body = body[:1], body[1:]
        if tag == self.TAG_RAW:
            return body
        if tag == self.TAG_ZLIB:
            do = zlib.decompressobj()
            try:
                out = do.decompress(body, MAX_FRAME_BYTES)
            except zlib.error as e:
                raise ValueError(f"corrupt compressed frame: {e}") from e
            if do.unconsumed_tail or not do.eof or do.unused_data:
                raise ValueError(
                    "compressed frame inflates past MAX_FRAME_BYTES, "
                    "is truncated, or has trailing bytes")
            return out
        raise ValueError(f"unknown frame tag {tag!r}")


def send_frame(sock: socket.socket, obj: Any,
               tally: Optional[WireTally] = None,
               codec: Optional[FrameCodec] = None) -> None:
    """One JSON frame — the raw framing plus a dumps. ``codec`` (a
    negotiated connection) tags/compresses the body; None keeps the
    legacy untagged bytes."""
    send_bytes_frame(sock, [json.dumps(obj).encode()], tally, codec)


def recv_frame(sock: socket.socket,
               deadline: Optional[float] = None,
               tally: Optional[WireTally] = None,
               codec: Optional[FrameCodec] = None) -> Optional[Any]:
    """Receive one JSON frame; ``deadline`` (a ``time.monotonic()``
    value) bounds the WHOLE frame, not just each chunk — a peer
    trickling bytes inside the per-recv socket timeout cannot stretch
    past it."""
    body = recv_bytes_frame(sock, deadline, tally, codec)
    return None if body is None else json.loads(body)


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> Optional[bytes]:
    import time as _time
    buf = bytearray()
    while len(buf) < n:
        if deadline is not None:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise socket.timeout("connection deadline exceeded")
            base = sock.gettimeout()
            sock.settimeout(remaining if base is None
                            else min(base, remaining))
            try:
                chunk = sock.recv(min(n - len(buf), 1 << 20))
            finally:
                sock.settimeout(base)
        else:
            chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _buf_nbytes(b) -> int:
    """Byte length of any buffer piece. ``len()`` of a
    multi-dimensional memoryview counts FIRST-DIMENSION elements, not
    bytes (the `_pack_split` flat-cast trap) — ``nbytes`` never
    lies, whatever the shape or item size."""
    if isinstance(b, (bytes, bytearray)):
        return len(b)
    return b.nbytes if isinstance(b, memoryview) else memoryview(b).nbytes


def _flat_views(bufs) -> list:
    """Normalize buffer pieces to flat byte memoryviews — what both
    the length prefix and the vectored send below need. Flattening a
    C-contiguous view is a cast, not a copy."""
    views = []
    for b in bufs:
        v = b if isinstance(b, memoryview) else memoryview(b)
        if v.ndim != 1 or v.format != "B":
            v = v.cast("B")
        views.append(v)
    return views


def _sendmsg_all(sock: socket.socket, views: list) -> None:
    """Vectored gather-send of every view with partial-send advance —
    ONE syscall per full frame in the common case, against N
    ``sendall`` calls (and zero concatenation copies either way)."""
    views = [v for v in views if v.nbytes]
    i = 0
    while i < len(views):
        sent = sock.sendmsg(views[i:])
        while sent > 0:
            n = views[i].nbytes
            if sent >= n:
                sent -= n
                i += 1
            else:
                views[i] = views[i][sent:]
                sent = 0


def send_bytes_frame(sock: socket.socket, bufs,
                     tally: Optional[WireTally] = None,
                     codec: Optional[FrameCodec] = None) -> None:
    """One length-prefixed RAW frame from a list of buffers — sent
    piecewise, never concatenated (a 100 MB delta must not allocate a
    second copy). The header and every body piece go out in one
    vectored ``socket.sendmsg`` where the platform has it, so a
    zero-copy pack's arena views reach the kernel directly."""
    if codec is not None:
        bufs = codec.encode(bufs, tally)
    views = _flat_views(bufs)
    total = sum(v.nbytes for v in views)
    if total > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {total} bytes exceeds "
                         f"MAX_FRAME_BYTES ({MAX_FRAME_BYTES})")
    header = struct.pack(">I", total)
    if hasattr(sock, "sendmsg"):
        _sendmsg_all(sock, [memoryview(header)] + views)
    else:                                   # pragma: no cover
        sock.sendall(header)
        for v in views:
            sock.sendall(v)
    if tally is not None:
        tally.sent += 4 + total


def recv_bytes_frame(sock: socket.socket,
                     deadline: Optional[float] = None,
                     tally: Optional[WireTally] = None,
                     codec: Optional[FrameCodec] = None
                     ) -> Optional[bytes]:
    """Receive one RAW frame (no JSON decode)."""
    head = _recv_exact(sock, 4, deadline)
    if head is None:
        return None
    (n,) = struct.unpack(">I", head)
    if n > MAX_FRAME_BYTES:
        raise ValueError(f"peer announced a {n}-byte frame (cap "
                         f"{MAX_FRAME_BYTES}); corrupt stream?")
    body = _recv_exact(sock, n, deadline)
    if body is not None and tally is not None:
        tally.received += 4 + n
    if body is not None and codec is not None:
        body = codec.decode(body)
    return body


# Exact lane dtypes per split form — anything else from a peer is a
# protocol violation (np.dtype on arbitrary strings is not a safe
# parser for untrusted input, and a mismatched-but-allowed dtype would
# reinterpret bytes instead of rejecting the frame).
_SPLIT_LANE_DTYPES = {
    "split": ("int32", "uint32", "int16", "int32", "uint32", "int8"),
    "narrow": ("int32", "uint32", "int16", "int32", "int8"),
}


def _pack_split(scs):
    """(meta, bufs) for a split changeset: lane descriptors + host
    buffers in field order."""
    import numpy as np

    from .ops.pallas_merge import NarrowSplitChangeset
    # Device lanes must land on host before framing — this copy is the
    # unavoidable device_get, not a pack-path regression.
    # crdtlint: disable=pack-path-extra-copy -- split lanes arrive as device arrays; materializing them on host is the one required copy of this wire form
    arrs = [np.ascontiguousarray(np.asarray(lane)) for lane in scs]
    meta = {
        "form": ("narrow" if isinstance(scs, NarrowSplitChangeset)
                 else "split"),
        "lanes": [[f, str(a.dtype), list(a.shape)]
                  for f, a in zip(scs._fields, arrs)],
    }
    # Flat byte casts kept for tidiness; the framing itself now sizes
    # buffers by nbytes (`_buf_nbytes`), so even a multi-dimensional
    # view could no longer make the length prefix lie.
    return meta, [a.data.cast("B") for a in arrs]


def _unpack_split(meta, blob: bytes):
    """Validate + reconstruct the split changeset a peer announced.
    Raises ValueError on any structural violation (wrong fields,
    disallowed dtypes, size mismatch) BEFORE touching the replica."""
    import numpy as np

    import jax.numpy as jnp

    from .ops.pallas_merge import NarrowSplitChangeset, SplitChangeset
    if not isinstance(meta, dict):
        raise ValueError("bad dense meta")
    cls = {"split": SplitChangeset,
           "narrow": NarrowSplitChangeset}.get(meta.get("form"))
    lanes_meta = meta.get("lanes")
    if cls is None or not isinstance(lanes_meta, list):
        raise ValueError("bad dense meta")
    if [l[0] for l in lanes_meta] != list(cls._fields):
        raise ValueError("dense lane fields mismatch")
    expected = _SPLIT_LANE_DTYPES[meta["form"]]
    lanes = []
    off = 0
    shape0 = None
    for (_, dt, shape), want in zip(lanes_meta, expected):
        if dt != want:
            raise ValueError(f"lane dtype {dt!r} != expected {want!r}")
        shape = tuple(int(s) for s in shape)
        # 2-D (r, n) or pre-tiled 3-D only — the shapes every kernel
        # wrapper accepts; a 1-D lane would fail deep inside the merge
        # instead of here.
        if len(shape) not in (2, 3) or any(s <= 0 for s in shape):
            raise ValueError("bad lane shape")
        if shape0 is None:
            shape0 = shape
        elif shape != shape0:
            raise ValueError("inconsistent lane shapes")
        count = 1
        for s in shape:
            count *= s
        a = np.frombuffer(blob, np.dtype(dt), count=count, offset=off)
        off += a.nbytes
        lanes.append(jnp.asarray(a.reshape(shape)))
    if off != len(blob):
        raise ValueError(f"dense frame size mismatch: lanes describe "
                         f"{off} bytes, frame holds {len(blob)}")
    return cls(*lanes)


# --- binary client op lane (docs/WIRE.md) ---
#
# The `binop` hello cap replaces per-op framed JSON on the client hot
# path with fixed little-endian columnar frames: one request carries
# up to 65535 put/delete/get ops as packed opcode/slot/value rows, one
# reply answers every op with a status byte (plus an optional value
# lane and a JSON detail tail for the non-OK minority). Both sides of
# the wire — the serve tier's decoder and every client encoder — live
# here so there is exactly ONE framing stack, same as the peer wire.
#
# Request body (after the usual 4-byte frame header + codec tag):
#   <BBHI  magic=0xB1, version=1, n_ops, epoch+1 (0 = no epoch)
#   u8[n]  opcodes (0=put, 1=delete, 2=get)
#   <u4[n] slots
#   <i8[n] values (ignored for delete/get rows)
# Reply body:
#   <BBHI  magic=0xB2, flags (bit0 = value lane), n_ops, detail_len
#   u8[n]  statuses (0=OK, 1=OK_NULL, 2=WRITE_REJECTED, 3=BUSY,
#          4=MOVED)
#   <i8[n] values, present iff flags bit0 (get replies; 0 elsewhere)
#   bytes  detail_len of JSON: a list of dicts carrying the non-OK
#          minority's codes/errors ("i" = op index; entries without
#          "i" apply frame-wide, e.g. a busy tick)
#
# A JSON op frame starts with '{' (0x7B) and a binop frame with 0xB1,
# so a negotiated session dispatches on the first body byte with no
# ambiguity. Malformed FRAMES (bad magic/version/size/opcode) raise
# ValueError — a protocol violation that hangs up the session, like
# any other framing fault; a bad op INSIDE a well-formed frame is a
# per-op status, never a hangup and never the batch's problem.

BINOP_MAGIC = 0xB1
BINOP_REPLY_MAGIC = 0xB2
BINOP_VERSION = 1
BINOP_MAX_OPS = 0xFFFF
BINOP_PUT, BINOP_DELETE, BINOP_GET = 0, 1, 2
(BINOP_ST_OK, BINOP_ST_OK_NULL, BINOP_ST_REJECTED,
 BINOP_ST_BUSY, BINOP_ST_MOVED) = range(5)
_BINOP_HEAD = struct.Struct("<BBHI")
_BINOP_REPLY_HEAD = struct.Struct("<BBHI")
_BINOP_ROW_BYTES = 1 + 4 + 8


def encode_binop_request(opcodes, slots, values,
                         epoch: Optional[int] = None) -> list:
    """Buffer pieces for one binary op frame, ready for
    `send_bytes_frame`/`frame_pieces` — the columnar lanes are handed
    to the transport as memoryviews, never concatenated."""
    import numpy as np
    ops = np.ascontiguousarray(opcodes, np.uint8)
    sl = np.ascontiguousarray(slots, np.uint32)
    va = np.ascontiguousarray(values, np.int64)
    n = len(ops)
    if not 1 <= n <= BINOP_MAX_OPS:
        raise ValueError(f"binop batch of {n} ops outside "
                         f"[1, {BINOP_MAX_OPS}]")
    if len(sl) != n or len(va) != n:
        raise ValueError("binop lanes must share one length")
    if int(ops.max()) > BINOP_GET:
        raise ValueError("unknown binop opcode")
    head = _BINOP_HEAD.pack(BINOP_MAGIC, BINOP_VERSION, n,
                            0 if epoch is None else int(epoch) + 1)
    return [head, ops.data, sl.data.cast("B"), va.data.cast("B")]


def decode_binop_request(body):
    """Validate + decode one binary op frame into
    ``(opcodes, slots, values, epoch)``. The lanes are zero-copy
    `np.frombuffer` views into ``body`` (uint8/uint32/int64) — the
    serve tier hands the write rows straight to the combiner's
    columnar staging. Raises ValueError on any structural violation
    BEFORE touching the replica, exactly like `_unpack_split`."""
    import numpy as np
    if len(body) < _BINOP_HEAD.size:
        raise ValueError("binop frame shorter than its header")
    magic, version, n, epoch1 = _BINOP_HEAD.unpack_from(body)
    if magic != BINOP_MAGIC:
        raise ValueError(f"bad binop magic 0x{magic:02x}")
    if version != BINOP_VERSION:
        raise ValueError(f"unsupported binop version {version}")
    if n < 1:
        raise ValueError("binop frame with zero ops")
    want = _BINOP_HEAD.size + n * _BINOP_ROW_BYTES
    if len(body) != want:
        raise ValueError(f"binop frame holds {len(body)} bytes; "
                         f"{n} ops need exactly {want}")
    off = _BINOP_HEAD.size
    ops = np.frombuffer(body, np.uint8, count=n, offset=off)
    off += n
    slots = np.frombuffer(body, "<u4", count=n, offset=off)
    off += 4 * n
    values = np.frombuffer(body, "<i8", count=n, offset=off)
    if int(ops.max()) > BINOP_GET:
        raise ValueError("unknown binop opcode")
    return ops, slots, values, (None if epoch1 == 0 else epoch1 - 1)


def encode_binop_reply(status, values=None, details=None) -> list:
    """Buffer pieces for one binop reply frame. ``values`` (int64 per
    op) is included iff given; ``details`` is the non-OK minority's
    JSON tail (empty list/None elides it)."""
    import numpy as np
    st = np.ascontiguousarray(status, np.uint8)
    n = len(st)
    if not 1 <= n <= BINOP_MAX_OPS:
        raise ValueError(f"binop reply of {n} ops outside "
                         f"[1, {BINOP_MAX_OPS}]")
    det = json.dumps(details).encode() if details else b""
    flags = 0 if values is None else 1
    head = _BINOP_REPLY_HEAD.pack(BINOP_REPLY_MAGIC, flags, n,
                                  len(det))
    bufs = [head, st.data]
    if values is not None:
        va = np.ascontiguousarray(values, np.int64)
        if len(va) != n:
            raise ValueError("binop reply lanes must share one length")
        bufs.append(va.data.cast("B"))
    if det:
        bufs.append(det)
    return bufs


def decode_binop_reply(body):
    """Validate + decode one binop reply into
    ``(statuses, values_or_None, details)`` — status/value lanes as
    zero-copy views, details as the parsed JSON tail (always a
    list)."""
    import numpy as np
    if len(body) < _BINOP_REPLY_HEAD.size:
        raise ValueError("binop reply shorter than its header")
    magic, flags, n, det_len = _BINOP_REPLY_HEAD.unpack_from(body)
    if magic != BINOP_REPLY_MAGIC:
        raise ValueError(f"bad binop reply magic 0x{magic:02x}")
    if n < 1:
        raise ValueError("binop reply with zero ops")
    want = (_BINOP_REPLY_HEAD.size + n
            + (8 * n if flags & 1 else 0) + det_len)
    if len(body) != want:
        raise ValueError(f"binop reply holds {len(body)} bytes; "
                         f"header describes {want}")
    off = _BINOP_REPLY_HEAD.size
    status = np.frombuffer(body, np.uint8, count=n, offset=off)
    off += n
    values = None
    if flags & 1:
        values = np.frombuffer(body, "<i8", count=n, offset=off)
        off += 8 * n
    details = json.loads(body[off:]) if det_len else []
    if not isinstance(details, list):
        raise ValueError("binop reply details must be a list")
    return status, values, details


def binop_round(sock: socket.socket, opcodes, slots, values,
                epoch: Optional[int] = None,
                deadline: Optional[float] = None,
                tally: Optional[WireTally] = None,
                codec: Optional[FrameCodec] = None):
    """One batched binary round over a negotiated socket: N ops out,
    N statuses back in a single frame each way — the client half of
    the lane a serve tier advertises with the ``binop`` hello cap."""
    send_bytes_frame(sock, encode_binop_request(opcodes, slots,
                                                values, epoch),
                     tally, codec)
    body = recv_bytes_frame(sock, deadline, tally, codec)
    if body is None:
        raise SyncTransportError("peer closed during binop round")
    return decode_binop_reply(body)


class SyncServer:
    """Serve a replica's merge/delta surface over TCP.

    Up to ``max_conns`` connections are served concurrently (each on
    its own handler thread), so pooled gossip peers can park keep-alive
    sessions between rounds without starving one another; every
    request still holds :attr:`lock` while it touches the replica, so
    replica access stays strictly serialized. A slow peer delays —
    and without bounds would starve — everyone contending for that
    lock, so each connection is capped: at most ``max_ops`` framed
    requests and ``conn_deadline`` seconds, after which it is dropped
    (a well-behaved anti-entropy round is 3 frames and well under a
    second); connections past ``max_conns`` are refused at accept.
    The endpoint still assumes a trusted network: there is no
    authentication and a peer can push arbitrary records.

    >>> server = SyncServer(crdt)          # port 0 = ephemeral
    >>> server.start()
    >>> ... sync_over_tcp(other, "host", server.port) ...
    >>> server.stop()
    """

    # crdtlint lock-discipline contract: every replica access holds
    # the replica lock (enforced by crdt_tpu.analysis.host_lint).
    _CRDTLINT_GUARDED = {"lock": ("crdt",)}

    def __init__(self, crdt: Crdt, host: str = "127.0.0.1",
                 port: int = 0,
                 key_encoder=None, value_encoder=None,
                 key_decoder=None, value_decoder=None,
                 max_ops: int = 1000, conn_deadline: float = 300.0,
                 io_timeout: float = 30.0, max_conns: int = 8):
        self.crdt = crdt
        self.lock = make_lock("SyncServer.lock", 42)
        self._max_ops = max_ops
        self._conn_deadline = conn_deadline
        # Per-recv socket timeout AND the bound on a push_dense/
        # push_packed continuation frame: a client that announces a
        # binary frame and never sends it holds its handler slot for
        # at most this long, not until conn_deadline.
        self._io_timeout = io_timeout
        self._max_conns = max_conns
        # codec passthrough, mirroring sync.sync_json: replicas with
        # custom-typed keys/values need the same coders over TCP
        self._kenc, self._venc = key_encoder, value_encoder
        self._kdec, self._vdec = key_decoder, value_decoder
        # Endpoint-lifetime wire byte tally, registered with the
        # process metrics registry (weakly — a test's short-lived
        # server vanishes from snapshots with the server). Touched by
        # the single handler thread only; snapshot reads are racy-but-
        # atomic int reads.
        from .obs.registry import default_registry
        self.tally = WireTally()
        default_registry().attach("wire", self.tally, replace=True,
                                  role="server", node=str(crdt.node_id))
        # Optional hook merged into the `metrics` op reply — a
        # `GossipNode` installs its lag snapshot here so the wire op
        # answers "how far behind is replica B?" without the server
        # knowing about gossip state.
        self.metrics_extra = None
        # Live connections + their handler threads, guarded by
        # _conns_lock: stop() shuts every socket down so a handler
        # blocked in a 30 s recv exits promptly.
        self._conns_lock = make_lock("SyncServer._conns_lock", 44)
        self._conns: set = set()
        self._handlers: set = set()
        self._lsock = socket.create_server((host, port))
        self._lsock.settimeout(0.2)  # poll the stop flag
        self.host, self.port = self._lsock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SyncServer":
        self._thread = threading.Thread(
            target=self._serve, daemon=True,
            name=f"sync-accept-{self.port}")
        self._thread.start()
        return self

    def _shutdown_conns(self) -> None:
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def stop(self) -> None:
        """Stop serving and wait for quiescence: every live
        connection (a handler may be blocked in a 30 s recv) is shut
        down so its handler thread exits promptly — after stop()
        returns, no server-side thread touches the replica again."""
        self._stop.set()
        import time as _time
        deadline = _time.monotonic() + 60

        def _join(thread) -> None:
            # repeatedly shut down whatever connections are live: a
            # conn accepted concurrently with stop() would otherwise
            # slip past a single read and idle out a 30 s recv
            while thread.is_alive():
                self._shutdown_conns()
                thread.join(timeout=0.2)
                if _time.monotonic() > deadline:
                    raise RuntimeError(
                        "SyncServer thread failed to stop; the "
                        "replica may still be accessed — do not "
                        "reuse it")

        if self._thread is not None:
            _join(self._thread)
        while True:
            with self._conns_lock:
                handler = next((t for t in self._handlers
                                if t.is_alive()), None)
            if handler is None:
                break
            _join(handler)
        self._lsock.close()

    def __enter__(self) -> "SyncServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                if self._stop.is_set():
                    return
                # transient accept failure (e.g. EMFILE): the
                # listener is still bound — keep serving
                self._stop.wait(0.05)
                continue
            with self._conns_lock:
                self._handlers = {t for t in self._handlers
                                  if t.is_alive()}
                full = len(self._conns) >= self._max_conns
                if not full:
                    self._conns.add(conn)
            if full or self._stop.is_set():
                # Over capacity (or stopping): say WHY before hanging
                # up. The refusal predates any hello, so it crosses in
                # the untagged framing every client generation reads.
                # "busy" is deliberately absent from the gossip
                # fallback code sets — it is a retryable admission
                # signal, not a capability verdict, so the client
                # backs off and redials instead of downgrading modes
                # or marking the session legacy.
                try:
                    conn.settimeout(self._io_timeout)
                    if full and not self._stop.is_set():
                        from .obs.registry import default_registry
                        with self.lock:
                            node = str(self.crdt.node_id)
                        default_registry().counter(
                            "crdt_tpu_net_busy_refusals_total",
                            "connections refused at accept with the "
                            "busy code (max_conns reached)"
                        ).inc(node=node)
                        send_frame(conn, {
                            "ok": False, "code": "busy",
                            "error": "server at capacity "
                                     f"(max_conns={self._max_conns})"},
                            self.tally)
                except (OSError, ValueError):
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            t = threading.Thread(
                target=self._conn_main, args=(conn,), daemon=True,
                name=f"sync-conn-{self.port}-fd{conn.fileno()}")
            with self._conns_lock:
                self._handlers.add(t)
            t.start()

    def _conn_main(self, conn: socket.socket) -> None:
        try:
            with conn:
                self._handle(conn)
        except Exception:
            # one misbehaving peer must never take the server down
            # for everyone else
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _caps(self) -> set:
        """Capabilities this endpoint may advertise in a hello reply.
        "dense" is deliberately NOT negotiated: dense/JSON discovery
        stays rejection-based (`dense_rejected` → sticky downgrade),
        the contract the gossip fallback counters are pinned to."""
        caps = {"zlib"}
        with self.lock:
            packed = (hasattr(self.crdt, "pack_since")
                      and hasattr(self.crdt, "merge_packed"))
            # "semantics" gates the packed frame's 6th (sem tag) lane
            # (docs/TYPES.md): only a replica that can VALIDATE tags
            # may receive them, so the cap requires the typed surface,
            # not just packed framing.
            semantics = packed and hasattr(self.crdt, "set_semantics")
            # "merkle" gates the digest/digest_resp walk ops
            # (docs/ANTIENTROPY.md): it implies the range pack, so it
            # requires the full packed surface too.
            merkle = packed and callable(
                getattr(self.crdt, "digest_tree", None))
        if packed:
            caps.add("packed")
        if semantics:
            caps.add("semantics")
        if merkle:
            caps.add("merkle")
        # "trace" is pure metadata: when both ends agree, sync frames
        # may carry a compact trace context ({rid, origin, hlc_lo,
        # hlc_hi}) so initiator sync spans and responder merge spans
        # correlate in the JSONL sink (docs/OBSERVABILITY.md). Needs
        # no replica surface, so it is always advertised.
        caps.add("trace")
        # "sketch" gates the metrics op's "sketches" section (obs/
        # sketch.py quantile payloads): a session that never agreed
        # gets the pre-sketch metrics reply byte-identically, so old
        # pollers keep parsing exactly what they always parsed.
        caps.add("sketch")
        return caps

    def _handle(self, conn: socket.socket) -> None:
        conn.settimeout(self._io_timeout)
        import time as _time

        from .obs.trace import tracer as _tracer
        ring = _tracer()
        deadline = _time.monotonic() + self._conn_deadline
        ops = 0
        codec: Optional[FrameCodec] = None
        sem_ok = False   # this session negotiated the sem tag lane
        trace_ok = False  # this session negotiated trace piggyback
        sketch_ok = False  # this session negotiated sketch payloads
        while not self._stop.is_set():
            sent0, received0 = self.tally.sent, self.tally.received
            try:
                msg = recv_frame(conn, deadline=deadline,
                                 tally=self.tally, codec=codec)
            except (socket.timeout, OSError, ValueError):
                return
            if msg is None or not isinstance(msg, dict) \
                    or msg.get("op") == "bye":
                return
            # Bound what one connection can monopolize (every request
            # contends for the replica lock). Checked after recv so a
            # frame landing past the deadline is dropped, not granted
            # one more op.
            ops += 1
            if ops > self._max_ops or _time.monotonic() > deadline:
                return
            op = msg.get("op")
            tctx = msg.get("trace") if trace_ok else None
            if not isinstance(tctx, dict):
                tctx = None
            if op == "hello":
                want = msg.get("caps")
                want = set(want) if isinstance(want, list) else set()
                agreed = sorted(want & self._caps())
                if not self._reply(conn, {"ok": True, "proto": 1,
                                          "caps": agreed},
                                   self.tally, codec):
                    return
                # The reply itself crossed untagged; everything AFTER
                # it speaks the tagged framing.
                codec = FrameCodec(compress="zlib" in agreed)
                sem_ok = "semantics" in agreed
                trace_ok = "trace" in agreed
                sketch_ok = "sketch" in agreed
            elif op == "push":
                try:
                    with _recv_span("push", tctx):
                        with self.lock:
                            self.crdt.merge_json(
                                msg["payload"],
                                key_decoder=self._kdec,
                                value_decoder=self._vdec)
                except Exception as e:
                    # clock guards (duplicate node, drift) reject the
                    # push; the server survives and tells the client
                    self._reply(conn, {"ok": False,
                                       "code": "merge_rejected",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not self._reply(conn, {"ok": True}, self.tally,
                                   codec):
                    return
            elif op == "delta":
                try:
                    since = msg.get("since")
                    with self.lock:
                        payload = self.crdt.to_json(
                            modified_since=None if since is None
                            else Hlc.parse(since),
                            key_encoder=self._kenc,
                            value_encoder=self._venc)
                except Exception as e:
                    # e.g. an unparseable `since` watermark
                    self._reply(conn, {"code": "delta_failed",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not self._reply(conn, {"payload": payload},
                                   self.tally, codec):
                    return
            elif op == "push_dense":
                # The meta frame is followed by ONE raw binary frame,
                # bounded by io_timeout (not the whole conn_deadline):
                # a peer that announces a frame and goes silent must
                # not hold its handler slot for minutes.
                try:
                    blob = recv_bytes_frame(
                        conn, deadline=min(
                            deadline,
                            _time.monotonic() + self._io_timeout),
                        tally=self.tally, codec=codec)
                except (socket.timeout, OSError, ValueError):
                    return
                if blob is None:
                    return
                try:
                    scs = _unpack_split(msg.get("meta"), blob)
                    ids = msg.get("node_ids")
                    if not isinstance(ids, list) or not ids:
                        raise ValueError("push_dense without node_ids")
                    with _recv_span("push_dense", tctx):
                        with self.lock:
                            # AttributeError on non-dense replicas
                            # reports back like any other rejection.
                            self.crdt.merge_split(scs, ids)
                except Exception as e:
                    self._reply(conn, {"ok": False,
                                       "code": "dense_rejected",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not self._reply(conn, {"ok": True}, self.tally,
                                   codec):
                    return
            elif op == "delta_dense":
                try:
                    since = msg.get("since")
                    with self.lock:
                        scs, ids = self.crdt.export_split_delta(
                            None if since is None else Hlc.parse(since))
                    meta, bufs = _pack_split(scs)
                    meta_msg = {"meta": meta, "node_ids": list(ids)}
                except Exception as e:
                    self._reply(conn, {"code": "dense_rejected",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not self._reply(conn, meta_msg, self.tally, codec):
                    return
                try:
                    send_bytes_frame(conn, bufs, self.tally, codec)
                except (OSError, ValueError):
                    return
            elif op == "push_packed":
                # Same continuation-frame shape as push_dense, but the
                # lanes are the O(k) modified-rows form
                # (`ops.packing.unpack_rows` / `merge_packed`).
                try:
                    blob = recv_bytes_frame(
                        conn, deadline=min(
                            deadline,
                            _time.monotonic() + self._io_timeout),
                        tally=self.tally, codec=codec)
                except (socket.timeout, OSError, ValueError):
                    return
                if blob is None:
                    return
                try:
                    from .ops.packing import unpack_rows
                    packed = unpack_rows(msg.get("meta"), blob)
                    ids = msg.get("node_ids")
                    if not isinstance(ids, list):
                        raise ValueError("push_packed without node_ids")
                    if packed.k:
                        with _recv_span("push_packed", tctx):
                            with self.lock:
                                self.crdt.merge_packed(packed, ids)
                    # k == 0: nothing to join — skipping the merge
                    # keeps the clock (and thus the pack cache) still.
                except Exception as e:
                    self._reply(conn, {"ok": False,
                                       "code": "packed_rejected",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not self._reply(conn, {"ok": True}, self.tally,
                                   codec):
                    return
            elif op == "digest":
                # Merkle walk probe (docs/ANTIENTROPY.md): one level's
                # digest values at the requested node indices, plus the
                # tree geometry so the peer can abort an incompatible
                # walk before any payload bytes move. The tree itself
                # is the replica's (clock, sem_version)-keyed cache —
                # a quiet store serves every probe of the walk from
                # one reduction.
                try:
                    level = msg.get("level")
                    idxs = msg.get("idx")
                    if not isinstance(level, int) or not isinstance(
                            idxs, list):
                        raise ValueError(
                            "digest needs int 'level' + list 'idx'")
                    # Frontier prefetch (docs/ANTIENTROPY.md): "more"
                    # carries extra [level, idx-list] groups so a
                    # walker can probe several tree levels in ONE
                    # round trip. Optional and additive — a request
                    # without it is answered exactly as before, so
                    # pre-prefetch walkers interoperate unchanged.
                    groups = [(level, idxs)]
                    more = msg.get("more")
                    if more is not None:
                        if not isinstance(more, list):
                            raise ValueError(
                                "digest 'more' must be a list of "
                                "[level, idx] pairs")
                        for pair in more:
                            lvl2, idx2 = pair
                            if not isinstance(lvl2, int) \
                                    or not isinstance(idx2, list):
                                raise ValueError(
                                    "digest 'more' entries need int "
                                    "level + list idx")
                            groups.append((lvl2, idx2))
                    with self.lock:
                        tree = self.crdt.digest_tree()
                        per_group = [tree.values(lvl, ix)
                                     for lvl, ix in groups]
                    # Values ride the BINARY continuation frame (8
                    # bytes/digest, big-endian u64) — decimal JSON
                    # would triple the walk's dominant byte term.
                    # Groups concatenate in request order; "ks" gives
                    # the split points.
                    import numpy as _np
                    flat = [v for vals in per_group for v in vals]
                    buf = _np.asarray(flat,
                                      _np.uint64).astype(">u8").tobytes()
                    reply = {"op": "digest_resp", "ok": True,
                             "k": len(flat),
                             "ks": [len(v) for v in per_group],
                             "n_slots": tree.n_slots,
                             "leaf_width": tree.leaf_width,
                             "depth": tree.depth}
                except Exception as e:
                    self._reply(conn, {"code": "merkle_rejected",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not self._reply(conn, reply, self.tally, codec):
                    return
                try:
                    send_bytes_frame(conn, [buf], self.tally, codec)
                except (OSError, ValueError):
                    return
            elif op == "delta_packed":
                try:
                    since = msg.get("since")
                    ranges = msg.get("ranges")
                    if ranges is not None:
                        ranges = tuple(
                            (int(lo), int(hi)) for lo, hi in ranges)
                    with self.lock:
                        packed, ids = _pack_for_peer(
                            self.crdt,
                            None if since is None else Hlc.parse(since),
                            sem_ok, ranges=ranges)
                    from .ops.packing import pack_rows
                    meta, bufs = pack_rows(packed)
                    meta_msg = {"meta": meta, "node_ids": list(ids),
                                "k": packed.k}
                except Exception as e:
                    self._reply(conn, {"code": "packed_rejected",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not self._reply(conn, meta_msg, self.tally, codec):
                    return
                try:
                    send_bytes_frame(conn, bufs, self.tally, codec)
                except (OSError, ValueError):
                    return
            elif op == "heartbeat":
                # Liveness probe (docs/REPLICATION.md): works pre-hello
                # on the untagged framing, so a monitor needs no
                # capability negotiation to ask "are you serving?".
                # ServeTier implements the full replica-group form
                # (lease grants, role); here the reply is just the
                # replica's durable head — enough for a health poller
                # or an election probe against a gossip node.
                try:
                    state: dict = {"ok": True, "op": "heartbeat"}
                    with self.lock:
                        state["node"] = str(self.crdt.node_id)
                        state["hlc"] = str(self.crdt.canonical_time)
                        if msg.get("want_root") and callable(
                                getattr(self.crdt, "digest_tree",
                                        None)):
                            state["root"] = int(
                                self.crdt.digest_tree().root)
                except Exception as e:
                    self._reply(conn, {"code": "hb_failed",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not self._reply(conn, state, self.tally, codec):
                    return
            elif op == "metrics":
                # Registry snapshot + whatever the embedding runtime
                # (GossipNode: per-peer HLC lag) contributes. The
                # registry and the hook take their own locks; only the
                # replica-identity read holds the replica lock.
                try:
                    from .obs import metrics_snapshot
                    snap = metrics_snapshot()
                    extra = self.metrics_extra
                    if extra is not None:
                        snap.update(extra())
                    if "node" not in snap:
                        with self.lock:
                            snap["node"] = {
                                "node_id": str(self.crdt.node_id),
                                "hlc_head":
                                    str(self.crdt.canonical_time)}
                except Exception as e:
                    self._reply(conn, {"code": "metrics_failed",
                                       "error": type(e).__name__,
                                       "detail": str(e)},
                                self.tally, codec)
                    return
                if not sketch_ok:
                    # Pre-sketch sessions (no hello, or one that did
                    # not agree "sketch") get the reply a pre-sketch
                    # server produced, byte for byte: stripping the
                    # section restores the old key order exactly.
                    snap.pop("sketches", None)
                if not self._reply(conn, {"metrics": snap},
                                   self.tally, codec):
                    return
            elif op == "debug_dump":
                # Flight-recorder bundles (obs/recorder.py): the
                # post-incident forensics surface. New op — legacy
                # pollers never send it, so no cap gate is needed;
                # sketch sections still honor the negotiated cap.
                from .obs.recorder import default_recorder
                bundles = default_recorder().bundles()
                if not sketch_ok:
                    bundles = [
                        {k: v for k, v in b.items()
                         if k != "sketches"} for b in bundles]
                if not self._reply(conn, {"ok": True,
                                          "bundles": bundles},
                                   self.tally, codec):
                    return
            else:
                self._reply(conn, {"code": "unknown_op",
                                   "error": f"unknown op {op!r}"},
                            self.tally, codec)
                return
            if ring.enabled:
                with self.lock:
                    stamp = str(self.crdt.canonical_time)
                extra = {}
                if tctx is not None:
                    # Correlate the responder's frame with the
                    # initiator's sync span by round id.
                    for k in ("rid", "origin"):
                        if tctx.get(k) is not None:
                            extra[k] = tctx[k]
                ring.emit("wire_frame", hlc=stamp, op=op,
                          sent=self.tally.sent - sent0,
                          received=self.tally.received - received0,
                          **extra)

    @staticmethod
    def _reply(conn: socket.socket, obj: Any,
               tally: Optional[WireTally] = None,
               codec: Optional[FrameCodec] = None) -> bool:
        """Send a reply; a peer that vanished mid-reply just ends the
        connection, never the server."""
        try:
            send_frame(conn, obj, tally, codec)
            return True
        except (OSError, ValueError):
            return False


def _check_reply(what: str, reply: Any, want_field: str) -> None:
    """Classify a reply frame: a peer that vanished or desynchronized
    (None / missing field, no error report) is a TRANSPORT fault —
    retryable; an explicit error report is a PROTOCOL rejection —
    fatal. Preserves the legacy '<what>: <reply>' message shape."""
    if isinstance(reply, dict) and want_field in reply \
            and "error" not in reply:
        return
    if isinstance(reply, dict) and reply.get("code") == "busy":
        # Admission refusal (the server is at max_conns): transport
        # class, so retry/backoff machinery handles it — never a
        # protocol rejection, never a mode downgrade.
        raise SyncTransportError(f"{what}: peer busy ({reply!r})")
    if isinstance(reply, dict) and reply.get("code") == "moved":
        # Federation redirect: the slot lives on another tier. Typed
        # and retryable (replay at reply["owner"] after refetching the
        # routing table) — like busy, never a protocol rejection,
        # never a mode downgrade (docs/FEDERATION.md).
        raise SyncRedirectError(
            f"{what}: moved to {reply.get('owner')!r} "
            f"(epoch {reply.get('epoch')})",
            owner=reply.get("owner"), epoch=reply.get("epoch"))
    if isinstance(reply, dict) and ("error" in reply
                                    or reply.get("ok") is False):
        raise SyncProtocolError.from_reply(what, reply)
    raise SyncTransportError(f"{what}: {reply!r}")


def _trace_ctx(conn: "PeerConnection", node: str,
               since: Optional[Hlc], watermark: Hlc
               ) -> Optional[dict]:
    """Initiator-side trace context for one sync round — the compact
    payload the "trace" hello cap lets ride on sync frames: origin
    node, the round's HLC stamp range, and a fleet-unique round id.
    Returns None unless the session negotiated "trace" AND the
    process tracer is enabled, so with tracing off (or against a
    pre-trace peer) every frame stays byte-identical to the un-traced
    protocol and the hot path pays one attribute read."""
    from .obs.trace import round_id, tracer
    if "trace" not in conn.caps or not tracer().enabled:
        return None
    return {"rid": round_id(node), "origin": node,
            "hlc_lo": None if since is None else str(since),
            "hlc_hi": str(watermark)}


def _recv_span(op: str, tctx: Optional[dict]):
    """Responder-side merge span named ``<op>_recv`` carrying the
    initiator's round id/origin/stamp range, so both ends of a round
    correlate in one JSONL sink. A no-op context when the frame bore
    no trace context or tracing is off."""
    from .obs.trace import span, tracer
    if not isinstance(tctx, dict) or not tracer().enabled:
        import contextlib
        return contextlib.nullcontext()
    fields = {k: tctx[k] for k in ("rid", "origin", "hlc_lo",
                                   "hlc_hi")
              if tctx.get(k) is not None}
    return span(f"{op}_recv", kind="sync_recv", **fields)


class PeerConnection:
    """One keep-alive framed session to a :class:`SyncServer`.

    Connect + hello happen at most once per session (``ensure``); the
    `*_over_conn` round functions then reuse the socket round after
    round — the fresh-TCP-setup cost the pooled gossip path removes.
    Failure handling is by RESET, not repair: any round error closes
    the socket, and the next ``ensure`` reconnects (and renegotiates),
    which is exactly the shape `GossipNode`'s retry/breaker machinery
    expects — a replayed round is an idempotent lattice join.

    Negotiation: ``ensure`` sends ``hello`` with ``want_caps`` and
    intersects with the server's reply (:attr:`caps`); a pre-hello
    server answers ``unknown_op`` and hangs up, so the session marks
    itself ``legacy`` (sticky) and reconnects speaking the untagged
    pre-hello framing. ``negotiate=False`` skips hello entirely — the
    one-shot `sync_over_tcp` wrappers use it to keep their legacy
    wire bytes byte-identical.

    ``idle_timeout`` must stay BELOW the server's ``io_timeout``
    (default 20 s vs 30 s): a session parked longer than that may
    already be half-closed server-side, so ``ensure`` proactively
    reconnects instead of racing a dead socket. Passing
    ``idle_timeout=None`` disables the bound and is flagged by the
    crdtlint socket-timeout rule."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 idle_timeout: Optional[float] = 20.0,
                 negotiate: bool = True,
                 want_caps: Iterable[str] = ("zlib", "packed",
                                             "semantics", "merkle",
                                             "trace", "sketch")):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.idle_timeout = idle_timeout
        self.negotiate = negotiate
        self.want_caps = tuple(want_caps)
        self.legacy = False
        # Cleared (sticky per session) the first time a peer answers a
        # multi-level digest probe with only the first group — a
        # pre-prefetch server that advertises "merkle" but ignores
        # "more". Later walks on the session go single-level directly.
        self.digest_prefetch = True
        self.caps: frozenset = frozenset()
        self.codec: Optional[FrameCodec] = None
        self.connects = 0      # raw TCP connects (tests/bench hook)
        self._sock: Optional[socket.socket] = None
        self._last_used = 0.0

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def ensure(self, tally: Optional[WireTally] = None
               ) -> socket.socket:
        """The live socket — connecting (and negotiating) if needed.
        Raises :class:`SyncTransportError` when the peer is
        unreachable or the hello exchange dies mid-flight."""
        import time as _time
        if self._sock is not None:
            if self.idle_timeout is not None and (
                    _time.monotonic() - self._last_used
                    > self.idle_timeout):
                self.reset()
            else:
                self._last_used = _time.monotonic()
                return self._sock
        try:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.timeout)
            sock.settimeout(self.timeout)
        except OSError as e:
            raise SyncTransportError(
                f"connect to {self.host}:{self.port} failed: {e!r}"
            ) from e
        self.connects += 1
        self.caps = frozenset()
        self.codec = None
        self.digest_prefetch = True   # re-probe: the peer may differ
        if self.negotiate and not self.legacy:
            try:
                send_frame(sock, {"op": "hello", "proto": 1,
                                  "caps": list(self.want_caps)}, tally)
                reply = recv_frame(
                    sock, deadline=_time.monotonic() + self.timeout,
                    tally=tally)
            except (OSError, ValueError) as e:
                sock.close()
                raise SyncTransportError(f"hello failed: {e!r}") from e
            if isinstance(reply, dict) and reply.get("ok") \
                    and isinstance(reply.get("caps"), list):
                self.caps = frozenset(reply["caps"])
                self.codec = FrameCodec(compress="zlib" in self.caps)
            elif isinstance(reply, dict) \
                    and reply.get("code") == "busy":
                # Admission refusal at accept (max_conns): the server
                # understood us perfectly well, it just has no slot.
                # Retryable — and emphatically NOT the legacy signal:
                # a busy modern server must not demote the session to
                # the pre-hello framing forever.
                sock.close()
                raise SyncTransportError(
                    f"peer {self.host}:{self.port} at capacity "
                    f"(busy): {reply.get('error')!r}")
            elif isinstance(reply, dict) \
                    and reply.get("code") == "moved":
                # Federation redirect at hello: a modern server naming
                # the owning tier. Typed and retryable — like busy,
                # NOT the legacy signal (docs/FEDERATION.md).
                sock.close()
                raise SyncRedirectError(
                    f"peer {self.host}:{self.port} redirected to "
                    f"{reply.get('owner')!r} "
                    f"(epoch {reply.get('epoch')})",
                    owner=reply.get("owner"),
                    epoch=reply.get("epoch"))
            elif isinstance(reply, dict) and ("error" in reply
                                              or reply.get("ok")
                                              is False):
                # Pre-hello server: it reported unknown_op and hung
                # up. Sticky — reconnect once, without hello, and
                # speak the legacy framing from here on.
                sock.close()
                self.legacy = True
                return self.ensure(tally)
            else:
                # None / garbage: the link died mid-handshake.
                sock.close()
                raise SyncTransportError(f"hello failed: {reply!r}")
        self._sock = sock
        self._last_used = _time.monotonic()
        return sock

    def reset(self) -> None:
        """Drop the session (error path); the next ``ensure``
        reconnects. The ``legacy`` mark survives — a pre-hello peer
        does not grow a hello by reconnecting."""
        sock, self._sock = self._sock, None
        self.codec = None
        self.caps = frozenset()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self, tally: Optional[WireTally] = None) -> None:
        """Polite teardown: best-effort ``bye`` (ends the server's
        handler loop promptly instead of waiting out its io_timeout),
        then close."""
        sock = self._sock
        if sock is not None:
            try:
                send_frame(sock, {"op": "bye"}, tally, self.codec)
            except (OSError, ValueError):
                pass
        self.reset()

    def __enter__(self) -> "PeerConnection":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def sync_over_conn(crdt: Crdt, conn: PeerConnection,
                   since: Optional[Hlc] = None,
                   key_encoder=None, value_encoder=None,
                   key_decoder=None, value_decoder=None,
                   lock: Optional[threading.Lock] = None,
                   tally: Optional[WireTally] = None) -> Hlc:
    """One JSON anti-entropy round over a pooled session — the
    semantics of :func:`sync_over_tcp` (watermark captured before the
    push, inclusive delta bound, lock held only around local replica
    calls) minus the per-round connect, and with the session's
    negotiated codec applied to every frame. No ``bye`` is sent: the
    session stays parked for the next round. ANY failure resets the
    session (the error taxonomy is unchanged), so a retry reconnects
    cleanly."""
    if lock is None:
        lock = threading.Lock()   # uncontended no-op
    with lock:
        watermark = crdt.canonical_time
        payload = crdt.to_json(key_encoder=key_encoder,
                               value_encoder=value_encoder)
    import time as _time
    from .obs.trace import span
    sock = conn.ensure(tally)
    node = str(getattr(crdt, "node_id", "?"))
    tctx = _trace_ctx(conn, node, since, watermark)
    rid = {"rid": tctx["rid"]} if tctx else {}
    try:
        codec = conn.codec
        with span("sync_json", kind="sync", hlc=lambda: watermark,
                  node=node, **rid):
            msg = {"op": "push", "payload": payload}
            if tctx:
                msg["trace"] = tctx
            send_frame(sock, msg, tally, codec)
            reply = recv_frame(
                sock, deadline=_time.monotonic() + conn.timeout,
                tally=tally, codec=codec)
            _check_reply("push rejected", reply, "ok")
            msg = {"op": "delta", "since": None if since is None
                   else str(since)}
            if tctx:
                msg["trace"] = tctx
            send_frame(sock, msg, tally, codec)
            reply = recv_frame(
                sock, deadline=_time.monotonic() + conn.timeout,
                tally=tally, codec=codec)
            _check_reply("delta failed", reply, "payload")
            pulled = reply["payload"]
            with lock:
                crdt.merge_json(pulled, key_decoder=key_decoder,
                                value_decoder=value_decoder)
    except SyncError:
        conn.reset()
        raise
    except (OSError, ValueError) as e:
        conn.reset()
        raise SyncTransportError(f"sync round failed: {e!r}") from e
    return watermark


def sync_dense_over_conn(crdt, conn: PeerConnection,
                         since: Optional[Hlc] = None,
                         lock: Optional[threading.Lock] = None,
                         tally: Optional[WireTally] = None) -> Hlc:
    """One DENSE (kernel wire form) round over a pooled session —
    :func:`sync_dense_over_tcp` semantics minus the per-round
    connect. See :func:`sync_over_conn` for the session contract."""
    if lock is None:
        lock = threading.Lock()   # uncontended no-op
    with lock:
        watermark = crdt.canonical_time
        scs, ids = crdt.export_split_delta()
        meta, bufs = _pack_split(scs)
    import time as _time
    from .obs.trace import span
    sock = conn.ensure(tally)
    node = str(getattr(crdt, "node_id", "?"))
    tctx = _trace_ctx(conn, node, since, watermark)
    rid = {"rid": tctx["rid"]} if tctx else {}
    try:
        codec = conn.codec
        with span("sync_dense", kind="sync", hlc=lambda: watermark,
                  node=node, **rid):
            msg = {"op": "push_dense", "meta": meta,
                   "node_ids": list(ids)}
            if tctx:
                msg["trace"] = tctx
            send_frame(sock, msg, tally, codec)
            send_bytes_frame(sock, bufs, tally, codec)
            reply = recv_frame(
                sock, deadline=_time.monotonic() + conn.timeout,
                tally=tally, codec=codec)
            _check_reply("push rejected", reply, "ok")
            msg = {"op": "delta_dense", "since": None if since is None
                   else str(since)}
            if tctx:
                msg["trace"] = tctx
            send_frame(sock, msg, tally, codec)
            reply = recv_frame(
                sock, deadline=_time.monotonic() + conn.timeout,
                tally=tally, codec=codec)
            _check_reply("delta failed", reply, "meta")
            blob = recv_bytes_frame(sock,
                                    deadline=_time.monotonic()
                                    + conn.timeout,
                                    tally=tally, codec=codec)
            if blob is None:
                raise SyncTransportError("delta binary frame missing")
            peer_scs = _unpack_split(reply["meta"], blob)
            ids_in = reply.get("node_ids")
            if not isinstance(ids_in, list) or not ids_in:
                raise SyncTransportError(
                    "delta reply without node_ids")
            with lock:
                crdt.merge_split(peer_scs, ids_in)
    except SyncError:
        conn.reset()
        raise
    except (OSError, ValueError) as e:
        conn.reset()
        raise SyncTransportError(f"sync round failed: {e!r}") from e
    return watermark


def _pack_for_peer(crdt, since: Optional[Hlc],
                   sem_include: bool, ranges=None) -> Tuple:
    """`pack_since` with the semantics tag lane included only when the
    session negotiated the "semantics" capability. Crdts predating the
    ``sem_mode`` kwarg (no typed surface) get the plain call — their
    packs are 5-lane regardless. An un-negotiated session against a
    typed store gets ``sem_mode="auto"``, i.e. typed rows WITHHELD
    (never silently stripped of their tags — docs/TYPES.md).
    ``ranges`` is the anti-entropy slot-span mask; a crdt advertising
    the "merkle" cap always supports it, and passing it to one that
    doesn't raises TypeError, which the wire surfaces as a
    rejection."""
    if hasattr(crdt, "set_semantics"):
        sem_mode = "include" if sem_include else "auto"
        if ranges is not None:
            return crdt.pack_since(since, sem_mode=sem_mode,
                                   ranges=ranges)
        return crdt.pack_since(since, sem_mode=sem_mode)
    if ranges is not None:
        return crdt.pack_since(since, ranges=ranges)
    return crdt.pack_since(since)


def sync_packed_over_conn(crdt, conn: PeerConnection,
                          since: Optional[Hlc] = None,
                          lock: Optional[threading.Lock] = None,
                          tally: Optional[WireTally] = None,
                          _prepacked: Optional[Tuple] = None,
                          fused_repack: bool = False) -> Hlc:
    """One INCREMENTAL round over a pooled session: both directions
    ship the O(k) packed columnar form (`DenseCrdt.pack_since` /
    `merge_packed`), so bytes are proportional to the rows modified
    since ``since`` — not to store capacity (the dense form) or to
    full-state JSON. The same single watermark bounds BOTH halves:
    after a successful round the peer holds everything stamped before
    it, so the next round's ``pack_since(watermark)`` (inclusive)
    misses nothing; the first round (``since=None``) pushes and pulls
    full state. An empty half (k == 0) is skipped entirely — no op on
    the wire for the push, no merge for the pull — which keeps both
    clocks (and so both pack caches) untouched on a no-change round.

    Requires the peer to have advertised the "packed" cap
    (:class:`SyncProtocolError` code ``packed_rejected`` otherwise —
    the sticky-downgrade signal, raised before any bytes move).
    ``_prepacked`` is the pipelined gossip hook: a
    ``(watermark, packed, ids)`` triple packed earlier (overlapped
    with another peer's network phase) to use instead of packing
    here.

    ``fused_repack=True`` merges the pulled delta through
    `DenseCrdt.merge_and_repack`: the join and the NEXT round's pack
    mask run as one device dispatch, and the post-merge pack is seeded
    into the cache under this round's outgoing watermark — which is
    exactly the ``since`` the next round asks for, so a
    steady-state relay alternates merge+pack, merge+pack with zero
    standalone pack dispatches (docs/FASTPATH.md)."""
    if lock is None:
        lock = threading.Lock()   # uncontended no-op
    from .ops.packing import pack_rows, unpack_rows
    import time as _time
    # Negotiate BEFORE packing: whether the sem tag lane rides (and so
    # whether typed rows ship at all) depends on the session's caps.
    sock = conn.ensure(tally)
    if "packed" not in conn.caps:
        # Raised before any bytes move: the session is still in sync,
        # so no reset — the caller can immediately retry dense/JSON
        # over the same connection.
        raise SyncProtocolError(
            "peer did not advertise the 'packed' capability",
            code="packed_rejected")
    if _prepacked is not None:
        watermark, packed, ids = _prepacked
    else:
        with lock:
            # Commit any staged ingest-window writes BEFORE reading
            # the watermark: pack_since drains too, but its flush
            # advances the canonical after a watermark read here,
            # and a stale watermark re-sends every flushed row on
            # the next round.
            drain = getattr(crdt, "drain_ingest", None)
            if drain is not None:
                drain()
            watermark = crdt.canonical_time
            packed, ids = _pack_for_peer(crdt, since,
                                         "semantics" in conn.caps)
    from .obs.trace import span
    node = str(getattr(crdt, "node_id", "?"))
    tctx = _trace_ctx(conn, node, since, watermark)
    rid = {"rid": tctx["rid"]} if tctx else {}
    try:
        codec = conn.codec
        with span("sync_packed", kind="sync", hlc=lambda: watermark,
                  node=node, rows=packed.k, **rid):
            if packed.k:
                meta, bufs = pack_rows(packed)
                msg = {"op": "push_packed", "meta": meta,
                       "node_ids": list(ids)}
                if tctx:
                    msg["trace"] = tctx
                send_frame(sock, msg, tally, codec)
                send_bytes_frame(sock, bufs, tally, codec)
                reply = recv_frame(
                    sock, deadline=_time.monotonic() + conn.timeout,
                    tally=tally, codec=codec)
                _check_reply("push rejected", reply, "ok")
            msg = {"op": "delta_packed",
                   "since": None if since is None else str(since)}
            if tctx:
                msg["trace"] = tctx
            send_frame(sock, msg, tally, codec)
            reply = recv_frame(
                sock, deadline=_time.monotonic() + conn.timeout,
                tally=tally, codec=codec)
            _check_reply("delta failed", reply, "meta")
            blob = recv_bytes_frame(sock,
                                    deadline=_time.monotonic()
                                    + conn.timeout,
                                    tally=tally, codec=codec)
            if blob is None:
                raise SyncTransportError("delta binary frame missing")
            peer_packed = unpack_rows(reply["meta"], blob)
            ids_in = reply.get("node_ids")
            if not isinstance(ids_in, list):
                raise SyncTransportError(
                    "delta reply without node_ids")
            if peer_packed.k:
                if not ids_in:
                    raise SyncTransportError(
                        "delta reply without node_ids")
                with lock:
                    if fused_repack and hasattr(crdt,
                                                "merge_and_repack"):
                        # Seed the next round's pack while the join is
                        # on device anyway; `watermark` (this round's
                        # pre-push canonical) is the `since` the next
                        # round's pack_for_peer will present.
                        crdt.merge_and_repack(
                            peer_packed, ids_in, since=watermark,
                            sem_mode=("include"
                                      if "semantics" in conn.caps
                                      else "auto"))
                    else:
                        crdt.merge_packed(peer_packed, ids_in)
    except SyncError:
        conn.reset()
        raise
    except (OSError, ValueError) as e:
        conn.reset()
        raise SyncTransportError(f"sync round failed: {e!r}") from e
    return watermark


class _DigestPrefetchUnsupported(Exception):
    """Internal walk signal: the peer advertises "merkle" but ignored
    a multi-level probe's "more" groups (pre-prefetch release). Both
    reply frames were consumed, so the session is still framed-in-sync
    — the walk restarts single-level instead of aborting."""


def sync_merkle_over_conn(crdt, conn: PeerConnection,
                          lock: Optional[threading.Lock] = None,
                          tally: Optional[WireTally] = None,
                          fused_repack: bool = False,
                          _stats: Optional[dict] = None) -> Hlc:
    """One Merkle ANTI-ENTROPY round over a pooled session
    (docs/ANTIENTROPY.md) — the cold/partitioned-peer complement to
    `sync_packed_over_conn`: instead of a watermark (which a fresh
    peer doesn't have) the two replicas compare digest trees, walking
    only differing subtrees via the ``digest`` op — one round trip per
    level, <= log2(n_leaves)+1 total — and then re-ship JUST the
    divergent leaf ranges through ``pack_since(ranges=...)`` in both
    directions. Matching roots end the round after ONE probe with
    zero payload bytes; traffic scales with divergence, not store
    size.

    Requires the "merkle" cap (:class:`SyncProtocolError` code
    ``merkle_rejected`` before any payload bytes otherwise — the
    sticky-downgrade signal), and aborts the same way on tree
    geometry (n_slots/leaf_width) mismatch, where a full packed round
    is the correct fallback. The walk probes a live peer: if the peer
    mutates mid-walk the ranges are computed against mixed snapshots,
    which is safe (the range pack + lattice join are idempotent; the
    next round converges the residue). Returns the local pre-walk
    canonical time — the watermark incremental rounds resume from.
    ``_stats`` (bench/test hook) receives rounds / digests / ranges /
    row counts."""
    if lock is None:
        lock = threading.Lock()   # uncontended no-op
    from .obs.registry import default_registry
    from .obs.trace import span
    from .ops.digest import coalesce_leaf_ranges, walk_divergent_leaves
    from .ops.packing import pack_rows, unpack_rows
    import time as _time
    sock = conn.ensure(tally)
    if "merkle" not in conn.caps:
        raise SyncProtocolError(
            "peer did not advertise the 'merkle' capability",
            code="merkle_rejected")
    with lock:
        drain = getattr(crdt, "drain_ingest", None)
        if drain is not None:
            drain()
        watermark = crdt.canonical_time
        tree = crdt.digest_tree()
    codec = conn.codec
    node = str(getattr(crdt, "node_id", "?"))
    # One round id spans the whole walk: every digest probe and both
    # re-ship halves carry it, so the responder's merge span and each
    # wire_frame correlate back to this initiator span.
    tctx = _trace_ctx(conn, node, None, watermark)
    rid = {"rid": tctx["rid"]} if tctx else {}

    def fetch_levels(groups):
        # One round trip for the whole multi-level probe: the first
        # group rides the original level/idx fields (so the request
        # degrades to the single-level op when there is only one) and
        # the rest ride "more" — the frontier-prefetch extension
        # (docs/ANTIENTROPY.md).
        import numpy as _np
        (level0, idxs0) = groups[0]
        msg = {"op": "digest", "level": level0, "idx": list(idxs0)}
        if len(groups) > 1:
            msg["more"] = [[lvl, list(ix)] for lvl, ix in groups[1:]]
        if tctx:
            msg["trace"] = tctx
        send_frame(sock, msg, tally, codec)
        reply = recv_frame(
            sock, deadline=_time.monotonic() + conn.timeout,
            tally=tally, codec=codec)
        _check_reply("digest failed", reply, "k")
        if level0 == 0 and not tree.same_geometry(
                reply.get("n_slots"), reply.get("leaf_width"),
                reply.get("depth")):
            # The probe exchange completed, so the session is still
            # framed-in-sync; the reset in the outer handler is the
            # conservative price of the shared error path.
            raise SyncProtocolError(
                f"merkle geometry mismatch: local "
                f"({tree.n_slots}, {tree.leaf_width}, {tree.depth}) "
                f"vs peer ({reply.get('n_slots')}, "
                f"{reply.get('leaf_width')}, {reply.get('depth')})",
                code="merkle_rejected")
        blob = recv_bytes_frame(
            sock, deadline=_time.monotonic() + conn.timeout,
            tally=tally, codec=codec)
        ks = reply.get("ks")
        if ks is None:
            if len(groups) > 1 and blob is not None \
                    and reply["k"] == len(groups[0][1]) \
                    and len(blob) == 8 * reply["k"]:
                # A pre-prefetch server (previous release, same
                # "merkle" cap) ignores "more" and answers ONLY the
                # first group, without "ks". The exchange is complete,
                # so degrade the walk to single-level rather than
                # treating the shorter reply as a framing error.
                raise _DigestPrefetchUnsupported
            ks = [reply["k"]]
        if blob is None or not isinstance(ks, list) \
                or len(ks) != len(groups) \
                or ks != [len(ix) for _, ix in groups] \
                or reply["k"] != sum(ks) \
                or len(blob) != 8 * reply["k"]:
            raise SyncTransportError("digest binary frame mismatch")
        flat = _np.frombuffer(blob, ">u8").tolist()
        out, off = [], 0
        for k in ks:
            out.append(flat[off:off + k])
            off += k
        return out

    def fetch_one(level, idxs):
        # Single-group probes never carry "more", so every "merkle"
        # server generation answers them identically.
        return fetch_levels([(level, idxs)])[0]

    try:
        with span("sync_merkle", kind="sync",
                  hlc=lambda: watermark, node=node, **rid):
            if conn.digest_prefetch:
                try:
                    leaves, rounds, fetched = walk_divergent_leaves(
                        tree, None, fetch_levels=fetch_levels)
                except _DigestPrefetchUnsupported:
                    # Sticky for the session: later walks skip the
                    # futile multi-level probe entirely.
                    conn.digest_prefetch = False
                    leaves, rounds, fetched = walk_divergent_leaves(
                        tree, fetch_one)
                    rounds += 1   # the aborted prefetch probe
            else:
                leaves, rounds, fetched = walk_divergent_leaves(
                    tree, fetch_one)
            reg = default_registry()
            reg.counter(
                "crdt_tpu_merkle_digest_rounds_total",
                "digest round trips spent walking peer trees"
            ).inc(rounds, node=node)
            reg.counter(
                "crdt_tpu_merkle_sync_total",
                "merkle anti-entropy rounds by outcome"
            ).inc(outcome="diverged" if leaves else "clean", node=node)
            if _stats is not None:
                _stats.update(rounds=rounds, digests=fetched,
                              ranges=(), pushed_rows=0, pulled_rows=0)
            if not leaves:
                return watermark
            ranges = coalesce_leaf_ranges(leaves, tree.leaf_width,
                                          tree.n_slots)
            reg.counter(
                "crdt_tpu_merkle_ranges_shipped_total",
                "divergent slot ranges re-shipped after walks"
            ).inc(len(ranges), node=node)
            # Both halves are clock-unbounded WITHIN the ranges: the
            # divergence may predate any watermark either side holds.
            with lock:
                packed, ids = _pack_for_peer(
                    crdt, None, "semantics" in conn.caps,
                    ranges=ranges)
            if packed.k:
                meta, bufs = pack_rows(packed)
                msg = {"op": "push_packed", "meta": meta,
                       "node_ids": list(ids)}
                if tctx:
                    msg["trace"] = tctx
                send_frame(sock, msg, tally, codec)
                send_bytes_frame(sock, bufs, tally, codec)
                reply = recv_frame(
                    sock, deadline=_time.monotonic() + conn.timeout,
                    tally=tally, codec=codec)
                _check_reply("push rejected", reply, "ok")
            msg = {"op": "delta_packed", "since": None,
                   "ranges": [list(r) for r in ranges]}
            if tctx:
                msg["trace"] = tctx
            send_frame(sock, msg, tally, codec)
            reply = recv_frame(
                sock, deadline=_time.monotonic() + conn.timeout,
                tally=tally, codec=codec)
            _check_reply("delta failed", reply, "meta")
            blob = recv_bytes_frame(
                sock, deadline=_time.monotonic() + conn.timeout,
                tally=tally, codec=codec)
            if blob is None:
                raise SyncTransportError("delta binary frame missing")
            peer_packed = unpack_rows(reply["meta"], blob)
            ids_in = reply.get("node_ids")
            if not isinstance(ids_in, list):
                raise SyncTransportError("delta reply without node_ids")
            if peer_packed.k:
                if not ids_in:
                    raise SyncTransportError(
                        "delta reply without node_ids")
                with lock:
                    if fused_repack and hasattr(crdt,
                                                "merge_and_repack"):
                        # Seed the FOLLOW-UP incremental round's pack
                        # (same contract as the packed path).
                        crdt.merge_and_repack(
                            peer_packed, ids_in, since=watermark,
                            sem_mode=("include"
                                      if "semantics" in conn.caps
                                      else "auto"))
                    else:
                        crdt.merge_packed(peer_packed, ids_in)
            if _stats is not None:
                _stats.update(ranges=ranges, pushed_rows=packed.k,
                              pulled_rows=peer_packed.k)
    except SyncError:
        conn.reset()
        raise
    except (OSError, ValueError) as e:
        conn.reset()
        raise SyncTransportError(f"sync round failed: {e!r}") from e
    return watermark


def sync_over_tcp(crdt: Crdt, host: str, port: int,
                  since: Optional[Hlc] = None,
                  timeout: float = 30.0,
                  key_encoder=None, value_encoder=None,
                  key_decoder=None, value_decoder=None,
                  lock: Optional[threading.Lock] = None,
                  tally: Optional[WireTally] = None) -> Hlc:
    """One anti-entropy round against a :class:`SyncServer`.

    ``since`` is this replica's delta watermark: pass None on first
    contact with a peer (cold start — a fresh replica has seen
    nothing, so the pull must be full) and the returned watermark on
    later rounds. The watermark is captured BEFORE pushing, exactly
    like the reference's `_sync` (test/map_crdt_test.dart:273-279);
    the inclusive `modified >= since` bound (map_crdt.dart:44-45)
    then guarantees nothing stamped after it is missed.

    ``lock`` serializes access to the LOCAL replica: when ``crdt`` is
    also served by its own `SyncServer` (the natural bidirectional
    mesh), pass that server's :attr:`SyncServer.lock` here — without
    it this round's reads/merges race the server thread. The lock is
    held only around local replica calls, never across network waits,
    so a gossiping mesh of self-served replicas cannot deadlock on
    each other's rounds.

    Failures raise the :class:`SyncError` taxonomy: link faults as
    retryable :class:`SyncTransportError`, peer rejections as fatal
    :class:`SyncProtocolError` — both still `ConnectionError`.
    ``tally``, when given, accumulates wire bytes for the round.

    This is the one-shot wrapper around :func:`sync_over_conn`: a
    non-negotiating session (no hello — the wire bytes are exactly
    the pre-hello protocol, so any server vintage interoperates) that
    lives for one round and says ``bye``. Gossip pools a
    :class:`PeerConnection` instead.
    """
    conn = PeerConnection(host, port, timeout=timeout,
                          negotiate=False)
    try:
        watermark = sync_over_conn(crdt, conn, since=since,
                                   key_encoder=key_encoder,
                                   value_encoder=value_encoder,
                                   key_decoder=key_decoder,
                                   value_decoder=value_decoder,
                                   lock=lock, tally=tally)
        conn.close(tally)
    except BaseException:
        conn.reset()
        raise
    return watermark


def sync_dense_over_tcp(crdt, host: str, port: int,
                        since: Optional[Hlc] = None,
                        timeout: float = 30.0,
                        lock: Optional[threading.Lock] = None,
                        tally: Optional[WireTally] = None) -> Hlc:
    """One anti-entropy round between DENSE replicas in the kernel
    wire form: split 32-bit lanes as raw binary frames
    (`DenseCrdt.export_split_delta` / `merge_split`) — ~19 B per slot
    on the wire instead of ~90 B of JSON text, and no text codec on
    either side. Watermark/``since``/``lock`` semantics are exactly
    :func:`sync_over_tcp`'s; both peers must be dense models at the
    same capacity (the server reports a rejection otherwise — fall
    back to :func:`sync_over_tcp`, the universal interop path).

    Cold-start caveat: a server whose kernel merge path has never
    compiled can exceed the default 30 s ``timeout`` on its FIRST
    round (Mosaic compiles run ~20-40 s on some TPU runtimes) — warm
    the replica with one local merge, or pass a larger timeout for
    first contact.

    One-shot wrapper around :func:`sync_dense_over_conn` (no hello —
    exactly the pre-hello wire bytes); gossip pools a
    :class:`PeerConnection` instead."""
    conn = PeerConnection(host, port, timeout=timeout,
                          negotiate=False)
    try:
        watermark = sync_dense_over_conn(crdt, conn, since=since,
                                         lock=lock, tally=tally)
        conn.close(tally)
    except BaseException:
        conn.reset()
        raise
    return watermark


def _poll_op(host: str, port: int, msg: dict, want_field: str,
             what: str, timeout: float,
             tally: Optional[WireTally], negotiate: bool) -> Any:
    """One-shot request/reply poll shared by `fetch_metrics` and
    `fetch_debug_dump`. With ``negotiate`` the poll opens with a
    hello asking for the ``sketch`` cap (so sketch-capable servers
    include quantile payloads); a pre-hello server answers
    ``unknown_op`` and hangs up, and the poll retries on a fresh
    socket WITHOUT hello — byte-identical to what an old poller
    sends, so mixed-version fleets scrape cleanly both ways."""
    import time as _time
    try:
        with socket.create_connection((host, port),
                                      timeout=timeout) as sock:
            sock.settimeout(timeout)
            codec: Optional[FrameCodec] = None
            if negotiate:
                send_frame(sock, {"op": "hello", "proto": 1,
                                  "caps": ["zlib", "sketch"]}, tally)
                hello = recv_frame(
                    sock, deadline=_time.monotonic() + timeout,
                    tally=tally)
                if isinstance(hello, dict) and hello.get("ok") \
                        and isinstance(hello.get("caps"), list):
                    codec = FrameCodec(
                        compress="zlib" in hello["caps"])
                else:
                    # Pre-hello server: it reported unknown_op and
                    # hung up. Fall back to the bare legacy poll.
                    return _poll_op(host, port, msg, want_field,
                                    what, timeout, tally,
                                    negotiate=False)
            send_frame(sock, msg, tally, codec)
            reply = recv_frame(sock,
                               deadline=_time.monotonic() + timeout,
                               tally=tally, codec=codec)
            _check_reply(what, reply, want_field)
            send_frame(sock, {"op": "bye"}, tally, codec)
            return reply[want_field]
    except SyncError:
        raise
    except (OSError, ValueError) as e:
        raise SyncTransportError(f"{what}: {e!r}") from e


def fetch_metrics(host: str, port: int, timeout: float = 10.0,
                  tally: Optional[WireTally] = None,
                  sketches: bool = True) -> dict:
    """Poll a :class:`SyncServer`'s ``metrics`` op: one registry
    snapshot (merge/peer/wire counters, and — when the server belongs
    to a `GossipNode` — per-peer HLC lag under ``"lag"``). Raises the
    usual :class:`SyncError` taxonomy; a pre-metrics server replies
    ``unknown_op``, surfaced as :class:`SyncProtocolError`.

    With ``sketches`` (the default) the poll negotiates the
    ``sketch`` hello cap first, so the snapshot includes the
    ``"sketches"`` quantile section from sketch-capable servers;
    pre-hello servers are re-polled with the legacy bare frame.
    ``sketches=False`` skips hello entirely — the legacy wire bytes,
    unchanged."""
    return _poll_op(host, port, {"op": "metrics"}, "metrics",
                    "metrics poll failed", timeout, tally,
                    negotiate=sketches)


def fetch_debug_dump(host: str, port: int, timeout: float = 10.0,
                     tally: Optional[WireTally] = None) -> list:
    """Fetch a server's flight-recorder bundles (``debug_dump`` op;
    obs/recorder.py) — the post-incident forensics pull. Pre-recorder
    servers answer ``unknown_op``, surfaced as
    :class:`SyncProtocolError`."""
    return _poll_op(host, port, {"op": "debug_dump"}, "bundles",
                    "debug dump failed", timeout, tally,
                    negotiate=True)

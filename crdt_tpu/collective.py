"""Pod-local replica groups: N co-located `DenseCrdt`s converged by
ONE collective dispatch (docs/COLLECTIVE.md).

`CollectiveGroup` is the host-side owner of the
`parallel.collective.make_collective_join` program: it pins the
member replicas to a 1-D member mesh, keeps their node tables and
semantics columns aligned (two replicas must never join one slot
under two different lattices — the same contract `merge_packed`
enforces on the wire), and exposes :meth:`join`, after which every
member's replicated lanes are bit-identical to the socket-path merge
of the same deltas.

One ``join()`` is one device dispatch. Everything the pairwise relay
path gets from `merge_and_repack` rides the same program: per-member
``mod`` stamps, the next round's repack masks (pack caches are
pre-seeded under each member's pre-join watermark), and the post-join
digest-tree levels (digest caches are pre-seeded too) — so a
follow-up cross-pod socket round packs and walks from warm caches
without dispatching anything.

Group membership is declared at construction, optionally with the
``"host:port"`` addresses the routing layer speaks (`routing.py`),
so `GossipNode` can detect mesh-co-located peers by address and route
intra-pod rounds here while cross-pod peers keep the
merkle→packed→dense→json ladder.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .hlc import Hlc
from .ops.digest import build_digest_tree
from .parallel.collective import (MEMBER_AXIS, make_collective_join,
                                  make_collective_mesh)


class CollectiveJoinReport:
    """What one collective round did — the in-process accounting twin
    of `sync.MerkleSyncReport`, for benches and invariant probes.
    ``bytes_to_wire`` is identically 0: the lattice join moved over
    the mesh, not a socket."""

    __slots__ = ("new_canonical", "win_counts", "digest_root",
                 "members")
    bytes_to_wire = 0

    def __init__(self, new_canonical: int, win_counts: List[int],
                 digest_root: int, members: int):
        self.new_canonical = new_canonical
        self.win_counts = win_counts
        self.digest_root = digest_root
        self.members = members

    @property
    def adopted(self) -> int:
        return sum(self.win_counts)


class CollectiveGroup:
    """N mesh-co-located `DenseCrdt` replicas joined as one collective.

    ``members`` are the live replica objects (>= 2, equal geometry,
    distinct node ids). ``mesh`` defaults to a 1-D member mesh over
    the first N devices. ``addresses`` optionally maps each member's
    node id to the ``"host:port"`` string its `GossipNode` server
    answers on — the routing-layer identity co-location detection
    keys on (consistent with `routing.py`, so replica groups per
    partition can adopt the same declaration)."""

    # Checked by analysis/concurrency.py: the collective path holds NO
    # host locks — the single-dispatch join serializes on the device
    # stream, and member stores are quiesced by the caller
    # (docs/COLLECTIVE.md). The empty contract makes "lock-free by
    # design" a checked statement rather than prose.
    _CRDTLINT_LOCK_ORDER: tuple = ()

    def __init__(self, members: Sequence[Any], mesh=None,
                 addresses: Optional[Dict[Any, str]] = None):
        members = list(members)
        if len(members) < 2:
            raise ValueError(
                f"a collective group needs >= 2 members, got "
                f"{len(members)}")
        ids = [m.node_id for m in members]
        if len(set(ids)) != len(ids):
            raise ValueError(
                f"collective group members must carry distinct node "
                f"ids, got {ids}")
        first = members[0]
        for m in members[1:]:
            if m.n_slots != first.n_slots:
                raise ValueError(
                    f"collective group members disagree on n_slots: "
                    f"{first.n_slots} vs {m.n_slots}")
            if m._value_width != first._value_width:
                raise ValueError(
                    "collective group members disagree on value_width")
            if m.DIGEST_LEAF_WIDTH != first.DIGEST_LEAF_WIDTH:
                raise ValueError(
                    "collective group members disagree on digest "
                    "leaf width")
        if mesh is None:
            mesh = make_collective_mesh(len(members))
        if mesh.shape[MEMBER_AXIS] != len(members):
            raise ValueError(
                f"mesh member extent {mesh.shape[MEMBER_AXIS]} != "
                f"{len(members)} members")
        self.members = members
        self.mesh = mesh
        self.addresses = dict(addresses or {})
        unknown = set(self.addresses) - set(ids)
        if unknown:
            raise ValueError(
                f"addresses name non-member node ids: {sorted(unknown)}")
        self._member_ids = set(id(m) for m in members)
        self._align_tables()

    # --- membership surface (what GossipNode's fast lane keys on) ---

    def contains(self, crdt: Any) -> bool:
        """Is this live replica object a group member (identity, not
        equality — a copy with the same node id is NOT co-located)."""
        return id(crdt) in self._member_ids

    def address_of(self, node_id: Any) -> Optional[str]:
        return self.addresses.get(node_id)

    def member_addresses(self) -> frozenset:
        """The declared ``"host:port"`` identities of the group —
        `GossipNode.add_peer` marks a peer collective when its
        address lands in this set."""
        return frozenset(self.addresses.values())

    # --- alignment: shared table, shared lattice ---

    def _align_tables(self) -> None:
        """Union-intern every member's node ids into every member.
        Node ordinals are replica-local (`ops.packing.NodeTable`), so
        the device compare of node lanes is only meaningful once all
        members hold the SAME sorted table; `_intern_ids` re-encodes
        stored lanes when ordinals shift (a dispatch — which is why
        steady-state rounds, where tables already agree, stay at one
        dispatch for the join itself)."""
        union: set = set()
        for m in self.members:
            union.update(m._table.ids())
        union_list = sorted(union, key=lambda x: (str(type(x)), str(x)))
        for m in self.members:
            if len(m._table) != len(union):
                m._intern_ids(union_list)

    def _check_semantics(self) -> bool:
        """All members must govern every slot by the same lattice
        before lanes may join — the collective twin of the packed
        wire's tag-mismatch rejection."""
        sems = [m._sem_host() for m in self.members]
        ref = sems[0]
        for m, sem in zip(self.members[1:], sems[1:]):
            mism = sem != ref
            if bool(mism.any()):
                slot = int(np.nonzero(mism)[0][0])
                raise ValueError(
                    f"semantics tag mismatch at slot {slot}: member "
                    f"{self.members[0].node_id!r} holds tag "
                    f"{int(ref[slot])}, member {m.node_id!r} holds "
                    f"{int(sem[slot])}; run the same set_semantics "
                    "migration on every group member before joining")
        return bool(ref.any())

    # --- the round ---

    def join(self, seed_packs: bool = True) -> CollectiveJoinReport:
        """One collective anti-entropy round: drain ingest overlays,
        run the single-dispatch lattice join, land every member on the
        joined store with its canonical clock, digest cache and (when
        ``seed_packs``) pack cache pre-seeded — the `merge_and_repack`
        contract, amortized over the whole group in one program."""
        from .obs.trace import round_id, span, tracer
        members = self.members
        for m in members:
            m.drain_ingest()
        self._align_tables()
        has_sem = self._check_semantics()

        watermarks = [m.canonical_time for m in members]
        table = members[0]._table
        me = np.asarray([table.ordinal(m.node_id) for m in members],
                        np.int32)
        since = np.asarray([w.logical_time for w in watermarks],
                           np.int64)
        canonical_in = jnp.int64(max(w.logical_time
                                     for w in watermarks))
        leaf_width = members[0].DIGEST_LEAF_WIDTH
        # CPU ignores donation (with a warning per call); only donate
        # when every member's snapshot is donatable on this backend.
        donate = all(m._donate_writes() for m in members)
        step = make_collective_join(self.mesh, has_sem, leaf_width,
                                    donate=donate)

        node = str(members[0].node_id)
        rid = {"rid": round_id(node)} if tracer().enabled else {}
        with span("collective_join", kind="sync", node=node,
                  hlc=lambda: members[0].canonical_time,
                  members=len(members), **rid):
            stores = tuple(m._store for m in members)
            args = ((members[0]._sem_device(),) if has_sem else ())
            stacked, res = step(stores, *args, since, me, canonical_in)

            # ONE batched fetch: masks + replicated lanes + clock.
            # mod lanes stay device-only, as everywhere else.
            win_h, repack_h, lt_h, node_h, val_h, tomb_h, canonical = \
                jax.device_get((res.win, res.repack, stacked.lt,
                                stacked.node, stacked.val, stacked.tomb,
                                res.new_canonical))
            canonical = int(canonical)
            tree = build_digest_tree(members[0].n_slots, leaf_width,
                                     res.levels)

        win_counts = []
        for i, m in enumerate(members):
            new_store = jax.tree_util.tree_map(lambda a: a[i], stacked)
            m._store = new_store            # setter clears both caches
            m._store_escaped = False
            # Clock lands without a refresh dispatch: the program's
            # canonical IS max(member canonicals, every lt joined).
            m._canonical_time = Hlc.from_logical_time(canonical,
                                                      m.node_id)
            m._digest_cache = ((canonical, m._sem_version,
                                m._store_gen), tree)
            m.stats.merges += 1
            win_counts.append(int(win_h[i].sum()))
            if seed_packs:
                self._seed_pack(m, watermarks[i], repack_h[i], lt_h[i],
                                node_h[i], val_h[i], tomb_h[i],
                                canonical, has_sem)
        return CollectiveJoinReport(
            new_canonical=canonical, win_counts=win_counts,
            digest_root=tree.root, members=len(members))

    @staticmethod
    def _seed_pack(m, watermark: Hlc, mask, lt, node, val, tomb,
                   canonical: int, has_sem: bool) -> None:
        """Seed the member's pack cache under its pre-join watermark —
        the exact key the next watermark-aligned `pack_since` (a
        cross-pod peer resuming delta sync) presents. Host-side
        column select only (`_pack_host_columns`): no wire stage runs,
        so ``crdt_tpu_pack_copy_bytes_total`` does not move."""
        resolved = m._resolve_sem_mode("include" if has_sem else "auto")
        # The lanes arrive as numpy rows of join()'s one batched
        # device_get — column select only, no further copy.
        packed = m._pack_host_columns(mask, lt, node, val, tomb,
                                      resolved)
        key = (watermark.logical_time, canonical, m._sem_version,
               m._store_gen, resolved, None)
        m._pack_cache_store(key, (packed, m._table.ids()))

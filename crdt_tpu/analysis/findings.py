"""Finding model, suppression comments, report rendering (crdtlint).

A :class:`Finding` is one analyzer verdict pinned to a location: a
source line for the host linter, a pseudo-path like ``<jaxpr:target>``
or ``<law:target>`` for the device-side auditors. Findings are data —
the CLI decides rendering and exit codes, tests assert on them
directly.

Suppression syntax (host-linter findings only — jaxpr/law findings
name no source line to hang a comment on)::

    x = risky_call()  # crdtlint: disable=rule-id -- why this is safe
    # crdtlint: disable=rule-a,rule-b -- reason covering the next line
    y = other_call()

A suppression comment applies to its own line and the line directly
below it, so both trailing and line-above placements work. The
``-- reason`` is required: an unexplained suppression is itself a
finding (``suppression-without-reason``) — the whole point of the
comment is to record the uniqueness/safety argument next to the code
that depends on it.
"""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional

_SUPPRESS_RE = re.compile(
    r"#\s*crdtlint:\s*disable=([a-z0-9_\-]+(?:\s*,\s*[a-z0-9_\-]+)*)"
    r"(\s*--\s*\S.*)?$")


@dataclass(frozen=True)
class Finding:
    """One analyzer verdict. ``line`` is 1-based; 0 for findings that
    are not pinned to source (law counterexamples, jaxpr hazards)."""

    rule: str
    path: str
    line: int
    message: str
    detail: str = ""

    def format(self) -> str:
        head = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if not self.detail:
            return head
        body = "\n".join("    " + ln for ln in self.detail.splitlines())
        return head + "\n" + body


@dataclass
class Suppressions:
    """Per-file suppression map: line -> rule ids suppressed there."""

    by_line: Dict[int, FrozenSet[str]] = field(default_factory=dict)
    #: suppression comments missing the mandatory ``-- reason``
    unexplained: List[int] = field(default_factory=list)

    def covers(self, rule: str, line: int) -> bool:
        return rule in self.by_line.get(line, frozenset())


def parse_suppressions(text: str) -> Suppressions:
    """Scan source text for ``# crdtlint: disable=...`` comments.

    A comment at line L suppresses the named rules at L (trailing
    comment) and L+1 (comment-above placement)."""
    supp = Suppressions()
    for lineno, raw in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(raw)
        if m is None:
            continue
        if m.group(2) is None:
            supp.unexplained.append(lineno)
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(",")
                          if r.strip())
        for at in (lineno, lineno + 1):
            supp.by_line[at] = supp.by_line.get(at, frozenset()) | rules
    return supp


def apply_suppressions(findings: Iterable[Finding], supp: Suppressions,
                       path: str) -> List[Finding]:
    """Drop findings covered by suppression comments; surface any
    suppression comment that carries no reason as its own finding."""
    kept = [f for f in findings if not supp.covers(f.rule, f.line)]
    for lineno in supp.unexplained:
        kept.append(Finding(
            rule="suppression-without-reason", path=path, line=lineno,
            message="crdtlint suppression without a '-- reason'; "
                    "record why the rule is safe to silence here"))
    return kept


def render_human(findings: List[Finding],
                 summary: Optional[str] = None) -> str:
    lines = [f.format() for f in findings]
    if summary:
        lines.append(summary)
    if findings:
        lines.append(f"{len(findings)} finding"
                     f"{'s' if len(findings) != 1 else ''}")
    return "\n".join(lines)


def render_json(findings: List[Finding], **extra) -> str:
    payload = {"findings": [asdict(f) for f in findings],
               "ok": not findings}
    payload.update(extra)
    return json.dumps(payload, indent=2, sort_keys=True)

"""Dense N-replica fan-in: the throughput-oriented API.

16 writer replicas each produce a batch of updates over a shared
64K-slot key space; a hub replica fans them all in with ONE fused
lattice join (`DenseCrdt.merge_many`), then a late writer's conflicting
updates demonstrate LWW resolution. On a multi-device machine the same
script runs the hub key-sharded over a mesh (`ShardedDenseCrdt`).
"""

import numpy as np

import jax

from crdt_tpu import DenseCrdt, ShardedDenseCrdt, sync_dense
from crdt_tpu.parallel import make_fanin_mesh

N_SLOTS = 1 << 16
N_WRITERS = 16


def main() -> None:
    rng = np.random.default_rng(0)

    writers = [DenseCrdt(f"writer-{i:02d}", N_SLOTS) for i in range(N_WRITERS)]
    for i, w in enumerate(writers):
        slots = rng.choice(N_SLOTS, size=2048, replace=False)
        w.put_batch(slots, slots * 10 + i)

    # Hub: key-sharded across all local devices if there are several.
    n_dev = len(jax.devices())
    if n_dev > 1:
        hub = ShardedDenseCrdt("hub", N_SLOTS, make_fanin_mesh(1, n_dev))
        kind = f"sharded over {n_dev} devices"
    else:
        hub = DenseCrdt("hub", N_SLOTS)
        kind = "single device"

    hub.merge_many([w.export_delta() for w in writers])
    print(f"hub ({kind}): {len(hub)} live records after fan-in; "
          f"stats={hub.stats.as_dict()}")

    # A later write wins its conflicts (LWW)...
    late = DenseCrdt("writer-99", N_SLOTS)
    late.put_batch([0, 1, 2], [900, 901, 902])
    sync_dense(late, hub)
    print(f"slot 0 after late writer: {hub.get(0)}")

    # ...and deletes propagate as tombstones.
    late.delete_batch([0])
    sync_dense(late, hub)
    print(f"slot 0 after delete: {hub.get(0)} "
          f"(live records: {len(hub)})")


if __name__ == "__main__":
    main()

"""Merge observability counters on the device-columnar backend."""

from crdt_tpu import Hlc, Record, TpuMapCrdt
from crdt_tpu.testing import FakeClock

MILLIS = 1_700_000_000_000


def test_counters_track_merge_flow():
    crdt = TpuMapCrdt("abc", wall_clock=FakeClock())
    crdt.put("x", 1)
    crdt.put_all({"y": 2, "z": 3})
    assert crdt.stats.puts == 2
    assert crdt.stats.records_put == 3

    h_new = Hlc(MILLIS + 50, 0, "other")
    h_old = Hlc(1, 0, "other")
    crdt.merge({"x": Record(h_old, 99, h_old),     # loses
                "w": Record(h_new, 4, h_new)})     # wins
    assert crdt.stats.merges == 1
    assert crdt.stats.records_seen == 2
    assert crdt.stats.records_adopted == 1

    crdt.stats.reset()
    assert crdt.stats.as_dict() == {
        "merges": 0, "records_seen": 0, "records_adopted": 0,
        "puts": 0, "records_put": 0}

"""Dense replicas syncing in the KERNEL WIRE FORM over TCP.

The JSON wire (crdt_json.dart:8-37) is the universal interop path;
between two DENSE replicas it is also ~5× more bytes than the data
deserves. This example runs the same anti-entropy round
(test/map_crdt_test.dart:273-279 semantics) through
`sync_dense_over_tcp`: the delta crosses the socket as ONE raw binary
frame of split 32-bit lanes — the exact form the Mosaic merge kernel
consumes (`DenseCrdt.export_split_delta` / `merge_split`) — so neither
side runs a text codec or a lane conversion.

The same `SyncServer` keeps answering the JSON ops too: a third,
non-dense replica joins the mesh over plain `sync_over_tcp` at the
end.

Run: python examples/binary_sync_example.py
"""

from crdt_tpu import (DenseCrdt, MapCrdt, SyncServer,
                      sync_dense_over_tcp, sync_over_tcp)

N_SLOTS = 256


def main() -> None:
    # Two dense replicas; the server side hosts `b`.
    a = DenseCrdt("alice", N_SLOTS)
    b = DenseCrdt("bob", N_SLOTS)
    a.put_batch([1, 2], [10, 20])
    b.put_batch([3], [30])
    b.delete_batch([3])

    with SyncServer(b) as server:
        # Round 1: full exchange in raw binary lanes. The returned
        # watermark makes the NEXT round's pull an inclusive delta.
        watermark = sync_dense_over_tcp(a, server.host, server.port,
                                        timeout=120)
        print("after binary round:",
              {s: a.get(s) for s in (1, 2, 3)},
              "| tombstone at 3:", a.is_deleted(3))

        # Round 2: only records modified since the watermark move.
        b.put_batch([7], [70])
        sync_dense_over_tcp(a, server.host, server.port,
                            since=watermark, timeout=120)
        print("after delta round: slot 7 =", a.get(7))

        # A record-dict replica joins over the JSON ops — same server,
        # same state, different backend family and wire form.
        m = MapCrdt("mapper")
        sync_over_tcp(m, server.host, server.port, key_decoder=int)
        print("JSON peer sees:", dict(sorted(m.map.items())))

    assert a.get(1) == 10 and a.get(7) == 70 and a.is_deleted(3)
    assert m.map == {1: 10, 2: 20, 7: 70}
    print("binary + JSON peers converged")


if __name__ == "__main__":
    main()

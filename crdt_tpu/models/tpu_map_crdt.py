"""Device-resident columnar CRDT backend — the TPU execution path.

Drop-in `Crdt` subclass (the reference's plugin pattern, README.md:39)
whose record store lives in HBM as structure-of-arrays lanes
(``crdt_tpu.ops.merge.Store``); `merge` is the fused batched
lattice-join `merge_step` instead of the reference's sequential
per-record loop (crdt.dart:77-94 → SURVEY.md §3.3/§7).

Division of labor:

- **Device**: HLC lanes, LWW compare, clock absorption, delta masks,
  canonical-time reduction.
- **Host**: key <-> slot assignment, node-id interning (order-preserving
  ordinals), variable-length payloads (values never enter the
  reduction), wall-clock reads, exception raising from reduced guard
  masks, and `watch` events (emitted after kernel writes land —
  reactivity never lives in jit).

For dense-array workloads (the benchmark path) use
`merge_changeset_arrays` to bypass per-record host encoding entirely.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, TypeVar

import numpy as np

import jax
import jax.numpy as jnp

from ..crdt import Crdt
from ..hlc import ClockDriftException, DuplicateNodeException, Hlc
from ..record import Record
from ..watch import ChangeHub, ChangeStream
from ..ops.merge import (Changeset, Store, delta_mask, empty_store,
                         grow_store, max_logical_time, merge_step,
                         scatter_put)
from ..ops.packing import NodeTable
from ..utils.stats import MergeStats, merge_annotation

K = TypeVar("K")
V = TypeVar("V")

_MIN_CAPACITY = 8


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length() if n > 2 else max(n, _MIN_CAPACITY)


class TpuMapCrdt(Crdt[K, V]):
    """LWW-map CRDT with a device-columnar record store."""

    def __init__(self, node_id: Any,
                 seed: Optional[Dict[K, Record[V]]] = None,
                 wall_clock: Optional[Callable[[], int]] = None,
                 capacity: int = _MIN_CAPACITY):
        self._node_id = node_id
        self._table = NodeTable([node_id])
        self._store: Store = empty_store(max(capacity, _MIN_CAPACITY))
        self._key_to_slot: Dict[K, int] = {}
        self._slot_keys: List[K] = []       # slot -> key, insertion order
        self._payload: List[Any] = []       # slot -> value (None = tombstone)
        self._hub = ChangeHub()
        self.stats = MergeStats()
        if seed:
            # Seed lands before the canonical clock is derived, so
            # refresh_canonical_time absorbs it (map_crdt.dart:16-18 +
            # crdt.dart:31-33).
            self.put_records(dict(seed))
        super().__init__(wall_clock=wall_clock)

    # --- host bookkeeping ---

    @property
    def node_id(self) -> Any:
        return self._node_id

    def _my_ordinal(self) -> int:
        return self._table.ordinal(self._node_id)

    def _intern_nodes(self, node_ids: Sequence[Any]) -> None:
        remap = self._table.intern(node_ids)
        if remap is not None:
            remap_dev = jnp.asarray(remap)
            self._store = self._store._replace(
                node=remap_dev[self._store.node],
                mod_node=remap_dev[self._store.mod_node])

    def _ensure_slots(self, keys: Sequence[K]) -> np.ndarray:
        slots = np.empty(len(keys), dtype=np.int32)
        for i, key in enumerate(keys):
            slot = self._key_to_slot.get(key)
            if slot is None:
                slot = len(self._slot_keys)
                self._key_to_slot[key] = slot
                self._slot_keys.append(key)
                self._payload.append(None)
            slots[i] = slot
        if len(self._slot_keys) > self._store.capacity:
            self._store = grow_store(
                self._store, _next_pow2(len(self._slot_keys)))
        return slots

    def _build_changeset(self, slots: np.ndarray, records: Sequence[Record]
                         ) -> Changeset:
        m = len(records)
        padded = _next_pow2(m)
        lt = np.zeros(padded, dtype=np.int64)
        node = np.zeros(padded, dtype=np.int32)
        tomb = np.zeros(padded, dtype=bool)
        valid = np.zeros(padded, dtype=bool)
        slot = np.zeros(padded, dtype=np.int32)
        slot[:m] = slots
        valid[:m] = True
        for i, r in enumerate(records):
            lt[i] = r.hlc.logical_time
            node[i] = self._table.ordinal(r.hlc.node_id)
            tomb[i] = r.value is None
        return Changeset(slot=jnp.asarray(slot), lt=jnp.asarray(lt),
                         node=jnp.asarray(node), tomb=jnp.asarray(tomb),
                         valid=jnp.asarray(valid))

    # --- storage primitives (crdt.dart:140-169) ---

    def contains_key(self, key: K) -> bool:
        return key in self._key_to_slot

    def get_record(self, key: K) -> Optional[Record[V]]:
        slot = self._key_to_slot.get(key)
        if slot is None:
            return None
        # One batched device->host transfer for the whole row.
        occ, lt, node, mod_lt, mod_node = (
            int(x) for x in jax.device_get(
                (self._store.occupied[slot], self._store.lt[slot],
                 self._store.node[slot], self._store.mod_lt[slot],
                 self._store.mod_node[slot])))
        if not occ:
            return None
        return Record(
            Hlc.from_logical_time(lt, self._table.id_of(node)),
            self._payload[slot],
            Hlc.from_logical_time(mod_lt, self._table.id_of(mod_node)))

    def put_record(self, key: K, record: Record[V]) -> None:
        self.put_records({key: record})

    def put_records(self, record_map: Dict[K, Record[V]]) -> None:
        if not record_map:
            return
        self.stats.puts += 1
        self.stats.records_put += len(record_map)
        keys = list(record_map.keys())
        records = list(record_map.values())
        self._intern_nodes([r.hlc.node_id for r in records] +
                           [r.modified.node_id for r in records])
        slots = self._ensure_slots(keys)
        cs = self._build_changeset(slots, records)
        m, padded = len(records), cs.slot.shape[0]
        mod_lt = np.zeros(padded, dtype=np.int64)
        mod_node = np.zeros(padded, dtype=np.int32)
        for i, r in enumerate(records):
            mod_lt[i] = r.modified.logical_time
            mod_node[i] = self._table.ordinal(r.modified.node_id)
        self._store = scatter_put(self._store, cs, jnp.asarray(mod_lt),
                                  jnp.asarray(mod_node))
        for key, record in record_map.items():
            self._payload[self._key_to_slot[key]] = record.value
            self._hub.add(key, record.value)

    def record_map(self, modified_since: Optional[Hlc] = None
                   ) -> Dict[K, Record[V]]:
        n = len(self._slot_keys)
        if n == 0:
            return {}
        if modified_since is None:
            mask = self._store.occupied[:n]
        else:
            since = jnp.int64(modified_since.logical_time)
            mask = delta_mask(self._store, since)[:n]
        # One batched fetch (async prefetch per leaf) instead of five
        # sequential device->host round trips.
        mask, lt, node, mod_lt, mod_node = jax.device_get(
            (mask, self._store.lt[:n], self._store.node[:n],
             self._store.mod_lt[:n], self._store.mod_node[:n]))
        out: Dict[K, Record[V]] = {}
        for slot in np.nonzero(mask)[0]:
            key = self._slot_keys[slot]
            out[key] = Record(
                Hlc.from_logical_time(int(lt[slot]),
                                      self._table.id_of(int(node[slot]))),
                self._payload[slot],
                Hlc.from_logical_time(int(mod_lt[slot]),
                                      self._table.id_of(int(mod_node[slot]))))
        return out

    def watch(self, key: Optional[K] = None) -> ChangeStream:
        return self._hub.stream(key)

    def purge(self) -> None:
        self._store = empty_store(self._store.capacity)
        self._key_to_slot.clear()
        self._slot_keys.clear()
        self._payload.clear()

    # --- overridden hot paths ---

    def refresh_canonical_time(self) -> None:
        """Vectorized canonical-clock rebuild: one max-reduce over the
        occupied lt lane (crdt.dart:114-121 'should be overridden')."""
        if not hasattr(self, "_store") or not self._slot_keys:
            self._canonical_time = Hlc.from_logical_time(0, self._node_id)
            return
        self._canonical_time = Hlc.from_logical_time(
            int(max_logical_time(self._store)), self._node_id)

    def merge(self, remote_records: Dict[K, Record[V]]) -> None:
        """Fused device lattice join (crdt.dart:77-94 semantics)."""
        wall = self._wall_clock()
        if not remote_records:
            # Dart still bumps the canonical clock on an empty merge
            # (crdt.dart:93 runs unconditionally). Second wall read keeps
            # clock-tick parity with the scalar oracle's merge.
            self._canonical_time = Hlc.send(self._canonical_time,
                                            millis=self._wall_clock())
            return

        keys = list(remote_records.keys())
        records = list(remote_records.values())
        self.stats.merges += 1
        self.stats.records_seen += len(records)
        self._intern_nodes([r.hlc.node_id for r in records])
        n_slots_before = len(self._slot_keys)
        slots = self._ensure_slots(keys)
        cs = self._build_changeset(slots, records)

        with merge_annotation():
            new_store, res = merge_step(
                self._store, cs,
                jnp.int64(self._canonical_time.logical_time),
                jnp.int32(self._my_ordinal()),
                jnp.int64(wall))

        # ONE batched host fetch of the whole result (leaves prefetch
        # async): on remote-proxied backends every separate readback is
        # a full round trip, and this path previously paid several.
        res = jax.device_get(res)

        if bool(res.any_bad):
            # Dart leaves the canonical clock partially advanced and the
            # store untouched when recv throws mid-loop — roll back the
            # speculative host-side slot allocations so contains_key
            # matches the oracle.
            for key in self._slot_keys[n_slots_before:]:
                del self._key_to_slot[key]
            del self._slot_keys[n_slots_before:]
            del self._payload[n_slots_before:]
            self._canonical_time = Hlc.from_logical_time(
                int(res.canonical_at_fail), self._node_id)
            i = int(res.first_bad)
            if bool(res.first_is_dup):
                raise DuplicateNodeException(str(self._node_id))
            raise ClockDriftException(records[i].hlc.millis, wall)

        self._store = new_store
        win = res.win
        self.stats.records_adopted += int(win[:len(keys)].sum())
        for i, key in enumerate(keys):
            if win[i]:
                value = records[i].value
                self._payload[self._key_to_slot[key]] = value
                self._hub.add(key, value)

        self._canonical_time = Hlc.from_logical_time(
            int(res.new_canonical), self._node_id)
        self._canonical_time = Hlc.send(self._canonical_time,
                                        millis=self._wall_clock())

"""HLC lane packing: scalar Hlc <-> (int64 lt, int32 node ordinal).

The hard part (SURVEY.md §7 build step 1) is an order-preserving node-id
encoding: ``Hlc.compareTo`` tie-breaks on the node id's natural
comparison (hlc.dart:160), which for arbitrary-length strings cannot be
embedded into a fixed-width integer in general. Instead each store keeps
a :class:`NodeTable` — a sorted dictionary of every node id it has seen —
and carries the *ordinal* in the lane. Ordinal comparison then equals
string comparison exactly. When a new node id lands between existing
ones, previously issued ordinals shift; the table reports a remap vector
so stored lanes can be re-encoded with one gather (node counts are tiny —
they are replicas, not records).
"""

from __future__ import annotations

import bisect
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..hlc import SHIFT, MAX_COUNTER, Hlc


def pack_logical_time(millis: int, counter: int) -> int:
    """(millis, counter) -> int64 logicalTime (hlc.dart:16)."""
    return (millis << SHIFT) + counter


def unpack_logical_time(lt: int) -> Tuple[int, int]:
    return lt >> SHIFT, lt & MAX_COUNTER


class NodeTable:
    """Order-preserving node-id interning for one store.

    Ordinals are indices into the sorted id list, so
    ``ordinal(a) < ordinal(b)  <=>  a < b`` under the ids' natural
    comparison — the exact tie-break ``Hlc.compareTo`` uses
    (hlc.dart:158-161). Node ids must be mutually comparable (all str or
    all int, as in the reference).
    """

    def __init__(self, ids: Optional[Sequence[Any]] = None):
        self._sorted: List[Any] = sorted(set(ids)) if ids else []
        self._omap = {v: i for i, v in enumerate(self._sorted)}

    def __len__(self) -> int:
        return len(self._sorted)

    def __contains__(self, node_id: Any) -> bool:
        i = bisect.bisect_left(self._sorted, node_id)
        return i < len(self._sorted) and self._sorted[i] == node_id

    def ordinal(self, node_id: Any) -> int:
        """Ordinal of an already-interned id."""
        i = bisect.bisect_left(self._sorted, node_id)
        if i == len(self._sorted) or self._sorted[i] != node_id:
            raise KeyError(node_id)
        return i

    def id_of(self, ordinal: int) -> Any:
        return self._sorted[ordinal]

    def ids(self) -> List[Any]:
        """All interned ids in ordinal order (a copy)."""
        return list(self._sorted)

    def intern(self, node_ids: Sequence[Any]
               ) -> Optional[np.ndarray]:
        """Add any unseen ids. Returns an int32 remap vector mapping old
        ordinal -> new ordinal if existing ordinals shifted, else None.
        Apply it to stored node lanes via ``remap[lane]``."""
        new = sorted(set(node_ids) - set(self._sorted))
        if not new:
            return None
        old = self._sorted
        merged = sorted(old + new)
        remap = np.empty(len(old), dtype=np.int32)
        positions = {v: i for i, v in enumerate(merged)}
        for i, v in enumerate(old):
            remap[i] = positions[v]
        self._sorted = merged
        self._omap = positions
        if np.array_equal(remap, np.arange(len(old), dtype=np.int32)):
            return None  # new ids all sort after existing ones
        return remap

    def encode(self, node_ids: Sequence[Any]) -> np.ndarray:
        """Ordinals for already-interned ids — one maintained dict
        lookup per id, O(m) for an m-id batch (the vectorized host
        encode every backend shares). KeyError on uninterned ids.
        The C batch lookup runs ~5× the fromiter genexpr at 1M ids
        (and its identity memo rides the wire scanners' node-string
        dedup); the Python path is the exact fallback."""
        from .. import native
        codec = native.load()
        if codec is not None:
            if not isinstance(node_ids, list):
                node_ids = list(node_ids)
            return np.frombuffer(
                codec.ordinals(node_ids, self._omap), np.int32)
        omap = self._omap
        return np.fromiter((omap[n] for n in node_ids), np.int32,
                           count=len(node_ids))


# Exact host lane dtypes of the PACKED wire form, in field order.
# Anything else from a peer is a protocol violation (mirrors
# net._SPLIT_LANE_DTYPES: never trust np.dtype as a parser for
# untrusted dtype strings). The optional 6th lane ("sem", uint8
# semantics tags) rides only between peers that negotiated the
# "semantics" hello capability — a pre-semantics receiver's field
# check rejects 6-lane frames, which is exactly why senders withhold
# the lane (and the rows needing it) from un-negotiated sessions.
PACKED_LANE_DTYPES = ("int32", "int64", "int32", "int64", "uint8")
PACKED_SEM_DTYPE = "uint8"


class PackedDelta(NamedTuple):
    """Incremental columnar wire form: ONE row per modified slot.

    The dense binary form (`net.sync_dense_over_tcp`) always ships
    n_slots-wide lanes with a validity mask — O(store) bytes even for
    a 3-record delta. This form is the O(k) counterpart: host numpy
    lanes holding only the rows ``DenseCrdt.pack_since`` selected, so
    a steady-state gossip round costs bytes proportional to what
    actually changed (~25 B/row). ``node`` carries ordinals into the
    ``node_ids`` list that travels beside the delta; ``modified``
    stamps are local-only and never serialized (record.dart:28-31).

    ``sem`` (None on all-LWW deltas and from pre-semantics peers)
    carries each row's semantics tag (`crdt_tpu.semantics`): the
    receiver validates tags against its own per-slot column before
    merging, so two replicas can never silently join one slot under
    two different lattices."""

    slots: np.ndarray   # int32[k], unique (last-wins collapsed)
    lt: np.ndarray      # int64[k] packed logical times
    node: np.ndarray    # int32[k] ordinals into the wire node_ids
    val: np.ndarray     # int64[k] (0 where tombstoned)
    tomb: np.ndarray    # uint8[k] 0/1 tombstone flags
    sem: Optional[np.ndarray] = None  # uint8[k] semantics tags

    @property
    def k(self) -> int:
        return len(self.slots)

    @property
    def nbytes(self) -> int:
        return sum(lane.nbytes for lane in self if lane is not None)


def arena_of(lane: np.ndarray):
    """Walk a lane view's base chain to its owning allocation — the
    single uint8 arena for lanes `pack_into_arena` produced. Lets
    tests prove buffer identity across pack → frame (the zero-copy
    acceptance check): every lane of one delta roots at one arena,
    and `pack_rows`' memoryviews expose that same storage."""
    a = lane
    while getattr(a, "base", None) is not None:
        a = a.base
    return a


def pack_into_arena(idx: np.ndarray, lt: np.ndarray, node: np.ndarray,
                    val: np.ndarray, tomb: np.ndarray,
                    sem: Optional[np.ndarray] = None) -> "PackedDelta":
    """Gather the rows selected by ``idx`` out of host store columns
    straight into ONE preallocated arena; the returned delta's lanes
    are aligned views into it, already in the exact wire dtypes
    (`PACKED_LANE_DTYPES`). `pack_rows` then frames those views as-is
    and `net.send_bytes_frame` vectors them to the socket — the bytes
    written by the gathers here are the bytes `sendmsg` ships, with
    zero intermediate ``bytes()``/``np.asarray`` copies in between.

    Column dtype contract (the host fetch of store lanes): ``lt``/
    ``val`` int64, ``node`` int32, ``tomb`` bool or (u)int8, ``sem``
    int8/uint8 — 1-byte lanes reinterpret via ``.view`` so even the
    bool→uint8 conversion is part of the gather, not an extra pass.

    Ownership: the arena belongs to the returned delta and is NEVER
    reused or resized — an evicted pack-cache entry may still be
    referenced by an in-flight send, so recycling arenas would
    corrupt frames already on the wire (docs/FASTPATH.md)."""
    specs = [("slots", np.dtype(np.int32)),
             ("lt", np.dtype(np.int64)),
             ("node", np.dtype(np.int32)),
             ("val", np.dtype(np.int64)),
             ("tomb", np.dtype(np.uint8))]
    if sem is not None:
        specs.append(("sem", np.dtype(np.uint8)))
    k = int(len(idx))
    offs = []
    total = 0
    for _, dt in specs:
        total = -(-total // 8) * 8      # 8-byte-align every lane
        offs.append(total)
        total += k * dt.itemsize
    arena = np.empty(total, np.uint8)
    views = {name: arena[off:off + k * dt.itemsize].view(dt)
             for (name, dt), off in zip(specs, offs)}
    views["slots"][:] = idx             # intp → int32 cast-assign
    np.take(lt, idx, out=views["lt"])
    np.take(node, idx, out=views["node"])
    np.take(val, idx, out=views["val"])
    np.take(tomb if tomb.dtype == np.uint8 else tomb.view(np.uint8),
            idx, out=views["tomb"])
    if sem is not None:
        np.take(sem if sem.dtype == np.uint8 else sem.view(np.uint8),
                idx, out=views["sem"])
    return PackedDelta(**views)


def pack_rows(delta: "PackedDelta") -> Tuple[dict, List[memoryview]]:
    """(meta, bufs) for a packed delta: lane descriptors plus host
    buffers in field order — the shape `net.send_bytes_frame` ships as
    one raw binary frame. The ``sem`` lane is appended only when
    present (capability-gated by the caller).

    Zero-copy: a lane already holding its exact wire dtype contiguously
    (every `pack_into_arena` lane) is framed as a flat memoryview over
    its OWN storage — no intermediate buffer. Foreign lanes (wrong
    dtype or layout, e.g. hand-built test deltas) are normalized with
    one counted copy, reported in
    ``crdt_tpu_pack_copy_bytes_total{stage="pack_rows"}`` — the
    counter a zero-copy regression trips."""
    lanes = list(delta[:5])
    fields = list(PackedDelta._fields[:5])
    dtypes = list(PACKED_LANE_DTYPES)
    if delta.sem is not None:
        lanes.append(delta.sem)
        fields.append("sem")
        dtypes.append(PACKED_SEM_DTYPE)
    arrs = []
    copied = 0
    for lane, dtype in zip(lanes, dtypes):
        want = np.dtype(dtype)
        if (isinstance(lane, np.ndarray) and lane.dtype == want
                and lane.ndim == 1 and lane.flags.c_contiguous):
            arrs.append(lane)
            continue
        # crdtlint: disable=pack-path-extra-copy -- normalizing a foreign lane (wrong dtype/layout) is the one legitimate pack-path copy; counted below so regressions still surface
        a = np.ascontiguousarray(np.asarray(lane), want)
        copied += a.nbytes
        arrs.append(a)
    if copied:
        from ..obs.registry import default_registry
        default_registry().counter(
            "crdt_tpu_pack_copy_bytes_total",
            "bytes copied between pack and frame (zero on the "
            "arena fast path)").inc(copied, stage="pack_rows")
    meta = {"form": "packed",
            "lanes": [[f, str(a.dtype), [len(a)]]
                      for f, a in zip(fields, arrs)]}
    return meta, [a.data.cast("B") for a in arrs]


def unpack_rows(meta: Any, blob: bytes) -> "PackedDelta":
    """Validate + reconstruct the packed delta a peer announced.
    Raises ValueError on any structural violation (wrong fields or
    dtypes, ragged lane lengths, frame size mismatch) BEFORE the
    replica is touched. ``k == 0`` is a legal empty delta. Accepts
    the 5-lane legacy form and the 6-lane form with the trailing
    ``sem`` tag lane."""
    if not isinstance(meta, dict) or meta.get("form") != "packed":
        raise ValueError("bad packed meta")
    lanes_meta = meta.get("lanes")
    base = list(PackedDelta._fields[:5])
    if not isinstance(lanes_meta, list) \
            or [l[0] for l in lanes_meta] not in (base, base + ["sem"]):
        raise ValueError("packed lane fields mismatch")
    want_dtypes = PACKED_LANE_DTYPES + (
        (PACKED_SEM_DTYPE,) if len(lanes_meta) == 6 else ())
    lanes = []
    off = 0
    k = None
    for (_, dt, shape), want in zip(lanes_meta, want_dtypes):
        if dt != want:
            raise ValueError(f"lane dtype {dt!r} != expected {want!r}")
        if not isinstance(shape, list) or len(shape) != 1 \
                or int(shape[0]) < 0:
            raise ValueError("bad packed lane shape")
        n = int(shape[0])
        if k is None:
            k = n
        elif n != k:
            raise ValueError("ragged packed lanes")
        a = np.frombuffer(blob, np.dtype(dt), count=n, offset=off)
        off += a.nbytes
        lanes.append(a)
    if off != len(blob):
        raise ValueError(f"packed frame size mismatch: lanes describe "
                         f"{off} bytes, frame holds {len(blob)}")
    return PackedDelta(*lanes)


def pack_hlcs(hlcs: Sequence[Hlc], table: NodeTable
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Scalar Hlcs -> (lt int64, node int32) lanes. Ids must be interned."""
    lt = np.array([h.logical_time for h in hlcs], dtype=np.int64)
    node = table.encode([h.node_id for h in hlcs])
    return lt, node


def unpack_hlc(lt: int, node_ord: int, table: NodeTable) -> Hlc:
    return Hlc.from_logical_time(int(lt), table.id_of(int(node_ord)))

"""Merge observability (SURVEY.md §5: tracing/metrics are absent in the
reference — the TPU build adds lightweight counters and profiler
annotations around the merge kernel).

`MergeStats` counts merges and record flow on a backend;
`merge_annotation` wraps the device dispatch in a profiler-annotated
trace span (`crdt_tpu.obs.trace.span`) so kernel time shows up named
in TPU profiles AND — when the process tracer is on — as HLC-stamped
``merge`` events in the trace ring.

The counter dataclasses are no longer orphans: ``register(**labels)``
attaches an instance to the process-wide metrics registry
(`crdt_tpu.obs.registry`) as a weak-referenced collector, so every
live backend/peer appears in one ``metrics`` snapshot. Registration is
read-side only — the hot-path accounting below stays plain host ints
and lazy device scalars, untouched.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..obs.trace import span as _span


@dataclass
class MergeStats:
    """Counters for one CRDT backend instance.

    ``records_seen`` may be fed unfetched device scalars via
    :meth:`add_seen_lazy` so the merge hot path never blocks on a
    device→host transfer; reading the property drains them.
    """
    merges: int = 0            # merge() calls
    puts: int = 0              # local write batches (put/put_all)
    records_put: int = 0       # local records written
    _seen: int = 0
    _seen_pending: Any = None  # lazy running sum (device scalar)
    _adopted: int = 0
    _adopted_pending: Any = None

    @property
    def records_seen(self) -> int:
        """Remote records examined, winners+losers (crdt.dart:80-85)."""
        if self._seen_pending is not None:
            self._seen += int(self._seen_pending)
            self._seen_pending = None
        return self._seen

    @records_seen.setter
    def records_seen(self, value: int) -> None:
        self._seen_pending = None
        self._seen = value

    def add_seen_lazy(self, count: Any) -> None:
        """Accumulate a host int or an unfetched device scalar without
        forcing a sync; kept as one running device sum (O(1) memory)."""
        self._seen_pending = (count if self._seen_pending is None
                              else self._seen_pending + count)

    @property
    def records_adopted(self) -> int:
        """LWW winners written; may drain a lazy device sum."""
        if self._adopted_pending is not None:
            self._adopted += int(self._adopted_pending)
            self._adopted_pending = None
        return self._adopted

    @records_adopted.setter
    def records_adopted(self, value: int) -> None:
        self._adopted_pending = None
        self._adopted = value

    def add_adopted_lazy(self, count: Any) -> None:
        self._adopted_pending = (
            count if self._adopted_pending is None
            else self._adopted_pending + count)

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in
                ("merges", "records_seen", "records_adopted", "puts",
                 "records_put")}

    def reset(self) -> None:
        for k in self.as_dict():
            setattr(self, k, 0)

    def register(self, **labels: Any) -> "MergeStats":
        """Attach to the process-wide metrics registry as a ``merge``
        collector (weakly held); returns self for chaining. Note the
        scrape drains the lazy device sums (`records_seen` /
        `records_adopted` force a device→host fetch) — snapshot from a
        monitoring thread, not from inside a pipelined window.
        Re-registering under an already-live label set supersedes it
        (the replica-restart idiom: same node id, new object)."""
        from ..obs.registry import default_registry
        default_registry().attach("merge", self, replace=True, **labels)
        return self


@dataclass
class PeerSyncStats:
    """Per-peer counters for the gossip runtime (`crdt_tpu.gossip`).

    One instance per `Peer`; every field is a plain host int so a
    monitoring loop can snapshot `as_dict()` without touching the
    replica. ``retries`` counts re-attempts after transport faults
    (first attempts are not retries); ``fallbacks`` counts dense→JSON
    wire-form downgrades; the ``breaker_*`` fields count state
    TRANSITIONS, so a soak can prove the breaker actually cycled."""
    rounds_ok: int = 0         # completed anti-entropy rounds
    rounds_failed: int = 0     # rounds abandoned (retries exhausted
    #                            or fatal protocol rejection)
    skipped: int = 0           # rounds refused locally: breaker open
    retries: int = 0           # transport-fault re-attempts
    fallbacks: int = 0         # dense wire form downgraded to JSON
    full_pulls: int = 0        # rounds pulled with since=None
    delta_pulls: int = 0       # rounds pulled from a watermark
    bytes_sent: int = 0        # wire bytes out, frame headers included
    bytes_received: int = 0    # wire bytes in, frame headers included
    breaker_opened: int = 0
    breaker_half_open: int = 0
    breaker_closed: int = 0

    def as_dict(self) -> dict:
        return {f: getattr(self, f) for f in (
            "rounds_ok", "rounds_failed", "skipped", "retries",
            "fallbacks", "full_pulls", "delta_pulls", "bytes_sent",
            "bytes_received", "breaker_opened", "breaker_half_open",
            "breaker_closed")}

    def reset(self) -> None:
        for f in self.as_dict():
            setattr(self, f, 0)

    def register(self, **labels: Any) -> "PeerSyncStats":
        """Attach to the process-wide metrics registry as a
        ``peer_sync`` collector (weakly held); returns self. A
        re-``add_peer`` under the same (node, peer) labels supersedes
        the prior collector rather than duplicating the series."""
        from ..obs.registry import default_registry
        default_registry().attach("peer_sync", self, replace=True,
                                  **labels)
        return self


def merge_annotation(name: str = "crdt_tpu.merge", hlc: Any = None):
    """Named span around a merge dispatch: always a
    `jax.profiler.TraceAnnotation` for TPU profile traces; also an
    HLC-stamped ``merge`` ring event when the process tracer is
    enabled. ``hlc`` may be a zero-arg callable, evaluated only when
    an event is actually recorded."""
    return _span(name, kind="merge", hlc=hlc)

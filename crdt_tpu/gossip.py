"""Resilient gossip runtime: long-running anti-entropy over flaky links.

The reference's replication story assumes a cooperative, always-up
peer — its example mocks the remote with a function returning a JSON
string (example/crdt_example.dart:21-25) — and :func:`sync_over_tcp`
inherits that: one socket error aborts the round and nothing retries.
This module turns the one-shot round into a runtime that keeps
converging through drops, delays, truncations and crashes:

- **Bounded retry** with exponential backoff + FULL jitter on
  transport faults. Rounds are idempotent lattice joins, so replaying
  one is always safe; jitter spreads uncoordinated replicas retrying
  a shared peer instead of synchronizing them into a thundering herd.
- A per-peer **circuit breaker**: open after N consecutive failed
  rounds, half-open probe after a cool-down, close again on success —
  a dead peer costs one probe per reset window, not a retry storm.
- **Graceful wire-form degradation**: peers start on the dense binary
  form when the local replica speaks it, and downgrade (sticky) to
  the universal JSON path the moment the peer rejects a dense op.
- **Durable watermarks** (`checkpoint.save_gossip_state`): the
  per-peer delta watermark survives a crash, so a restarted node
  resumes DELTA sync instead of re-pulling full peer state. (The
  replica contents persist separately — `checkpoint.save_json` /
  `load_json`, or a durable backend like `SqliteCrdt`.)
- **Per-peer counters** (`utils.stats.PeerSyncStats`): rounds,
  retries, fallbacks, pull kinds, bytes, breaker transitions — a
  fault-injection soak can prove its faults actually fired.

Time sources are injectable (``clock``/``sleep``/``rng``) so tests
drive the breaker and backoff deterministically; production uses the
defaults. The fault-injection counterpart lives in
`crdt_tpu.testing_faults` (a TCP proxy that drops, delays, truncates,
corrupts and duplicates on a seeded schedule).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from .checkpoint import load_gossip_state, save_gossip_state
from .crdt import Crdt
from .hlc import Hlc
from .net import (SyncProtocolError, SyncServer, SyncTransportError,
                  WireTally, sync_dense_over_tcp, sync_over_tcp)
from .obs.lag import health_status, lag_entry
from .obs.registry import default_registry
from .obs.trace import tracer
from .utils.stats import PeerSyncStats


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff and full jitter:
    ``sleep = uniform(0, min(max_delay, base_delay * 2**attempt))``.
    Full jitter (rather than equal or decorrelated) because gossiping
    replicas share peers — a deterministic backoff ladder would march
    every client of a briefly-down peer back in lockstep."""

    max_attempts: int = 4      # total tries per round, first included
    base_delay: float = 0.05   # seconds; the cap grows base * 2^n
    max_delay: float = 2.0

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        return rng.uniform(0.0, min(self.max_delay,
                                    self.base_delay * (2 ** attempt)))


@dataclass(frozen=True)
class BreakerPolicy:
    failure_threshold: int = 5   # consecutive failed ROUNDS to open
    reset_timeout: float = 30.0  # seconds open before one probe


class CircuitBreaker:
    """CLOSED → (N consecutive round failures) → OPEN →
    (reset_timeout elapses) → HALF_OPEN → one probe round →
    success: CLOSED / failure: OPEN again.

    Failures are counted per ROUND (after the retry budget is spent),
    not per attempt — a peer that needs one retry per round is slow,
    not down, and must not trip the breaker. Transitions are counted
    into the owning peer's :class:`PeerSyncStats` and, when the
    process tracer is enabled, emitted as ``breaker`` trace events."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, policy: BreakerPolicy,
                 clock: Callable[[], float] = time.monotonic,
                 stats: Optional[PeerSyncStats] = None,
                 name: str = ""):
        self.policy = policy
        self._clock = clock
        self._stats = stats
        self.name = name           # owning peer, for trace events
        self.state = self.CLOSED
        self.failures = 0          # consecutive, resets on success
        self._opened_at = 0.0

    def _transition(self, state: str) -> None:
        self.state = state
        ring = tracer()
        if ring.enabled:
            ring.emit("breaker", peer=self.name, state=state,
                      failures=self.failures)

    def allow(self) -> bool:
        """May a round be attempted now? Flips OPEN → HALF_OPEN when
        the cool-down has elapsed (the probe is the caller's round)."""
        if self.state == self.OPEN:
            if self._clock() - self._opened_at \
                    < self.policy.reset_timeout:
                return False
            self._transition(self.HALF_OPEN)
            if self._stats is not None:
                self._stats.breaker_half_open += 1
        return True

    def record_success(self) -> None:
        self.failures = 0
        if self.state != self.CLOSED:
            self._transition(self.CLOSED)
            if self._stats is not None:
                self._stats.breaker_closed += 1

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == self.HALF_OPEN \
                or (self.state == self.CLOSED
                    and self.failures >= self.policy.failure_threshold):
            self._transition(self.OPEN)
            self._opened_at = self._clock()
            if self._stats is not None:
                self._stats.breaker_opened += 1


class Peer:
    """One gossip neighbour: address, current wire mode, delta
    watermark, breaker, counters. ``name`` is the durable identity the
    watermark persists under — keep it stable across restarts."""

    def __init__(self, name: str, host: str, port: int, *,
                 dense: bool,
                 breaker: CircuitBreaker,
                 stats: PeerSyncStats,
                 watermark: Optional[Hlc] = None):
        self.name = name
        self.host = host
        self.port = port
        self.dense = dense            # sticky: downgraded on rejection
        self.breaker = breaker
        self.stats = stats
        self.watermark = watermark
        self.last_error: Optional[Exception] = None

    def __repr__(self) -> str:
        return (f"Peer({self.name!r}, {self.host}:{self.port}, "
                f"{'dense' if self.dense else 'json'}, "
                f"breaker={self.breaker.state}, "
                f"watermark={self.watermark})")


# Protocol codes that mean "this peer does not speak the dense wire
# form" — downgrade to JSON and retry the round immediately. Any other
# rejection (e.g. a clock guard) would fail identically on JSON, so it
# is terminal for the round. "rejected" is the default code replies
# from pre-taxonomy servers map to.
_DENSE_FALLBACK_CODES = frozenset(
    {"dense_rejected", "unknown_op", "rejected"})


class GossipNode:
    """A replica + its :class:`SyncServer` + a set of :class:`Peer`s,
    run as a resilient long-lived gossip participant.

    >>> node = GossipNode(crdt, state_path="/var/lib/app/gossip.json")
    >>> node.add_peer("b", "10.0.0.2", 7000)
    >>> node.start(gossip_interval=1.0)   # background anti-entropy
    ... # or drive rounds yourself:
    >>> node.sync_peer("b")               # 'ok' | 'skipped' | 'failed'
    >>> node.stop()

    Local writes from other threads must hold :attr:`lock` (the
    server's replica lock) — the same contract as `SyncServer`.
    `sync_peer`/`run_round` themselves are not re-entrant; drive them
    from one thread (the built-in loop, or your own)."""

    # crdtlint lock-discipline contract: the peer registry is touched
    # only under self._peers_lock (enforced statically by
    # crdt_tpu.analysis.host_lint).
    _CRDTLINT_GUARDED = {"_peers_lock": ("peers",)}

    def __init__(self, crdt: Crdt, host: str = "127.0.0.1",
                 port: int = 0, *,
                 state_path: Optional[str] = None,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[BreakerPolicy] = None,
                 prefer_dense: Optional[bool] = None,
                 round_timeout: float = 30.0,
                 key_encoder=None, value_encoder=None,
                 key_decoder=None, value_decoder=None,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 **server_kwargs):
        self.crdt = crdt
        self.retry = retry or RetryPolicy()
        self.breaker_policy = breaker or BreakerPolicy()
        # Dense binary wire form only when the local replica speaks it.
        self.prefer_dense = (hasattr(crdt, "export_split_delta")
                             if prefer_dense is None else prefer_dense)
        self.round_timeout = round_timeout
        self._codecs = dict(key_encoder=key_encoder,
                            value_encoder=value_encoder,
                            key_decoder=key_decoder,
                            value_decoder=value_decoder)
        self._rng = rng or random.Random()
        self._clock = clock
        self._sleep = sleep
        self.server = SyncServer(crdt, host, port,
                                 **self._codecs, **server_kwargs)
        # Client-side wire bytes across all peers, node lifetime
        # (per-peer splits live in each PeerSyncStats). The server's
        # metrics op folds our per-peer lag table into its snapshot.
        self.wire = WireTally()
        default_registry().attach("wire", self.wire, role="client",
                                  node=str(crdt.node_id))
        self.server.metrics_extra = self._metrics_extra
        # Guards the peer REGISTRY (the dict itself): add_peer may run
        # from any thread while the gossip loop iterates. Per-peer
        # mutable state stays single-writer (the gossip thread).
        self._peers_lock = threading.Lock()
        self.peers: Dict[str, Peer] = {}
        self._state_path = state_path
        # Crash resume: watermarks persisted by a previous incarnation
        # seed add_peer — the first round after restart is a DELTA
        # pull, not a full re-pull.
        self._saved_marks = ({} if state_path is None else
                             load_gossip_state(state_path,
                                               crdt.node_id))
        self._gossip_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # --- topology ---

    @property
    def host(self) -> str:
        return self.server.host

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def lock(self) -> threading.Lock:
        """The replica lock (the server's): hold it around any local
        write from outside the gossip thread."""
        return self.server.lock

    def add_peer(self, name: str, host: str, port: int,
                 dense: Optional[bool] = None) -> Peer:
        """Register (or re-address) a peer. A persisted watermark for
        ``name`` is resumed; ``dense`` overrides the node-level wire
        preference for this peer."""
        stats = PeerSyncStats().register(
            node=str(self.crdt.node_id), peer=name)
        peer = Peer(
            name, host, port,
            dense=self.prefer_dense if dense is None else dense,
            breaker=CircuitBreaker(self.breaker_policy,
                                   clock=self._clock, stats=stats,
                                   name=name),
            stats=stats,
            watermark=self._saved_marks.get(name))
        with self._peers_lock:
            self.peers[name] = peer
        return peer

    # --- lifecycle ---

    def start(self, gossip_interval: Optional[float] = None
              ) -> "GossipNode":
        """Serve the replica; with ``gossip_interval`` also run
        `run_round` on a background loop every that many seconds."""
        self.server.start()
        if gossip_interval is not None:
            self._stop.clear()

            def loop() -> None:
                while not self._stop.is_set():
                    self.run_round()
                    self._stop.wait(gossip_interval)

            self._gossip_thread = threading.Thread(target=loop,
                                                   daemon=True)
            self._gossip_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._gossip_thread is not None:
            self._gossip_thread.join(timeout=60)
            self._gossip_thread = None
        self.server.stop()

    def __enter__(self) -> "GossipNode":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- rounds ---

    def run_round(self) -> Dict[str, str]:
        """One gossip sweep: sync every peer once, in a shuffled order
        (uncoordinated nodes must not all visit peers in registration
        order). Returns ``{peer name: outcome}``."""
        with self._peers_lock:
            names = list(self.peers)
        self._rng.shuffle(names)
        return {name: self.sync_peer(name) for name in names}

    def sync_peer(self, name: str) -> str:
        """One resilient anti-entropy round against a peer.

        Returns ``'ok'`` (round completed, watermark advanced and
        persisted), ``'skipped'`` (breaker open — no network attempt),
        or ``'failed'`` (retry budget exhausted on transport faults,
        or the peer rejected the round; see ``peer.last_error``).
        Failures never raise — a long-running mesh must keep gossiping
        with its healthy peers."""
        ring = tracer()
        if not ring.enabled:
            return self._sync_peer(name)
        start = time.perf_counter()
        outcome = self._sync_peer(name)
        dur = time.perf_counter() - start
        with self.server.lock:
            stamp = str(self.crdt.canonical_time)
        ring.emit("gossip_round", hlc=stamp, peer=name,
                  outcome=outcome, dur_s=dur)
        default_registry().histogram(
            "crdt_tpu_gossip_round_seconds",
            "anti-entropy round wall time, retries included"
        ).observe(dur, peer=name, outcome=outcome)
        return outcome

    def _sync_peer(self, name: str) -> str:
        with self._peers_lock:
            peer = self.peers[name]
        if not peer.breaker.allow():
            peer.stats.skipped += 1
            return "skipped"
        was_full = peer.watermark is None
        attempt = 0
        while True:
            try:
                mark = self._one_round(peer)
            except SyncProtocolError as e:
                if peer.dense and e.code in _DENSE_FALLBACK_CODES:
                    # The peer doesn't speak the dense wire form:
                    # downgrade (sticky) and rerun on the universal
                    # JSON path. Not a link fault — no backoff, and
                    # the retry budget is untouched.
                    peer.stats.fallbacks += 1
                    peer.dense = False
                    continue
                return self._round_failed(peer, e)
            except SyncTransportError as e:
                attempt += 1
                if attempt >= self.retry.max_attempts:
                    return self._round_failed(peer, e)
                peer.stats.retries += 1
                peer.last_error = e
                self._sleep(self.retry.delay(attempt, self._rng))
                continue
            if was_full:
                peer.stats.full_pulls += 1
            else:
                peer.stats.delta_pulls += 1
            peer.stats.rounds_ok += 1
            peer.last_error = None
            peer.breaker.record_success()
            peer.watermark = mark
            self._persist()
            return "ok"

    def _one_round(self, peer: Peer) -> Hlc:
        """One wire round in the peer's current form, byte-tallied."""
        tally = WireTally()
        try:
            if peer.dense:
                return sync_dense_over_tcp(
                    self.crdt, peer.host, peer.port,
                    since=peer.watermark, timeout=self.round_timeout,
                    lock=self.server.lock, tally=tally)
            return sync_over_tcp(
                self.crdt, peer.host, peer.port,
                since=peer.watermark, timeout=self.round_timeout,
                lock=self.server.lock, tally=tally, **self._codecs)
        finally:
            peer.stats.bytes_sent += tally.sent
            peer.stats.bytes_received += tally.received
            self.wire.sent += tally.sent
            self.wire.received += tally.received

    def _round_failed(self, peer: Peer, exc: Exception) -> str:
        peer.last_error = exc
        peer.stats.rounds_failed += 1
        peer.breaker.record_failure()
        return "failed"

    def _persist(self) -> None:
        if self._state_path is not None:
            with self._peers_lock:
                entries = list(self.peers.items())
            save_gossip_state(
                self._state_path, self.crdt.node_id,
                {name: p.watermark for name, p in entries})

    # --- observability ---

    def stats_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-peer counter snapshot plus breaker state — cheap, no
        replica access, safe to poll from a monitoring thread."""
        with self._peers_lock:
            entries = list(self.peers.items())
        return {name: {**p.stats.as_dict(),
                       "breaker": p.breaker.state,
                       "dense": p.dense,
                       "watermark": None if p.watermark is None
                       else str(p.watermark)}
                for name, p in entries}

    def lag_snapshot(self, include_pending: bool = True
                     ) -> Dict[str, Dict[str, Any]]:
        """Per-peer convergence lag: how far each peer's last
        completed round is behind the local HLC head.

        ``lag_ms`` is ``local_head.millis - watermark.millis`` (the
        watermark is the local canonical time captured at the start of
        the peer's last completed round, so this measures sync
        staleness, not network latency); ``pending_records`` counts
        local records modified since that watermark — the backlog the
        next delta round would push. Never-synced peers report
        ``synced: False`` with null lag. ``include_pending=False``
        skips the replica scan (and its lock) for cheap polling."""
        with self._peers_lock:
            entries = list(self.peers.items())
        with self.server.lock:
            head = self.crdt.canonical_time
            pending = {}
            if include_pending:
                for name, p in entries:
                    pending[name] = self.crdt.count_modified_since(
                        p.watermark)
        return {name: lag_entry(head, p.watermark,
                                pending=pending.get(name),
                                breaker=p.breaker.state,
                                dense=p.dense,
                                last_error=p.last_error)
                for name, p in entries}

    def health(self, include_pending: bool = True,
               stale_after_ms: int = 60_000) -> Dict[str, Any]:
        """One-call node health: identity, HLC head, per-peer lag, and
        an overall ``status`` — ``"degraded"`` when any peer is
        never-synced, breaker-impaired, or staler than
        ``stale_after_ms``; else ``"ok"``."""
        peers = self.lag_snapshot(include_pending=include_pending)
        with self.server.lock:
            head = self.crdt.canonical_time
        return {"node_id": str(self.crdt.node_id),
                "hlc_head": str(head),
                "head_millis": head.millis,
                "status": health_status(peers,
                                        stale_after_ms=stale_after_ms),
                "peers": peers}

    def _metrics_extra(self) -> Dict[str, Any]:
        """Folded into the server's ``metrics`` op reply (called
        WITHOUT the server lock held — lag_snapshot takes it)."""
        with self.server.lock:
            node = {"node_id": str(self.crdt.node_id),
                    "hlc_head": str(self.crdt.canonical_time)}
        return {"node": node, "lag": self.lag_snapshot()}

"""Bench trajectory: every benchmark run as one normalized record.

The repo accumulated nine `BENCH_r*.json` files that share no schema —
wrapper dicts with a parsed summary, raw result dicts, multi-line
suites — so "did PR N regress the ingest floor?" had no machine
answer: the bench trajectory was literally unreadable as a series.
This module fixes the substrate:

- **One record per bench run**, schema
  ``{run_id, mode, git_sha, host_class, smoke, metrics{...}, slo}``,
  appended to ``benchmarks/history/trajectory.jsonl``. ``metrics`` is
  the bench result's numeric leaves flattened to dotted keys
  (``cold_peer.bytes_per_s``), so heterogeneous modes coexist in one
  file and any metric is addressable by name. ``host_class``
  (``{backend}-{machine}-cpu{n}``) keeps cross-host floors from being
  compared: a CPU-smoke record never regresses against a TPU soak.

- **A regression verdict** (`compare`) with `evaluate_slo` semantics
  (obs.fleet): per-metric ``{value, baseline, budget, ok}`` where
  ``ok`` is None when unmeasured (metric absent from either side, or
  direction unknown) and the top-level verdict requires every
  *measured* check to pass — unmeasured is never silently "passed",
  the count is surfaced as ``unmeasured``. Baselines are
  **fastest-of-N floors** over the preceding records of the same
  ``(mode, host_class, smoke)`` group: min over the pool for
  lower-is-better metrics, max for higher-is-better — one slow
  baseline run cannot manufacture a pass, one fast one sets the bar.

- **Noise budgets** are per-metric multiplicative headroom
  (default ±25%): a lower-is-better metric fails when
  ``value > floor * (1 + budget)``, higher-is-better when
  ``value < ceiling * (1 - budget)``. Counters/sizes (``bytes``,
  ``rows``, ``n``, ``count``) and booleans are identity-checked
  metrics only when the caller lists them via ``--metric``; by default
  only rate/latency metrics participate (see `metric_direction`).

``python -m crdt_tpu.obs bench --compare <baseline.jsonl>`` is the CI
gate: exit 0 when the verdict is ok, 1 on regression, 2 when nothing
was comparable (unmeasured != passed applies to the whole run too).
Pure stdlib — importable before jax initializes, usable from CI
without a device.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: Default multiplicative noise headroom per metric. Wide on purpose:
#: the gate exists to catch step regressions (a dropped fast path, an
#: extra dispatch), not 5% jitter on shared CI hosts. Tighten per-run
#: with ``--budget``.
DEFAULT_BUDGET_FRAC = 0.25

#: Baseline pool: fastest-of-N floors over this many preceding runs of
#: the same (mode, host_class, smoke) group.
DEFAULT_BASELINE_POOL = 5

#: Default on-disk series (repo-relative).
TRAJECTORY_PATH = os.path.join("benchmarks", "history",
                               "trajectory.jsonl")

# Metric-name tokens that decide comparison direction. Substring match
# on the LAST dotted component, lower-is-better checked first so
# "merge_ms_per_round" classifies by its unit suffix.
_LOWER_TOKENS = ("_ms", "_s", "_seconds", "_us", "_ns", "latency",
                 "overhead", "floor_ms", "_frac")
_HIGHER_TOKENS = ("per_sec", "per_s", "_ops", "ops_s", "throughput",
                  "speedup", "rate", "per_round_per_sec")
# Never auto-compared: configuration echoes and counts that legitimately
# change run to run (shape knobs, totals, budgets themselves).
# "overhead_frac" is the bench's own self-measurement, gated absolutely
# in-bench against ledger_overhead_budget_frac — its floor bounces 2x
# run to run, so a multiplicative trajectory floor would only flap.
# "ceiling" tags metrics derived from histogram_quantile bucket
# ceilings (obs/fleet.py): those are log2-quantized upper bounds, so
# gating a real sample against a ceiling floor would verdict the
# quantization, not the latency — benches record the sketch-true
# quantile (obs/sketch.py) in a separate, gated key alongside.
_SKIP_TOKENS = ("budget", "_n", "n_", "rounds", "repeats", "bytes",
                "rows", "slots", "count", "size", "width", "port",
                "seed", "chunk", "depth", "within", "ok", "vs_baseline",
                "overhead_frac", "ceiling")
# Checked BEFORE the skip list: byte/size metrics that ARE the thing
# being optimized (churn-soak steady-state footprint, docs/STORAGE.md)
# rather than configuration echoes. "bytes" alone stays skipped — only
# these explicit steady-state shapes gate.
_LOWER_OVERRIDES = ("bytes_hwm", "bytes_per_live_row", "bytes_steady",
                    "tombstone_bytes_shipped")


def metric_direction(name: str) -> Optional[str]:
    """``"lower"`` / ``"higher"`` is better, or None (not compared).
    Heuristic over the last dotted component's unit-ish tokens —
    deliberately conservative: an unclassifiable metric is recorded in
    the trajectory but never gated on."""
    leaf = name.rsplit(".", 1)[-1].lower()
    for tok in _LOWER_OVERRIDES:
        if tok in leaf:
            return "lower"
    for tok in _SKIP_TOKENS:
        if tok in leaf:
            return None
    for tok in _HIGHER_TOKENS:
        if tok in leaf:
            return "higher"
    for tok in _LOWER_TOKENS:
        if leaf.endswith(tok) or tok in leaf:
            return "lower"
    return None


def flatten_metrics(obj: Any, prefix: str = "",
                    out: Optional[Dict[str, float]] = None
                    ) -> Dict[str, float]:
    """Numeric leaves of a nested bench result as dotted keys. Bools,
    strings, lists and None are dropped — the trajectory carries
    scalars a floor can be computed over."""
    if out is None:
        out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            flatten_metrics(v, key, out)
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix] = float(obj)
    return out


def host_class() -> str:
    """Coarse hardware identity for grouping comparable runs:
    ``{backend}-{machine}-cpu{n}``. Backend resolves through jax when
    it is already importable and falls back to "cpu" — the class must
    be computable in CI without waking an accelerator."""
    import platform
    backend = "cpu"
    try:
        import sys
        if "jax" in sys.modules:
            backend = sys.modules["jax"].default_backend()
    except Exception:
        pass
    return (f"{backend}-{platform.machine() or 'unknown'}"
            f"-cpu{os.cpu_count() or 0}")


def git_sha(repo_dir: Optional[str] = None) -> str:
    """Current commit sha, or "unknown" outside a checkout — records
    must still append from a bare CI artifact dir."""
    import subprocess
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=repo_dir or os.getcwd(), capture_output=True,
            text=True, timeout=10).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def normalize_record(mode: str, result: dict, *,
                     run_id: Optional[str] = None,
                     sha: Optional[str] = None,
                     host: Optional[str] = None,
                     smoke: bool = False,
                     source: Optional[str] = None) -> dict:
    """One trajectory record from one bench result dict. ``slo`` rides
    along verbatim when the result carries one (bench.py prints it as
    a trailing line; callers pass it merged into ``result``)."""
    if run_id is None:
        import time
        import uuid
        run_id = (time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
                  + "-" + uuid.uuid4().hex[:8])
    rec = {
        "run_id": run_id,
        "mode": mode,
        "git_sha": sha if sha is not None else git_sha(),
        "host_class": host if host is not None else host_class(),
        "smoke": bool(smoke),
        "metrics": flatten_metrics({k: v for k, v in result.items()
                                    if k != "slo"}),
        "slo": result.get("slo") if isinstance(result.get("slo"),
                                               dict) else None,
    }
    if source:
        rec["source"] = source
    return rec


def append_record(record: dict,
                  path: str = TRAJECTORY_PATH) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")


def load_trajectory(path: str) -> List[dict]:
    """Records in file order; malformed lines are skipped (a torn
    append must not take the whole series down), schema-less lines
    (no ``mode``/``metrics``) too."""
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "mode" in rec \
                    and isinstance(rec.get("metrics"), dict):
                out.append(rec)
    return out


def _group_key(rec: dict) -> Tuple[str, str, bool]:
    return (str(rec.get("mode")), str(rec.get("host_class")),
            bool(rec.get("smoke")))


def compare(baseline: Sequence[dict], candidate: dict, *,
            budget_frac: float = DEFAULT_BUDGET_FRAC,
            pool: int = DEFAULT_BASELINE_POOL,
            metrics: Optional[Sequence[str]] = None) -> dict:
    """Regression verdict for ``candidate`` against the fastest-of-N
    floors of its ``(mode, host_class, smoke)`` group in ``baseline``.

    Returns ``{checks: {metric: {value, baseline, budget, ok,
    direction}}, ok, unmeasured, compared, group, baseline_runs}``
    with `evaluate_slo` semantics: ``ok`` is None for unmeasured
    checks, the verdict requires every measured check to pass, and a
    run with zero measured checks is NOT ok (``ok`` None) — unmeasured
    never reads as passed."""
    key = _group_key(candidate)
    peers = [r for r in baseline if _group_key(r) == key
             and r.get("run_id") != candidate.get("run_id")]
    peers = peers[-pool:]
    cand_metrics = candidate.get("metrics", {})
    names = (list(metrics) if metrics
             else sorted(cand_metrics.keys()))
    checks: Dict[str, dict] = {}
    for name in names:
        direction = metric_direction(name)
        value = cand_metrics.get(name)
        floor: Optional[float] = None
        vals = [r["metrics"][name] for r in peers
                if isinstance(r.get("metrics", {}).get(name),
                              (int, float))]
        ok: Optional[bool] = None
        budget: Optional[float] = None
        if direction is not None and value is not None and vals:
            if direction == "lower":
                floor = min(vals)
                if floor <= 0.0:
                    # A zero floor gives no scale for multiplicative
                    # noise — any nonzero value would "regress".
                    checks[name] = {"value": value, "baseline": floor,
                                    "budget": None, "ok": None,
                                    "direction": direction}
                    continue
                budget = floor * (1.0 + budget_frac)
                ok = bool(value <= budget)
            else:
                floor = max(vals)
                budget = floor * (1.0 - budget_frac)
                ok = bool(value >= budget)
        elif direction is None and metrics:
            # Explicitly requested but unclassifiable: surface it as
            # unmeasured rather than dropping the row.
            ok = None
        elif direction is None:
            continue
        checks[name] = {"value": value, "baseline": floor,
                        "budget": budget, "ok": ok,
                        "direction": direction}
    measured = [c["ok"] for c in checks.values() if c["ok"] is not None]
    unmeasured = sum(1 for c in checks.values() if c["ok"] is None)
    ok = (bool(measured) and all(measured)) if measured else None
    return {"checks": checks, "ok": ok, "compared": len(measured),
            "unmeasured": unmeasured,
            "group": {"mode": key[0], "host_class": key[1],
                      "smoke": key[2]},
            "baseline_runs": [r.get("run_id") for r in peers]}


def bench_main(argv: Optional[List[str]] = None, out=None) -> int:
    """``python -m crdt_tpu.obs bench`` entry point.

    ``--compare BASELINE`` verdicts the newest record of BASELINE's
    last group (self-trajectory: append, then gate) or, with
    ``--candidate FILE``, the newest record of FILE against the whole
    of BASELINE. Exit 0 = every measured metric within budget, 1 =
    regression, 2 = nothing comparable (missing group, empty series —
    unmeasured != passed, for the run as a whole too)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m crdt_tpu.obs bench",
        description="bench-trajectory regression verdicts "
                    "(benchmarks/history/trajectory.jsonl)")
    ap.add_argument("--compare", metavar="BASELINE",
                    default=TRAJECTORY_PATH,
                    help="baseline trajectory jsonl "
                         f"(default {TRAJECTORY_PATH})")
    ap.add_argument("--candidate", metavar="FILE", default=None,
                    help="candidate trajectory jsonl; default: the "
                         "baseline's own last record (self-gate)")
    ap.add_argument("--pool", type=int, default=DEFAULT_BASELINE_POOL,
                    help="fastest-of-N baseline pool size")
    ap.add_argument("--budget", type=float,
                    default=DEFAULT_BUDGET_FRAC,
                    help="per-metric noise budget fraction")
    ap.add_argument("--metric", action="append", default=None,
                    help="gate only these metric names (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full verdict JSON")
    args = ap.parse_args(argv)
    out = sys.stdout if out is None else out

    baseline = load_trajectory(args.compare)
    if args.candidate:
        cand_series = load_trajectory(args.candidate)
        if not cand_series:
            out.write(f"no candidate records in {args.candidate}\n")
            return 2
        candidate = cand_series[-1]
    else:
        if not baseline:
            out.write(f"no records in {args.compare}\n")
            return 2
        candidate = baseline[-1]
        baseline = baseline[:-1]

    verdict = compare(baseline, candidate, budget_frac=args.budget,
                      pool=args.pool, metrics=args.metric)
    if args.json:
        out.write(json.dumps({"candidate": candidate.get("run_id"),
                              "verdict": verdict}, sort_keys=True)
                  + "\n")
    else:
        g = verdict["group"]
        out.write(f"candidate {candidate.get('run_id')} "
                  f"mode={g['mode']} host={g['host_class']} "
                  f"smoke={g['smoke']} vs "
                  f"{len(verdict['baseline_runs'])} baseline run(s)\n")
        for name, c in sorted(verdict["checks"].items()):
            if c["ok"] is None:
                state = "unmeasured"
            else:
                state = "ok" if c["ok"] else "REGRESSED"
            base = ("-" if c["baseline"] is None
                    else f"{c['baseline']:.6g}")
            val = "-" if c["value"] is None else f"{c['value']:.6g}"
            out.write(f"  {state:<10} {name} value={val} "
                      f"floor={base} dir={c['direction']}\n")
        out.write(f"verdict ok={verdict['ok']} "
                  f"compared={verdict['compared']} "
                  f"unmeasured={verdict['unmeasured']}\n")
    out.flush()
    if verdict["ok"] is None:
        return 2
    return 0 if verdict["ok"] else 1

"""Pallas fan-in kernel vs the XLA fold — bit-identical store lanes.

Runs in interpreter mode on CPU (the kernel itself targets TPU; the
driver's bench exercises the compiled path on hardware).
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu.hlc import SHIFT
from crdt_tpu.ops.dense import (DenseStore, empty_dense_store, fanin_step)
from crdt_tpu.ops.pallas_merge import (join_store, pallas_fanin_batch,
                                       pallas_fanin_step,
                                       pallas_fanin_stream,
                                       split_changeset, split_store)

from test_dense import LOCAL, MILLIS, lt_of, make_changeset

from crdt_tpu.ops.pallas_merge import TILE as BLOCK


def run_both(store, cs, canonical_lt=0, local_node=LOCAL,
             wall=MILLIS + 10_000):
    ref_store, ref_res = fanin_step(store, cs, jnp.int64(canonical_lt),
                                    jnp.int32(local_node), jnp.int64(wall))
    pl_store, pl_res = pallas_fanin_step(
        split_store(store), split_changeset(cs), jnp.int64(canonical_lt),
        jnp.int32(local_node), jnp.int64(wall),
        interpret=True)
    return ref_store, ref_res, join_store(pl_store), pl_res


def assert_stores_equal(a: DenseStore, b: DenseStore):
    # Unoccupied slots: dense keeps zeros, split keeps sentinels —
    # only occupied slots are observable (record_map filters).
    from crdt_tpu.testing import assert_dense_stores_equal
    assert_dense_stores_equal(a, b)


@pytest.mark.parametrize("seed", range(4))
def test_random_matches_xla_fold(seed):
    rng = random.Random(seed)
    r, n = 5, 2 * BLOCK
    entries = []
    for ri in range(r):
        for k in range(n):
            if rng.random() < 0.5:
                continue
            entries.append((ri, k,
                            lt_of(MILLIS + rng.randrange(40),
                                  rng.randrange(3)),
                            rng.randrange(1, 6), rng.randrange(1000),
                            rng.random() < 0.3))
    cs = make_changeset(r, n, entries)
    ref_store, ref_res, pl_store, pl_res = run_both(empty_dense_store(n), cs)

    assert_stores_equal(ref_store, pl_store)
    assert int(pl_res.new_canonical) == int(ref_res.new_canonical)
    # From an empty store every occupied slot is a winner.
    np.testing.assert_array_equal(np.asarray(pl_res.win),
                                  np.asarray(ref_store.occupied))
    assert int(np.sum(np.asarray(pl_res.win))) == int(ref_res.win_count)
    assert not bool(pl_res.any_dup) and not bool(pl_res.any_drift)


def test_sequential_merges_accumulate():
    # Two consecutive kernel steps on the same split store: LWW holds
    # across steps (older second write loses; newer wins).
    n = BLOCK
    s = split_store(empty_dense_store(n))
    cs1 = make_changeset(1, n, [(0, 0, lt_of(MILLIS + 5), 2, 10, False),
                                (0, 1, lt_of(MILLIS + 5), 2, 11, False)])
    s, r1 = pallas_fanin_step(s, split_changeset(cs1), jnp.int64(0),
                              jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
                              interpret=True)
    cs2 = make_changeset(1, n, [(0, 0, lt_of(MILLIS), 3, 99, False),
                                (0, 2, lt_of(MILLIS + 9), 3, 12, False)])
    s, r2 = pallas_fanin_step(s, split_changeset(cs2), r1.new_canonical,
                              jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
                              interpret=True)
    out = join_store(s)
    assert int(out.val[0]) == 10      # older write lost
    assert int(out.val[2]) == 12      # new key adopted
    assert int(r2.new_canonical) == lt_of(MILLIS + 9)


def test_local_wins_exact_tie():
    n = BLOCK
    cs1 = make_changeset(1, n, [(0, 0, lt_of(MILLIS), 2, 10, False)])
    s = split_store(empty_dense_store(n))
    s, r1 = pallas_fanin_step(s, split_changeset(cs1), jnp.int64(0),
                              jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
                              interpret=True)
    cs2 = make_changeset(1, n, [(0, 0, lt_of(MILLIS), 2, 99, False)])
    s, _ = pallas_fanin_step(s, split_changeset(cs2), r1.new_canonical,
                             jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
                             interpret=True)
    assert int(join_store(s).val[0]) == 10


def test_tombstone_and_node_tiebreak():
    n = BLOCK
    cs = make_changeset(3, n, [
        (0, 0, lt_of(MILLIS), 1, 10, False),
        (1, 0, lt_of(MILLIS), 2, 0, True),    # same lt, higher node: wins
        (2, 1, lt_of(MILLIS), 2, 7, False),
    ])
    s, _ = pallas_fanin_step(split_store(empty_dense_store(n)),
                             split_changeset(cs), jnp.int64(0),
                             jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
                             interpret=True)
    out = join_store(s)
    assert bool(out.tomb[0]) and int(out.node[0]) == 2
    assert int(out.val[1]) == 7


def test_guards():
    n = BLOCK
    # Duplicate node ahead of canonical → any_dup.
    cs = make_changeset(1, n, [(0, 0, lt_of(MILLIS), LOCAL, 1, False)])
    _, res = pallas_fanin_step(split_store(empty_dense_store(n)),
                               split_changeset(cs), jnp.int64(0),
                               jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
                               interpret=True)
    assert bool(res.any_dup) and not bool(res.any_drift)

    # Same record with canonical already ahead → fast path, no guard.
    _, res = pallas_fanin_step(split_store(empty_dense_store(n)),
                               split_changeset(cs),
                               jnp.int64(lt_of(MILLIS)), jnp.int32(LOCAL),
                               jnp.int64(MILLIS + 10_000),
                               interpret=True)
    assert not bool(res.any_dup)

    # >60s ahead of the wall → drift.
    from crdt_tpu.hlc import MAX_DRIFT
    wall = MILLIS
    cs = make_changeset(1, n, [
        (0, 0, lt_of(wall + MAX_DRIFT + 1), 1, 1, False)])
    _, res = pallas_fanin_step(split_store(empty_dense_store(n)),
                               split_changeset(cs), jnp.int64(0),
                               jnp.int32(LOCAL), jnp.int64(wall),
                               interpret=True)
    assert bool(res.any_drift) and not bool(res.any_dup)

    # Column-local shielding: an earlier row in the SAME column lifts
    # the running clock past the local-ordinal record → no dup.
    cs = make_changeset(2, n, [
        (0, 0, lt_of(MILLIS + 5), 1, 1, False),
        (1, 0, lt_of(MILLIS), LOCAL, 2, False),
    ])
    _, res = pallas_fanin_step(split_store(empty_dense_store(n)),
                               split_changeset(cs), jnp.int64(0),
                               jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
                               interpret=True)
    assert not bool(res.any_dup)


def test_drift_boundary_counter_bits():
    # Round-1 off-by-one: a record at EXACTLY wall+MAX_DRIFT millis with
    # counter > 0 must NOT drift (the reference check is millis-level,
    # hlc.dart:92-94); one millisecond later must.
    from crdt_tpu.hlc import MAX_DRIFT
    n = BLOCK
    wall = MILLIS
    at_limit = make_changeset(1, n, [
        (0, 0, lt_of(wall + MAX_DRIFT, 3), 1, 1, False)])
    _, res = pallas_fanin_step(split_store(empty_dense_store(n)),
                               split_changeset(at_limit), jnp.int64(0),
                               jnp.int32(LOCAL), jnp.int64(wall),
                               interpret=True)
    assert not bool(res.any_drift)

    past_limit = make_changeset(1, n, [
        (0, 0, lt_of(wall + MAX_DRIFT + 1, 0), 1, 1, False)])
    _, res = pallas_fanin_step(split_store(empty_dense_store(n)),
                               split_changeset(past_limit), jnp.int64(0),
                               jnp.int32(LOCAL), jnp.int64(wall),
                               interpret=True)
    assert bool(res.any_drift)


def run_sequential_folds(store, cs, n_chunks, canonical_lt=0,
                         local_node=LOCAL, wall=MILLIS + 10_000):
    """The reference semantics for `pallas_fanin_stream`: n_chunks
    XLA folds, chunk c advancing every clock by c ms, canonical
    threaded; win masks OR'd. Guard flags from the equivalent
    sequential kernel steps (column-local semantics)."""
    st, canon = store, jnp.int64(canonical_lt)
    pst = split_store(store)
    pcanon = jnp.int64(canonical_lt)
    win = np.zeros(store.n_slots, bool)
    any_dup = any_drift = False
    for c in range(n_chunks):
        cs_c = cs._replace(lt=cs.lt + (c << SHIFT))
        st, res = fanin_step(st, cs_c, canon, jnp.int32(local_node),
                             jnp.int64(wall))
        canon = res.new_canonical
        pst, pres = pallas_fanin_step(pst, split_changeset(cs_c), pcanon,
                                      jnp.int32(local_node),
                                      jnp.int64(wall), interpret=True)
        pcanon = pres.new_canonical
        win |= np.asarray(pres.win)
        any_dup |= bool(pres.any_dup)
        any_drift |= bool(pres.any_drift)
    return st, canon, win, any_dup, any_drift


@pytest.mark.parametrize("seed", range(3))
def test_stream_matches_sequential_folds(seed):
    rng = random.Random(seed + 100)
    r, n, n_chunks = 3, 2 * BLOCK, 4
    entries = []
    for ri in range(r):
        for k in range(n):
            if rng.random() < 0.6:
                continue
            entries.append((ri, k,
                            lt_of(MILLIS + rng.randrange(40),
                                  rng.randrange(3)),
                            rng.randrange(1, 6), rng.randrange(1000),
                            rng.random() < 0.3))
    cs = make_changeset(r, n, entries)
    ref_store, ref_canon, ref_win, ref_dup, ref_drift = \
        run_sequential_folds(empty_dense_store(n), cs, n_chunks)

    sst, sres = pallas_fanin_stream(
        split_store(empty_dense_store(n)), split_changeset(cs),
        jnp.int64(0), jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
        n_chunks=n_chunks, interpret=True)

    assert_stores_equal(ref_store, join_store(sst))
    assert int(sres.new_canonical) == int(ref_canon)
    np.testing.assert_array_equal(np.asarray(sres.win), ref_win)
    assert bool(sres.any_dup) == ref_dup
    assert bool(sres.any_drift) == ref_drift


def test_stream_single_chunk_equals_step():
    cs = make_changeset(2, BLOCK, [
        (0, 0, lt_of(MILLIS), 1, 10, False),
        (1, 0, lt_of(MILLIS), 2, 0, True),
        (1, 5, lt_of(MILLIS + 3), 4, 7, False)])
    s0 = split_store(empty_dense_store(BLOCK))
    a_st, a_res = pallas_fanin_step(s0, split_changeset(cs), jnp.int64(0),
                                    jnp.int32(LOCAL),
                                    jnp.int64(MILLIS + 10_000),
                                    interpret=True)
    b_st, b_res = pallas_fanin_stream(s0, split_changeset(cs),
                                      jnp.int64(0), jnp.int32(LOCAL),
                                      jnp.int64(MILLIS + 10_000),
                                      n_chunks=1, interpret=True)
    assert_stores_equal(join_store(a_st), join_store(b_st))
    assert int(a_res.new_canonical) == int(b_res.new_canonical)
    np.testing.assert_array_equal(np.asarray(a_res.win),
                                  np.asarray(b_res.win))


def test_stream_guards_across_chunks():
    # A local-ordinal record beyond canonical trips dup in chunk 0; by
    # chunk 1 the threaded canonical has absorbed chunk 0's max, but the
    # chunk-1 record advances by 1ms and trips again — flags accumulate.
    cs = make_changeset(1, BLOCK, [
        (0, 0, lt_of(MILLIS), LOCAL, 1, False)])
    _, res = pallas_fanin_stream(split_store(empty_dense_store(BLOCK)),
                                 split_changeset(cs), jnp.int64(0),
                                 jnp.int32(LOCAL),
                                 jnp.int64(MILLIS + 10_000),
                                 n_chunks=3, interpret=True)
    assert bool(res.any_dup) and not bool(res.any_drift)

    # Canonical far ahead: every chunk fast-paths, no flags, no wins.
    ahead = lt_of(MILLIS + 1000)
    st, res = pallas_fanin_stream(split_store(empty_dense_store(BLOCK)),
                                  split_changeset(cs), jnp.int64(ahead),
                                  jnp.int32(LOCAL),
                                  jnp.int64(MILLIS + 10_000),
                                  n_chunks=3, interpret=True)
    assert not bool(res.any_dup)
    assert int(res.new_canonical) == ahead
    # The record itself still merges (guards gate the clock, LWW gates
    # the store).
    assert int(join_store(st).val[0]) == 1


@pytest.mark.parametrize("seed", range(3))
def test_fast_guards_same_results_superset_flags(seed):
    # guards="fast" must produce identical store/win/canonical and flag
    # a SUPERSET of exact mode's guard trips.
    rng = random.Random(seed + 300)
    r, n, n_chunks = 3, BLOCK, 3
    entries = []
    for ri in range(r):
        for k in range(n):
            if rng.random() < 0.7:
                continue
            # Include local-ordinal records and shielding patterns.
            node = rng.choice([1, 2, LOCAL, LOCAL, 5])
            entries.append((ri, k,
                            lt_of(MILLIS + rng.randrange(10),
                                  rng.randrange(2)),
                            node, rng.randrange(1000),
                            rng.random() < 0.3))
    cs = make_changeset(r, n, entries)
    canon = lt_of(MILLIS + rng.randrange(8))
    args = (split_store(empty_dense_store(n)), split_changeset(cs),
            jnp.int64(canon), jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000))
    e_st, e_res = pallas_fanin_stream(*args, n_chunks=n_chunks,
                                      guards="exact", interpret=True)
    f_st, f_res = pallas_fanin_stream(*args, n_chunks=n_chunks,
                                      guards="fast", interpret=True)
    assert_stores_equal(join_store(e_st), join_store(f_st))
    np.testing.assert_array_equal(np.asarray(e_res.win),
                                  np.asarray(f_res.win))
    assert int(e_res.new_canonical) == int(f_res.new_canonical)
    # Superset: exact trip => fast trip.
    assert (not bool(e_res.any_dup)) or bool(f_res.any_dup)
    assert (not bool(e_res.any_drift)) or bool(f_res.any_drift)


def test_fast_guards_clean_on_steady_state():
    # No local-node records, clocks within drift: neither mode flags.
    cs = make_changeset(2, BLOCK, [
        (0, 0, lt_of(MILLIS), 1, 10, False),
        (1, 3, lt_of(MILLIS + 2), 2, 11, False)])
    for mode in ("exact", "fast"):
        _, res = pallas_fanin_stream(
            split_store(empty_dense_store(BLOCK)), split_changeset(cs),
            jnp.int64(0), jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
            n_chunks=4, guards=mode, interpret=True)
        assert not bool(res.any_dup), mode
        assert not bool(res.any_drift), mode


def test_fast_guards_catch_real_anomalies():
    # A genuine duplicate-node record and a genuine drift record must
    # trip fast mode (no false negatives).
    dup_cs = make_changeset(1, BLOCK, [
        (0, 0, lt_of(MILLIS), LOCAL, 1, False)])
    _, res = pallas_fanin_stream(
        split_store(empty_dense_store(BLOCK)), split_changeset(dup_cs),
        jnp.int64(0), jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
        n_chunks=2, guards="fast", interpret=True)
    assert bool(res.any_dup)

    from crdt_tpu.hlc import MAX_DRIFT
    drift_cs = make_changeset(1, BLOCK, [
        (0, 0, lt_of(MILLIS + MAX_DRIFT + 1), 1, 1, False)])
    _, res = pallas_fanin_stream(
        split_store(empty_dense_store(BLOCK)), split_changeset(drift_cs),
        jnp.int64(0), jnp.int32(LOCAL), jnp.int64(MILLIS),
        n_chunks=1, guards="fast", interpret=True)
    assert bool(res.any_drift)


def test_stream_empty_store_offsets_dont_resurrect_invalid():
    # Round-2 hazard: chunk offsets must not lift the NEG sentinel of an
    # invalid lane above an empty store slot.
    cs = make_changeset(1, BLOCK, [
        (0, 0, lt_of(MILLIS), 1, 42, False)])   # slot 0 only; rest invalid
    st, res = pallas_fanin_stream(split_store(empty_dense_store(BLOCK)),
                                  split_changeset(cs), jnp.int64(0),
                                  jnp.int32(LOCAL),
                                  jnp.int64(MILLIS + 10_000),
                                  n_chunks=4, interpret=True)
    out = join_store(st)
    assert int(np.sum(np.asarray(out.occupied))) == 1
    assert bool(out.occupied[0]) and int(out.val[0]) == 42
    assert int(np.sum(np.asarray(res.win))) == 1


@pytest.mark.parametrize("seed", range(3))
def test_batch_matches_one_shot_step(seed):
    # pallas_fanin_batch walks DISTINCT row groups of ONE logical
    # merge: store/win/canonical must match the full-batch step
    # bit-for-bit, for any chunk_rows that divides R.
    rng = random.Random(seed + 500)
    r, n = 8, BLOCK
    entries = []
    for ri in range(r):
        for k in range(n):
            if rng.random() < 0.6:
                continue
            entries.append((ri, k,
                            lt_of(MILLIS + rng.randrange(20),
                                  rng.randrange(3)),
                            rng.randrange(1, 6), rng.randrange(1000),
                            rng.random() < 0.3))
    cs = make_changeset(r, n, entries)
    canon = lt_of(MILLIS + 3)
    args = (split_store(empty_dense_store(n)), split_changeset(cs),
            jnp.int64(canon), jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000))
    ref_st, ref_res = pallas_fanin_step(*args, interpret=True)
    for chunk_rows in (2, 4, 8):
        b_st, b_res = pallas_fanin_batch(*args, chunk_rows=chunk_rows,
                                         interpret=True)
        assert_stores_equal(join_store(ref_st), join_store(b_st))
        np.testing.assert_array_equal(np.asarray(ref_res.win),
                                      np.asarray(b_res.win))
        assert int(ref_res.new_canonical) == int(b_res.new_canonical)


def test_batch_guard_superset():
    # Dup/drift anomalies must trip the batch's optimistic flags.
    dup = make_changeset(2, BLOCK, [
        (1, 0, lt_of(MILLIS), LOCAL, 1, False)])
    _, res = pallas_fanin_batch(
        split_store(empty_dense_store(BLOCK)), split_changeset(dup),
        jnp.int64(0), jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
        chunk_rows=2, interpret=True)
    assert bool(res.any_dup)

    clean = make_changeset(2, BLOCK, [
        (0, 0, lt_of(MILLIS), 1, 1, False),
        (1, 1, lt_of(MILLIS + 1), 2, 2, False)])
    _, res = pallas_fanin_batch(
        split_store(empty_dense_store(BLOCK)), split_changeset(clean),
        jnp.int64(0), jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000),
        chunk_rows=2, interpret=True)
    assert not bool(res.any_dup) and not bool(res.any_drift)


def test_split_roundtrip():
    n = BLOCK
    cs = make_changeset(2, n, [(0, 3, lt_of(MILLIS, 2), 4, 123, False),
                               (1, 4, lt_of(MILLIS), 5, 0, True)])
    store, _ = fanin_step(empty_dense_store(n), cs, jnp.int64(0),
                          jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000))
    assert_stores_equal(store, join_store(split_store(store)))


class TestNarrowVal:
    """Value-ref (int32 val lane) kernel mode: bit-identical store
    results to the wide kernel whenever values fit int32 — including
    negative values (sign extension) — and a raised overflow flag
    when they don't."""

    def _cs(self, r, n, seed, lo=-(2 ** 31), hi=2 ** 31):
        import numpy as np
        rng = np.random.default_rng(seed)
        lt = ((1_700_000_000_000 + rng.integers(0, 500, (r, n))) << 16) \
            + rng.integers(0, 3, (r, n))
        from crdt_tpu.ops.dense import DenseChangeset
        return DenseChangeset(
            lt=jnp.asarray(lt, jnp.int64),
            node=jnp.asarray(rng.integers(1, 9, (r, n)), jnp.int32),
            val=jnp.asarray(rng.integers(lo, hi, (r, n)), jnp.int64),
            tomb=jnp.asarray(rng.random((r, n)) < 0.3),
            valid=jnp.asarray(rng.random((r, n)) < 0.8),
        )

    def test_batch_matches_wide_kernel(self):
        from crdt_tpu.ops.dense import empty_dense_store
        from crdt_tpu.ops.pallas_merge import (
            TILE, join_store, pallas_fanin_batch, split_changeset,
            split_changeset_narrow, split_store)
        from crdt_tpu.testing import assert_dense_stores_equal
        n = TILE
        cs = self._cs(16, n, seed=3)
        store = split_store(empty_dense_store(n))
        canonical = jnp.int64(0)
        local = jnp.int32(0)
        wall = jnp.int64(1_700_000_100_000)
        wide_st, wide_res = pallas_fanin_batch(
            store, split_changeset(cs), canonical, local, wall,
            chunk_rows=8, interpret=True)
        ncs, overflow = split_changeset_narrow(cs)
        assert not bool(overflow)
        nar_st, nar_res = pallas_fanin_batch(
            store, ncs, canonical, local, wall,
            chunk_rows=8, interpret=True)
        assert_dense_stores_equal(join_store(wide_st),
                                  join_store(nar_st), "wide vs narrow")
        assert int(wide_res.new_canonical) == int(nar_res.new_canonical)
        import numpy as np
        np.testing.assert_array_equal(np.asarray(wide_res.win),
                                      np.asarray(nar_res.win))

    def test_negative_values_sign_extend(self):
        from crdt_tpu.ops.dense import empty_dense_store
        from crdt_tpu.ops.pallas_merge import (
            TILE, join_store, pallas_fanin_batch,
            split_changeset_narrow, split_store)
        n = TILE
        cs = self._cs(8, n, seed=5, lo=-1000, hi=0)
        ncs, overflow = split_changeset_narrow(cs)
        assert not bool(overflow)
        st, _ = pallas_fanin_batch(
            split_store(empty_dense_store(n)), ncs, jnp.int64(0),
            jnp.int32(0), jnp.int64(1_700_000_100_000),
            chunk_rows=8, interpret=True)
        out = join_store(st)
        import numpy as np
        occ = np.asarray(out.occupied)
        vals = np.asarray(out.val)[occ]
        assert vals.size and (vals < 0).all()
        assert vals.min() >= -1000

    def test_overflow_flag(self):
        from crdt_tpu.ops.pallas_merge import split_changeset_narrow
        cs = self._cs(2, 256, seed=1)
        cs = cs._replace(val=cs.val.at[0, 0].set(2 ** 40),
                         valid=cs.valid.at[0, 0].set(True))
        _, overflow = split_changeset_narrow(cs)
        assert bool(overflow)
        # invalid lanes never flag
        cs2 = cs._replace(valid=cs.valid.at[0, 0].set(False))
        _, overflow2 = split_changeset_narrow(cs2)
        assert not bool(overflow2)

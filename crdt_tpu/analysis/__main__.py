"""Entry point: ``python -m crdt_tpu.analysis``.

Environment setup must precede any jax import: the jaxpr audit's
sharded targets trace on 8 virtual CPU devices (the same layout
tests/conftest.py forces), and forcing the CPU platform keeps the CI
gate runnable on machines without an accelerator."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from .cli import main  # noqa: E402  (env setup must run first)

sys.exit(main())

"""In-memory dict-backed CRDT — the scalar oracle backend (L4).

Matches the reference `lib/src/map_crdt.dart:1-53`: a plain map of
records plus a broadcast change stream. This backend is the semantic
oracle the TPU path is differentially tested against; it is also the
right choice for small, host-resident stores.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Generic, Optional, TypeVar

from ..crdt import Crdt
from ..hlc import Hlc
from ..record import Record
from ..utils.stats import MergeStats
from ..watch import ChangeHub, ChangeStream

K = TypeVar("K")
V = TypeVar("V")


class MapCrdt(Crdt[K, V], Generic[K, V]):
    """A CRDT backed by an in-memory map (map_crdt.dart:9-53)."""

    def __init__(self, node_id: Any,
                 seed: Optional[Dict[K, Record[V]]] = None,
                 wall_clock: Optional[Callable[[], int]] = None):
        self._node_id = node_id
        self._map: Dict[K, Record[V]] = dict(seed or {})
        self._hub = ChangeHub()
        self.stats = MergeStats().register(backend="MapCrdt",
                                           node=str(node_id))
        super().__init__(wall_clock=wall_clock)

    @property
    def node_id(self) -> Any:
        return self._node_id

    def contains_key(self, key: K) -> bool:
        return key in self._map

    def get_record(self, key: K) -> Optional[Record[V]]:
        return self._map.get(key)

    def put_record(self, key: K, record: Record[V]) -> None:
        self._map[key] = record
        self._hub.add(key, record.value)

    def put_records(self, record_map: Dict[K, Record[V]]) -> None:
        self._map.update(record_map)
        for key, record in record_map.items():
            self._hub.add(key, record.value)

    def record_map(self, modified_since: Optional[Hlc] = None
                   ) -> Dict[K, Record[V]]:
        # Inclusive bound: keep modified.logical_time >= t
        # (map_crdt.dart:44-45).
        since = 0 if modified_since is None else modified_since.logical_time
        return {k: r for k, r in self._map.items()
                if r.modified.logical_time >= since}

    def watch(self, key: Optional[K] = None) -> ChangeStream:
        return self._hub.stream(key)

    def purge(self) -> None:
        self._map.clear()

"""Federated serving suite (docs/FEDERATION.md): routed client ops
across partitions, the `moved` wire protocol (shape, session
survival, never-legacy classification), server-side proxying for
pre-federation sessions, the stale-epoch refusal that fences live
splits, watch fan-out end-to-end, and a kill-and-restart split under
a write storm proving zero acked writes are lost.

Metrics recorded here stay here: the conftest registry-isolation
fixture snapshots and restores the process-global registry around
each module, so this suite's ack latency samples cannot leak into
another module's fleet-poller SLO verdict (modules may run in any
order)."""

import socket
import threading
import time

import pytest

from crdt_tpu import (FederatedClient, FederatedTier, PeerConnection,
                      SyncProtocolError, SyncRedirectError,
                      SyncTransportError)
from crdt_tpu.net import (FrameCodec, _check_reply, recv_frame,
                          send_frame)
from crdt_tpu.testing import FaultProxy, ScriptedSchedule

pytestmark = pytest.mark.serve

N_SLOTS = 256


def _req(sock, obj, codec=None):
    send_frame(sock, obj, None, codec)
    return recv_frame(sock, deadline=time.monotonic() + 10.0,
                      codec=codec)


def _fed_session(tier):
    """Raw federated session: hello with the federation cap, then the
    post-hello codec (no zlib requested, so uncompressed tagged
    frames)."""
    sock = socket.create_connection((tier.host, tier.port),
                                    timeout=10.0)
    sock.settimeout(10.0)
    reply = _req(sock, {"op": "hello", "proto": 1,
                        "caps": ["federation"]})
    assert reply["ok"] and "federation" in reply["caps"]
    return sock, FrameCodec(compress=False)


def _foreign_slot(fed, tier):
    """A slot the given tier does NOT own."""
    for slot in range(fed.table.n_slots):
        if fed.table.owner_of(slot) != tier.router.addr:
            return slot
    raise AssertionError("single-owner table")


def _owned_slot(fed, tier):
    for slot in range(fed.table.n_slots):
        if fed.table.owner_of(slot) == tier.router.addr:
            return slot
    raise AssertionError(f"{tier.router.addr} owns nothing")


# --- routed client across partitions ---

def test_client_put_get_across_partitions():
    with FederatedTier(N_SLOTS, partitions=3,
                       flush_interval=0.002) as fed:
        assert len(set(fed.table.owners())) == 3
        cli = FederatedClient(fed.addrs())
        try:
            # One write per partition plus range edges: every op must
            # land regardless of which tier owns the slot.
            slots = sorted({_owned_slot(fed, t) for t in fed.tiers}
                           | {0, N_SLOTS // 2, N_SLOTS - 1})
            for slot in slots:
                cli.put(slot, 1000 + slot)
            for slot in slots:
                assert cli.get(slot) == 1000 + slot
            cli.delete(slots[0])
            assert cli.get(slots[0]) is None
            # A well-routed client never needed a redirect.
            assert cli.moved_redirects == 0
        finally:
            cli.close()


# --- the moved wire protocol ---

def test_moved_reply_shape_and_session_survives():
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        tier = fed.tiers[0]
        sock, codec = _fed_session(tier)
        with sock:
            foreign = _foreign_slot(fed, tier)
            reply = _req(sock, {"op": "put", "slot": foreign,
                                "value": 1, "epoch": fed.table.epoch},
                         codec)
            assert reply["ok"] is False
            assert reply["code"] == "moved"
            assert reply["owner"] == fed.table.owner_of(foreign)
            assert reply["epoch"] == fed.table.epoch
            # The redirect carries everything a single-slot client
            # needs — and the session is NOT torn down by it.
            owned = _owned_slot(fed, tier)
            assert _req(sock, {"op": "put", "slot": owned,
                               "value": 7,
                               "epoch": fed.table.epoch},
                        codec) == {"ok": True}
            assert _req(sock, {"op": "get", "slot": owned,
                               "epoch": fed.table.epoch},
                        codec)["value"] == 7
            send_frame(sock, {"op": "bye"}, None, codec)


def test_pre_federation_session_is_proxied():
    """A session that never negotiated the federation cap cannot
    parse `moved`; the server must forward the op to the owner and
    relay the ack — pre-federation clients keep working unchanged."""
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        tier = fed.tiers[0]
        foreign = _foreign_slot(fed, tier)
        with socket.create_connection((tier.host, tier.port),
                                      timeout=10.0) as sock:
            sock.settimeout(10.0)
            # No hello at all: the oldest client generation.
            assert _req(sock, {"op": "put", "slot": foreign,
                               "value": 9}) == {"ok": True}
            assert _req(sock, {"op": "get",
                               "slot": foreign})["value"] == 9
            send_frame(sock, {"op": "bye"})
        # The write really lives on the owning tier, not the proxy.
        owner = fed.tier_at(fed.table.owner_of(foreign))
        with socket.create_connection((owner.host, owner.port),
                                      timeout=10.0) as sock:
            sock.settimeout(10.0)
            assert _req(sock, {"op": "get",
                               "slot": foreign})["value"] == 9
            send_frame(sock, {"op": "bye"})


def test_stale_epoch_refused_even_on_owned_slot():
    """After a split bumps the epoch, an op stamped with the old
    epoch answers `moved` even when the slot's owner did not change —
    the refusal that forces a table refetch before a write can race a
    migrating range."""
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        tier = fed.tiers[0]
        sock, codec = _fed_session(tier)
        with sock:
            assert _req(sock, {"op": "put", "slot": 3, "value": 1,
                               "epoch": 0}, codec) == {"ok": True}
            split = fed.split_hot(src=0)
            assert split["epoch"] == 1
            # Slot 3 sits in the donor's KEPT half: same owner, new
            # epoch. The stale stamp must still be refused.
            assert fed.table.owner_of(3) == tier.router.addr
            reply = _req(sock, {"op": "put", "slot": 3, "value": 2,
                                "epoch": 0}, codec)
            assert reply["code"] == "moved"
            assert reply["owner"] == tier.router.addr
            assert reply["epoch"] == 1
            # Re-stamped with the new epoch, the same op lands.
            assert _req(sock, {"op": "put", "slot": 3, "value": 2,
                               "epoch": 1}, codec) == {"ok": True}
            send_frame(sock, {"op": "bye"}, None, codec)


# --- client-side classification: moved is typed, never legacy ---

def test_check_reply_moved_raises_typed_redirect():
    reply = {"ok": False, "code": "moved", "owner": "10.0.0.2:7002",
             "epoch": 5, "error": "slot 9 owned elsewhere"}
    with pytest.raises(SyncRedirectError) as exc:
        _check_reply("put", reply, "ok")
    assert exc.value.owner == "10.0.0.2:7002"
    assert exc.value.epoch == 5
    # Retryable-by-construction: transport class, not a protocol
    # rejection (a protocol error would poison the peer forever).
    assert isinstance(exc.value, SyncTransportError)
    assert not isinstance(exc.value, SyncProtocolError)


def test_hello_moved_does_not_demote_to_legacy():
    """A `moved` at hello must raise the typed redirect and leave the
    connection un-demoted: the pre-hello fallback is for servers that
    don't SPEAK hello, and a federated tier emphatically does."""
    lsock = socket.create_server(("127.0.0.1", 0))
    lsock.settimeout(10.0)
    host, port = lsock.getsockname()[:2]

    def serve_one():
        conn, _ = lsock.accept()
        with conn:
            conn.settimeout(10.0)
            recv_frame(conn, deadline=time.monotonic() + 10.0)
            send_frame(conn, {"ok": False, "code": "moved",
                              "owner": "10.0.0.9:7009", "epoch": 4})

    t = threading.Thread(target=serve_one, daemon=True)
    t.start()
    conn = PeerConnection(host, port, timeout=5.0)
    try:
        with pytest.raises(SyncRedirectError) as exc:
            conn.ensure()
        assert exc.value.owner == "10.0.0.9:7009"
        assert exc.value.epoch == 4
        assert conn.legacy is False
        assert conn.connected is False
    finally:
        conn.close()
        t.join(timeout=10)
        lsock.close()


# --- watch fan-out ---

def test_watch_fan_out_delivers_committed_writes():
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        cli = FederatedClient(fed.addrs())
        slot = _owned_slot(fed, fed.tiers[1])
        owner = fed.table.owner_of(slot)
        watch = cli.watch(owner, slots=[slot])
        try:
            cli.put(slot, 42)
            deadline = time.monotonic() + 10.0
            events = []
            # Shared-tick packs are filtered client-side, so a pack
            # carrying only other slots legally arrives empty.
            while not events and time.monotonic() < deadline:
                events = watch.next_event(timeout=10.0)
            assert events == [(slot, 42)]
            cli.delete(slot)
            events = []
            while not events and time.monotonic() < deadline:
                events = watch.next_event(timeout=10.0)
            assert events == [(slot, None)]
        finally:
            watch.close()
            cli.close()


# --- kill-and-restart split under a write storm ---

class _ProxiedFed(FederatedTier):
    """Arms a FaultProxy at the newly spawned recipient before the
    split engine can dial it: `_spawn_tier` runs inside
    `_split_locked` strictly before the `_Upstream(stream_addr)`
    connect, so retargeting here cannot race the stream."""

    def __init__(self, *args, proxy=None, **kw):
        super().__init__(*args, **kw)
        self._proxy = proxy

    def _spawn_tier(self, index):
        tier = super()._spawn_tier(index)
        if self._proxy is not None and index >= self._n_initial:
            self._proxy.target_port = tier.port
        return tier


def test_split_survives_mid_handoff_cut_with_zero_lost_writes():
    """The acceptance drill: cut the migration stream mid-frame while
    a write storm targets the migrating range. The split must retry
    on a fresh connection (idempotent replay), complete, and every
    acked write must read back — zero lost."""
    sched = ScriptedSchedule([
        # Connection 1 (the split engine's initial upstream): let the
        # ~70-byte hello through, then cut the round-1 push mid-frame.
        {"kind": "truncate", "after": 150},
        # Connection 2+ (the retry): behave.
        None,
    ])
    proxy = FaultProxy("127.0.0.1", 1, sched)   # retargeted at spawn
    with proxy:
        with _ProxiedFed(N_SLOTS, partitions=2,
                         flush_interval=0.002, proxy=proxy) as fed:
            cli = FederatedClient(fed.addrs())
            # Seed the migrating half [64, 128) so round 1's pack is
            # fat enough to trip the truncate.
            for slot in range(64, 128):
                cli.put(slot, slot)

            storm_slots = (70, 90, 110, 127)
            acked = {s: None for s in storm_slots}
            stop = threading.Event()
            failures = []

            def storm():
                scli = FederatedClient(fed.addrs())
                v = 1000
                try:
                    while not stop.is_set():
                        for s in storm_slots:
                            v += 1
                            scli.put(s, v)
                            acked[s] = v
                except Exception as e:     # pragma: no cover
                    failures.append(e)
                finally:
                    scli.close()

            t = threading.Thread(target=storm, daemon=True)
            t.start()
            try:
                split = fed.split_hot(src=0, settle_rows=8,
                                      dst_addr_override=(
                                          f"{proxy.host}:{proxy.port}"))
            finally:
                stop.set()
                t.join(timeout=30)

            assert not failures, f"storm writes failed: {failures!r}"
            assert proxy.counters.get("truncate", 0) >= 1, \
                f"cut never fired: {proxy.counters}"
            assert proxy.counters["connections"] >= 2   # reconnected
            assert split["epoch"] == 1
            assert split["migrated_rows"] >= 64
            assert len(fed.tiers) == 3
            assert fed.table.owner_of(64) == fed.tiers[2].router.addr

            # Zero lost writes: per-slot values are monotone, so the
            # last ACK is exactly what a read must return — from the
            # NEW owner, post-migration.
            cli.refresh()
            for slot in range(64, 128):
                want = acked.get(slot)
                if want is None:
                    want = slot            # seed value, never stormed
                assert cli.get(slot) == want, f"slot {slot}"
            cli.close()
